"""Time-series retention + SLO health engine pins (ISSUE 16).

The ring (common/timeseries.py): bounded memory, delta-encoded
counters with reset clamping, per-window histogram p99s, survival
across suspend()/resume() and a membership-epoch change without
phantom counter resets.  The judge (common/health.py): K-window
hysteresis in both directions, every rule's breach predicate, the
flight-recorder ``alert`` trail, and the /healthz 200→503→200 cycle
over real HTTP.
"""

import json
import urllib.error
import urllib.request

import pytest

from byteps_tpu.common import flight_recorder as _flight
from byteps_tpu.common import health, obs_server, timeseries
from byteps_tpu.common.config import Config
from byteps_tpu.common.metrics import counters, gauges, histograms, registry
from byteps_tpu.common.timeseries import TimeSeriesStore
from byteps_tpu.fault import membership as mm


@pytest.fixture(autouse=True)
def _fresh_plane():
    """The store/sampler/engine singletons are process-lifetime by
    design — tests must not leak a window or a firing alert into the
    next test (conftest's _fresh_telemetry resets the registry/flight
    ring underneath)."""
    timeseries.stop_for_tests()
    health._reset_for_tests()
    yield
    timeseries.stop_for_tests()
    health._reset_for_tests()


class _FakeStore:
    """A hand-fed window: the engine's predicates are pure over
    ``points()``/``values()``, so rule tests inject exact shapes."""

    def __init__(self, interval_s=1.0):
        self.interval_s = interval_s
        self._pts = []

    def push(self, **kw):
        kw.setdefault("t", float(len(self._pts)))
        self._pts.append(kw)

    def points(self):
        return list(self._pts)

    def values(self, key):
        return [(p["t"], p[key]) for p in self._pts if key in p]


def _alert_events(state=None):
    evs = [e for e in _flight.recorder.snapshot() if e["kind"] == "alert"]
    if state is not None:
        evs = [e for e in evs if e.get("state") == state]
    return evs


# -- the ring ---------------------------------------------------------------

def test_timeseries_ring_is_bounded_at_window():
    store = TimeSeriesStore(interval_s=0.5, window=8)
    for i in range(25):
        store.sample_once(now=float(i))
    pts = store.points()
    assert len(pts) == 8                       # deque(maxlen): fixed memory
    assert pts[0]["t"] == 17.0 and pts[-1]["t"] == 24.0
    d = store.dump()
    assert d["len"] == 8 and d["window"] == 8
    assert {"overlap", "steps", "rtt_p99_ms", "ef_norm"} <= set(d["keys"])


def test_timeseries_counters_enter_delta_encoded():
    store = TimeSeriesStore(interval_s=1.0, window=16)
    counters.inc("integrity.retransmit", 5)
    p0 = store.sample_once()
    # the first sample establishes the baseline — pre-existing totals
    # must not read as a burst in the first window
    assert p0["retransmit"] == 0.0
    counters.inc("integrity.retransmit", 3)
    counters.inc("step.completed", 2)
    p1 = store.sample_once()
    assert p1["retransmit"] == 3.0 and p1["steps"] == 2.0
    p2 = store.sample_once()
    assert p2["retransmit"] == 0.0             # quiet window reads as rate 0


def test_timeseries_counter_reset_clamps_to_new_baseline():
    store = TimeSeriesStore(interval_s=1.0, window=16)
    counters.inc("integrity.retransmit", 4)
    store.sample_once()
    registry.reset("counters")                 # a fresh process under the ring
    p = store.sample_once()
    assert p["retransmit"] == 0.0              # clamped, not -4 or a burst
    counters.inc("integrity.retransmit", 2)
    assert store.sample_once()["retransmit"] == 2.0


def test_timeseries_histograms_enter_as_windowed_p99():
    store = TimeSeriesStore(interval_s=1.0, window=16)
    store.sample_once()
    for _ in range(40):
        histograms.observe("transport.rtt_ms", 1.0)
    histograms.observe("transport.rtt_ms", 100.0)
    p = store.sample_once()
    assert p["rtt_p99_ms"] >= 64.0             # the tail bucket, not the bulk
    # no new observations -> no p99 for the window (absent, not stale)
    assert "rtt_p99_ms" not in store.sample_once()


def test_timeseries_summary_carries_stats_and_spark():
    store = TimeSeriesStore(interval_s=1.0, window=16)
    for i in range(12):
        gauges.set("step.overlap_fraction", i / 11.0)
        store.sample_once(now=float(i))
    s = store.summary()
    assert s["n"] == 12 and s["span_s"] == 11.0
    ov = s["series"]["overlap"]
    assert ov["min"] == 0.0 and ov["max"] == 1.0 and ov["last"] == 1.0
    assert len(ov["spark"]) == 8               # a bounded tail, bus-sized
    assert ov["spark"][-1] == 1.0


def test_timeseries_ring_survives_suspend_resume_and_epoch_change():
    cfg = Config(ts_on=True, ts_interval_s=60.0, ts_window=16)
    store = timeseries.ensure_started(cfg)
    assert store is not None
    counters.inc("step.completed", 3)
    store.sample_once()                        # baseline
    counters.inc("step.completed", 2)
    assert store.sample_once()["steps"] == 2.0
    # an elastic transition re-runs init(): the store (and its window)
    # must be the same object, not a fresh ring
    assert timeseries.ensure_started(cfg) is store
    before = mm.current_epoch()
    mm.advance_epoch()
    try:
        counters.inc("step.completed", 4)
        p = store.sample_once()
        # the registry is process-wide: counters stayed monotonic across
        # the epoch change, so the delta is exact — no phantom reset
        assert p["steps"] == 4.0
        assert len(store.points()) == 3
    finally:
        mm.set_epoch(before)


# -- the judge --------------------------------------------------------------

def test_health_overlap_floor_hysteresis_both_directions():
    eng = health.HealthEngine(Config(health_windows=2))
    store = _FakeStore()
    store.push(overlap=0.05, steps=1.0)
    eng.evaluate(store)
    assert "overlap_floor" not in eng.active_alerts()   # 1 breach < K
    store.push(overlap=0.05, steps=1.0)
    eng.evaluate(store)
    alerts = eng.active_alerts()
    assert alerts["overlap_floor"]["overlap"] == 0.05
    assert gauges.snapshot()['health.alerts_active{rule="overlap_floor"}'] \
        == 1.0
    firing = _alert_events("firing")
    assert firing and firing[-1]["rule"] == "overlap_floor"
    # one clean window must NOT un-page
    store.push(overlap=0.9, steps=1.0)
    eng.evaluate(store)
    assert "overlap_floor" in eng.active_alerts()
    store.push(overlap=0.9, steps=1.0)
    eng.evaluate(store)
    assert eng.active_alerts() == {}
    assert gauges.snapshot()['health.alerts_active{rule="overlap_floor"}'] \
        == 0.0
    assert _alert_events("cleared")[-1]["rule"] == "overlap_floor"


def test_health_overlap_floor_ignores_idle_windows():
    eng = health.HealthEngine(Config(health_windows=1))
    store = _FakeStore()
    for _ in range(3):
        store.push(overlap=0.0, steps=0.0)     # idle: nothing completed
        eng.evaluate(store)
    assert eng.active_alerts() == {}


def test_health_burn_rules_fire_on_rate_over_interval():
    eng = health.HealthEngine(Config(health_windows=1,
                                     health_burn_rate=1.0))
    store = _FakeStore(interval_s=2.0)
    store.push(retransmit=5.0, shed=0.0, conn_resets=3.0)
    eng.evaluate(store)
    alerts = eng.active_alerts()
    assert alerts["retransmit_burn"]["rate_per_s"] == 2.5   # 5 / 2s
    assert alerts["conn_reset_burn"]["rate_per_s"] == 1.5
    assert "shed_burn" not in alerts                        # 0/s is clean


def test_health_ef_growth_needs_monotonic_rise():
    eng = health.HealthEngine(Config(health_windows=2))
    store = _FakeStore()
    for v in (1.0, 1.3, 1.6, 2.0):
        store.push(ef_norm=v)
        eng.evaluate(store)
    assert "ef_growth" in eng.active_alerts()
    # a sawtooth (EF draining normally) never fires
    eng2 = health.HealthEngine(Config(health_windows=2))
    store2 = _FakeStore()
    for v in (1.0, 1.8, 0.4, 1.9, 0.3, 2.0):
        store2.push(ef_norm=v)
        eng2.evaluate(store2)
    assert "ef_growth" not in eng2.active_alerts()


def test_health_slow_peer_rule_reads_phi_score():
    cfg = Config(health_windows=1)
    eng = health.HealthEngine(cfg)
    store = _FakeStore()
    store.push(slow_score=cfg.slowness_phi + 1.0)
    eng.evaluate(store)
    assert eng.active_alerts()["slow_peer"]["phi"] == cfg.slowness_phi + 1.0


def test_health_attrib_skew_findings_pure():
    hist = {
        0: {"series": {"attrib_sync": {"mean": 100.0}}},
        1: {"series": {"attrib_sync": {"mean": 5.0}}},
        2: {"series": {"attrib_sync": {"mean": 6.0}}},
    }
    fs = health.attrib_skew_findings(hist, ratio=4.0)
    assert len(fs) == 1
    assert fs[0]["rank"] == 0 and fs[0]["component"] == "sync"
    assert fs[0]["mean_ms"] == 100.0
    # below the absolute floor: a 4x ratio over noise is still noise
    tiny = {0: {"series": {"attrib_sync": {"mean": 2.0}}},
            1: {"series": {"attrib_sync": {"mean": 0.1}}}}
    assert health.attrib_skew_findings(tiny, ratio=4.0) == []
    # a single rank has no cluster to diverge from
    assert health.attrib_skew_findings({0: hist[0]}, ratio=4.0) == []


def test_health_attrib_skew_via_cluster_history_provider():
    health.configure(Config(health_windows=1))
    hist = {0: {"series": {"attrib_sync": {"mean": 80.0}}},
            1: {"series": {"attrib_sync": {"mean": 4.0}}},
            2: {"series": {"attrib_sync": {"mean": 5.0}}}}
    provider = lambda: hist  # noqa: E731
    health.set_cluster_history_provider(provider)
    try:
        store = _FakeStore()
        store.push(overlap=0.9, steps=1.0)
        health.evaluate(store)
        alerts = health.active_alerts()
        assert alerts["attrib_skew"]["worst"]["rank"] == 0
    finally:
        health.clear_cluster_history_provider(provider)
    # a successor's provider must survive a dying bus's clear
    other = lambda: {}  # noqa: E731
    health.set_cluster_history_provider(other)
    health.clear_cluster_history_provider(provider)   # stale clear: no-op
    assert health._cluster_history_provider is other
    health.clear_cluster_history_provider(other)


def test_health_disabled_by_knob():
    health.configure(Config(health_on=False, health_windows=1))
    store = _FakeStore()
    store.push(overlap=0.0, steps=1.0)
    health.evaluate(store)
    assert health.active_alerts() == {}


# -- /healthz over real HTTP ------------------------------------------------

def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, json.loads(r.read().decode())


def test_healthz_http_degrades_to_503_and_recovers():
    health.configure(Config(health_windows=1))
    eng = health.get_engine()
    store = _FakeStore()
    srv = obs_server.ensure_started(Config(obs_port=0))
    base = f"http://127.0.0.1:{srv.port}"
    status, doc = _get(base + "/healthz")
    assert status == 200 and doc["ok"] is True

    store.push(overlap=0.01, steps=1.0)
    eng.evaluate(store)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(base + "/healthz")
    assert ei.value.code == 503
    doc = json.loads(ei.value.read().decode())
    assert doc["degraded"] is True and "overlap_floor" in doc["alerts"]
    assert doc["alert_details"]["overlap_floor"]["overlap"] == 0.01

    store.push(overlap=0.95, steps=1.0)
    eng.evaluate(store)
    status, doc = _get(base + "/healthz")
    assert status == 200 and doc["ok"] is True and doc["alerts"] == []


def test_timeseries_http_route_serves_ring_and_disabled_doc():
    srv = obs_server.ensure_started(Config(obs_port=0))
    base = f"http://127.0.0.1:{srv.port}"
    status, doc = _get(base + "/timeseries")
    assert status == 200 and doc["len"] == 0 and "disabled" in doc
    store = timeseries.ensure_started(
        Config(ts_interval_s=60.0, ts_window=16))
    gauges.set("step.overlap_fraction", 0.8)
    store.sample_once()
    status, doc = _get(base + "/timeseries")
    assert status == 200 and doc["len"] == 1
    assert doc["points"][0]["overlap"] == 0.8
    assert doc["window"] == 16 and "keys" in doc


def test_bench_smoke_ts_sampler_gate_arithmetic():
    from tools import bench_smoke as bs
    floor = json.load(open(bs.FLOOR_PATH))
    assert 0 < floor["ts_sampler_overhead_floor"] <= 1
    good = {"samples": 9, "overhead_ratio": 0.99}
    assert bs._ts_ok(good, floor, 0.3)
    slow = dict(good, overhead_ratio=0.2)
    assert not bs._ts_ok(slow, floor, 0.3)
    empty = dict(good, samples=0)   # 1.0 ratio but sampled nothing
    assert not bs._ts_ok(empty, floor, 0.3)
    # the key is read via .get(): an older floor file without it still
    # gates at the 0.95 default instead of crashing the bench
    assert bs._ts_ok(good, {}, 0.3)
