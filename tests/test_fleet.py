"""Fleet reconciler tests (launcher/reconciler.py, ISSUE 18): the
autoscaler proposes, the reconciler DISPOSES.

What is pinned here:

- the directory's drain/victim semantics, busless and over the bus: a
  ``draining`` registration stays visible in :meth:`info` (in-flight
  pulls still need the address) but leaves every :meth:`hosts` routing
  view with a generation bump; heartbeat re-assertion does NOT bump
  again; victim proposals are filtered to live hosts and never bump the
  gen (routing only changes when a victim actually flips to DRAINING);
- the host core's drain latch: ``serve_ctl drain`` sets the latch,
  counts ``serve.drain_requested``, acks with the in-flight depth, and
  a retransmitted drain is idempotent;
- the ``kill:site=serve_host_start`` chaos predicate: kill-only,
  requires ``step=N``, counts serve-host STARTS (not answered pulls) —
  the deterministic crash-looper the flap ban is tested with;
- the reconciler's unit-testable core (fake processes, injected clock
  and backoff — ``step()`` never sleeps): converge-to-target without
  over-spawning cold starts, the max-host clamp, crash → full-jitter
  backoff as a not-before stamp → restart, the flap ban (directory ban
  + arc re-homed under a FRESH id, the banned id never reused),
  scale-down draining probation/highest-id victims, bus-proposed
  victims drained first and replaced, clean drain completion vs the
  deadline escalation to kill + force-unregister (which must NOT count
  as a crash);
- ``TierAutoscaler(dispose="drain")``: scale-down PROPOSES victims over
  the bus instead of retiring them (and the dispose value is
  validated);
- the observability surfaces: the bps_top fleet banner
  (``target=N actual=M``, DRAINING rows) fed by the same
  ``cluster_metrics()`` fields the ``--json`` consumer reads, the
  ``/debug/state`` reconciler section, and bps_doctor's
  reconciler-incident postmortem fold;
- the acceptance storm: a REAL 8-host fleet under one reconciler —
  pull storm, scale-up with real spawned ``serve_host`` processes,
  chaos kill-storm healed by supervised restart, a crash-looping host
  (``kill:site=serve_host_start``) banned without destabilizing the
  ring, scale-down through the graceful drain — ZERO failed reads,
  post-heal staleness bounded, finals exact.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from byteps_tpu.common.telemetry import counters
from byteps_tpu.fault import injector as inj
from byteps_tpu.fault.membership import (SERVE_RANK_BASE, MembershipView,
                                         _BusServer)
from byteps_tpu.launcher.reconciler import FleetReconciler
from byteps_tpu.server.kv_store import KVStore
from byteps_tpu.server.serve_autoscaler import TierAutoscaler
from byteps_tpu.server.serving_tier import (ServingHostCore, ServingTier,
                                            TierDirectory, inproc_host)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh():
    yield
    inj.disarm()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _store(keys, numel=8):
    s = KVStore()
    for i, k in enumerate(keys):
        s.init_key(k, np.full(numel, float(i), np.float32))
    return s


class _FakeProc:
    """A supervisable stand-in for a serve_host process."""

    def __init__(self):
        self.rc = None
        self.terminated = False

    def poll(self):
        return self.rc

    def exit(self, code):
        self.rc = code

    def terminate(self):
        self.terminated = True
        self.rc = -15

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        del timeout
        return self.rc


class _FixedRetry:
    """Deterministic backoff: attempt n -> n/2 seconds (no jitter, so
    the not-before stamps are exact against the injected clock)."""

    def backoff(self, attempt):
        return 0.5 * attempt


def _await(pred, deadline_s, what):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        if pred():
            return
        time.sleep(0.1)
    pytest.fail(f"timeout after {deadline_s}s waiting for {what}")


# -- directory drain/victim semantics ----------------------------------------


def test_fleet_directory_drain_mark_gen_and_victims_busless():
    d = TierDirectory(static_hosts={0: ("h", 1), 1: ("h", 2),
                                    2: ("h", 3)})
    gen0, hosts = d.hosts()
    assert sorted(hosts) == [0, 1, 2]
    # the drain mark: visible in info (in-flight pulls still need the
    # address), excluded from routing, gen bumped so consumers re-sync
    d.register(("h", 2), host_id=1, draining=True)
    gen1, hosts = d.hosts()
    assert gen1 > gen0 and sorted(hosts) == [0, 2]
    info = d.info()
    assert info["draining"] == [1] and 1 in info["hosts"]
    # heartbeat re-assertion must NOT bump again (a flapping gen would
    # force every consumer into a pointless re-sync per beat)
    d.register(("h", 2), host_id=1, draining=True)
    gen2, _ = d.hosts()
    assert gen2 == gen1
    # victim proposals: filtered to live hosts, NO gen bump — routing
    # only changes when a victim actually flips to DRAINING
    d.propose_victims([2, 9])
    assert d.info()["victims"] == [2]
    gen3, _ = d.hosts()
    assert gen3 == gen1
    # the final unregister clears both marks
    d.unregister(1)
    d.unregister(2)
    info = d.info()
    assert info["draining"] == [] and info["victims"] == []
    # un-drain via plain re-registration: back in the ring, gen bumped
    d.register(("h", 4), host_id=3, draining=True)
    d.register(("h", 4), host_id=3, draining=False)
    _, hosts = d.hosts()
    assert 3 in hosts and d.info()["draining"] == []


def test_fleet_bus_directory_drain_victims_target_and_top_parity():
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0,)), 5.0,
                     5.0)
    try:
        d = TierDirectory(bus=f"127.0.0.1:{port}", ttl_s=5.0)
        d.register(("127.0.0.1", 7000), host_id=0)
        d.register(("127.0.0.1", 7001), host_id=1)
        gen0, hosts = d.hosts(force=True)
        assert sorted(hosts) == [0, 1]
        d.register(("127.0.0.1", 7001), host_id=1, draining=True)
        gen1, hosts = d.hosts(force=True)
        assert gen1 > gen0 and sorted(hosts) == [0]
        d.set_target(4)
        d.propose_victims([0])
        # a SECOND consumer sees the same view through serve_dir
        d2 = TierDirectory(bus=f"127.0.0.1:{port}")
        d2.refresh(force=True)
        info = d2.info()
        assert info["draining"] == [1] and 1 in info["hosts"]
        assert info["target"] == 4 and info["victims"] == [0]
        # cluster_metrics carries the fleet fields — the SAME dict the
        # bps_top banner renders from and `--once --json` prints, so
        # the human and machine views cannot disagree
        from byteps_tpu.core.api import cluster_metrics
        cluster = cluster_metrics(bus=f"127.0.0.1:{port}")
        assert cluster["serve_target"] == 4
        assert cluster["serve_draining"] == [1]
        from tools import bps_top
        text = bps_top.render(cluster)
        assert "fleet: target=4 actual=1" in text
        assert "draining=[1]" in text
        assert "DRAINING" in text
        json.dumps(cluster, default=str)   # the --json path serializes
        # the final unregister handshake clears mark + proposal
        d.unregister(1)
        d.unregister(0)
        d2.refresh(force=True)
        info = d2.info()
        assert info["draining"] == [] and info["victims"] == []
    finally:
        bus.close()


# -- the host core's drain latch ---------------------------------------------


def test_fleet_host_core_drain_latch_idempotent_and_counted():
    core = ServingHostCore(host_id=5)
    c0 = counters.get("serve.drain_requested")
    r = core.control({"cmd": "drain"})
    assert r["draining"] is True and "inflight" in r
    assert core.draining.is_set()
    # a retransmitted drain finds the latch set — idempotent
    r2 = core.control({"cmd": "drain"})
    assert r2["draining"] is True
    assert counters.get("serve.drain_requested") == c0 + 2
    assert core.debug_state()["draining"] is True


# -- the crash-looper predicate ----------------------------------------------


@pytest.mark.chaos
def test_fleet_kill_site_serve_host_start_validation_and_counter():
    # kill-only predicate: a woven kind there would silently never fire
    with pytest.raises(ValueError, match="kill-only"):
        inj.parse_spec("delay:site=serve_host_start:ms=5")
    with pytest.raises(ValueError, match="step"):
        inj.parse_spec("kill:site=serve_host_start")
    rules = inj.parse_spec("kill:step=1:site=serve_host_start")
    assert rules[0].site == "serve_host_start"
    # the START counter matches, not pulls or pushes
    killed = []
    inj.arm("kill:step=2:site=serve_host_start", rank=0)
    orig = inj._exit
    inj._exit = lambda code: killed.append(code)
    try:
        inj.on_serve()        # answered pulls do not consume start kills
        inj.on_step()
        inj.on_serve_start()  # 1st start: step=2 not reached
        assert not killed
        inj.on_serve_start()  # the 2nd start
        assert killed
    finally:
        inj._exit = orig
        inj.disarm()


# -- the reconciler core (fake processes, injected clock) --------------------


def _mk_rec(directory, spawn_fn, clock, **kw):
    kw.setdefault("flap_limit", 3)
    kw.setdefault("flap_window_s", 30.0)
    kw.setdefault("drain_deadline_s", 5.0)
    kw.setdefault("ban_s", 30.0)
    kw.setdefault("max_hosts", 8)
    return FleetReconciler(directory=directory, spawn_fn=spawn_fn,
                           retry=_FixedRetry(), interval_s=0.05,
                           now=lambda: clock[0], **kw)


def test_fleet_reconciler_converges_without_overspawn_and_clamps():
    d = TierDirectory()
    procs = {}

    def spawn(hid, env):
        # the launch identity travels the child env, fault specs are
        # opt-in per host (never inherited), overrides apply
        assert env["BYTEPS_SERVE_HOST_ID"] == str(hid)
        assert "BYTEPS_FAULT_SPEC" not in env
        assert env["X_MARK"] == str(hid)
        p = _FakeProc()
        procs[hid] = p
        return p

    clock = [0.0]
    rec = _mk_rec(d, spawn, clock, max_hosts=4,
                  spawn_env=lambda hid: {"X_MARK": str(hid)})
    try:
        c0 = counters.get("reconcile.spawned")
        d.set_target(3)
        rec.step()
        assert sorted(procs) == [0, 1, 2]
        # none has registered yet (cold start): further passes must
        # count the in-flight spawns, not spawn more
        rec.step()
        rec.step()
        assert sorted(procs) == [0, 1, 2]
        for h in list(procs):
            d.register(("127.0.0.1", 1000 + h), host_id=h)   # HOST-UP
        view = rec.step()
        assert view["target"] == 3 and view["actual"] == 3
        assert counters.get("reconcile.spawned") == c0 + 3
        # the ceiling clamps a runaway target
        d.set_target(99)
        rec.step()
        assert sorted(procs) == [0, 1, 2, 3]
    finally:
        rec.close()


def test_fleet_reconciler_crash_backoff_restart_then_flap_ban():
    d = TierDirectory()
    procs = {}
    spawn_log = []

    def spawn(hid, env):
        del env
        p = _FakeProc()
        procs[hid] = p
        spawn_log.append(hid)
        d.register(("127.0.0.1", 1000 + hid), host_id=hid)
        return p

    clock = [0.0]
    rec = _mk_rec(d, spawn, clock)
    try:
        d.set_target(2)
        rec.step()
        assert sorted(procs) == [0, 1]
        # crash 1: restart is a NOT-BEFORE stamp (attempt 1 -> 0.5s),
        # never a sleep inside the loop
        procs[1].exit(1)
        rec.step()
        assert counters.get("reconcile.crashed") == 1
        assert rec.debug_state()["pending_restarts"] == {1: 0.5}
        clock[0] = 0.2
        rec.step()                      # before the not-before: no spawn
        assert spawn_log == [0, 1]
        clock[0] = 0.6
        rec.step()
        assert spawn_log == [0, 1, 1]   # restarted in place
        assert counters.get("reconcile.restarted") == 1
        # crash 2: backoff grows (attempt 2 -> 1.0s)
        procs[1].exit(1)
        rec.step()
        assert rec.debug_state()["pending_restarts"][1] == pytest.approx(
            clock[0] + 1.0)
        clock[0] += 1.1
        rec.step()
        assert spawn_log == [0, 1, 1, 1]
        # crash 3 inside the flap window: BANNED — directory ban, the
        # id never reused, the arc re-homed under a FRESH id
        procs[1].exit(1)
        rec.step()
        assert counters.get("reconcile.banned") == 1
        assert rec.debug_state()["banned"] == [1]
        assert 1 not in d.info()["hosts"]
        view = rec.step()               # convergence spawns replacement
        assert spawn_log == [0, 1, 1, 1, 2]
        assert 1 not in view["supervised"] and 2 in view["supervised"]
    finally:
        rec.close()


def test_fleet_reconciler_scale_down_drains_then_escalates():
    d = TierDirectory()
    procs = {}
    cores = {}

    def spawn(hid, env):
        del env
        p = _FakeProc()
        procs[hid] = p
        # an in-process core stands in for the host's ctl endpoint, so
        # the drain RPC lands on a real drain latch
        cores[hid] = inproc_host(ServingHostCore(host_id=hid))
        d.register(("127.0.0.1", 1000 + hid), host_id=hid)
        return p

    clock = [0.0]
    rec = _mk_rec(d, spawn, clock, drain_deadline_s=5.0)
    try:
        d.set_target(3)
        rec.step()
        assert sorted(procs) == [0, 1, 2]
        # scale-down: the highest id (youngest arc) drains first
        d.set_target(2)
        view = rec.step()
        assert view["draining"] == [2]
        assert cores[2].draining.is_set()
        assert counters.get("reconcile.drain_started") == 1
        # clean completion: exit 0 + final unregister (what the real
        # serve_host state machine does) completes the drain
        procs[2].exit(0)
        d.unregister(2)
        rec.step()
        assert counters.get("reconcile.drained") == 1
        assert counters.get("reconcile.drain_escalated") == 0
        # a WEDGED drain: the latch is set but the host never exits —
        # the deadline escalates to kill + force-unregister
        d.set_target(1)
        rec.step()
        assert cores[1].draining.is_set()
        clock[0] += 5.1
        rec.step()
        assert counters.get("reconcile.drain_escalated") == 1
        assert procs[1].terminated
        assert 1 not in d.info()["hosts"]
        # the escalated corpse reaps WITHOUT counting as a crash (no
        # restart of a host we just killed on purpose)
        rec.step()
        assert counters.get("reconcile.crashed") == 0
        assert rec.debug_state()["draining"] == []
    finally:
        rec.close()


def test_fleet_reconciler_bus_proposed_victims_drain_first():
    d = TierDirectory()
    procs = {}
    cores = {}

    def spawn(hid, env):
        del env
        p = _FakeProc()
        procs[hid] = p
        cores[hid] = inproc_host(ServingHostCore(host_id=hid))
        d.register(("127.0.0.1", 1000 + hid), host_id=hid)
        return p

    clock = [0.0]
    rec = _mk_rec(d, spawn, clock)
    try:
        d.set_target(2)
        rec.step()
        assert sorted(procs) == [0, 1]
        # the autoscaler names host 0 (NOT the default highest-id
        # choice); the reconciler drains it and — target unchanged —
        # spawns its replacement in the same pass
        d.propose_victims([0])
        view = rec.step()
        assert 0 in view["draining"]
        assert cores[0].draining.is_set()
        assert not cores[1].draining.is_set()
        assert 2 in view["supervised"]     # replacement under a fresh id
    finally:
        rec.close()


def test_fleet_publisher_and_router_reship_restarted_host():
    """A host restarted in place (the reconciler's crash-restart path)
    re-registers under the SAME id at a NEW address with EMPTY state.
    Publisher and router must both treat it as a new incarnation: the
    publisher re-ships the full owned slice (its acked map described
    the dead process), the router drops its delta base and cached
    connection.  replicas=1 and a strict client so neither failover nor
    stale-degradation can mask a miss."""
    keys = [f"r{i}" for i in range(6)]
    d = TierDirectory(static_hosts={0: ("127.0.0.1", 1),
                                    1: ("127.0.0.1", 2)})
    for i in range(2):
        inproc_host(ServingHostCore(host_id=i))
    store = _store(keys)
    tier = ServingTier(store, directory=d, replicas=1,
                       cut_interval_s=None)
    try:
        tier.cut()
        client = tier.client(max_staleness_s=0.0, stale_on_error=False)
        assert set(client.pull()) == set(keys)
        # host 1 crashes and restarts EMPTY: same id, new address
        new_core = inproc_host(ServingHostCore(host_id=1))
        d.register(("127.0.0.1", 3), host_id=1)
        store.push_delta(keys[0], np.ones(8, np.float32))
        tier.cut()
        # the full owned slice landed on the new incarnation, not just
        # the one changed key
        assert new_core.debug_state()["snapshot_id"] is not None
        assert new_core.debug_state()["keys"] >= 1
        # the router follows within one sync interval (0.25s): its next
        # sync sees the gen bump, drops the stale endpoint + delta base
        time.sleep(0.3)
        vals = client.pull()
        for k in keys:
            np.testing.assert_array_equal(vals[k], store.pull(k))
        client.close()
    finally:
        tier.close()


# -- the autoscaler's dispose="drain" mode ------------------------------------


def test_fleet_autoscaler_dispose_drain_proposes_instead_of_retiring():
    with pytest.raises(ValueError, match="dispose"):
        TierAutoscaler(object(), dispose="nuke")
    keys = [f"a{i}" for i in range(6)]
    d = TierDirectory(static_hosts={i: ("127.0.0.1", i + 1)
                                    for i in range(3)})
    for i in range(3):
        inproc_host(ServingHostCore(host_id=i))
    store = _store(keys)
    tier = ServingTier(store, directory=d, replicas=2,
                       cut_interval_s=None)
    try:
        tier.cut()
        asc = TierAutoscaler(tier, min_hosts=1, max_hosts=4,
                             cooldown_s=0.0, low_pulls_per_s=50.0,
                             dispose="drain")
        first = asc.step(force=True)   # warming: structural zero rates
        assert first is not None and first.action == "hold"
        decision = asc.step(force=True)
        assert decision is not None and decision.action == "down"
        assert decision.victims
        # drain mode: victims PROPOSED over the bus for the reconciler,
        # NOT retired — every host is still registered and placed
        assert len(tier.ring.hosts()) == 3
        info = tier.directory.info()
        assert info["victims"] == decision.victims
        assert sorted(info["hosts"]) == [0, 1, 2]
        assert tier.directory.target() == decision.target
    finally:
        tier.close()


# -- observability surfaces ---------------------------------------------------


def test_fleet_obs_debug_state_reconciler_section():
    rec = _mk_rec(TierDirectory(), lambda h, e: _FakeProc(), [0.0])
    try:
        from byteps_tpu.common import obs_server
        doc = obs_server.debug_state()
        sections = doc["reconciler"]
        assert sections and sections[0]["kind"] == "reconciler"
        assert "flap_limit" in sections[0]
        assert sections[0]["supervised"] == []
        json.dumps(doc, default=str)
    finally:
        rec.close()


def test_fleet_doctor_postmortem_reconciler_incidents(tmp_path):
    events = [
        {"t": 1.0, "mono": 1.0, "kind": "reconcile.spawn", "host": 4},
        {"t": 2.0, "mono": 2.0, "kind": "reconcile.crash", "host": 4,
         "code": 1},
        {"t": 2.1, "mono": 2.1, "kind": "reconcile.restart", "host": 4,
         "flaps": 1},
        {"t": 3.0, "mono": 3.0, "kind": "reconcile.banned", "host": 4,
         "flap_limit": 3, "ban_s": 30.0},
        {"t": 4.0, "mono": 4.0, "kind": "reconcile.drain", "host": 2,
         "deadline_s": 5.0},
        {"t": 9.5, "mono": 9.5, "kind": "reconcile.drain_escalated",
         "host": 2},
    ]
    path = tmp_path / "bps_flight_1_rank0_100_exit_6.json"
    path.write_text(json.dumps({"reason": "exit", "wall_time": 10.0,
                                "pid": 100, "rank": 0, "capacity": 64,
                                "events": events}))
    from tools.bps_doctor import diagnose_postmortem, render_markdown
    report = diagnose_postmortem(str(tmp_path))
    rec = report["reconciler"]
    assert [r["kind"] for r in rec] == [
        "spawn", "crash", "restart", "banned", "drain",
        "drain_escalated"]
    assert rec[3]["host"] == 4 and rec[3]["detail"]["flap_limit"] == 3
    md = render_markdown(report)
    assert "Reconciler incidents" in md
    assert "BANNED (crash loop): host(s) [4]" in md
    assert "ESCALATED" in md
    json.dumps(report)   # the --json path must serialize


def test_fleet_bpslaunch_fleet_flag_requires_bus(monkeypatch, capsys):
    monkeypatch.delenv("BYTEPS_SERVE_TIER_BUS", raising=False)
    from byteps_tpu.launcher.launch import main as launch_main
    assert launch_main(["--fleet"]) == 2
    assert "no bus" in capsys.readouterr().err


# -- the real drain protocol (one host, end to end) ---------------------------


def _spawn_host_proc(i, bus_port, ttl=3.0, spec=""):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               BYTEPS_SERVE_TIER_BUS=f"127.0.0.1:{bus_port}",
               BYTEPS_SERVE_HOST_ID=str(i),
               BYTEPS_SERVE_TIER_TTL=str(ttl),
               BYTEPS_LOG_LEVEL="ERROR",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    if spec:
        env["BYTEPS_FAULT_SPEC"] = spec
    else:
        env.pop("BYTEPS_FAULT_SPEC", None)
    return subprocess.Popen(
        [sys.executable, "-m", "byteps_tpu.server.serve_host"],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)


@pytest.mark.chaos
def test_fleet_single_host_graceful_drain_protocol():
    """The drain handshake against a REAL serve_host process:
    ``serve_ctl drain`` acks with the in-flight depth, the DRAINING
    mark lands on the bus (routing excludes the host while its address
    stays visible), the final unregister clears it, the process prints
    ``HOST-DRAINED`` and exits 0."""
    bus_port = _free_port()
    bus = _BusServer(("127.0.0.1", bus_port), MembershipView(0, (0,)),
                     5.0, 5.0)
    proc = None
    try:
        proc = _spawn_host_proc(0, bus_port, ttl=2.0)
        line = proc.stdout.readline()
        assert "HOST-UP" in line, line
        d = TierDirectory(bus=f"127.0.0.1:{bus_port}")
        _await(lambda: 0 in d.hosts(force=True)[1], 30,
               "host 0 registered")
        gen0, addrs = d.hosts(force=True)
        from byteps_tpu.comm.transport import TcpEndpoint
        ctl = TcpEndpoint(addrs[0], peer=SERVE_RANK_BASE + 0,
                          send_deadline_s=2.0, keepalive_s=0.0)
        reply = ctl.serve_ctl(cmd="drain")
        ctl.close(drain=False)
        assert reply.get("draining") is True and "inflight" in reply
        # the DRAINING mark: routing excludes, info keeps the address
        def _marked():
            d.refresh(force=True)
            info = d.info()
            return (0 in info["draining"] and 0 in info["hosts"]) \
                or 0 not in info["hosts"]   # already finished draining
        _await(_marked, 15, "the DRAINING mark on the bus")
        # in-flight (none) finish; final unregister + clean exit
        assert proc.wait(timeout=30) == 0
        rest = proc.stdout.read()
        assert "HOST-DRAINED 0" in rest, rest
        def _gone():
            d.refresh(force=True)
            info = d.info()
            return 0 not in info["hosts"] and info["draining"] == []
        _await(_gone, 15, "the final unregister handshake")
    finally:
        if proc is not None and proc.poll() is None:
            proc.terminate()
            proc.wait(timeout=15)
        bus.close()


# -- THE acceptance storm (ISSUE 18) ------------------------------------------


@pytest.mark.chaos
def test_fleet_storm_8hosts_scaleup_killstorm_crashloop_ban_drain():
    """THE acceptance pin (ISSUE 18): one reconciler supervises a REAL
    fleet through a full chaos storm —

    - a pull storm runs against the initial 4 hosts while the target is
      raised to 8 (the ``serve_scale`` verb, the same channel the
      autoscaler posts on): the reconciler spawns real ``serve_host``
      processes to converge;
    - two of the originals die mid-storm (``kill:site=serve_host`` at
      their Nth answered pull): supervised restart heals them in place;
    - the host id the scale-up allocates to slot 6 is a deliberate
      crash-looper (``kill:step=1:site=serve_host_start`` armed through
      ``spawn_env`` on EVERY spawn of that id — it dies after
      registering, before HOST-UP): crash-loop backoff absorbs the
      flaps, the flap ban evicts the id, and its arc re-homes under a
      fresh id;
    - the target drops back to 3: the spares retire through the
      graceful drain, no deadline escalation;

    and the tier keeps its promises: ZERO failed reads end to end,
    post-heal staleness bounded, finals exact."""
    nkeys = 6
    keys = [f"f{i}" for i in range(nkeys)]
    bound = 0.25
    bus_port = _free_port()
    bus = _BusServer(("127.0.0.1", bus_port), MembershipView(0, (0,)),
                     5.0, 5.0)
    CRASH = 6                    # the id slot the scale-up will allocate
    KILL_AT = {1: "kill:step=40:site=serve_host",
               3: "kill:step=70:site=serve_host"}
    armed = set()

    def host_env(hid):
        env = {"JAX_PLATFORMS": "cpu", "BYTEPS_LOG_LEVEL": "ERROR"}
        if hid == CRASH:
            # EVERY spawn of this id dies at startup — the respawns die
            # too, which is exactly what the flap ban must absorb
            env["BYTEPS_FAULT_SPEC"] = "kill:step=1:site=serve_host_start"
        elif hid in KILL_AT and hid not in armed:
            # the kill-storm victims: armed only on their FIRST spawn,
            # so the supervised restart comes back clean
            armed.add(hid)
            env["BYTEPS_FAULT_SPEC"] = KILL_AT[hid]
        return env

    directory = TierDirectory(bus=f"127.0.0.1:{bus_port}", ttl_s=3.0)
    rec = FleetReconciler(directory=directory, interval_s=0.2,
                          flap_limit=3, flap_window_s=60.0,
                          drain_deadline_s=12.0, ban_s=60.0,
                          max_hosts=10, spawn_env=host_env,
                          conn_kw={"send_deadline_s": 1.0,
                                   "keepalive_s": 1.0})
    stop = threading.Event()
    rec_thread = threading.Thread(target=rec.run, args=(stop,),
                                  daemon=True)
    tier = None
    consumer = TierDirectory(bus=f"127.0.0.1:{bus_port}")

    def _live():
        return set(consumer.hosts(force=True)[1])

    try:
        directory.set_target(4)
        rec_thread.start()
        _await(lambda: len(_live()) >= 4, 90, "the initial 4-host fleet")

        store = KVStore()
        rng = np.random.RandomState(0)
        for k in keys:
            store.init_key(k, rng.randn(64).astype(np.float32))
        # fail_streak high: the RECONCILER owns healing here — the
        # publisher retiring+banning a killed id would fight the
        # supervised restart of that same id
        tier = ServingTier(store, bus=f"127.0.0.1:{bus_port}",
                           replicas=2, cut_interval_s=None,
                           ship_deadline_s=0.75, fail_streak=99,
                           conn_kw={"send_deadline_s": 0.75,
                                    "keepalive_s": 1.0})
        tier.cut()

        pub_lock = threading.Lock()
        pub_times = {}          # version of keys[0] -> monotonic

        def pusher():
            while not stop.is_set():
                store.push_delta(keys[0], np.ones(64, np.float32))
                for k in keys[1:]:
                    store.push_delta(k, np.ones(64, np.float32) * 1e-3)
                snap = tier.cut()
                if snap is not None:
                    with pub_lock:
                        pub_times[snap.versions[keys[0]]] = \
                            time.monotonic()
                time.sleep(0.12)

        samples = []            # (t, seen version of keys[0])
        errors = []

        def puller(idx):
            client = tier.client(max_staleness_s=bound,
                                 pull_deadline_s=0.75)
            try:
                while not stop.is_set():
                    try:
                        client.pull()
                    except Exception as e:  # noqa: BLE001 — THE assertion
                        errors.append((idx, repr(e)))
                        continue
                    with pub_lock:
                        samples.append((time.monotonic(),
                                        client.version(keys[0])))
                    time.sleep(0.01)
            finally:
                client.close()

        push_t = threading.Thread(target=pusher, daemon=True)
        pull_ts = [threading.Thread(target=puller, args=(i,),
                                    daemon=True) for i in range(4)]
        push_t.start()
        for t in pull_ts:
            t.start()

        time.sleep(1.5)                     # healthy storm
        # the storm drives the target up (serve_scale — the channel the
        # autoscaler posts on); the kill-storm victims' pull counters
        # are climbing toward their kill steps at the same time
        directory.set_target(8)

        # heal point: 8 live non-draining hosts, the crash-looper
        # BANNED (arc re-homed under a fresh id), both kill victims
        # dead AND restarted in place — crashed >= 5 (2 kills + the
        # looper's 3 flaps) pins that the kills actually fired, and
        # live >= 8 with the looper banned means both victims are back
        def _healed():
            return (len(_live()) >= 8
                    and CRASH in rec.debug_state()["banned"]
                    and counters.get("reconcile.crashed") >= 5)
        _await(_healed, 90, "scale-up + kill-storm heal + flap ban")
        assert CRASH not in _live()
        assert counters.get("reconcile.banned") == 1
        assert counters.get("reconcile.restarted") >= 2
        t_heal = time.monotonic()
        time.sleep(3.0)                     # post-heal steady state
        t_down = time.monotonic()

        # scale-down: the spares retire through the graceful drain
        directory.set_target(3)

        def _drained_down():
            state = rec.debug_state()
            return (len(_live()) == 3 and not state["draining"]
                    and not state["pending_restarts"])
        _await(_drained_down, 90, "graceful scale-down to 3")
        assert counters.get("reconcile.drained") >= 5
        assert counters.get("reconcile.drain_escalated") == 0
        time.sleep(1.0)                     # steady at the new size
        stop.set()
        push_t.join(timeout=20)
        for t in pull_ts:
            t.join(timeout=20)

        # 1) ZERO failed reads through spawn storm + kills + ban + drain
        assert not errors, errors[:5]
        # 2) bounded staleness after the heal: every steady-state sample
        # between the heal and the scale-down saw at least the newest
        # version published (bound + slack) before it — the drain churn
        # itself is covered by the zero-failed-reads promise above
        slack = 0.8
        with pub_lock:
            history = sorted(pub_times.items())
        checked = 0
        for t_s, seen in samples:
            if t_s < t_heal or t_s > t_down:
                continue
            floor_v = 0
            for v, t_pub in history:
                if t_pub <= t_s - bound - slack:
                    floor_v = max(floor_v, v)
            assert seen >= floor_v, (t_s, seen, floor_v)
            checked += 1
        assert checked > 10, "no post-heal staleness samples"
        # 3) finals exact: a fresh blocking pull equals the store.  The
        # client is COLD (no cache to degrade to), so give the ring a
        # short settle window after the drain churn before failing.
        tier.cut()
        fc = tier.client(max_staleness_s=0.0, pull_deadline_s=2.0)
        settle = time.monotonic() + 15
        while True:
            try:
                final = fc.pull()
                break
            except Exception:  # noqa: BLE001 — transient post-churn
                if time.monotonic() > settle:
                    raise
                time.sleep(0.25)
        fc.close()
        for k in keys:
            np.testing.assert_array_equal(final[k], store.pull(k))
        # 4) the fleet view agrees end to end
        state = rec.debug_state()
        assert state["banned"] == [CRASH]
        assert len(state["supervised"]) == len(_live())
    finally:
        stop.set()
        if tier is not None:
            tier.close()
        rec.close(kill_hosts=True)
        rec_thread.join(timeout=15)
        bus.close()
