"""Observability plane (ISSUE 6): the unified metrics registry +
Prometheus exposition, the per-rank HTTP endpoint, cross-rank
aggregation over the membership bus, the flight recorder, and per-step
StepStats.

The acceptance pin lives at the end: a REAL 3-process chaos run where
every rank serves ``/metrics``/``/healthz``, ``cluster_metrics()``
answers over the bus, and the chaos-killed worker leaves a
flight-recorder dump whose tail holds the events leading into the kill.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import byteps_tpu.core.api as api
from byteps_tpu.common import flight_recorder as flight
from byteps_tpu.common import metrics as metrics_mod
from byteps_tpu.common import obs_server
from byteps_tpu.common.config import Config, set_config
from byteps_tpu.common.metrics import MetricsRegistry, pow2_bucket
from byteps_tpu.common.telemetry import (SpeedMonitor, StepStatsTracker,
                                         counters, gauges, histograms)
from byteps_tpu.fault import membership as mm
from byteps_tpu.fault.membership import (ElasticMembership, MembershipView,
                                         _BusServer, _recv_obj, _send_obj)

from .conftest import free_port as _free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "elastic_worker.py")


@pytest.fixture(autouse=True)
def _fresh_epoch():
    mm._reset_epoch_for_tests()
    yield
    if api.initialized():
        api.shutdown()
    api._declared_order = []
    mm._reset_epoch_for_tests()


def _req(port, msg, timeout=20.0):
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.settimeout(timeout)
    _send_obj(s, msg)
    reply = _recv_obj(s)
    s.close()
    return reply


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read().decode()


# -- the registry -----------------------------------------------------------


def test_registry_labels_and_consistent_snapshot():
    reg = MetricsRegistry()
    reg.inc("integrity.crc_reject")
    reg.inc("wire_bytes", 100)
    reg.inc("wire_bytes", 40, {"key": "grad.0"})
    reg.set("engine.sched_pending", 7)
    reg.observe("engine.unit_sync_ms", 5)
    snap = reg.snapshot()
    # unlabeled series keep their bare established names; the labeled
    # breakdown exists BESIDE them, never instead of them
    assert snap["counters"]["integrity.crc_reject"] == 1
    assert snap["counters"]["wire_bytes"] == 100
    assert snap["counters"]['wire_bytes{key="grad.0"}'] == 40
    assert snap["gauges"]["engine.sched_pending"] == 7.0
    assert snap["histograms"]["engine.unit_sync_ms"] == {8: 1}
    assert reg.get_counter("wire_bytes") == 100
    assert reg.get_counter("wire_bytes", {"key": "grad.0"}) == 40
    # per-kind reset (the legacy facade contract)
    reg.reset("counters")
    assert reg.snapshot()["counters"] == {}
    assert reg.snapshot()["gauges"] != {}


def test_legacy_singletons_share_one_registry():
    counters.inc("membership.shrink")
    gauges.set("engine.bytes_in_flight", 3.0)
    histograms.observe("engine.dispatch_unit_width", 4)
    snap = metrics_mod.registry.snapshot()
    assert snap["counters"]["membership.shrink"] == 1
    assert snap["gauges"]["engine.bytes_in_flight"] == 3.0
    assert snap["histograms"]["engine.dispatch_unit_width"] == {4: 1}
    # facade reads go through the same store
    assert counters.get("membership.shrink") == 1
    assert histograms.count("engine.dispatch_unit_width") == 1


def test_histogram_pow2_bucket_edges():
    # the satellite pins: 0, negatives, exact powers of two
    assert pow2_bucket(0) == 0
    assert pow2_bucket(-3) == 0
    assert pow2_bucket(0.5) == 1
    assert pow2_bucket(1) == 1
    assert pow2_bucket(8) == 8          # exact power lands in its own bucket
    assert pow2_bucket(8.0001) == 16
    assert pow2_bucket(9) == 16
    # non-finite values must neither hang the doubling loop (+inf) nor
    # silently land in bucket 1 (NaN)
    assert pow2_bucket(float("inf")) == 1 << 62
    assert pow2_bucket(float("nan")) == 0
    assert pow2_bucket(float("-inf")) == 0
    h = metrics_mod.Histograms()
    for v in (0, -1, 1, 2, 8, 9):
        h.observe("x", v)
    assert h.snapshot()["x"] == {0: 2, 1: 1, 2: 1, 8: 1, 16: 1}


def test_prometheus_rendering_and_escaping():
    reg = MetricsRegistry()
    reg.inc("integrity.crc_reject", 3)
    reg.inc("wire_bytes", 7, {"key": 'a"b\\c\nd'})
    reg.set("engine.running", 1)
    reg.observe("engine.unit_sync_ms", 3)
    reg.observe("engine.unit_sync_ms", 5)
    out = reg.render_prometheus()
    assert "# TYPE byteps_integrity_crc_reject_total counter" in out
    assert "byteps_integrity_crc_reject_total 3" in out
    # label-value escaping: backslash, double quote, newline
    assert 'key="a\\"b\\\\c\\nd"' in out
    # histogram: cumulative le buckets + _sum/_count
    assert 'byteps_engine_unit_sync_ms_bucket{le="4"} 1' in out
    assert 'byteps_engine_unit_sync_ms_bucket{le="8"} 2' in out
    assert 'byteps_engine_unit_sync_ms_bucket{le="+Inf"} 2' in out
    assert "byteps_engine_unit_sync_ms_sum 8" in out
    assert "byteps_engine_unit_sync_ms_count 2" in out
    # every sample line is "<name>[{labels}] <value>"
    for line in out.strip().splitlines():
        if line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        assert name and not name.startswith(" "), line
        float(value)  # parses


# -- SpeedMonitor (satellite 6) ---------------------------------------------


def test_speedmonitor_rollover_and_just_rolled_guard():
    t = [0.0]
    sm = SpeedMonitor(window_sec=10.0, clock=lambda: t[0])
    sm.record(10 * 2**20)
    t[0] = 5.0
    # matured partial window: live rate (10 MB over 5 s)
    assert sm.speed()[1] == pytest.approx(2.0)
    t[0] = 10.0
    sm.record(0)                         # rolls: 10 MB / 10 s
    assert sm.total_windows() == 1
    t[0] = 10.5
    # the satellite's pin: a JUST-rolled window (0.5 s of partial data)
    # must report the closed window's 1 MB/s, not a near-zero partial
    assert sm.speed()[1] == pytest.approx(1.0)


def test_speedmonitor_rolls_on_read_when_record_pauses():
    t = [0.0]
    sm = SpeedMonitor(window_sec=10.0, clock=lambda: t[0])
    sm.record(10 * 2**20)
    t[0] = 10.0
    sm.record(0)                         # window 1: 1 MB/s
    t[0] = 40.0
    # record() went quiet for 30 s: speed() must not freeze on the old
    # 1 MB/s figure — the stale partial rolls on read and reports idle
    assert sm.speed()[1] == pytest.approx(0.0)
    assert sm.total_windows() == 2


# -- StepStats --------------------------------------------------------------


def test_step_stats_tracker_boundaries_and_surfaces():
    rec = flight.FlightRecorder(capacity=64)
    tr = StepStatsTracker(recorder=rec)
    tr.on_push("a", 100)
    tr.on_push("b", 50)                  # same step (b's count == step)
    tr.add_stall(5.0)
    assert tr.current_step == 1
    tr.on_push("a", 100)                 # a advances -> step 1 finalizes
    last = tr.last()
    assert last.step == 1
    assert last.bytes_pushed == 150
    assert last.pushes == 2
    assert last.sync_stall_ms == pytest.approx(5.0)
    assert 0.0 <= last.overlap_fraction <= 1.0
    assert last.retransmits == 0
    # surfaced through the gauges (the /metrics route) ...
    assert gauges.get("step.bytes_pushed") == 150
    assert counters.get("step.completed") == 1
    # ... and the flight recorder
    kinds = [e["kind"] for e in rec.snapshot()]
    assert "step_stats" in kinds
    # flush() finalizes the in-progress tail step
    tr.add_stall(1.0)
    done = tr.flush()
    assert done is not None and done.step == 2 and done.bytes_pushed == 100
    assert tr.summary()["steps"] == 2


# -- flight recorder --------------------------------------------------------


def test_flight_recorder_ring_bound_and_dump(tmp_path):
    rec = flight.FlightRecorder(capacity=32)
    for i in range(100):
        rec.record("ev", i=i)
    assert len(rec) == 32
    path = rec.dump("unit_test", path=str(tmp_path / "dump.json"))
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "unit_test"
    assert len(doc["events"]) == 32
    # oldest -> newest; the TAIL is the most recent event
    assert doc["events"][0]["i"] == 68
    assert doc["events"][-1]["i"] == 99
    assert doc["events"][-1]["kind"] == "ev"
    # disabled recorder records and dumps nothing
    rec.configure(enabled=False)
    rec.record("ev", i=200)
    assert len(rec) == 32
    assert rec.dump("nope") is None


def test_flight_exit_dump_fires_once_and_only_when_asked(tmp_path):
    set_config(Config(flight_dir=str(tmp_path)))
    flight.record("something")
    assert flight.maybe_exit_dump() is None          # default: off
    set_config(Config(flight_dir=str(tmp_path), flight_dump_on_exit=True))
    assert flight.maybe_exit_dump() is not None
    assert flight.maybe_exit_dump() is None          # once per process
    assert len(list(tmp_path.glob("bps_flight_*_exit_*.json"))) == 1


def test_flight_dump_on_quarantine(tmp_path):
    from byteps_tpu.server.engine import ServerEngine
    set_config(Config(nonfinite_policy="skip", flight_dir=str(tmp_path)))
    srv = ServerEngine(num_threads=1)
    try:
        srv.push("k", np.array([np.nan, 1.0], np.float32), 0, 2)
    finally:
        srv.shutdown()
    dumps = list(tmp_path.glob("bps_flight_*_quarantine_*.json"))
    assert len(dumps) == 1
    doc = json.loads(dumps[0].read_text())
    kinds = [e["kind"] for e in doc["events"]]
    assert "quarantine" in kinds
    assert "integrity.nonfinite" in kinds


def test_flight_dump_on_chaos_kill_inproc(tmp_path, monkeypatch):
    from byteps_tpu.fault import injector
    set_config(Config(flight_dir=str(tmp_path)))
    exits = []
    monkeypatch.setattr(injector, "_exit", lambda code: exits.append(code))
    flight.record("engine.init", ranks=8)
    injector.arm("kill:step=2", seed=0, rank=0)
    try:
        injector.on_step()
        injector.on_step()
    finally:
        injector.disarm()
    assert exits, "kill rule never fired"
    dumps = list(tmp_path.glob("bps_flight_*_chaos_kill_*.json"))
    assert len(dumps) == 1
    events = json.loads(dumps[0].read_text())["events"]
    # the tail holds the events leading into the kill, kill last
    assert events[-1]["kind"] == "fault.kill"
    assert events[-1]["step"] == 2
    assert "engine.init" in [e["kind"] for e in events]


# -- the HTTP endpoint ------------------------------------------------------


def test_obs_endpoints_serve_metrics_healthz_debug_state(tmp_path):
    from byteps_tpu.server.engine import ServerEngine
    from byteps_tpu.server.kv_store import KVStore
    api.init(Config(obs_port=0))
    srv = obs_server.get_server()
    assert srv is not None and srv.port > 0
    eng = api._require()
    x = np.ones(2048, np.float32)
    for _ in range(3):
        eng.push_pull_local(x, "obs.g")
    # satellite: integrity.* / membership.* / wire_bytes reach /metrics
    counters.inc("integrity.crc_reject")
    counters.inc("membership.stale_pushes_dropped")
    kv = KVStore()
    kv.init_key("w", np.zeros(4, np.float32))
    kv.push_delta("w", np.ones(4, np.float32), worker_id=1, seq=3)
    # one real compressed wire push: _account_wire moves the process-wide
    # wire_bytes counter the /metrics route must surface
    import jax.numpy as jnp

    from byteps_tpu.compression import registry as creg
    kv.register_compression("w", {"compressor": "onebit"}, 4)
    comp = creg.create({"compressor": "onebit"}, 4, np.float32)
    payload, _ = comp.compress(jnp.ones(4), comp.init_state())
    wire = comp.wire_encode(payload)
    kv.push_delta_wire("w", wire, worker_id=1, seq=4)
    se = ServerEngine(num_threads=1)
    try:
        base = f"http://127.0.0.1:{srv.port}"

        status, ctype, body = _get(base + "/metrics")
        assert status == 200 and ctype.startswith("text/plain")
        assert "byteps_integrity_crc_reject_total 1" in body
        assert "byteps_membership_stale_pushes_dropped_total 1" in body
        assert f"byteps_wire_bytes_total {len(wire)}" in body
        assert "byteps_engine_running 1" in body
        assert "byteps_step_bytes_pushed" in body
        for line in body.strip().splitlines():     # valid exposition
            if not line.startswith("#"):
                float(line.rpartition(" ")[2])

        status, ctype, body = _get(base + "/healthz")
        doc = json.loads(body)
        assert doc["ok"] is True
        assert doc["membership_epoch"] == mm.current_epoch() == 0
        assert doc["engine_running"] is True
        assert doc["last_heartbeat_age_s"] is None   # no monitor armed
        assert "pushpull_mbps" in doc and doc["step"] == 3

        status, ctype, body = _get(base + "/debug/state")
        doc = json.loads(body)
        assert doc["engine"]["running"] is True
        assert doc["engine"]["sched_pending"] == 0
        assert doc["engine"]["bytes_in_flight"] == 0
        assert "planner" in doc["engine"]
        assert doc["engine"]["step"]["bytes_pushed"] == 8192
        kv_states = [c for c in doc["kv_stores"]]
        # dedup_floors is CLAMPED (ISSUE 9 satellite): worst-N entries
        # plus the true count, so the shape carries both fields
        assert any(c["dedup_floors"] == {"w:1": 4}
                   and c["dedup_floor_count"] == 1 for c in kv_states)
        assert "serving_planes" in doc
        assert any(c["kind"] == "server_engine"
                   for c in doc["server_engines"])

        with pytest.raises(urllib.error.HTTPError):
            _get(base + "/nope")
    finally:
        se.shutdown()
    # /healthz keeps answering after the engine is gone (the endpoint
    # outlives suspend/resume) and reports the engine stopped
    api.shutdown()
    _, _, body = _get(f"http://127.0.0.1:{srv.port}/healthz")
    assert json.loads(body)["engine_running"] is False


# -- cross-rank aggregation -------------------------------------------------


def test_bus_metrics_verbs_and_cluster_metrics():
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0, 1)),
                     5.0, 5.0)
    try:
        r = _req(port, {"op": "metrics_put", "rank": 0,
                        "metrics": {"x": 1}})
        assert r["ok"] and r["world"] == [0, 1]
        _req(port, {"op": "metrics_put", "rank": 1, "metrics": {"x": 2}})
        out = api.cluster_metrics(bus=f"127.0.0.1:{port}")
        assert out["epoch"] == 0 and out["world"] == [0, 1]
        assert set(out["ranks"]) == {0, 1}
        assert out["ranks"][0]["metrics"] == {"x": 1}
        assert out["ranks"][1]["age_s"] >= 0.0
    finally:
        bus.close()


def test_sync_piggyback_feeds_metrics_cache():
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0,)), 5.0, 5.0)
    try:
        r = _req(port, {"op": "sync", "rank": 0, "epoch": 0, "step": 1,
                        "payload": None, "metrics": {"speed_mbps": 9.5}})
        assert r["ok"]
        out = api.cluster_metrics(bus=f"127.0.0.1:{port}")
        assert out["ranks"][0]["metrics"]["speed_mbps"] == 9.5
    finally:
        bus.close()


def test_membership_step_sync_attaches_real_snapshot():
    port = _free_port()
    counters.inc("integrity.retransmit", 2)
    m = ElasticMembership(0, [0], f"127.0.0.1:{port}").start()
    try:
        m.step_sync(1)
        out = api.cluster_metrics(bus=f"127.0.0.1:{port}")
        snap = out["ranks"][0]["metrics"]
        assert snap["counters"]["integrity.retransmit"] == 2
        assert snap["epoch"] == 0
        assert m.publish_metrics() is True
    finally:
        m.stop()


def test_cluster_metrics_local_fallback_without_bus():
    out = api.cluster_metrics(bus=f"127.0.0.1:{_free_port()}")
    assert out["local_only"] is True
    assert out["world"] == [0]
    assert out["ranks"][0]["metrics"]["pid"] == os.getpid()


def test_bps_top_render_and_once_json(capsys):
    from tools import bps_top
    cluster = {"epoch": 1, "world": [0, 2], "ranks": {
        0: {"age_s": 0.4, "metrics": {
            "epoch": 1, "speed_mbps": 2048.0, "sched_pending": 3,
            "bytes_in_flight": 64,
            "counters": {"integrity.retransmit": 5},
            "step": {"step": 12, "wall_ms": 100.0,
                     "sync_stall_ms": 25.0}}}}}
    text = bps_top.render(cluster)
    assert "epoch 1" in text and "RANK" in text
    assert "2.147" in text    # 2048 MiB/s -> 2.147 decimal GB/s (bench unit)
    assert "25" in text                   # stall %
    assert "rank(s) [2]" in text          # missing-rank note
    # --once --json against a live bus
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0,)), 5.0, 5.0)
    try:
        _req(port, {"op": "metrics_put", "rank": 0, "metrics": {"x": 1}})
        rc = bps_top.main(["--bus", f"127.0.0.1:{port}", "--once", "--json"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert doc["world"] == [0]
    finally:
        bus.close()


# -- the 3-process acceptance run -------------------------------------------


class _Reader(threading.Thread):
    def __init__(self, proc):
        super().__init__(daemon=True)
        self.proc = proc
        self.lines = []
        self.start()

    def run(self):
        for line in self.proc.stdout:
            self.lines.append(line.rstrip("\n"))

    def wait_for(self, prefix, timeout):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for line in list(self.lines):
                if line.startswith(prefix):
                    return line
            if self.proc.poll() is not None and not any(
                    ln.startswith(prefix) for ln in self.lines):
                break
            time.sleep(0.1)
        pytest.fail(f"no {prefix!r} line within {timeout}s; output:\n"
                    + "\n".join(self.lines[-50:]))


def _spawn_obs_worker(rank, bus_port, hb_port, steps, flight_dir,
                      extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["DMLC_NUM_WORKER"] = "1"
    env["DMLC_WORKER_ID"] = str(rank)
    env["BYTEPS_ELASTIC_RANK"] = str(rank)
    env["BYTEPS_ELASTIC_WORLD"] = "0,1,2"
    env["BYTEPS_ELASTIC_BUS"] = f"127.0.0.1:{bus_port}"
    env["BYTEPS_ELASTIC_HB_PORT"] = str(hb_port)
    env["BYTEPS_ELASTIC_STEPS"] = str(steps)
    env["BYTEPS_ELASTIC_STEP_SLEEP"] = "0.2"
    env["BYTEPS_MEMBERSHIP_RENDEZVOUS_TIMEOUT"] = "3"
    env["BYTEPS_MEMBERSHIP_SYNC_TIMEOUT"] = "15"
    env["BYTEPS_LOG_LEVEL"] = "ERROR"
    env["BYTEPS_OBS_PORT"] = "0"              # every rank serves HTTP
    env["BYTEPS_FLIGHT_DIR"] = str(flight_dir)
    env.pop("BYTEPS_FAULT_SPEC", None)
    env.pop("BYTEPS_ELASTIC_REJOIN", None)
    env.update(extra or {})
    return subprocess.Popen([sys.executable, WORKER], env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


@pytest.mark.chaos
def test_obs_cluster_3proc_chaos_kill_flight_recorder(tmp_path):
    """The ISSUE 6 acceptance pin, all three clauses on one real run:
    with BYTEPS_OBS_PORT set, every rank of a 3-process run serves
    /metrics in valid Prometheus text and /healthz reflects the live
    membership epoch; cluster_metrics() returns the live ranks'
    snapshots over the membership bus (before AND after the shrink);
    and the chaos-killed worker leaves a flight-recorder dump whose
    tail contains the events leading into the kill."""
    steps, kill_at = 25, 6
    bus_port, hb_port = _free_port(), _free_port()
    procs = {
        r: _spawn_obs_worker(r, bus_port, hb_port, steps, tmp_path, extra=(
            {"BYTEPS_FAULT_SPEC": f"kill:rank=1:step={kill_at}",
             "BYTEPS_FAULT_SEED": "7"} if r == 1 else None))
        for r in (0, 1, 2)}
    readers = {r: _Reader(p) for r, p in procs.items()}
    try:
        # every rank announces its obs endpoint
        ports = {}
        for r in (0, 1, 2):
            line = readers[r].wait_for("OBS ", timeout=120)
            ports[r] = int(line.split()[2])

        # clause 1: every rank serves valid Prometheus text + healthz
        scraped = set()
        for r in (0, 1, 2):
            try:
                _, ctype, body = _get(
                    f"http://127.0.0.1:{ports[r]}/metrics", timeout=10)
                _, _, hz = _get(f"http://127.0.0.1:{ports[r]}/healthz",
                                timeout=10)
            except OSError:
                if r == 1:
                    continue      # the victim can die under our scrape
                raise
            assert ctype.startswith("text/plain"), (r, ctype)
            assert "# TYPE byteps_" in body, (r, body[:200])
            for line in body.strip().splitlines():
                if not line.startswith("#"):
                    float(line.rpartition(" ")[2])
            assert json.loads(hz)["membership_epoch"] in (0, 1)
            scraped.add(r)
        assert {0, 2} <= scraped     # both survivors really served

        # the shrink happens (victim killed, survivors agree on epoch 1)
        for r in (0, 2):
            readers[r].wait_for("WORLD 1 0,2", timeout=120)

        # clause 1 (cont.): /healthz reflects the LIVE epoch after the
        # shrink — the endpoint survived the suspend/resume transition
        deadline = time.monotonic() + 60
        epochs = {}
        while time.monotonic() < deadline and set(epochs) != {0, 2}:
            for r in (0, 2):
                try:
                    _, _, hz = _get(
                        f"http://127.0.0.1:{ports[r]}/healthz", timeout=5)
                    if json.loads(hz)["membership_epoch"] == 1:
                        epochs[r] = 1
                except OSError:
                    pass
            time.sleep(0.3)
        assert set(epochs) == {0, 2}, epochs

        # clause 2: one bus round-trip returns every live rank's snapshot
        deadline = time.monotonic() + 60
        cluster = None
        while time.monotonic() < deadline:
            try:
                out = api.cluster_metrics(bus=f"127.0.0.1:{bus_port}",
                                          timeout=5)
            except (ConnectionError, TimeoutError):
                break             # survivors finished; bus gone
            # the bus caches each rank's LAST sync frame, so right after
            # the shrink a survivor's cached snapshot can still be the
            # epoch-0 one — poll until the snapshots themselves have
            # caught up, not just the bus epoch
            if (not out.get("local_only") and out["epoch"] == 1
                    and {0, 2} <= set(out["ranks"])
                    and all(out["ranks"][r]["metrics"].get("epoch") == 1
                            for r in (0, 2))):
                cluster = out
                break
            time.sleep(0.3)
        assert cluster is not None, "never saw both survivors' snapshots"
        assert cluster["world"] == [0, 2]
        for r in (0, 2):
            snap = cluster["ranks"][r]["metrics"]
            assert snap["rank"] == r
            assert snap["epoch"] == 1
            assert "counters" in snap and "gauges" in snap

        outs = {}
        for r, p in procs.items():
            p.communicate(timeout=180)
            outs[r] = "\n".join(readers[r].lines)
        assert procs[1].returncode == 1, outs[1][-2000:]
        for r in (0, 2):
            assert procs[r].returncode == 0, outs[r][-2000:]
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()

    # clause 3: the chaos-killed worker left a flight-recorder dump
    # whose tail holds the events leading into the kill
    dumps = list(tmp_path.glob("bps_flight_*rank1_*_chaos_kill_*.json"))
    assert len(dumps) == 1, list(tmp_path.iterdir())
    doc = json.loads(dumps[0].read_text())
    assert doc["rank"] == 1 and doc["reason"] == "chaos_kill"
    events = doc["events"]
    assert events[-1]["kind"] == "fault.kill"
    assert events[-1]["step"] == kill_at
    kinds = {e["kind"] for e in events}
    assert "engine.init" in kinds          # the run's history, not just
    assert "step_stats" in kinds           # the final instant
