"""Tensor-parallel GPT tests on the 8-device CPU mesh.

TP is GSPMD-driven (parallel/tensor_parallel.py): these tests pin that
(a) parameters are actually distributed (per-device shard sizes), (b)
the (dp, tp) step trains, and (c) TP math equals single-device math on
identical inputs — the sharding must change the placement, never the
numbers.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from byteps_tpu.models.gpt import GPT, GPTConfig, lm_loss
from byteps_tpu.parallel.tensor_parallel import (
    TP_AXIS, gpt_tp_shardings, init_tp_opt_state, make_dp_tp_train_step,
    make_tp_mesh, shard_gpt_params, shard_tp_batch, tp_spec_for)
from byteps_tpu.parallel.long_context import synthetic_lm_batch


def _cfg():
    # f32 end to end: the parity test needs bit-comparable math
    return GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=4, intermediate_size=64, max_position=64,
                     dtype=jnp.float32)


def test_rules_cover_the_sharded_layers():
    assert tp_spec_for("h0/attn/qkv/kernel") == jax.sharding.PartitionSpec(
        None, None, TP_AXIS, None)
    assert tp_spec_for("h1/mlp_out/kernel") == jax.sharding.PartitionSpec(
        TP_AXIS, None)
    assert tp_spec_for("ln_f/scale") == jax.sharding.PartitionSpec()
    assert tp_spec_for("wpe/embedding") == jax.sharding.PartitionSpec()


def test_params_are_distributed():
    cfg = _cfg()
    mesh = make_tp_mesh(jax.devices()[:8], n_tp=4)
    model = GPT(cfg)
    batch = synthetic_lm_batch(jax.random.PRNGKey(0), cfg, 4, 16)
    params = model.init(jax.random.PRNGKey(1), batch["input_ids"][:1])
    sharded = shard_gpt_params(mesh, params)
    qkv = sharded["params"]["h0"]["attn"]["qkv"]["kernel"]
    # heads axis split 4 ways: each device holds 1/4 of the kernel
    shard = qkv.addressable_shards[0].data
    assert shard.shape[2] * 4 == qkv.shape[2]
    mlp = sharded["params"]["h0"]["mlp_in"]["kernel"]
    assert mlp.addressable_shards[0].data.shape[1] * 4 == mlp.shape[1]
    ln = sharded["params"]["h0"]["ln1"]["scale"]
    assert ln.addressable_shards[0].data.shape == ln.shape  # replicated


def test_dp_tp_step_trains():
    cfg = _cfg()
    mesh = make_tp_mesh(jax.devices()[:8], n_tp=4)  # dp=2 x tp=4
    model = GPT(cfg)
    rng = jax.random.PRNGKey(2)
    batch = synthetic_lm_batch(rng, cfg, batch=8, seq_len=16)
    params = shard_gpt_params(mesh, model.init(rng, batch["input_ids"][:1]))
    tx = optax.adam(1e-2)
    opt_state = init_tp_opt_state(tx, params)
    step = make_dp_tp_train_step(mesh, cfg, tx)
    batch = shard_tp_batch(mesh, batch)
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses
    # updated params keep their TP placement (no silent gather)
    qkv = params["params"]["h0"]["attn"]["qkv"]["kernel"]
    shard = qkv.addressable_shards[0].data
    assert shard.shape[2] * 4 == qkv.shape[2]


def test_tp_matches_single_device_math():
    cfg = _cfg()
    model = GPT(cfg)
    rng = jax.random.PRNGKey(3)
    batch = synthetic_lm_batch(rng, cfg, batch=4, seq_len=16)
    params0 = model.init(rng, batch["input_ids"][:1])
    tx = optax.sgd(0.1)

    # single device reference
    @jax.jit
    def ref_step(p, o, b):
        loss, g = jax.value_and_grad(
            lambda q: lm_loss(model.apply(q, b["input_ids"]),
                              b["labels"]))(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    p_ref, o_ref = params0, tx.init(params0)
    for _ in range(3):
        p_ref, o_ref, loss_ref = ref_step(p_ref, o_ref, batch)

    mesh = make_tp_mesh(jax.devices()[:8], n_tp=4)
    p_tp = shard_gpt_params(mesh, params0)
    o_tp = init_tp_opt_state(tx, p_tp)
    step = make_dp_tp_train_step(mesh, cfg, tx)
    b_tp = shard_tp_batch(mesh, batch)
    for _ in range(3):
        p_tp, o_tp, loss_tp = step(p_tp, o_tp, b_tp)

    np.testing.assert_allclose(float(loss_tp), float(loss_ref),
                               rtol=1e-5, atol=1e-6)
    for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(p_ref),
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_leaves_with_path(p_tp),
                   key=lambda kv: str(kv[0]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=str(ka))


def test_unsharded_params_rejected():
    """Fresh init output (never mesh-sharded) must raise, not silently
    run single-device replicated."""
    cfg = _cfg()
    mesh = make_tp_mesh(jax.devices()[:8], n_tp=4)
    model = GPT(cfg)
    batch = synthetic_lm_batch(jax.random.PRNGKey(5), cfg, 4, 16)
    params = model.init(jax.random.PRNGKey(6), batch["input_ids"][:1])
    tx = optax.sgd(0.1)
    step = make_dp_tp_train_step(mesh, cfg, tx)
    with pytest.raises(ValueError, match="not mesh-sharded"):
        step(params, tx.init(params), shard_tp_batch(mesh, batch))
