"""Smoke-test the end-to-end overlap harness (tools/overlap_bench.py):
each mode must train, the modes must agree bit-for-bit on the loss
trajectory (cross-barrier changes WHEN updates apply, not their math),
and the cross-barrier pass must leave no pending updates behind."""

import os
import sys

import pytest

torch = pytest.importorskip("torch")

from byteps_tpu.common.config import Config  # noqa: E402
from byteps_tpu.core import api  # noqa: E402


@pytest.fixture()
def engine():
    api.init(Config(telemetry_on=False, trace_on=False,
                    enable_priority=True, scheduling_credit=2 * 32 * 32 * 4))
    yield
    api.shutdown()


def test_modes_agree_on_losses(engine):
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.overlap_bench import one_mode_pass

    losses = {}
    for mode in ("nocomm", "sync", "xb"):
        times, ls = one_mode_pass(mode, steps=2, warmup=1, width=32,
                                  depth=3, batch=8)
        assert len(times) == 2 and all(t > 0 for t in times)
        losses[mode] = ls
    # same seed, same data: communication modes must not change the math
    assert losses["nocomm"] == losses["sync"] == losses["xb"]
    # and training must actually move
    assert losses["nocomm"][-1] < losses["nocomm"][0]


@pytest.fixture()
def engine_compress():
    # min_compress_bytes=0 so the tiny test layers actually compress
    # (the shared fixture's explicit Config keeps the 64 KiB default,
    # which would silently strip the codec from 32x32 layers)
    api.init(Config(telemetry_on=False, trace_on=False,
                    enable_priority=True, min_compress_bytes=0,
                    scheduling_credit=2 * 32 * 32 * 4))
    yield
    api.shutdown()


def test_compressed_modes_train(engine_compress):
    """--compression lane (ISSUE 11 satellite): the sync/xb passes run
    on the fused quantized stream and still optimize.  Lossy codecs
    change gradient values, so the pin is 'trains and stays finite',
    not loss equality with nocomm."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import math

    from tools.overlap_bench import COMPRESSION_KWARGS, one_mode_pass

    assert set(COMPRESSION_KWARGS) == {"none", "onebit", "randomk", "topk"}
    for mode in ("sync", "xb"):
        times, ls = one_mode_pass(mode, steps=2, warmup=1, width=32,
                                  depth=3, batch=8,
                                  compression=COMPRESSION_KWARGS["onebit"])
        assert len(times) == 2 and all(t > 0 for t in times)
        assert all(math.isfinite(v) for v in ls)


def test_pin_disjoint_skips_with_reason_on_small_hosts(monkeypatch):
    # round-5 (VERDICT r4 task 4 path B): on a 1-core host the skip
    # reason is the datum; on >=2 cores the split must be disjoint and
    # cover compute + transport.
    from tools import overlap_bench as ob

    monkeypatch.setenv("BYTEPS_BENCH_PIN", "off")
    info, reason = ob._pin_disjoint()
    assert info is None and "disabled" in reason

    monkeypatch.delenv("BYTEPS_BENCH_PIN", raising=False)
    monkeypatch.setattr(os, "sched_getaffinity", lambda pid: {0},
                        raising=False)
    info, reason = ob._pin_disjoint()
    assert info is None and "1 available core" in reason


def test_pin_disjoint_splits_multicore(monkeypatch):
    from tools import overlap_bench as ob

    monkeypatch.delenv("BYTEPS_BENCH_PIN", raising=False)
    monkeypatch.setattr(os, "sched_getaffinity",
                        lambda pid: set(range(8)), raising=False)
    calls = []
    monkeypatch.setattr(os, "sched_setaffinity",
                        lambda tid, cores: calls.append((tid, sorted(cores))),
                        raising=False)
    # the real set_num_threads would leave the pytest process permanently
    # single-threaded for torch
    monkeypatch.setattr(torch, "set_num_threads", lambda n: None)
    info, reason = ob._pin_disjoint()
    assert reason is None
    assert info["compute_cores"] == [0, 1, 2, 3]
    assert info["transport_cores"] == [4, 5, 6, 7]
    assert not set(info["compute_cores"]) & set(info["transport_cores"])
    # main thread pinned to compute, every other thread to transport
    import threading
    main_calls = [c for t, c in calls if t == threading.get_native_id()]
    assert main_calls == [[0, 1, 2, 3]]
    other = [c for t, c in calls if t != threading.get_native_id()]
    assert all(c == [4, 5, 6, 7] for c in other)
    assert len(other) == info["other_threads_pinned"]


def test_pin_disjoint_honors_core_spec(monkeypatch):
    # BYTEPS_BENCH_PIN="0,1,2,3" confines the split to those cores even
    # on a wider host (pin_cores spec semantics, code-review r5)
    from tools import overlap_bench as ob

    monkeypatch.setenv("BYTEPS_BENCH_PIN", "0-3")
    monkeypatch.setattr(os, "sched_getaffinity",
                        lambda pid: set(range(8)), raising=False)
    monkeypatch.setattr(os, "sched_setaffinity",
                        lambda tid, cores: None, raising=False)
    monkeypatch.setattr(torch, "set_num_threads", lambda n: None)
    info, reason = ob._pin_disjoint()
    assert reason is None
    assert info["compute_cores"] == [0, 1]
    assert info["transport_cores"] == [2, 3]
    # a spec leaving <2 cores skips with a reason
    monkeypatch.setenv("BYTEPS_BENCH_PIN", "5")
    info, reason = ob._pin_disjoint()
    assert info is None and "1 available core" in reason
    monkeypatch.setenv("BYTEPS_BENCH_PIN", "5-bogus")
    info, reason = ob._pin_disjoint()
    assert info is None and "malformed" in reason
