"""Smoke-test the end-to-end overlap harness (tools/overlap_bench.py):
each mode must train, the modes must agree bit-for-bit on the loss
trajectory (cross-barrier changes WHEN updates apply, not their math),
and the cross-barrier pass must leave no pending updates behind."""

import os
import sys

import pytest

torch = pytest.importorskip("torch")

from byteps_tpu.common.config import Config  # noqa: E402
from byteps_tpu.core import api  # noqa: E402


@pytest.fixture()
def engine():
    api.init(Config(telemetry_on=False, trace_on=False,
                    enable_priority=True, scheduling_credit=2 * 32 * 32 * 4))
    yield
    api.shutdown()


def test_modes_agree_on_losses(engine):
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.overlap_bench import one_mode_pass

    losses = {}
    for mode in ("nocomm", "sync", "xb"):
        times, ls = one_mode_pass(mode, steps=2, warmup=1, width=32,
                                  depth=3, batch=8)
        assert len(times) == 2 and all(t > 0 for t in times)
        losses[mode] = ls
    # same seed, same data: communication modes must not change the math
    assert losses["nocomm"] == losses["sync"] == losses["xb"]
    # and training must actually move
    assert losses["nocomm"][-1] < losses["nocomm"][0]
