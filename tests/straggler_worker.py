"""Worker body for the gray-failure / straggler chaos tests
(test_straggler.py).

Same toy topology as elastic_worker.py — three real processes, each
with its own engine on the virtual CPU mesh, sharing one heartbeat
endpoint and one membership bus; the cross-process data plane is the
bus's step_sync payload all-gather.  What THIS worker adds is the
gray-failure lifecycle under ``BYTEPS_STRAGGLER_POLICY=demote``:

- One rank runs under a sustained ``slow`` fault
  (``BYTEPS_FAULT_SPEC=slow:rank=R:site=sync:ms=...:n=...``): every
  engine sync visit sleeps, so the rank reaches each step barrier last
  by ~ms — slow-but-alive, invisible to heartbeats and kill detection.
- The bus scores arrival lags; after ``straggler_demote_after``
  consecutive slow barriers it demotes the rank: survivors apply a
  shrink (``WORLD`` line) and keep stepping at full speed, while the
  straggler gets :class:`Demoted` (``DEMOTED`` line) and parks on
  probation.
- On probation the straggler probes its own data path (a small local
  ``push_pull`` — it visits the chaos ``sync`` site, so the probe stays
  honest until the fault's ``n`` budget really clears), and once
  ``utils.slowness.wait_recovered`` sees consecutive healthy probes
  (``RECOVERED`` line) it suspends and rejoins through the ordinary
  step-boundary admission (``REJOINED`` line) with survivor-broadcast
  parameters — probation cleared bus-side.

Every step prints ``STEP <step> <wall_s>`` so the test can compare
throughput across the faulted / demoted / readmitted windows, and the
``FINAL`` line carries the converged state for the zero-lost /
zero-double-counted gradient equivalence check.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

LR = 0.1
DIM = 8


def _grad(rank: int) -> np.ndarray:
    # rank-distinct so demotion/readmission change the mean: the test's
    # window-by-window simulation catches any lost or double-counted
    # contribution
    return np.full(DIM, float((rank + 1) ** 2), np.float32)


def main() -> int:
    rank = int(os.environ["BYTEPS_ELASTIC_RANK"])
    world = [int(r) for r in os.environ["BYTEPS_ELASTIC_WORLD"].split(",")]
    bus = os.environ["BYTEPS_ELASTIC_BUS"]
    hb_port = os.environ.get("BYTEPS_ELASTIC_HB_PORT", "")
    n_steps = int(os.environ["BYTEPS_ELASTIC_STEPS"])
    sleep_s = float(os.environ.get("BYTEPS_ELASTIC_STEP_SLEEP", "0.1"))
    probe_baseline = float(os.environ.get("BYTEPS_PROBE_BASELINE_S", "0.1"))

    import jax

    jax.config.update("jax_platforms", "cpu")

    import byteps_tpu.core.api as api
    from byteps_tpu.fault import membership as mm
    from byteps_tpu.fault.membership import (Demoted, ElasticMembership,
                                             MembershipTimeout, WorldChanged)
    from byteps_tpu.utils.failure_detector import install_failure_action
    from byteps_tpu.utils.slowness import wait_recovered

    api.init()   # arms the slow fault from BYTEPS_FAULT_SPEC
    m = ElasticMembership(rank, world, bus).start()
    w = np.zeros(DIM, np.float32)
    install_failure_action(m.on_failure)
    if hb_port:
        m.host_heartbeat(interval=0.08, timeout=2.0, grace=60.0,
                         addr="127.0.0.1:" + hb_port,
                         on_failure=m.on_failure)
    # warm the engine's compiled programs BEFORE the measured loop: the
    # first push's compile stall otherwise lands in round-1 arrival lags
    # and pollutes every rank's early slowness baseline (the scorer is
    # MAD-robust, but there is no reason to feed it startup noise; the
    # straggler's warm pushes deliberately consume slow-fault budget —
    # the fault is armed, so warmup is slow too, exactly like a real
    # throttled host)
    for i in range(3):
        api._require().push_pull_local(_grad(rank), "grad", op="sum")
    print("START", rank, flush=True)

    step = 1
    retries = 0
    while step <= n_steps:
        if retries > 300:
            print("RETRY-BUDGET-EXHAUSTED at", step, flush=True)
            return 6
        t_step = time.monotonic()
        try:
            red = np.asarray(api._require().push_pull_local(
                _grad(rank), "grad", op="sum"))
        except RuntimeError:
            # engine torn down / rebuilt by a concurrent world change
            retries += 1
            m.wait_ready(mm.current_epoch(), timeout=30)
            time.sleep(0.05)
            continue
        try:
            _, payloads = m.step_sync(step, payload=red,
                                      state={"w": w, "step": step - 1})
        except Demoted as e:
            # -- the gray-failure lifecycle ------------------------------
            print("DEMOTED at", step, "probation",
                  ",".join(map(str, e.probation)), flush=True)
            install_failure_action(None)
            m.stop()
            # probation: probe the very data path whose slowness got us
            # demoted (the probe's push visits the chaos `sync` site, so
            # it stays slow until the fault window really ends)
            eng = api._require()
            probe_i = [0]

            def probe():
                probe_i[0] += 1
                eng.push_pull_local(np.ones(4, np.float32),
                                    "probe", op="sum")

            if not wait_recovered(probe, baseline_s=probe_baseline,
                                  factor=2.0, consecutive=3,
                                  interval_s=0.02, timeout_s=120.0):
                print("NEVER-RECOVERED", flush=True)
                return 7
            print("RECOVERED after", probe_i[0], "probes", flush=True)
            api.suspend()
            m, step0, state = ElasticMembership.rejoin(rank, bus)
            w = np.asarray(state["w"], np.float32)
            step = int(step0) + 1
            install_failure_action(m.on_failure)
            if hb_port:
                # re-arm the managed heartbeat: the readmitted rank must
                # beat again or the survivors' rebuilt monitors would
                # eventually declare it stale after the startup grace
                m.host_heartbeat(interval=0.08, timeout=2.0, grace=60.0,
                                 addr="127.0.0.1:" + hb_port,
                                 on_failure=m.on_failure)
            print("REJOINED", mm.current_epoch(),
                  ",".join(map(str, m.view().world)), step0, flush=True)
            continue
        except WorldChanged as e:
            print("WORLD", e.view.epoch,
                  ",".join(map(str, e.view.world)), "at", step, flush=True)
            continue   # engine already on the new world; retry the step
        except MembershipTimeout:
            retries += 1
            continue
        retries = 0
        grads = [np.asarray(p) for p in payloads.values()]
        w = w - np.float32(LR) * (np.sum(grads, axis=0, dtype=np.float32)
                                  / np.float32(len(grads)))
        print("STEP", step, round(time.monotonic() - t_step, 4), flush=True)
        step += 1
        time.sleep(sleep_s)

    assert np.all(w == w[0]), w   # uniform by construction
    from byteps_tpu.common.telemetry import counters as _counters
    print("SLOW-FIRED", _counters.get("fault.slow"),
          "CLEARED", _counters.get("fault.slow_cleared"), flush=True)
    view = m.view()
    print("FINAL", view.epoch, ",".join(map(str, view.world)),
          repr(float(w[0])), flush=True)
    install_failure_action(None)
    m.stop()
    api.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
