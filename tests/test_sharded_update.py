"""Sharded weight update fused into push_pull (ISSUE 20).

What is pinned here:

- the float32 replay proof: ``sharded_update=True`` reproduces the
  unsharded engine trajectory **bit-for-bit** on the virtual 8-device
  mesh, on both the parts fallback and the buffer-mode hot path, through
  the ``DistributedOptimizer`` adapter, and ACROSS one elastic shrink
  (8 -> 4 via suspend/resume — the slot re-pad re-shards optimizer
  state);
- wire accounting: the per-leg ``wire_bytes{leg=push|pull}`` split
  (ISSUE satellite a), steady-state sharded wire-bytes/step <= 0.6x the
  unsharded figure (push N + pull N/R vs push N + pull N), and
  ``StepStats.wire_bytes_per_step``;
- the quantized parameter leg: reported separately
  (``compression.param_wire_bytes``), gated by the golden-error
  ceiling at declare time;
- the adapter contracts: ``init`` returns ``optax.EmptyState`` (state
  lives in the engine), declare-time validation, config validation of
  the BYTEPS_SHARDED_UPDATE knob family;
- shard-published serving cuts: ``ServingTier.cut()`` under sharded
  update publishes per-owner slices (never a full-parameter buffer —
  ``slot.params`` is monkeypatched to raise during the cut) and the
  reassembled read is bitwise the unsharded trajectory.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import byteps_tpu as bps
from byteps_tpu import jax as bpsjax
from byteps_tpu.comm.mesh import CommContext, _build_mesh
from byteps_tpu.common.config import Config
from byteps_tpu.common.telemetry import counters
from byteps_tpu.core.engine import PushPullEngine
from byteps_tpu.jax.async_opt import AsyncDistributedOptimizer
from byteps_tpu.server import KVStore

SHAPE = (256, 33)
N = int(np.prod(SHAPE))
R = 8


def _comm():
    devices = jax.devices()
    return CommContext(mesh=_build_mesh(devices, 1), n_dcn=1,
                       n_ici=len(devices))


def _unsharded_replay(comm, tx, p0, grads, **cfg_kw):
    """The reference arm: engine push_pull + caller-side eager optax —
    the trajectory the unsharded DistributedOptimizer produces.  (The
    merged gradient carries collective rounding, so comparing against
    raw-gradient optax would be vacuously loose: both arms must
    integrate the ENGINE's merge.)"""
    eng = PushPullEngine(comm, Config(**cfg_kw))
    eng.declare_tensor("w", p0.shape, np.float32, op="average", local=True)
    params = jnp.asarray(p0)
    state = tx.init(params)
    push0 = counters.get("wire_bytes", leg="push")
    pull0 = counters.get("wire_bytes", leg="pull")
    for g in grads:
        red = eng.push_pull_local(g, "w", op="average")
        upd, state = tx.update(jnp.asarray(red), state, params)
        params = optax.apply_updates(params, upd)
    wire = (counters.get("wire_bytes", leg="push") - push0,
            counters.get("wire_bytes", leg="pull") - pull0)
    eng.shutdown(wait=True)
    return np.asarray(params), wire


def _sharded_replay(comm, tx, p0, grads, **cfg_kw):
    eng = PushPullEngine(comm, Config(sharded_update=True, **cfg_kw))
    eng.declare_update("w", p0.shape, np.float32, tx=tx, init_value=p0)
    params = jnp.asarray(p0)
    push0 = counters.get("wire_bytes", leg="push")
    pull0 = counters.get("wire_bytes", leg="pull")
    for g in grads:
        upd = eng.push_pull_update(g, "w")
        params = optax.apply_updates(params, jnp.asarray(upd))
    wire = (counters.get("wire_bytes", leg="push") - push0,
            counters.get("wire_bytes", leg="pull") - pull0)
    master_ok = np.array_equal(eng.update_slots["w"].params(),
                               np.asarray(params))
    stats = eng.step_stats.last()
    eng.shutdown(wait=True)
    return np.asarray(params), wire, master_ok, stats


def _data(seed=0, steps=5, shape=SHAPE):
    rng = np.random.RandomState(seed)
    p0 = rng.randn(*shape).astype(np.float32)
    grads = [rng.randn(*shape).astype(np.float32) for _ in range(steps)]
    return p0, grads


def test_replay_bitexact_parts_path():
    comm = _comm()
    tx = optax.adam(1e-2)
    p0, grads = _data()
    ref, _ = _unsharded_replay(comm, tx, p0, grads)
    got, _, master_ok, _ = _sharded_replay(comm, tx, p0, grads)
    assert np.array_equal(ref, got)
    assert master_ok  # the engine-resident master IS the trajectory


def test_replay_bitexact_buffered_and_wire_ratio():
    """The buffer-mode hot path: bitexact AND the acceptance wire bound
    — sharded steady state ships push N + pull N/R, <= 0.6x the
    unsharded push N + pull N."""
    comm = _comm()
    tx = optax.adam(1e-2)
    p0, grads = _data(seed=1)
    ref, (push_u, pull_u) = _unsharded_replay(comm, tx, p0, grads,
                                              partition_bytes=4096)
    got, (push_s, pull_s), master_ok, stats = _sharded_replay(
        comm, tx, p0, grads, partition_bytes=4096, telemetry_on=True)
    assert np.array_equal(ref, got)
    assert master_ok
    assert push_s == push_u                     # push leg unchanged
    assert pull_s * R == pull_u                 # pull leg is 1/R exactly
    ratio = (push_s + pull_s) / (push_u + pull_u)
    assert ratio <= 0.6, ratio
    # ISSUE satellite a: the per-step figure lands in StepStats too
    assert stats is not None
    assert stats.wire_bytes_per_step == N * 4 + (N * 4) // R


def test_fused_mode_close_but_single_dispatch():
    """BYTEPS_SHARDED_UPDATE_FUSED: one fused program per step — the
    documented trade is ulp-level FMA-contraction drift, not equality."""
    comm = _comm()
    tx = optax.adam(1e-2)
    p0, grads = _data(seed=2, steps=3)
    ref, _ = _unsharded_replay(comm, tx, p0, grads)
    base = counters.get("engine.sharded_updates")
    got, _, _, _ = _sharded_replay(comm, tx, p0, grads,
                                   sharded_update_fused=True)
    assert counters.get("engine.sharded_updates") - base == len(grads)
    np.testing.assert_allclose(ref, got, rtol=0, atol=1e-6)


def test_adapter_parity_and_elastic_shrink():
    """DistributedOptimizer(sharded_update=True) == unsharded bit-for-
    bit over 4 steps INCLUDING an 8 -> 4 suspend/resume at step 2: the
    suspend stash -> declare_update(restore=) re-pad re-shards the
    owner-resident optimizer state with no lost or doubled update."""
    rng = np.random.RandomState(1)
    params = {"w": rng.randn(64, 33).astype(np.float32),
              "b": rng.randn(33).astype(np.float32)}
    grads_per_step = [
        {"w": rng.randn(8, 64, 33).astype(np.float32),
         "b": rng.randn(8, 33).astype(np.float32)} for _ in range(4)]

    def run(sharded, shrink_at=None):
        bps.init(config=Config(sharded_update=sharded),
                 devices=jax.devices())
        opt = bpsjax.DistributedOptimizer(optax.adam(1e-2),
                                          name_prefix="g",
                                          sharded_update=sharded)
        p = jax.tree.map(jnp.asarray, params)
        s = opt.init(p)
        if sharded:
            assert isinstance(s, optax.EmptyState)
        for i, g in enumerate(grads_per_step):
            if shrink_at is not None and i == shrink_at:
                bps.suspend()
                bps.resume(config=Config(sharded_update=sharded),
                           devices=jax.devices()[:4])
            g = jax.tree.map(lambda a: a[: bps.size()], g)
            u, s = opt.update(g, s, p)
            # updates/state are mesh-placed (deferred gather): host-
            # materialize before mixing across the elastic transition
            s = jax.tree.map(lambda a: jnp.asarray(np.asarray(a)), s)
            p = jax.tree.map(
                lambda a, b: optax.apply_updates(
                    jnp.asarray(np.asarray(a)), jnp.asarray(np.asarray(b))),
                p, u)
        out = jax.tree.map(np.asarray, p)
        bps.shutdown()
        return out

    for shrink_at in (None, 2):
        ref = run(False, shrink_at)
        got = run(True, shrink_at)
        for k in ref:
            assert np.array_equal(ref[k], got[k]), (shrink_at, k)


def test_async_adapter_parity():
    """AsyncDistributedOptimizer sharded mode: no gradient collective,
    so the async trajectory is bitwise the unsharded async one."""
    rng = np.random.RandomState(2)
    params = {"w": rng.randn(32, 17).astype(np.float32)}
    grads = [{"w": rng.randn(32, 17).astype(np.float32)}
             for _ in range(3)]

    def run(sharded):
        bps.init(config=Config(sharded_update=sharded),
                 devices=jax.devices())
        opt = AsyncDistributedOptimizer(optax.adam(1e-2), store=KVStore(),
                                        name_prefix="a",
                                        sharded_update=sharded)
        p = jax.tree.map(jnp.asarray, params)
        s = opt.init(p)
        for g in grads:
            p, s = opt.update_and_sync(jax.tree.map(jnp.asarray, g), s, p)
        out = jax.tree.map(np.asarray, p)
        bps.shutdown()
        return out

    ref = run(False)
    got = run(True)
    assert np.array_equal(ref["w"], got["w"])


def test_quantized_param_leg_reported_separately():
    comm = _comm()
    tx = optax.adam(1e-2)
    p0, grads = _data(seed=3, steps=3)
    base = counters.get("compression.param_wire_bytes")
    got, (push_s, pull_s), _, _ = _sharded_replay(
        comm, tx, p0, grads, partition_bytes=4096,
        min_compress_bytes=0, sharded_param_codec="dithering:64")
    param_wire = counters.get("compression.param_wire_bytes") - base
    assert param_wire > 0
    assert pull_s == param_wire       # the pull leg IS the codec payload
    assert pull_s < push_s            # quantized leg beats full precision
    assert not np.array_equal(got, p0)  # the lossy leg still trains


def test_quantized_param_leg_quality_gate():
    comm = _comm()
    eng = PushPullEngine(comm, Config(sharded_update=True,
                                      min_compress_bytes=0,
                                      sharded_param_codec="onebit",
                                      compress_error_ceiling=0.01))
    try:
        with pytest.raises(ValueError, match="quality gate"):
            eng.declare_update("w", SHAPE, np.float32,
                               tx=optax.adam(1e-2))
    finally:
        eng.shutdown(wait=True)


def test_config_validation():
    with pytest.raises(ValueError, match="requires sharded_update"):
        Config(sharded_update_fused=True)
    with pytest.raises(ValueError, match="requires sharded_update"):
        Config(sharded_param_codec="onebit")
    with pytest.raises(ValueError, match="sharded_param_codec"):
        Config(sharded_update=True, sharded_param_codec="a:b:c")
    Config(sharded_update=True, sharded_update_fused=True,
           sharded_param_codec="auto")  # the valid combination


def test_declare_update_validation():
    comm = _comm()
    eng = PushPullEngine(comm, Config())
    try:
        with pytest.raises(ValueError, match="sharded-update mode"):
            eng.declare_update("w", SHAPE, np.float32,
                               tx=optax.adam(1e-2))
    finally:
        eng.shutdown(wait=True)
    eng = PushPullEngine(comm, Config(sharded_update=True))
    try:
        with pytest.raises(ValueError, match="float tensor"):
            eng.declare_update("i", (8,), np.int32, tx=optax.adam(1e-2))
        with pytest.raises(ValueError, match="no sharded-update slot"):
            eng.push_pull_update(np.zeros(SHAPE, np.float32), "nope")
    finally:
        eng.shutdown(wait=True)


def test_adapter_requires_init_before_update():
    bps.init(config=Config(sharded_update=True), devices=jax.devices())
    try:
        opt = bpsjax.DistributedOptimizer(optax.adam(1e-2),
                                          sharded_update=True)
        with pytest.raises(RuntimeError, match="init"):
            opt.update({"w": np.zeros((8, 4), np.float32)},
                       optax.EmptyState())
    finally:
        bps.shutdown()


def test_serving_cut_shard_published():
    """ServingTier.cut() under sharded update: per-owner slices land as
    ring-routed keys with NO full-parameter materialization, and the
    reassembled read is bitwise what an unsharded cut would serve."""
    from byteps_tpu.server.serving_tier import (ServingHostCore,
                                                ServingTier, TierDirectory,
                                                assemble_shard_keys,
                                                inproc_host)
    comm = _comm()
    tx = optax.adam(1e-2)
    p0, grads = _data(seed=4, steps=3)
    ref, _ = _unsharded_replay(comm, tx, p0, grads)

    eng = PushPullEngine(comm, Config(sharded_update=True))
    eng.declare_update("w", p0.shape, np.float32, tx=tx, init_value=p0)
    for g in grads:
        eng.push_pull_update(g, "w")
    slot = eng.update_slots["w"]

    def boom(*a, **k):
        raise AssertionError("full-parameter materialization during cut")

    slot.params = boom
    d = TierDirectory(static_hosts={i: ("127.0.0.1", i + 1)
                                    for i in range(2)})
    for i in range(2):
        inproc_host(ServingHostCore(host_id=i))
    store = KVStore()
    tier = ServingTier(store, directory=d, replicas=1,
                       cut_interval_s=None,
                       update_slots=lambda: eng.update_slots)
    try:
        snap = tier.cut()
        # every published buffer is shard-sized, never full-parameter
        cap = slot.C * np.dtype(np.float32).itemsize
        shard_keys = [k for k in snap.refs if k.startswith("w@shard")
                      and not k.endswith("@shards")]
        assert len(shard_keys) == R
        assert all(snap.refs[k].nbytes <= cap for k in shard_keys)
        # the cut — and a client read through the tier — serve bitwise
        # the unsharded trajectory
        assert np.array_equal(
            assemble_shard_keys(snap.refs.__getitem__, "w"), ref)
        client = tier.client(max_staleness_s=0.0, stale_on_error=False)
        vals = client.pull()
        assert np.array_equal(
            assemble_shard_keys(vals.__getitem__, "w"), ref)
        # steady-state cut with no new steps publishes nothing
        before = counters.get("serve.shard_publishes")
        tier.cut()
        assert counters.get("serve.shard_publishes") == before
    finally:
        tier.close()
        eng.shutdown(wait=True)
