"""Durable state plane tests (byteps_tpu/server/wal.py, ISSUE 19).

What is pinned here:

- the WAL record format and replay state machine: length-prefixed
  sealed records, LSN continuity, torn tails truncated in place (appends
  resume right after the valid prefix), a corrupt mid-log record
  truncating there and DISCARDING later segments — recovery always lands
  on the last durable point, never past a hole;
- atomic snapshot cuts: write-to-temp + fsync + rename, manifest with
  the version vector, retention pruning, and the corrupt-newest-falls-
  back-to-older path (counted, flight-recorded, never silently used);
- the KVStore coupling: journal-before-merge (a failed append leaves
  memory untouched and the dedup floor unburned), checkpoint/recover
  bit-exactness for arrays + versions + generation + membership epoch +
  dedup floors, and the epoch/clear record kinds;
- the chaos sites (``disk_full``, ``wal_write``, ``fsync``) and their
  counters;
- SnapshotStore.cut() driving the durable checkpoint + WAL truncation;
- RecoveryCoordinator composed with the durable trainer-store restore
  (satellite: fault/recovery.py);
- the observability surfaces: /debug/state wal section, bps_top's WAL
  column, bps_doctor's durability postmortem fold;
- serve-host restart-in-place: the committed arc restored from local
  disk before registration, the publisher's arc_info probe seeding its
  acked view so the next cut ships ZERO bytes (fleet lane);
- the headline acceptance: SIGKILL the ENTIRE world mid-step, cold
  restart from disk, finals bit-exact vs a fault-free run.
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from byteps_tpu.common.telemetry import counters, gauges
from byteps_tpu.fault import injector as inj
from byteps_tpu.server import wal
from byteps_tpu.server.kv_store import KVStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh():
    yield
    inj.disarm()


def _mk_store(dirpath, n=8, **cfg_over):
    store = KVStore()
    dur = wal.attach(store, str(dirpath), cfg=_cfg(**cfg_over))
    store.init_key("w", np.zeros(n, np.float32))
    return store, dur


def _cfg(**over):
    from byteps_tpu.common.config import get_config
    cfg = get_config()
    if not over:
        return cfg
    import dataclasses
    return dataclasses.replace(cfg, **over)


def _digest(store):
    return hashlib.sha256(
        np.ascontiguousarray(store.pull("w")).tobytes()
        + str(store._generation).encode()).hexdigest()


# -- WAL record format / replay state machine ---------------------------------


def test_wal_roundtrip_records_and_lsn_sequence(tmp_path):
    log = wal.WriteAheadLog(str(tmp_path))
    assert log.replay() == ([], {"records": 0, "bytes": 0,
                                 "truncated_tails": 0, "corrupt_records": 0,
                                 "dropped_segments": 0})
    a = np.arange(4, dtype=np.float32)
    assert log.append("init", ("k", a)) == 1
    assert log.append("delta", ("k", a, 0, 1)) == 2
    assert log.append("epoch", 3) == 3
    assert log.lsn == 3
    log.close()

    log2 = wal.WriteAheadLog(str(tmp_path))
    recs, stats = log2.replay()
    assert [(lsn, kind) for lsn, kind, _ in recs] == [
        (1, "init"), (2, "delta"), (3, "epoch")]
    np.testing.assert_array_equal(recs[0][2][1], a)
    assert stats["records"] == 3 and stats["truncated_tails"] == 0
    # appends continue the sequence right after the valid prefix
    assert log2.append("epoch", 4) == 4
    log2.close()


def test_wal_append_before_replay_raises(tmp_path):
    log = wal.WriteAheadLog(str(tmp_path))
    with pytest.raises(RuntimeError, match="before replay"):
        log.append("epoch", 1)


def test_wal_torn_tail_truncated_and_appends_resume(tmp_path):
    log = wal.WriteAheadLog(str(tmp_path))
    log.replay()
    for i in range(1, 6):
        log.append("epoch", i)
    log.close()
    seg = log.segments()[-1][1]
    good_size = os.path.getsize(seg)
    # a torn final write: half a record's bytes reached the disk
    with open(seg, "ab") as fh:
        fh.write(b"\x00\x00\x01\x00" + b"\xde\xad")

    log2 = wal.WriteAheadLog(str(tmp_path))
    recs, stats = log2.replay()
    assert [r[0] for r in recs] == [1, 2, 3, 4, 5]
    assert stats["truncated_tails"] == 1
    assert stats["corrupt_records"] == 0
    # the torn bytes are GONE from disk (truncated, fsynced) and appends
    # resume the LSN sequence
    assert os.path.getsize(seg) == good_size
    assert log2.append("epoch", 6) == 6
    log2.close()
    log3 = wal.WriteAheadLog(str(tmp_path))
    recs, stats = log3.replay()
    assert [r[0] for r in recs] == [1, 2, 3, 4, 5, 6]
    assert stats["truncated_tails"] == 0
    log3.close()


def test_wal_midlog_corruption_discards_later_segments(tmp_path):
    # tiny segments force a multi-segment log
    log = wal.WriteAheadLog(str(tmp_path), segment_bytes=256)
    log.replay()
    for i in range(1, 30):
        log.append("epoch", i)
    log.close()
    segs = log.segments()
    assert len(segs) >= 3
    # flip one byte in the middle of the FIRST segment's first record
    first = segs[0][1]
    with open(first, "r+b") as fh:
        fh.seek(10)
        b = fh.read(1)
        fh.seek(10)
        fh.write(bytes([b[0] ^ 0x40]))

    before_dropped = counters.get("wal.dropped_segments")
    log2 = wal.WriteAheadLog(str(tmp_path))
    recs, stats = log2.replay()
    # replay stops AT the corruption: nothing later is trusted
    assert recs == []
    assert stats["corrupt_records"] == 1
    assert stats["truncated_tails"] == 0
    assert stats["dropped_segments"] == len(segs) - 1
    assert counters.get("wal.dropped_segments") - before_dropped \
        == len(segs) - 1
    # the later segments are gone from disk
    assert len(log2.segments()) <= 1
    log2.close()


def test_wal_fsync_policy_validation_and_off_interval_replay(tmp_path):
    from byteps_tpu.common.config import Config
    with pytest.raises(ValueError, match="wal_fsync"):
        Config(wal_fsync="sometimes")
    with pytest.raises(ValueError, match="wal_fsync_interval"):
        Config(wal_fsync_interval_s=0.0)
    with pytest.raises(ValueError, match="wal_segment_bytes"):
        Config(wal_segment_bytes=1)
    with pytest.raises(ValueError, match="wal_retain"):
        Config(wal_retain_snapshots=0)
    for policy in ("off", "interval"):
        d = tmp_path / policy
        log = wal.WriteAheadLog(str(d), fsync=policy,
                                fsync_interval_s=0.01)
        log.replay()
        for i in range(1, 4):
            log.append("epoch", i)
        log.close()
        log2 = wal.WriteAheadLog(str(d))
        recs, _ = log2.replay()
        assert [r[0] for r in recs] == [1, 2, 3]
        log2.close()


def test_wal_segment_roll_and_truncate_upto(tmp_path):
    log = wal.WriteAheadLog(str(tmp_path), segment_bytes=256)
    log.replay()
    for i in range(1, 40):
        log.append("epoch", i)
    segs = log.segments()
    assert len(segs) >= 4
    # truncate up to the start of the third segment: exactly the first
    # two (whose every record is covered) are removable
    cover = segs[2][0] - 1
    removed = log.truncate_upto(cover)
    assert removed == 2
    left = log.segments()
    assert left[0][0] == segs[2][0]
    # replay of the survivor suffix still works (expected-LSN chain
    # starts fresh at the first surviving record)
    log.close()
    log2 = wal.WriteAheadLog(str(tmp_path))
    recs, stats = log2.replay()
    assert recs[0][0] == segs[2][0] and recs[-1][0] == 39
    assert stats["corrupt_records"] == 0
    log2.close()


# -- atomic snapshots ---------------------------------------------------------


def test_snapshot_save_load_retention_and_manifest(tmp_path):
    d = str(tmp_path)
    for lsn in (5, 9, 14):
        wal.save_snapshot(d, {"arrays": {}, "versions": {"w": lsn},
                              "generation": 1, "epoch": 0, "seen": {}},
                          lsn=lsn, generation=1, retain=2)
    state, lsn = wal.load_snapshot(d)
    assert lsn == 14 and state["versions"] == {"w": 14}
    # retention pruned the oldest cut
    names = sorted(os.listdir(d))
    assert sum(n.endswith(".bin") for n in names) == 2
    manifest = json.load(open(os.path.join(d, "kv-manifest.json")))
    assert manifest["lsn"] == 14 and manifest["generation"] == 1
    assert manifest["versions"] == {"w": "14"} or \
        manifest["versions"] == {"w": 14}


def test_snapshot_corrupt_newest_falls_back_to_older(tmp_path):
    d = str(tmp_path)
    wal.save_snapshot(d, {"versions": {"w": 1}}, lsn=3, generation=0)
    wal.save_snapshot(d, {"versions": {"w": 2}}, lsn=7, generation=0)
    newest = [f for f in os.listdir(d) if f.endswith("0000007.bin")][0]
    path = os.path.join(d, newest)
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0x5A        # flip a bit mid-payload
    open(path, "wb").write(bytes(blob))
    before = counters.get("wal.snapshot_corrupt")
    state, lsn = wal.load_snapshot(d)
    assert lsn == 3 and state["versions"] == {"w": 1}
    assert counters.get("wal.snapshot_corrupt") == before + 1


# -- the KVStore coupling -----------------------------------------------------


def test_durable_kv_checkpoint_recover_bitexact(tmp_path):
    store, dur = _mk_store(tmp_path)
    store.set_membership_epoch(4)
    for w in range(2):
        store.push_delta("w", np.full(8, float(w + 1), np.float32),
                         worker_id=w, seq=1)
    assert dur.checkpoint() is True
    assert dur.checkpoint() is False      # nothing journaled since
    for w in range(2):
        store.push_delta("w", np.full(8, 0.25, np.float32),
                         worker_id=w, seq=2)
    want = _digest(store)
    want_versions = dict(store._versions)
    want_seen = dict(store._seen)
    dur.close()

    store2, stats = wal.recover(str(tmp_path))
    assert stats["had_snapshot"] == 1 and stats["applied"] >= 2
    assert _digest(store2) == want
    assert store2._versions == want_versions
    assert store2._seen == want_seen
    assert store2._membership_epoch == 4
    # the restored dedup floor absorbs a duplicate retry post-restart
    v = store2.pull("w").copy()
    store2.push_delta("w", np.full(8, 0.25, np.float32), worker_id=0,
                      seq=2)
    np.testing.assert_array_equal(store2.pull("w"), v)


def test_durable_kv_clear_and_generation_survive_recovery(tmp_path):
    store, dur = _mk_store(tmp_path)
    store.push_delta("w", np.ones(8, np.float32), worker_id=0, seq=1)
    gen0 = store._generation
    store.clear()
    assert store._generation == gen0 + 1
    store.init_key("w", np.full(8, 7.0, np.float32))
    dur.close()
    store2, _ = wal.recover(str(tmp_path))
    assert store2._generation == gen0 + 1
    np.testing.assert_array_equal(store2.pull("w"),
                                  np.full(8, 7.0, np.float32))


def test_durable_kv_clear_restores_epoch_at_clear_time(tmp_path,
                                                       monkeypatch):
    """A replayed ``clear`` must re-sync the membership epoch the way
    the live clear() did — to the epoch observed AT CLEAR TIME.  A
    cold-started store keeping the stale pre-clear epoch would drop
    every new-world delta as stale until a later epoch record lands."""
    from byteps_tpu.server import kv_store as kv_mod
    store, dur = _mk_store(tmp_path)
    store.set_membership_epoch(3)
    real_epoch = kv_mod._membership.current_epoch
    monkeypatch.setattr(kv_mod._membership, "current_epoch", lambda: 7)
    store.clear()                       # clear-time world is epoch 7
    assert store._membership_epoch == 7
    store.init_key("w", np.zeros(8, np.float32))
    dur.close()
    monkeypatch.setattr(kv_mod._membership, "current_epoch", real_epoch)

    store2, _ = wal.recover(str(tmp_path))
    assert store2._membership_epoch == 7
    # a new-world delta stamped with the clear-time epoch LANDS — the
    # pre-fix replay kept epoch 3 and dropped it as stale
    store2.push_delta("w", np.ones(8, np.float32), mepoch=7,
                      worker_id=0, seq=1)
    np.testing.assert_array_equal(store2.pull("w"),
                                  np.ones(8, np.float32))


@pytest.mark.chaos
def test_wal_corruption_below_cut_point_post_restart_pushes_survive(
        tmp_path):
    """A corrupt record BELOW the snapshot cut truncates the journal to
    an LSN the restored snapshot already covers.  Recovery must advance
    the journal past the cut (sealed ``__advance__`` marker) so new
    appends take fresh LSNs — without it, acknowledged post-restart
    pushes reuse covered LSNs and the SECOND restart's ``lsn <=
    snapshot`` skip silently discards them."""
    store, dur = _mk_store(tmp_path, wal_segment_bytes=4096)
    for seq in range(1, 25):
        store.push_delta("w", np.full(8, 0.5, np.float32), worker_id=0,
                         seq=seq)
    assert dur.checkpoint(force=True)
    snap_lsn = dur.wal.lsn
    want_cut = _digest(store)
    dur.close()
    # corrupt the first surviving record — strictly below the cut point
    # (the covered prefix segments were truncated away by the cut)
    segs = dur.wal.segments()
    assert segs and segs[0][0] <= snap_lsn
    with open(segs[0][1], "r+b") as fh:
        fh.seek(8)
        b = fh.read(1)
        fh.seek(8)
        fh.write(bytes([b[0] ^ 0x20]))

    # restart 1: snapshot restores the cut; the journal truncated below
    # it, so recovery must advance past snap_lsn before taking appends
    store2, stats = wal.recover(str(tmp_path))
    assert stats["had_snapshot"] == 1
    assert stats.get("advanced_to", 0) > snap_lsn
    assert _digest(store2) == want_cut
    dur2 = store2._durable
    assert dur2.wal.lsn > snap_lsn
    # acknowledged post-restart pushes...
    store2.push_delta("w", np.full(8, 2.0, np.float32), worker_id=0,
                      seq=25)
    store2.push_delta("w", np.full(8, 3.0, np.float32), worker_id=1,
                      seq=1)
    want = _digest(store2)
    dur2.close()

    # restart 2: ...must SURVIVE (the pre-fix world skipped them here
    # as "covered by the snapshot")
    store3, stats3 = wal.recover(str(tmp_path))
    assert _digest(store3) == want
    assert store3._seen[("w", 0)] == 25
    assert store3._seen[("w", 1)] == 1
    # and the checkpoint no-op guard is healed too: the journal position
    # sits above the restored cut, so a fresh cut is not refused
    assert store3._durable.checkpoint() is True
    store3._durable.close()


def test_wal_restricted_unpickler_rejects_foreign_globals(tmp_path):
    """The durable dir is CRC-checked, not authenticated: a hand-crafted
    record whose pickle names a global off the durable-plane allowlist
    must be treated as corruption (truncated, counted) — never
    resolved, never executed."""
    import pickle as _pickle
    from byteps_tpu.common import integrity as _integrity

    pwned = tmp_path / "pwned"

    class Evil:
        def __reduce__(self):
            return (os.system, (f"touch {pwned}",))

    with pytest.raises(_pickle.UnpicklingError, match="allowlist"):
        wal._loads(_pickle.dumps(Evil()))
    # the allowlist still round-trips everything the plane serializes
    state = {"arrays": {"w": np.arange(3, dtype=np.float32)},
             "seen": {("w", 0): 2}, "generation": 1}
    out = wal._loads(_pickle.dumps(state,
                                   protocol=_pickle.HIGHEST_PROTOCOL))
    np.testing.assert_array_equal(out["arrays"]["w"], state["arrays"]["w"])
    assert out["seen"] == state["seen"]

    # a forged-but-correctly-sealed journal record: replay must classify
    # it as corruption at the unpickle, not resolve the global
    payload = _pickle.dumps((1, "delta", Evil()),
                            protocol=_pickle.HIGHEST_PROTOCOL)
    frame = _integrity.seal_bytes(payload, key="wal", seq=1)
    seg = os.path.join(str(tmp_path), f"kv-{1:016d}.wal")
    with open(seg, "wb") as fh:
        fh.write(wal._LEN.pack(len(frame)) + frame)
    log = wal.WriteAheadLog(str(tmp_path))
    recs, stats = log.replay()
    assert recs == []
    assert stats["truncated_tails"] == 1
    assert not pwned.exists()
    log.close()


@pytest.mark.integrity
def test_wal_disk_full_append_fails_store_untouched(tmp_path):
    """Journal-before-merge: a failed append must leave the in-memory
    value unchanged AND the dedup floor unburned, so the caller's
    legitimate retry (after space frees) lands exactly once."""
    store, dur = _mk_store(tmp_path)
    store.push_delta("w", np.ones(8, np.float32), worker_id=0, seq=1)
    v = store.pull("w").copy()
    inj.arm("drop:site=disk_full:p=1", seed=1, rank=0)
    before = counters.get("wal.disk_full_errors")
    with pytest.raises(OSError):
        store.push_delta("w", np.ones(8, np.float32), worker_id=0, seq=2)
    inj.disarm()
    assert counters.get("wal.disk_full_errors") == before + 1
    np.testing.assert_array_equal(store.pull("w"), v)
    assert store._seen[("w", 0)] == 1
    # the retry lands once space is back
    store.push_delta("w", np.ones(8, np.float32), worker_id=0, seq=2)
    np.testing.assert_array_equal(store.pull("w"), v + 1.0)
    dur.close()


@pytest.mark.chaos
def test_wal_torn_write_chaos_recovers_to_last_durable_point(tmp_path):
    store, dur = _mk_store(tmp_path)
    store.push_delta("w", np.ones(8, np.float32), worker_id=0, seq=1)
    want = _digest(store)
    inj.arm("drop:site=wal_write:p=1", seed=2, rank=0)
    before = counters.get("wal.torn_writes")
    with pytest.raises(OSError):
        store.push_delta("w", np.ones(8, np.float32), worker_id=0, seq=2)
    inj.disarm()
    assert counters.get("wal.torn_writes") == before + 1
    dur.close()
    # cold start: the torn tail is truncated; state is the last durable
    # point (the failed push never reached memory either — consistent)
    store2, stats = wal.recover(str(tmp_path))
    assert stats["truncated_tails"] == 1
    assert _digest(store2) == want


@pytest.mark.chaos
def test_wal_bitflip_chaos_detected_and_truncated_at_replay(tmp_path):
    store, dur = _mk_store(tmp_path)
    store.push_delta("w", np.ones(8, np.float32), worker_id=0, seq=1)
    want = _digest(store)
    want_floor = dict(store._seen)
    inj.arm("bitflip:site=wal_write:p=1", seed=3, rank=0)
    # the append "succeeds" (memory merges) but the on-disk frame is
    # corrupt — the crash model where the disk lied about the bytes
    store.push_delta("w", np.ones(8, np.float32), worker_id=0, seq=2)
    inj.disarm()
    dur.close()
    store2, stats = wal.recover(str(tmp_path))
    assert stats["truncated_tails"] == 1    # last record of last segment
    assert _digest(store2) == want
    assert store2._seen == want_floor       # floor matches the arrays


@pytest.mark.chaos
def test_wal_fsync_dropped_chaos_counted_replay_still_whole(tmp_path):
    inj.arm("drop:site=fsync:p=1", seed=4, rank=0)
    store, dur = _mk_store(tmp_path)
    before = counters.get("wal.fsync_dropped")
    store.push_delta("w", np.ones(8, np.float32), worker_id=0, seq=1)
    assert counters.get("wal.fsync_dropped") > before
    want = _digest(store)
    inj.disarm()
    dur.close()
    # a SIGKILL-style crash keeps the page cache: the un-fsynced bytes
    # still replay whole (the drop models durability loss on power
    # failure, which a unit test cannot produce — the counter is the pin)
    store2, _ = wal.recover(str(tmp_path))
    assert _digest(store2) == want


def test_snapshotstore_cut_checkpoints_and_truncates_wal(tmp_path):
    from byteps_tpu.server.serving import SnapshotStore
    store, dur = _mk_store(tmp_path, wal_segment_bytes=4096)
    snapstore = SnapshotStore(store)
    try:
        for seq in range(1, 40):
            store.push_delta("w", np.full(8, 0.5, np.float32),
                             worker_id=0, seq=seq)
        lag_before = dur.wal.lag_bytes()
        before_saves = counters.get("wal.snapshot_saves")
        snapstore.cut()
        assert counters.get("wal.snapshot_saves") == before_saves + 1
        # the cut bounded the replay suffix: covered whole segments gone
        assert dur.wal.lag_bytes() < lag_before
        assert gauges.get("wal.last_snapshot_lsn") == dur.wal.lsn
    finally:
        snapstore.detach()
        dur.close()
    # cold start restores from the cut without replaying the truncated
    # prefix
    store2, stats = wal.recover(str(tmp_path))
    assert stats["had_snapshot"] == 1
    assert _digest(store2) == _digest(store)


# -- fault/recovery.py composition (satellite) --------------------------------


def test_recovery_coordinator_durable_restore(tmp_path, monkeypatch):
    """RecoveryCoordinator composed with the durable plane, cold-start
    side: when BYTEPS_DURABLE_DIR is set and NO incarnation of the
    trainer store is open (this process did not survive with state in
    memory), the recovery flow rebuilds the store from disk and reports
    the replay stats on the result."""
    monkeypatch.setenv("BYTEPS_DURABLE_DIR", str(tmp_path))
    from byteps_tpu.common.config import reset_config
    reset_config()
    # a previous incarnation persisted state ... and died
    store, dur = wal.ensure_process_store()
    store.init_key("w", np.zeros(8, np.float32))
    store.push_delta("w", np.ones(8, np.float32), worker_id=0, seq=1)
    dur.checkpoint(force=True)
    store.push_delta("w", np.ones(8, np.float32), worker_id=0, seq=2)
    want = _digest(store)
    wal._reset_for_tests()          # the process is gone
    assert wal.process_store() is None

    from byteps_tpu.fault.recovery import RecoveryCoordinator
    import byteps_tpu.core.api as api
    monkeypatch.setenv("BYTEPS_HEARTBEAT_ON", "0")
    before = counters.get("recovery.durable_restore")
    try:
        # no api.init() first: the coordinator's resume() performs the
        # re-init, and the durable block must classify this as a
        # restore-from-disk, not a survivor
        coord = RecoveryCoordinator(template={"w": np.zeros(8)})
        res = coord.recover({1})
        assert res.durable is not None
        assert res.durable["had_snapshot"] == 1
        assert res.durable["applied"] >= 1
        restored = wal.process_store()
        assert restored is not None
        assert _digest(restored) == want
        assert counters.get("recovery.durable_restore") == before + 1
    finally:
        api.shutdown()


def test_recovery_coordinator_survivor_keeps_open_store(tmp_path,
                                                        monkeypatch):
    """RecoveryCoordinator composed with the durable plane, survivor
    side: a process that lives through the failure event with its
    durable store OPEN must keep that incarnation — closing and
    re-replaying from disk would orphan every component holding the old
    store object and discard any journal tail the chaos fsync site
    dropped.  The coordinator syncs the live journal instead."""
    monkeypatch.setenv("BYTEPS_DURABLE_DIR", str(tmp_path))
    from byteps_tpu.common.config import reset_config
    reset_config()
    from byteps_tpu.fault.recovery import RecoveryCoordinator
    import byteps_tpu.core.api as api
    monkeypatch.setenv("BYTEPS_HEARTBEAT_ON", "0")
    api.init()                      # opens the process store
    try:
        store, dur = wal.ensure_process_store()
        store.init_key("w", np.zeros(8, np.float32))
        store.push_delta("w", np.ones(8, np.float32), worker_id=0, seq=1)
        want = _digest(store)
        kept = counters.get("recovery.durable_kept")
        restored = counters.get("recovery.durable_restore")
        coord = RecoveryCoordinator(template={"w": np.zeros(8)})
        res = coord.recover({1})
        assert counters.get("recovery.durable_kept") == kept + 1
        assert counters.get("recovery.durable_restore") == restored
        # the SAME incarnation is still open — never closed + re-replayed
        assert wal.process_store() is store
        assert _digest(store) == want
        assert res.durable is not None
        # acknowledged pushes keep landing on the surviving incarnation
        store.push_delta("w", np.ones(8, np.float32), worker_id=0, seq=2)
    finally:
        api.shutdown()


# -- observability surfaces ---------------------------------------------------


def test_obs_debug_state_wal_section(tmp_path):
    store, dur = _mk_store(tmp_path)
    store.push_delta("w", np.ones(8, np.float32), worker_id=0, seq=1)
    from byteps_tpu.common import obs_server
    doc = obs_server.debug_state()
    sections = doc["wal"]
    assert sections and sections[0]["kind"] == "wal"
    assert sections[0]["lsn"] == dur.wal.lsn
    assert sections[0]["fsync"] == "always"
    json.dumps(doc, default=str)
    dur.close()


def test_bps_top_wal_column_and_json_parity(tmp_path):
    from tools.bps_top import _COLUMNS, _wal_cell, render
    assert "WAL" in _COLUMNS
    assert _wal_cell({}) == "-"
    assert _wal_cell({"wal.lag_bytes": 512}) == "512"
    assert _wal_cell({"wal.lag_bytes": 8192}) == "8.0K"
    assert _wal_cell({"wal.lag_bytes": 3 << 20}) == "3.0M"
    cluster = {"epoch": 1, "world": [0], "coordinator": 0,
               "ranks": {0: {"metrics": {"counters": {}, "gauges":
                                         {"wal.lag_bytes": 2048}}}}}
    out = render(cluster)
    assert "WAL" in out and "2.0K" in out


def test_doctor_postmortem_durability_fold(tmp_path):
    from tools.bps_doctor import diagnose_postmortem, render_markdown
    dump = {"rank": 0, "reason": "test", "events": [
        {"t": 2.0, "kind": "wal.recovered", "snapshot_lsn": 14,
         "applied": 6, "truncated_tails": 1, "corrupt_records": 0,
         "dropped_segments": 0},
        {"t": 1.0, "kind": "wal.truncated_tail",
         "segment": "kv-0000000000000001.wal", "offset": 812,
         "reason": "short record body"},
        {"t": 3.0, "kind": "wal.arc_restored", "host": 2,
         "snapshot_id": 9, "keys": 6},
    ]}
    with open(os.path.join(str(tmp_path), "bps_flight_rank0.json"),
              "w") as fh:
        json.dump(dump, fh)
    report = diagnose_postmortem(str(tmp_path))
    kinds = [d["kind"] for d in report["durability"]]
    assert kinds == ["truncated_tail", "recovered", "arc_restored"]
    md = render_markdown(report)
    assert "## Durability / cold start" in md
    assert "restored from local disk" in md
    assert "truncated to the last durable point" in md


# -- serve-host restart-in-place (fleet lane) ---------------------------------


@pytest.mark.chaos
def test_fleet_serve_host_restart_in_place_durable_arc_zero_reship(tmp_path):
    """A serving host cold-restarted against its durable dir publishes
    its persisted arc BEFORE registering; the publisher's arc_info probe
    then seeds the acked view and the next cut ships ZERO bytes — the
    full-arc DCN re-ship is gone from the happy path."""
    from byteps_tpu.server.serving_tier import (ServingHostCore,
                                                ServingTier, TierDirectory,
                                                inproc_host)
    keys = [f"r{i}" for i in range(6)]
    store = KVStore()
    for i, k in enumerate(keys):
        store.init_key(k, np.full(32, float(i), np.float32))
    d = TierDirectory(static_hosts={0: ("127.0.0.1", 1),
                                    1: ("127.0.0.1", 2)})
    inproc_host(ServingHostCore(host_id=0))
    core1 = ServingHostCore(host_id=1, durable_dir=str(tmp_path))
    inproc_host(core1)
    tier = ServingTier(store, directory=d, replicas=1,
                       cut_interval_s=None)
    try:
        snap = tier.cut()
        assert counters.get("wal.arc_saves") >= 1
        committed = core1.debug_state()["snapshot_id"]
        assert committed == snap.id

        # the whole host process "dies"; a new incarnation cold-starts
        # against the SAME durable dir and restores the arc in __init__
        new_core = ServingHostCore(host_id=1, durable_dir=str(tmp_path))
        assert new_core.restored_commit == committed
        assert counters.get("wal.arc_restores") == 1
        inproc_host(new_core)
        # re-register at a NEW address: the publisher sees a new
        # incarnation and drops its acked map (the pre-durable world
        # would now re-ship the full owned slice)
        d.register(("127.0.0.1", 3), host_id=1)

        shipped_before = counters.get("serve.tier_ship_bytes")
        snap2 = tier.cut()
        assert counters.get("serve.tier_ship_bytes") == shipped_before
        assert counters.get("wal.arc_probe_hits") >= 1
        # the restored host committed the new cut entirely from
        # carried-forward refs
        st = new_core.debug_state()
        assert st["snapshot_id"] == snap2.id
        assert st["restored_commit"] == committed
        client = tier.client(max_staleness_s=10.0, stale_on_error=False)
        vals = client.pull()
        for k in keys:
            np.testing.assert_array_equal(vals[k], store.pull(k))
        client.close()
    finally:
        tier.close()


@pytest.mark.chaos
def test_fleet_serve_host_corrupt_arc_quarantined_full_reship(tmp_path):
    """A corrupt on-disk arc is detected, removed, and counted — the
    host starts EMPTY and the publisher's normal un-acked re-ship path
    restores it (degraded, never wrong)."""
    from byteps_tpu.server.serving_tier import ServingHostCore
    core = ServingHostCore(host_id=5, durable_dir=str(tmp_path))
    from byteps_tpu.server.serving import Snapshot
    snap = Snapshot(id=3, ts=time.monotonic(),
                    versions={"a": 1},
                    refs={"a": np.ones(8, np.float32)}, gen=0)
    core._persist_arc(snap)
    path = core._arc_path
    with open(path, "r+b") as fh:
        fh.seek(20)
        fh.write(b"\x00\x01\x02\x03")
    before = counters.get("wal.arc_corrupt")
    core2 = ServingHostCore(host_id=5, durable_dir=str(tmp_path))
    assert core2.restored_commit == 0
    assert counters.get("wal.arc_corrupt") == before + 1
    assert not os.path.exists(path)     # quarantined, never re-read


# -- the headline acceptance: full-world kill, cold restart -------------------


def _run_worker(env, timeout=120):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tests",
                                      "durability_worker.py")],
        env=env, capture_output=True, text=True, timeout=timeout)


def _worker_env(durable_dir, steps=260, **extra):
    env = dict(os.environ)
    env.update({"PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
                "JAX_PLATFORMS": "cpu",
                "BYTEPS_DURABLE_DIR": str(durable_dir),
                "BYTEPS_DUR_STEPS": str(steps),
                "BYTEPS_DUR_CKPT_EVERY": "20"})
    env.pop("BYTEPS_FAULT_SPEC", None)
    env.update(extra)
    return env


def _final(out: str) -> str:
    for line in out.splitlines():
        if line.startswith("FINAL "):
            return line.split()[1]
    raise AssertionError(f"no FINAL line in worker output:\n{out}")


@pytest.mark.chaos
@pytest.mark.slow
def test_durability_full_world_kill_cold_restart_bitexact(tmp_path):
    """Kill the ENTIRE world mid-step (SIGKILL — no atexit, no flush),
    cold-restart from local disk, and finish: the finals must be
    bit-exact against a fault-free run.  The restored dedup floor names
    exactly the deltas folded into the restored arrays
    (journal-before-merge), so resuming at floor+1 double-applies
    nothing and loses nothing, whatever instant the kill landed."""
    # fault-free reference
    ref = _run_worker(_worker_env(tmp_path / "ref"))
    assert ref.returncode == 0, ref.stdout + ref.stderr
    want = _final(ref.stdout)

    # the chaos run: kill the world mid-step
    kdir = tmp_path / "kill"
    env = _worker_env(kdir)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests",
                                      "durability_worker.py")],
        env=env, stdout=subprocess.PIPE, text=True)
    saw_step = None
    t0 = time.monotonic()
    for line in proc.stdout:
        if line.startswith("STEP "):
            saw_step = int(line.split()[1])
            if saw_step >= 100:
                break
        assert time.monotonic() - t0 < 60, "worker never reached step 100"
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait(timeout=30)
    proc.stdout.close()
    assert saw_step is not None and saw_step >= 100

    # cold restart: one process, same dir, zero survivors
    again = _run_worker(_worker_env(kdir))
    assert again.returncode == 0, again.stdout + again.stderr
    stats = json.loads(
        [ln for ln in again.stdout.splitlines()
         if ln.startswith("RECOVERED ")][0][len("RECOVERED "):])
    assert stats["had_snapshot"] == 1      # at least one cut landed
    floor = int([ln for ln in again.stdout.splitlines()
                 if ln.startswith("FLOOR ")][0].split()[1])
    assert floor >= 100                    # restored past the kill point
    assert _final(again.stdout) == want    # bit-exact


@pytest.mark.chaos
def test_durability_cold_restart_after_clean_exit_bitexact(tmp_path):
    """The graceful sibling of the kill test (fast, not slow-marked):
    run to completion, then a second cold start over the same dir must
    restore the exact final state and add nothing (every seq is at or
    below the restored floor)."""
    first = _run_worker(_worker_env(tmp_path, steps=80))
    assert first.returncode == 0, first.stdout + first.stderr
    again = _run_worker(_worker_env(tmp_path, steps=80))
    assert again.returncode == 0, again.stdout + again.stderr
    assert _final(first.stdout) == _final(again.stdout)
    floor = int([ln for ln in again.stdout.splitlines()
                 if ln.startswith("FLOOR ")][0].split()[1])
    assert floor == 80
