"""Expert-parallel MoE tests on the 8-device CPU mesh.

The contract: moe_mlp over an ep axis is the same FUNCTION as
moe_mlp_reference on each token shard with the full expert stacks — the
all_to_all moves placement, never math.  Plus: training (router and
experts both update), capacity-drop semantics, and gradient parity of
the full (dp, ep) step against a hand-computed mean-of-shards objective.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from byteps_tpu.parallel.expert import (
    DP_AXIS, EP_AXIS, init_moe_params, make_dp_ep_train_step, make_ep_mesh,
    moe_mlp, moe_mlp_reference, shard_moe_params)
from .conftest import legacy_skip

H, F, E = 16, 32, 8


def _params(seed=0):
    return init_moe_params(jax.random.PRNGKey(seed), H, F, E)


def _tokens(n, seed=1):
    return jax.random.normal(jax.random.PRNGKey(seed), (n, H), jnp.float32)


def test_reference_shapes_and_capacity_drop():
    p = _params()
    x = _tokens(64)
    out, aux = moe_mlp_reference(x, p, E, capacity_factor=1.25)
    assert out.shape == x.shape and np.isfinite(np.asarray(out)).all()
    assert float(aux) > 0
    # capacity so small that most tokens are dropped -> output rows zero
    out2, _ = moe_mlp_reference(x, p, E, capacity_factor=0.125)
    zero_rows = (np.abs(np.asarray(out2)).sum(axis=1) == 0).sum()
    assert zero_rows > (np.abs(np.asarray(out)).sum(axis=1) == 0).sum()


@pytest.mark.parametrize("n_ep,n_dp", [(4, 2), (8, 1), (2, 4)])
def test_distributed_matches_reference_per_shard(n_ep, n_dp):
    mesh = make_ep_mesh(jax.devices()[:8], n_ep=n_ep)
    full = _params()
    tokens_per_shard = 32
    n_shards = n_dp * n_ep
    x_all = _tokens(tokens_per_shard * n_shards)
    cf = 1.5

    def fwd(p_local, x):
        out, aux = moe_mlp(x, p_local, E, cf, axis_name=EP_AXIS)
        return out, aux[None]

    p_spec = jax.tree_util.tree_map_with_path(
        lambda path, l: P() if path[-1].key == "router" else P(EP_AXIS),
        full)
    mapped = jax.jit(jax.shard_map(
        fwd, mesh=mesh,
        in_specs=(p_spec, P((DP_AXIS, EP_AXIS))),
        out_specs=(P((DP_AXIS, EP_AXIS)), P((DP_AXIS, EP_AXIS)))))
    sharded = shard_moe_params(mesh, full)
    xg = jax.device_put(x_all, NamedSharding(mesh, P((DP_AXIS, EP_AXIS))))
    out, aux = mapped(sharded, xg)
    out, aux = np.asarray(out), np.asarray(aux)

    for g in range(n_shards):
        xs = x_all[g * tokens_per_shard:(g + 1) * tokens_per_shard]
        ref_out, ref_aux = moe_mlp_reference(xs, full, E, cf)
        np.testing.assert_allclose(
            out[g * tokens_per_shard:(g + 1) * tokens_per_shard],
            np.asarray(ref_out), rtol=1e-5, atol=1e-5,
            err_msg=f"shard {g}")
        np.testing.assert_allclose(aux[g], float(ref_aux), rtol=1e-5)


@legacy_skip  # reference-gradient match diverges on pre-VMA shard_map
def test_dp_ep_training_matches_reference_gradients():
    """One step of the (dp, ep) trainer == one step of the hand-built
    mean-of-shards objective on one device."""
    mesh = make_ep_mesh(jax.devices()[:8], n_ep=4)
    full = _params(seed=2)
    n_shards = 8
    tokens_per_shard = 16
    x = _tokens(tokens_per_shard * n_shards, seed=3)
    y = _tokens(tokens_per_shard * n_shards, seed=4)
    cf, aux_w = 1.5, 0.01
    tx = optax.sgd(0.1)

    def shard_loss(out, batch):
        return jnp.mean((out - batch["y"]) ** 2)

    # reference: mean over shards of (mse + aux_w * aux)
    def ref_objective(p):
        tot = 0.0
        for g in range(n_shards):
            xs = x[g * tokens_per_shard:(g + 1) * tokens_per_shard]
            ys = y[g * tokens_per_shard:(g + 1) * tokens_per_shard]
            out, aux = moe_mlp_reference(xs, p, E, cf)
            tot = tot + jnp.mean((out - ys) ** 2) + aux_w * aux
        return tot / n_shards

    loss_ref, g_ref = jax.value_and_grad(ref_objective)(full)
    u, _ = tx.update(g_ref, tx.init(full), full)
    p_ref = optax.apply_updates(full, u)

    step = make_dp_ep_train_step(mesh, E, cf, tx, shard_loss,
                                 aux_weight=aux_w, donate=False)
    p_ep = shard_moe_params(mesh, full)
    o_ep = jax.jit(tx.init)(p_ep)
    batch = jax.device_put({"x": x, "y": y},
                           NamedSharding(mesh, P((DP_AXIS, EP_AXIS))))
    p_ep, o_ep, loss_ep = step(p_ep, o_ep, batch)

    np.testing.assert_allclose(float(loss_ep), float(loss_ref),
                               rtol=1e-5, atol=1e-6)
    for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(p_ref),
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_leaves_with_path(
                jax.device_get(p_ep)), key=lambda kv: str(kv[0]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5, err_msg=str(ka))


def test_dp_ep_trains_and_stays_sharded():
    mesh = make_ep_mesh(jax.devices()[:8], n_ep=4)
    full = _params(seed=5)
    x = _tokens(128, seed=6)
    tx = optax.adam(3e-3)

    def shard_loss(out, batch):
        return jnp.mean((out - batch["y"]) ** 2)

    # donation + CPU device_put aliasing would delete `full`'s buffers;
    # snapshot the router before training for the learned-delta check
    router0 = np.array(full["router"])
    step = make_dp_ep_train_step(mesh, E, 1.5, tx, shard_loss)
    p = shard_moe_params(mesh, full)
    o = jax.jit(tx.init)(p)
    batch = jax.device_put(
        {"x": x, "y": jnp.tanh(x[:, ::-1])},
        NamedSharding(mesh, P((DP_AXIS, EP_AXIS))))
    losses = []
    for _ in range(25):
        p, o, loss = step(p, o, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[::6]
    w1 = p["w1"]
    assert w1.addressable_shards[0].data.shape[0] * 4 == w1.shape[0]
    # router actually learned (replicated, updated via summed cotangents)
    assert float(np.abs(np.asarray(p["router"]) - router0).max()) > 0
