"""Driver-contract tests: __graft_entry__.dryrun_multichip must compile and
execute the full DP train step on virtual meshes, and bench.py must emit its
JSON line (CPU smoke path)."""

import json
import subprocess
import sys

import pytest
from .conftest import legacy_skip


@legacy_skip  # dry-run subprocess uses bare jax.shard_map
def test_dryrun_multichip_8():
    sys.path.insert(0, "/root/repo")
    from __graft_entry__ import dryrun_multichip
    dryrun_multichip(8)


@pytest.mark.slow  # full dry-run compile: tier-1 budget on small CPU hosts
def test_dryrun_multichip_odd():
    sys.path.insert(0, "/root/repo")
    from __graft_entry__ import dryrun_multichip
    dryrun_multichip(5)  # odd count: falls back to flat 1 x n mesh


@pytest.mark.slow  # full bench smoke: minutes of XLA compile on small CPU hosts
def test_bench_smoke_cpu(tmp_path):
    import os
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    # bench.py's outer process probes/benches in subprocesses that only
    # inherit env — an in-process config.update would never reach them
    env["JAX_PLATFORMS"] = "cpu"
    # redirect the artifact writes: a suite run must not overwrite the
    # committed BENCH_FULL record
    env["_BPS_BENCH_REPO"] = str(tmp_path)
    code = (
        "import jax; jax.config.update('jax_platforms','cpu');"
        "import runpy, sys; sys.argv=['bench.py'];"
        "runpy.run_path('/root/repo/bench.py', run_name='__main__')"
    )
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=900,
                         cwd="/root/repo")
    lines = out.stdout.strip().splitlines()
    # full record: the BENCH_FULL stream line + the committed file
    full = [l for l in lines if l.startswith("BENCH_FULL ")]
    assert full, out.stdout + out.stderr
    rec = json.loads(full[-1][len("BENCH_FULL "):])
    assert set(rec) >= {"metric", "value", "unit", "vs_baseline",
                        "push_pull_gbps", "onebit_pallas"}
    assert rec["value"] > 0
    assert any(k.startswith("engine_") for k in rec["push_pull_gbps"])
    assert (tmp_path / "BENCH_FULL.json").exists()
    assert (tmp_path / "BENCH_FULL_LATEST.json").exists()
    # final stdout line: the compact driver summary (rounds 3-4 lost
    # their records to a ~10 kB final line; this contract prevents that)
    last = [l for l in lines if l.startswith("{")][-1]
    compact = json.loads(last)
    sys.path.insert(0, "/root/repo")
    import bench
    assert len(last) <= bench._COMPACT_BUDGET
    assert compact["full_record"] == "BENCH_FULL.json"
    assert compact["value"] == rec["value"]
