"""Driver-contract tests: __graft_entry__.dryrun_multichip must compile and
execute the full DP train step on virtual meshes, and bench.py must emit its
JSON line (CPU smoke path)."""

import json
import subprocess
import sys

import pytest


def test_dryrun_multichip_8():
    sys.path.insert(0, "/root/repo")
    from __graft_entry__ import dryrun_multichip
    dryrun_multichip(8)


def test_dryrun_multichip_odd():
    sys.path.insert(0, "/root/repo")
    from __graft_entry__ import dryrun_multichip
    dryrun_multichip(5)  # odd count: falls back to flat 1 x n mesh


def test_bench_smoke_cpu():
    import os
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    # bench.py's outer process probes/benches in subprocesses that only
    # inherit env — an in-process config.update would never reach them
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import jax; jax.config.update('jax_platforms','cpu');"
        "import runpy, sys; sys.argv=['bench.py'];"
        "runpy.run_path('/root/repo/bench.py', run_name='__main__')"
    )
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=900,
                         cwd="/root/repo")
    lines = [l for l in out.stdout.strip().splitlines() if l.startswith("{")]
    assert lines, out.stdout + out.stderr
    rec = json.loads(lines[-1])
    assert set(rec) >= {"metric", "value", "unit", "vs_baseline",
                        "push_pull_gbps", "onebit_pallas"}
    assert rec["value"] > 0
    assert any(k.startswith("engine_") for k in rec["push_pull_gbps"])
