"""End-to-end framework-adapter tests: the 'ONE model' milestone of
SURVEY.md §7 step 4 — a flax MLP trained data-parallel on the 8-device mesh,
in both engine mode and fused mode, checked for exact data-parallel
equivalence against single-worker full-batch training (the strongest
correctness property of synchronous DP: mean of per-rank grads over equal
shards == grad over the concatenated batch)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import byteps_tpu as bps
import byteps_tpu.jax as bps_jax
from byteps_tpu.models.mlp import mnist_mlp, softmax_cross_entropy


@pytest.fixture
def session():
    bps.init()
    yield
    bps.shutdown()


def _data(n=64, d=16, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype(np.float32)
    y = rng.randint(0, classes, n)
    return jnp.asarray(x), jnp.asarray(y)


def _init_model():
    model = mnist_mlp()
    x, _ = _data()
    params = model.init(jax.random.PRNGKey(0), x[:1])
    return model, params


def _loss_fn(model):
    def loss(params, x, y):
        return softmax_cross_entropy(model.apply(params, x), y)
    return loss


def _reference_training(steps=5, lr=0.1):
    """Single-worker full-batch SGD — the ground truth trajectory."""
    model, params = _init_model()
    loss = _loss_fn(model)
    x, y = _data()
    tx = optax.sgd(lr)
    state = tx.init(params)
    losses = []
    for _ in range(steps):
        l, g = jax.value_and_grad(loss)(params, x, y)
        upd, state = tx.update(g, state)
        params = optax.apply_updates(params, upd)
        losses.append(float(l))
    return params, losses


def test_engine_mode_matches_single_worker(session):
    """DistributedOptimizer over 8 ranks == full-batch single worker."""
    model, params = _init_model()
    loss = _loss_fn(model)
    x, y = _data()
    xs = x.reshape(8, 8, -1)   # 8 ranks x 8 examples
    ys = y.reshape(8, 8)
    opt = bps_jax.DistributedOptimizer(optax.sgd(0.1))
    state = opt.init(params)
    per_rank_grads = jax.jit(jax.vmap(jax.grad(loss), in_axes=(None, 0, 0)))
    for _ in range(5):
        grads = per_rank_grads(params, xs, ys)   # rank-stacked tree
        upd, state = opt.update(grads, state, params)
        params = optax.apply_updates(params, upd)
    ref_params, _ = _reference_training()
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-4, atol=1e-5),
        params, ref_params)


def test_fused_mode_matches_single_worker(session):
    """distributed_optimizer inside shard_map == full-batch single worker."""
    from byteps_tpu.comm.mesh import get_comm
    comm = get_comm()
    model, params = _init_model()
    loss = _loss_fn(model)
    x, y = _data()
    tx = bps_jax.distributed_optimizer(optax.sgd(0.1))
    state = tx.init(params)

    def step(params, state, xb, yb):
        g = jax.grad(loss)(params, xb, yb)
        upd, state = tx.update(g, state, params)
        return optax.apply_updates(params, upd), state

    sharded_step = jax.jit(jax.shard_map(
        step, mesh=comm.mesh,
        in_specs=(P(), P(), P(("dcn", "ici")), P(("dcn", "ici"))),
        out_specs=(P(), P()),
        check_vma=False,
    ))
    for _ in range(5):
        params, state = sharded_step(params, state, x, y)
    ref_params, _ = _reference_training()
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-4, atol=1e-5),
        params, ref_params)


def test_gradient_accumulation(session):
    """backward_passes_per_step=2: two micro batches == one big batch."""
    model, params = _init_model()
    loss = _loss_fn(model)
    x, y = _data()
    xs = x.reshape(2, 8, 4, -1)  # 2 micro x 8 ranks x 4 examples
    ys = y.reshape(2, 8, 4)
    opt = bps_jax.DistributedOptimizer(optax.sgd(0.1),
                                       backward_passes_per_step=2)
    state = opt.init(params)
    per_rank_grads = jax.jit(jax.vmap(jax.grad(loss), in_axes=(None, 0, 0)))
    # micro step 1: zero updates
    upd, state = opt.update(per_rank_grads(params, xs[0], ys[0]), state,
                            params)
    assert all(float(jnp.abs(u).max()) == 0
               for u in jax.tree.leaves(upd))
    params0 = params
    upd, state = opt.update(per_rank_grads(params, xs[1], ys[1]), state,
                            params)
    params = optax.apply_updates(params, upd)
    # reference: one full-batch step
    ref_g = jax.grad(loss)(params0, x, y)
    ref_tx = optax.sgd(0.1)
    ref_upd, _ = ref_tx.update(ref_g, ref_tx.init(params0))
    ref_params = optax.apply_updates(params0, ref_upd)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-4, atol=1e-5),
        params, ref_params)


def test_broadcast_parameters(session):
    _, params = _init_model()
    # fake divergence: stack 8 different versions of one leaf
    stacked = jax.tree.map(
        lambda p: jnp.stack([p + i for i in range(8)]), params)
    synced = bps_jax.broadcast_parameters(stacked, root=3)
    jax.tree.map(
        lambda s, p: np.testing.assert_allclose(np.asarray(s),
                                                np.asarray(p) + 3, rtol=1e-6),
        synced, params)
    # plain (unstacked) input: passes through root's values
    synced2 = bps_jax.broadcast_parameters(params, root=0)
    jax.tree.map(
        lambda s, p: np.testing.assert_allclose(np.asarray(s), np.asarray(p)),
        synced2, params)


def test_broadcast_optimizer_state(session):
    _, params = _init_model()
    tx = optax.adam(1e-3)
    state = tx.init(params)
    synced = bps_jax.broadcast_optimizer_state(state, root=0)
    # structure preserved, values equal
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
        jax.tree.leaves(state), jax.tree.leaves(synced))


def test_distributed_gradient_tape(session):
    model, params = _init_model()
    loss = _loss_fn(model)
    x, y = _data()
    tape = bps_jax.DistributedGradientTape(loss)
    grads = tape.gradient(params, x.reshape(8, 8, -1), y.reshape(8, 8))
    ref = jax.grad(loss)(params, x, y)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-4, atol=1e-5),
        grads, ref)


def test_push_pull_tree_roundtrip(session):
    tree = {"a": jnp.ones((8, 3)), "b": {"c": jnp.full((8, 2, 2), 2.0)}}
    out = bps_jax.push_pull(tree, "t", op="sum")
    np.testing.assert_allclose(np.asarray(out["a"]), 8.0)
    np.testing.assert_allclose(np.asarray(out["b"]["c"]), 16.0)
    assert out["a"].shape == (3,)


def test_fused_step_accum_matches_full_batch(session):
    """make_dp_train_step(accum_steps=k): scanning k microbatches locally
    with one push_pull at the end == the one-shot full-batch step (the
    reference's backward_passes_per_step, in the fused path)."""
    from byteps_tpu.comm.mesh import get_comm
    from byteps_tpu.parallel import make_dp_train_step, replicate, shard_batch

    comm = get_comm()
    model, params = _init_model()
    loss = _loss_fn(model)
    x, y = _data()
    tx = optax.adam(1e-2)

    def loss_fn(p, b):
        return loss(p, b["x"], b["y"])

    results = {}
    for k in (1, 2, 4):
        step = make_dp_train_step(comm, loss_fn, tx, donate=False,
                                  accum_steps=k)
        p = replicate(comm, params)
        o = replicate(comm, tx.init(params))
        b = shard_batch(comm, {"x": x, "y": y})
        losses = []
        for _ in range(3):
            p, o, l_ = step(p, o, b)
            losses.append(float(l_))
        results[k] = (losses, p)

    for k in (2, 4):
        np.testing.assert_allclose(results[k][0], results[1][0],
                                   rtol=1e-5, atol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            results[k][1], results[1][1])


def test_fused_step_accum_bf16_loss(session):
    """accum_steps > 1 with a bf16-returning loss_fn must trace: the scan
    carry accumulates the loss in f32 regardless of the loss dtype
    (round-2 advisor finding: a weak-typed 0.0 carry flipped dtype after
    the first add and failed lax.scan's carry check)."""
    from byteps_tpu.comm.mesh import get_comm
    from byteps_tpu.parallel import make_dp_train_step, replicate, shard_batch

    comm = get_comm()
    model, params = _init_model()
    loss = _loss_fn(model)
    x, y = _data()
    tx = optax.adam(1e-2)

    def bf16_loss_fn(p, b):
        return loss(p, b["x"], b["y"]).astype(jnp.bfloat16)

    step = make_dp_train_step(comm, bf16_loss_fn, tx, donate=False,
                              accum_steps=2)
    p = replicate(comm, params)
    o = replicate(comm, tx.init(params))
    b = shard_batch(comm, {"x": x, "y": y})
    p, o, l_ = step(p, o, b)
    assert np.isfinite(float(l_))


def test_llama_dp_with_distributed_optimizer(session):
    """BASELINE.json configs[4] as literally written: a Llama-family model
    trained through byteps_tpu.jax.distributed_optimizer wrapping optax —
    plain DP over the mesh (the composite (fsdp, tp) path has its own
    suite in test_llama.py)."""
    from jax import lax

    from byteps_tpu.comm.mesh import get_comm
    from byteps_tpu.models.llama import Llama, llama_tiny_f32, lm_loss
    from byteps_tpu.parallel.long_context import synthetic_lm_batch

    comm = get_comm()
    cfg = llama_tiny_f32()
    model = Llama(cfg)
    rng = jax.random.PRNGKey(2)
    batch = synthetic_lm_batch(rng, cfg, batch=8, seq_len=16)
    params = model.init(rng, batch["input_ids"][:1])
    tx = bps_jax.distributed_optimizer(optax.adam(1e-2))
    state = tx.init(params)

    def step(p, s, ids, labels):
        def loss_fn(q):
            return lm_loss(model.apply(q, ids), labels)

        loss, g = jax.value_and_grad(loss_fn)(p)
        upd, s = tx.update(g, s, p)   # grads reduced across the mesh here
        return (optax.apply_updates(p, upd), s,
                lax.pmean(loss, ("dcn", "ici")))

    sharded = jax.jit(jax.shard_map(
        step, mesh=comm.mesh,
        in_specs=(P(), P(), P(("dcn", "ici")), P(("dcn", "ici"))),
        out_specs=(P(), P(), P()),
        check_vma=False))
    losses = []
    for _ in range(6):
        params, state, loss = sharded(params, state, batch["input_ids"],
                                      batch["labels"])
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses
