"""SWIM gossip plane pins (ISSUE 17, fault/gossip.py).

In-process coverage of the partition-tolerant control plane: merge
precedence and incarnation refutation, the suspicion state machine
under a fake clock, strict-majority quorum math (the split-brain
predicate), 64-rank convergence over :class:`InMemoryWire`, the
``quorum_loss`` health rule, the ``partition:ranks=A|B`` chaos site,
the bus ``gossip`` verb's frame clamp, and the observability surfaces
(bps_top / bps_doctor / cluster_metrics) that answer from the table.

The multi-process split-brain proof lives in tests/test_partition.py.
"""

import socket
import time

import pytest

from byteps_tpu.common import flight_recorder as _flight
from byteps_tpu.common.config import get_config, reset_config
from byteps_tpu.common.telemetry import counters
from byteps_tpu.fault.gossip import (ALIVE, DEAD, PARKED, SUSPECT,
                                     GossipAgent, GossipTable,
                                     InMemoryWire, quorum_ok)

from .conftest import free_port as _free_port


def _wire_fn(wire, rank, clock=None):
    """Adapt InMemoryWire.exchange to the agent's (peer, digest) shape.
    ``clock`` (a {"now": t} cell) keeps the peer-side merges on the same
    fake clock the sweeps run on — mixing wall-clock progress stamps
    with fake-clock sweeps would mis-age every entry."""
    if clock is None:
        return lambda peer, digest: wire.exchange(rank, peer, digest)
    return lambda peer, digest: wire.exchange(rank, peer, digest,
                                              now=clock["now"])


# ---------------------------------------------------------------- quorum


def test_quorum_ok_strict_majority_truth_table():
    last = (0, 1, 2)
    assert quorum_ok((0, 1), last)          # 2 of 3
    assert quorum_ok((0, 1, 2), last)       # full world
    assert not quorum_ok((0,), last)        # 1 of 3
    assert not quorum_ok((), last)
    # the even-split proof: 2-of-4 is NOT a quorum, so neither half of
    # an even partition can commit an epoch — strictness is the point
    assert not quorum_ok((0, 1), (0, 1, 2, 3))
    assert quorum_ok((0, 1, 2), (0, 1, 2, 3))
    # a growing world is always a quorum of the smaller last world
    assert quorum_ok((0, 1, 2, 3), (0, 1))


# ----------------------------------------------------- merge precedence


def test_gossip_merge_precedence_incarnation_state_heartbeat():
    now = time.monotonic()
    t = GossipTable(0, (0, 1), now=now)
    # same incarnation: the more-damning state wins
    t.merge({"from": 9, "entries": {1: {"inc": 0, "state": SUSPECT,
                                        "hb": 0}}}, now=now)
    assert t.state_of(1) == SUSPECT
    # same incarnation, LESS damning: the stale happy claim loses
    t.merge({"from": 9, "entries": {1: {"inc": 0, "state": ALIVE,
                                        "hb": 5}}}, now=now)
    assert t.state_of(1) == SUSPECT
    # higher incarnation wins outright, even back to alive (refutation)
    t.merge({"from": 9, "entries": {1: {"inc": 1, "state": ALIVE,
                                        "hb": 1}}}, now=now)
    assert t.snapshot()[1] == {"inc": 1, "state": ALIVE, "hb": 1}
    # equal inc + equal state: higher heartbeat is the only progress
    t.merge({"from": 9, "entries": {1: {"inc": 1, "state": ALIVE,
                                        "hb": 7}}}, now=now)
    assert t.snapshot()[1]["hb"] == 7
    t.merge({"from": 9, "entries": {1: {"inc": 1, "state": ALIVE,
                                        "hb": 3}}}, now=now)
    assert t.snapshot()[1]["hb"] == 7
    # unknown rank in a digest = an observed join
    t.merge({"from": 9, "entries": {5: {"inc": 0, "state": ALIVE,
                                        "hb": 2}}}, now=now)
    assert t.state_of(5) == ALIVE
    # garbage states are ignored, not merged
    t.merge({"from": 9, "entries": {1: {"inc": 9, "state": "zombie",
                                        "hb": 0}}}, now=now)
    assert t.snapshot()[1]["inc"] == 1


def test_gossip_refutation_outbids_the_accusation():
    now = time.monotonic()
    t = GossipTable(2, (0, 1, 2), now=now)
    # someone claims WE are dead at our own incarnation: out-bid it
    t.merge({"from": 0, "entries": {2: {"inc": 0, "state": DEAD,
                                        "hb": 0}}}, now=now)
    me = t.snapshot()[2]
    assert me["state"] == ALIVE and me["inc"] == 1
    assert counters.get("gossip.refutations") == 1
    kinds = [e["kind"] for e in _flight.recorder.snapshot()]
    assert "gossip.refuted" in kinds
    # an accusation BELOW our incarnation is stale — no bump needed
    t.merge({"from": 0, "entries": {2: {"inc": 0, "state": SUSPECT,
                                        "hb": 0}}}, now=now)
    assert t.snapshot()[2] == me
    assert counters.get("gossip.refutations") == 1


def test_gossip_parked_rank_never_refutes():
    """A parked rank KNOWS it is out of the world (minority side of a
    partition) — it must not gossip itself back to alive."""
    now = time.monotonic()
    t = GossipTable(0, (0, 1), now=now)
    t.mark(0, PARKED, now=now)
    t.merge({"from": 1, "entries": {0: {"inc": 5, "state": SUSPECT,
                                        "hb": 0}}}, now=now)
    assert t.state_of(0) == PARKED
    assert counters.get("gossip.refutations") == 0


def test_gossip_beat_self_refutes_a_slept_through_accusation():
    now = time.monotonic()
    t = GossipTable(1, (0, 1), now=now)
    t.snapshot()  # sanity
    t._entries[1]["state"] = SUSPECT  # accusation merged while we slept
    t.beat(now=now)
    me = t.snapshot()[1]
    assert me["state"] == ALIVE and me["inc"] == 1 and me["hb"] == 1


# ------------------------------------------------- suspicion state machine


def test_gossip_sweep_suspect_then_dead_on_fake_clock():
    now = time.monotonic()
    t = GossipTable(0, (0, 1), suspect_s=1.0, dead_s=2.0, now=now)
    assert t.sweep(now=now + 0.5) == {}
    assert t.sweep(now=now + 1.0) == {1: SUSPECT}
    assert t.state_of(1) == SUSPECT
    assert counters.get("gossip.suspect") == 1
    # suspect holds through the refutation window...
    assert t.sweep(now=now + 2.5) == {}
    # ...then dies dead_s after suspicion onset
    assert t.sweep(now=now + 3.0) == {1: DEAD}
    assert counters.get("gossip.dead") == 1
    assert t.alive_ranks() == [0]
    assert t.reachable_ranks() == [0]
    kinds = [e["kind"] for e in _flight.recorder.snapshot()]
    assert kinds.count("gossip.state") == 2
    # the local rank never sweeps itself
    assert t.sweep(now=now + 99.0) == {}


def test_gossip_heartbeat_progress_defers_suspicion():
    now = time.monotonic()
    t = GossipTable(0, (0, 1), suspect_s=1.0, dead_s=2.0, now=now)
    t.merge({"from": 1, "entries": {1: {"inc": 0, "state": ALIVE,
                                        "hb": 3}}}, now=now + 0.9)
    assert t.sweep(now=now + 1.5) == {}  # progress reset the timer
    assert t.sweep(now=now + 1.9) == {1: SUSPECT}
    assert t.reachable_ranks() == [0, 1]  # suspect still counts


def test_gossip_mark_and_add_rank_revival_bump_incarnation():
    now = time.monotonic()
    t = GossipTable(0, (0, 1), now=now)
    t.mark(1, DEAD, now=now)
    assert t.snapshot()[1] == {"inc": 1, "state": DEAD, "hb": 0}
    # a rejoin admitted by the bus revives with a HIGHER incarnation so
    # the revival beats the stale death claim still circulating
    t.add_rank(1, now=now)
    assert t.snapshot()[1]["inc"] == 2
    assert t.state_of(1) == ALIVE
    # add_rank on a healthy entry is a no-op
    t.add_rank(1, now=now)
    assert t.snapshot()[1]["inc"] == 2
    with pytest.raises(ValueError, match="unknown gossip state"):
        t.mark(1, "zombie", now=now)


# ----------------------------------------------------------- payloads


def test_gossip_payload_versioning_highest_wins():
    now = time.monotonic()
    a = GossipTable(0, (0, 1), now=now)
    b = GossipTable(1, (0, 1), now=now)
    a.set_payload("metrics", {"t": 1.0, "v": {"step": 1}})
    a.set_payload("metrics", {"t": 2.0, "v": {"step": 2}})  # ver 2
    b.merge(a.digest(), now=now)
    assert b.payload(0, "metrics")["v"] == {"step": 2}
    # a stale lower-version replay does not roll the value back
    b.merge({"from": 0, "entries": {},
             "payloads": {"0/metrics": [1, {"t": 1.0,
                                            "v": {"step": 1}}]}}, now=now)
    assert b.payload(0, "metrics")["v"] == {"step": 2}
    b.set_payload("metrics", {"t": 3.0, "v": {"step": 9}})
    assert set(b.payloads_of_kind("metrics")) == {0, 1}


# -------------------------------------------------------- convergence


@pytest.mark.chaos
def test_gossip_convergence_64_ranks_join_and_death_subsecond():
    """64 tables on the in-memory wire: a join and a death both reach
    every table in well under a second of wall clock."""
    n = 64
    wire = InMemoryWire()
    now0 = time.monotonic()
    clock = {"now": now0}
    # ranks 0..62 start without rank 63 (it joins via dissemination)
    tables = {r: GossipTable(r, range(n - 1), suspect_s=1e9, dead_s=1e9,
                             now=now0) for r in range(n - 1)}
    tables[n - 1] = GossipTable(n - 1, range(n), suspect_s=1e9,
                                dead_s=1e9, now=now0)
    agents = {}
    for r, t in tables.items():
        wire.register(t)
        agents[r] = GossipAgent(t, _wire_fn(wire, r, clock), fanout=3,
                                seed=r)

    t_start = time.monotonic()
    for rnd in range(1, 40):
        clock["now"] = now0 + 0.01 * rnd
        for r in range(n):
            agents[r].step(now=clock["now"])
        if all(t.state_of(n - 1) == ALIVE for t in tables.values()):
            break
    assert all(t.state_of(n - 1) == ALIVE for t in tables.values()), \
        "join did not disseminate to every table"

    # rank 7 is killed; rank 0 observes it out-of-band (bus eviction)
    tables[0].mark(7, DEAD, now=now0 + 1.0)
    for rnd in range(1, 40):
        clock["now"] = now0 + 1.0 + 0.01 * rnd
        for r in range(n):
            if r == 7:
                continue  # dead ranks don't gossip (and can't refute)
            agents[r].step(now=clock["now"])
        if all(tables[r].state_of(7) == DEAD
               for r in range(n) if r != 7):
            break
    assert all(tables[r].state_of(7) == DEAD for r in range(n) if r != 7)
    elapsed = time.monotonic() - t_start
    assert elapsed < 1.0, f"convergence took {elapsed:.2f}s"


@pytest.mark.chaos
def test_gossip_gray_suspect_refutes_after_wire_heals():
    """A rank severed long enough to be suspected un-suspects itself by
    incarnation bump once the wire heals — it is gray, not dead."""
    now0 = time.monotonic()
    clock = {"now": now0}
    wire = InMemoryWire()
    tables = {r: GossipTable(r, (0, 1, 2), suspect_s=0.2, dead_s=10.0,
                             now=now0) for r in range(3)}
    agents = {r: GossipAgent(tables[r], _wire_fn(wire, r, clock),
                             fanout=2, seed=r) for r in range(3)}
    for t in tables.values():
        wire.register(t)

    wire.cut({2}, {0, 1})
    for k in range(1, 5):
        clock["now"] = now0 + 0.1 * k
        for r in range(3):
            agents[r].step(now=clock["now"])
    assert tables[0].state_of(2) == SUSPECT
    assert tables[1].state_of(2) == SUSPECT

    wire.heal()
    for k in range(5, 10):
        clock["now"] = now0 + 0.1 * k
        for r in range(3):
            agents[r].step(now=clock["now"])
    for t in tables.values():
        assert all(t.state_of(r) == ALIVE for r in range(3)), t.snapshot()
    # the un-suspect was a refutation (incarnation out-bid), not decay
    assert tables[0].snapshot()[2]["inc"] >= 1
    assert counters.get("gossip.refutations") >= 1


# ------------------------------------------------- quorum_loss health rule


class _StubStore:
    interval_s = 1.0

    def points(self):
        return [{"steps": 0}]

    def values(self, key):
        return []


def test_quorum_view_and_health_quorum_loss_rule():
    from byteps_tpu.common import health
    from byteps_tpu.common.health import HealthEngine
    now = time.monotonic()
    table = GossipTable(0, (0, 1, 2), now=now)
    agent = GossipAgent(table, lambda peer, digest: None,
                        world_fn=lambda: (0, 1, 2))
    agent.register_health_provider()
    try:
        engine = HealthEngine(get_config())
        store = _StubStore()
        # full world reachable: no breach
        assert engine._breaches(store)["quorum_loss"] is None
        # a suspect rank still counts toward quorum (gray, refutable)
        table.mark(1, SUSPECT, now=now)
        assert agent.quorum_view() == {"reachable": 3, "world": 3}
        assert engine._breaches(store)["quorum_loss"] is None
        # losing the strict majority of the last agreed world breaches
        table.mark(1, DEAD, now=now)
        table.mark(2, DEAD, now=now)
        assert agent.quorum_view() == {"reachable": 1, "world": 3}
        assert engine._breaches(store)["quorum_loss"] == {
            "reachable": 1, "world": 3}
        # K-window hysteresis before the alert fires
        for _ in range(engine.k):
            engine.evaluate(store)
        assert "quorum_loss" in engine.active_alerts()
        # heal: K clear windows retire it
        table.mark(1, ALIVE, now=now)
        table.mark(2, ALIVE, now=now)
        for _ in range(engine.k):
            engine.evaluate(store)
        assert "quorum_loss" not in engine.active_alerts()
    finally:
        agent.stop()
    assert health._quorum_provider is None  # stop() unregistered it


# --------------------------------------------- partition chaos site


def test_partition_spec_parse_validation():
    from byteps_tpu.fault.injector import parse_spec
    rules = parse_spec("partition:ranks=0|1.2:ms=500")
    assert len(rules) == 1
    with pytest.raises(ValueError, match="non-empty"):
        parse_spec("partition:ranks=|1")
    with pytest.raises(ValueError, match="overlap"):
        parse_spec("partition:ranks=0.1|1.2")
    with pytest.raises(ValueError, match="ms"):
        parse_spec("partition:ranks=0|1:ms=-5")


def test_partition_edge_cut_is_per_edge_and_heals_once():
    from byteps_tpu.fault import injector
    injector.arm("partition:ranks=0|1.2:ms=80", rank=0)
    try:
        assert injector.edge_cut(1)      # crosses the cut; starts clock
        assert injector.edge_cut(2)
        assert not injector.edge_cut(0)  # same side: edge stays open
        assert not injector.edge_cut(7)  # rank outside either side
        assert counters.get("fault.partition") == 1
        time.sleep(0.15)
        # heal is lazy (evaluated at the call site) and one-shot
        assert not injector.edge_cut(1)
        assert not injector.edge_cut(2)
        assert counters.get("fault.partition_healed") == 1
        kinds = [e["kind"] for e in _flight.recorder.snapshot()]
        assert "fault.partition" in kinds
        assert "fault.partition_healed" in kinds
    finally:
        injector.disarm()


# ------------------------------------------ bus verb: frame clamp (sat 4)


def test_gossip_verb_oversize_reply_names_frame_knob(monkeypatch):
    """A gossip digest reply inflated past BYTEPS_BUS_MAX_FRAME (huge
    piggybacked payload) must answer with a SMALL error naming the knob
    — not close silently and strand the anti-entropy loop retrying."""
    from byteps_tpu.fault import membership as mem
    monkeypatch.setenv("BYTEPS_BUS_MAX_FRAME", "4096")
    reset_config()
    srv = mem._BusServer(("127.0.0.1", _free_port()),
                         mem.MembershipView(0, (0, 1)), 1.0, 1.0)
    try:
        table = GossipTable(0, (0, 1))
        table.set_payload("history", "h" * 1_000_000)
        srv.gossip_table = table
        conn = socket.create_connection(srv.addr, timeout=5)
        try:
            mem._send_obj(conn, {"op": "gossip", "rank": 1,
                                 "digest": GossipTable(1, (0, 1)).digest()})
            reply = mem._recv_obj(conn)
        finally:
            conn.close()
        assert reply["ok"] is False
        assert "BYTEPS_BUS_MAX_FRAME" in reply["error"]
    finally:
        srv.close()


# ------------------------------------------- observability surfaces


def test_partition_incident_from_synthetic_events():
    from tools.bps_doctor import _partition_incident
    faults = [
        {"t": 100.0, "rank": 1, "kind": "partition",
         "detail": {"side_a": [0], "side_b": [1, 2]}},
        {"t": 110.5, "rank": 1, "kind": "partition_healed",
         "detail": {"side_a": [0], "side_b": [1, 2],
                    "after_ms": 10500.0}},
    ]
    parks = [{"t": 101.0, "rank": 0, "kind": "partition_minority",
              "detail": {"epoch": 0}}]
    inc = _partition_incident(faults, parks)
    assert inc["side_a"] == [0] and inc["side_b"] == [1, 2]
    assert inc["parked_ranks"] == [0]
    assert inc["healed"] is True
    assert inc["split_ms"] == 10500.0
    # an unhealed split still reports both sides and the parked minority
    inc = _partition_incident(faults[:1], parks)
    assert inc["healed"] is False and "split_ms" not in inc
    assert _partition_incident([], []) is None


def test_bps_top_renders_gossip_states_and_banner():
    from tools.bps_top import render
    out = render({
        "epoch": 2, "world": [0, 1, 2], "coordinator": 0, "standby": 1,
        "gossip": True,
        "states": {0: {"inc": 0, "state": "alive", "hb": 9},
                   1: {"inc": 1, "state": "suspect", "hb": 4},
                   2: {"inc": 2, "state": "parked", "hb": 0}},
        "ranks": {}, "history": {},
    })
    assert "gossip view (no bus round-trip)" in out
    assert "suspect" in out
    assert "parked" in out


def test_cluster_metrics_answers_from_gossip_table(monkeypatch):
    """With BYTEPS_GOSSIP_ON, cluster_metrics() is answered from the
    local SWIM table — no bus round-trip, so it keeps working on either
    side of a partition."""
    from byteps_tpu.core import api
    from byteps_tpu.fault import membership as mem
    monkeypatch.setenv("BYTEPS_GOSSIP_ON", "1")
    monkeypatch.setenv("BYTEPS_GOSSIP_INTERVAL_S", "30")
    reset_config()
    mem._reset_epoch_for_tests()
    m = mem.ElasticMembership(0, [0],
                              f"127.0.0.1:{_free_port()}").start()
    try:
        assert m.gossip is not None
        m.gossip.set_payload("metrics",
                             {"t": time.time(), "v": {"step": 3}})
        out = api.cluster_metrics()
        assert out["gossip"] is True
        assert out["states"][0]["state"] == "alive"
        assert out["ranks"][0]["metrics"] == {"step": 3}
    finally:
        m.stop()
        mem._reset_epoch_for_tests()
