"""Lock-order witness (byteps_tpu/common/lock_witness.py, ISSUE 13).

The acceptance pin: an AB/BA acquisition pattern across two threads
raises :class:`LockOrderError` at the second thread's acquire — before
the deadlock — and the message names BOTH witnessed code sites.
"""

import re
import threading

import pytest

from byteps_tpu.common import lock_witness as lw


@pytest.fixture(autouse=True)
def _armed_witness():
    lw._force_for_tests(True)
    lw.reset_witness_for_tests()
    yield
    lw._force_for_tests(None)
    lw.reset_witness_for_tests()


def test_disabled_returns_plain_locks():
    lw._force_for_tests(False)
    plain = lw.named_lock("x")
    # a bare threading lock: no wrapper attribute, no witness cost
    assert not isinstance(plain, lw._WitnessLock)
    r = lw.named_lock("x", reentrant=True)
    r.acquire(); r.acquire(); r.release(); r.release()


def test_consistent_order_never_raises():
    a = lw.named_lock("WA")
    b = lw.named_lock("WB")
    errs = []

    def worker():
        try:
            for _ in range(50):
                with a:
                    with b:
                        pass
        except lw.LockOrderError as e:  # pragma: no cover - failure path
            errs.append(e)

    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    assert ("WA", "WB") in lw.witness_edges()


def test_ab_ba_cycle_raises_naming_both_sites():
    a = lw.named_lock("WA")
    b = lw.named_lock("WB")
    recorded = threading.Event()
    errs = []

    def t1():
        with a:
            with b:          # records WA -> WB at THIS line
                pass
        recorded.set()

    def t2():
        recorded.wait(5)
        try:
            with b:
                with a:      # closes the cycle: raises HERE
                    pass
        except lw.LockOrderError as e:
            errs.append(str(e))

    x = threading.Thread(target=t1)
    y = threading.Thread(target=t2)
    x.start(); y.start(); x.join(5); y.join(5)
    assert len(errs) == 1, "the reversed acquisition must raise"
    msg = errs[0]
    # both lock names and both witnessed sites (two distinct lines of
    # THIS file) are in the message — the operator sees where each
    # ordering was established, not just that a cycle exists
    assert "'WA'" in msg and "'WB'" in msg
    lines = {int(m) for m in
             re.findall(r"test_lock_witness\.py:(\d+)", msg)}
    assert len(lines) >= 2, msg
    # and the second thread did NOT deadlock: both locks are free again
    assert a.acquire(blocking=False)
    a.release()
    assert b.acquire(blocking=False)
    b.release()


def test_transitive_cycle_detected():
    a, b, c = (lw.named_lock(n) for n in ("TA", "TB", "TC"))
    done = threading.Event()

    def chain():
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        done.set()

    t = threading.Thread(target=chain)
    t.start(); t.join(5)
    assert done.is_set()
    with pytest.raises(lw.LockOrderError):
        with c:
            with a:
                pass


def test_reentrant_reacquire_is_not_a_cycle():
    r = lw.named_lock("WR", reentrant=True)
    other = lw.named_lock("WO")
    with r:
        with other:
            with r:          # re-entry: no WO -> WR ordering event
                pass
    assert ("WO", "WR") not in lw.witness_edges()
    assert ("WR", "WO") in lw.witness_edges()


def test_try_acquire_skips_order_check():
    a = lw.named_lock("QA")
    b = lw.named_lock("QB")
    with a:
        with b:
            pass
    with b:
        # non-blocking acquire against the recorded order: deadlock-free
        # by construction, so no raise — and no reverse edge recorded
        assert a.acquire(blocking=False)
        a.release()
    assert ("QB", "QA") not in lw.witness_edges()


def test_condition_wait_through_witnessed_lock():
    cv = threading.Condition(lw.named_lock("WCV", reentrant=True))
    hits = []

    def waiter():
        with cv:
            if cv.wait(timeout=5):
                hits.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    # let the waiter park (wait() fully releases the witnessed lock)
    for _ in range(500):
        with cv:
            parked = bool(cv._waiters)
        if parked:
            break
        threading.Event().wait(0.01)
    with cv:
        cv.notify_all()
    t.join(5)
    assert hits == [1]


def test_installed_config_arms_witness_without_env(monkeypatch):
    # review regression: Config.lock_witness must be LIVE —
    # set_config(Config(lock_witness=True)) arms locks constructed
    # after it, with no env var exported
    from byteps_tpu.common.config import Config, reset_config, set_config
    lw._force_for_tests(None)
    monkeypatch.delenv("BYTEPS_LOCK_WITNESS", raising=False)
    try:
        set_config(Config(lock_witness=True))
        assert isinstance(lw.named_lock("cfg_armed"), lw._WitnessLock)
        set_config(Config(lock_witness=False))
        assert not isinstance(lw.named_lock("cfg_off"), lw._WitnessLock)
        # env-backed default: an explicit Config built under the chaos
        # lanes' exported var stays armed
        monkeypatch.setenv("BYTEPS_LOCK_WITNESS", "1")
        set_config(Config())
        assert isinstance(lw.named_lock("env_default"), lw._WitnessLock)
    finally:
        reset_config()
        lw._force_for_tests(True)


def test_adopted_components_construct_witnessed():
    # the high-traffic locks adopt named_lock: with the witness forced
    # on, a fresh registry/store construct witnessed locks (the chaos
    # lanes run this way end to end)
    from byteps_tpu.common.metrics import MetricsRegistry
    r = MetricsRegistry()
    assert isinstance(r._lock, lw._WitnessLock)
    r.inc("x")
    assert r.get_counter("x") == 1
    from byteps_tpu.server.kv_store import KVStore
    s = KVStore()
    assert isinstance(s._lock, lw._WitnessLock)
