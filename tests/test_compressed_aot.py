"""Fused quantized collectives on the AOT hot path (ISSUE 11).

The tentpole's contract, pinned here:

- **zero compiles at steady state, compressed**: a tensor declared WITH
  ``compression=`` pre-lowers and compiles its whole steady-state
  program family at declare time — in-graph chunk slice, quantize,
  quantized-payload gather, dequant-accumulate, merged re-quantize,
  error-feedback state update — and a compressed push stream then
  triggers ZERO new cache programs;
- **engine wiring correctness**: the fused multi-chunk path (staged
  flat + traced offsets + per-chunk codec state) matches the per-chunk
  compression pipeline computed directly from the codec module;
- **declare-time validation**: a bad codec name / decorator value /
  parameter fails at declare or enqueue with a ValueError in the
  caller's stack, and the local-fast-path rejection names the supported
  alternative (the old ``_CompressionSlot`` cold-path satellites);
- **the compressor ladder**: per size bucket the planner explores
  none/onebit/randomk/topk (with EF) round-robin, gates candidates on
  the codec-golden error ceiling, locks by measured wall time, and
  never tunes pinned tensors or multi-process worlds;
- **elastic interaction**: a compressed push crossing a membership
  epoch change drops-not-sums, at the engine AND the server engine.
"""

import numpy as np
import pytest

import byteps_tpu as bps
from byteps_tpu.common.config import Config, set_config
from byteps_tpu.common.scheduler import COMPRESS_LADDER, ChunkPlanner
from byteps_tpu.common.telemetry import counters, gauges

ONEBIT_EF = {"compressor": "onebit", "ef": "vanilla"}


@pytest.fixture
def bps_session():
    bps.init()
    yield bps
    bps.shutdown()


@pytest.fixture
def bps_chunked():
    # 64 KiB partitions: a 160 KB tensor compresses as THREE chunks (two
    # body widths + tail), exercising the per-chunk codec programs and
    # the traced in-graph offsets
    set_config(Config(partition_bytes=65536, min_compress_bytes=4096))
    bps.init()
    yield bps
    bps.shutdown()


@pytest.fixture
def bps_ladder():
    set_config(Config(partition_bytes=16384, partition_pinned=False,
                      credit_pinned=False, compress_autotune=True,
                      min_compress_bytes=4096))
    bps.init()
    yield bps
    bps.shutdown()


def _stacked(x):
    return np.ascontiguousarray(
        np.broadcast_to(np.asarray(x)[None], (bps.size(),) + x.shape))


# ---------------------------------------------------------------- headline


def test_compressed_steady_state_stream_compiles_nothing(bps_chunked):
    """The regression test the acceptance criteria name: declare with
    ``compression=`` -> warm -> N pushes -> compile counter delta == 0.
    The declare-time warm must cover the ENTIRE compressed program set,
    so even the FIRST push is compile-free."""
    eng = bps.core.api._engine
    bps.declare("cz/a", shape=(40_000,), dtype=np.float32,
                compression=ONEBIT_EF)
    ctx = eng.registry.get("cz/a")
    assert len(ctx.chunk_bounds) == 3          # the multi-chunk shape
    assert counters.get("engine.aot_compiled") >= 2   # body + tail codec
    assert counters.get("engine.aot_compile_failed") == 0
    m0 = counters.get("engine.compile_cache_miss")
    rng = np.random.RandomState(0)
    for _ in range(5):
        x = rng.randn(40_000).astype(np.float32)
        out = eng.push_pull_async(_stacked(x), "cz/a", op="sum",
                                  out_shape=(40_000,)).wait()
        out = np.asarray(out)
        assert out.shape == (40_000,) and np.isfinite(out).all()
    assert counters.get("engine.compile_cache_miss") == m0
    assert counters.get("compression.compressed_chunks") >= 15


def test_multichunk_compressed_matches_per_chunk_pipeline(bps_chunked):
    """Engine wiring pin: the fused path (staged flat, in-graph traced
    offsets, per-chunk EF state) must equal the per-chunk compression
    pipeline computed directly from the codec module — all ranks push
    identical rows, so the merged chunk is
    D_s(C_s(R * D_w(C_w(x_chunk))))."""
    import jax.numpy as jnp

    from byteps_tpu.compression import create as create_compressor

    eng = bps.core.api._engine
    R = bps.size()
    rng = np.random.RandomState(7)
    x = rng.randn(40_000).astype(np.float32)
    out = np.asarray(eng.push_pull_async(
        _stacked(x), "cz/m", op="sum", out_shape=(40_000,),
        compression=ONEBIT_EF).wait())
    ctx = eng.registry.get("cz/m")
    assert len(ctx.chunk_bounds) == 3
    exp = np.empty(40_000, np.float32)
    for off, ln in ctx.chunk_bounds:
        wc = create_compressor(ONEBIT_EF, ln)
        sc = create_compressor(ONEBIT_EF, ln, for_server=True)
        p, _ = wc.compress(jnp.asarray(x[off:off + ln]), wc.init_state())
        y = R * np.asarray(wc.decompress(p), np.float32)
        p2, _ = sc.compress(jnp.asarray(y), sc.init_state())
        exp[off:off + ln] = np.asarray(sc.decompress(p2))
    np.testing.assert_allclose(out, exp, rtol=1e-5, atol=1e-6)


# ------------------------------------------------------------- validation


def test_declare_validates_codec_name(bps_session):
    with pytest.raises(ValueError, match="unknown compressor"):
        bps.declare("cz/bad", shape=(65536,),
                    compression={"compressor": "gzip"})


def test_declare_validates_decorator_values(bps_session):
    with pytest.raises(ValueError, match="unknown ef"):
        bps.declare("cz/bad2", shape=(65536,),
                    compression={"compressor": "onebit", "ef": "vanila"})
    with pytest.raises(ValueError, match="unknown momentum"):
        bps.declare("cz/bad3", shape=(65536,),
                    compression={"compressor": "onebit",
                                 "momentum": "nestorov"})


def test_declare_validates_numeric_params(bps_session):
    with pytest.raises(ValueError, match="invalid compression kwargs"):
        bps.declare("cz/bad4", shape=(65536,),
                    compression={"compressor": "topk", "k": "lots"})


def test_push_validates_codec_at_enqueue(bps_session):
    eng = bps.core.api._engine
    with pytest.raises(ValueError, match="unknown compressor"):
        eng.push_pull_async(
            np.zeros((bps.size(), 1024), np.float32), "cz/bad5",
            compression={"compressor": "nope"})


def test_server_engine_unregistered_codec_is_actionable():
    """The old cold-path failure: push_compressed on an unregistered key
    surfaced as a bare KeyError deep in ``_codec``.  The error now names
    the missing registration call."""
    from byteps_tpu.server.engine import ServerEngine
    eng = ServerEngine(num_threads=1)
    try:
        with pytest.raises(ValueError, match="register_compression"):
            eng.push_compressed("ck", b"x", 0, 1)
    finally:
        eng.shutdown()


def test_local_fast_path_compression_error_names_alternative(bps_session):
    """core/engine.py's local-path rejection must name the knob and the
    supported alternative, not a bare 'excludes compression'."""
    eng = bps.core.api._engine
    with pytest.raises(ValueError, match="push_pull_local"):
        eng.push_pull_async(np.zeros(1024, np.float32), "cz/loc",
                            local=True, compression=ONEBIT_EF)


# ------------------------------------------------------- compressor ladder


def _planner(ceiling=0.55, min_compress=4096, procs=1, autotune=True):
    cfg = Config(partition_bytes=16384, partition_pinned=False,
                 credit_pinned=False, compress_autotune=autotune,
                 compress_error_ceiling=ceiling,
                 min_compress_bytes=min_compress)
    return ChunkPlanner(cfg, num_procs=procs)


def _lock_partition(p, nbytes):
    for _ in range(64):
        if p.locked(nbytes):
            return
        p.observe(nbytes, p.plan_partition(nbytes), 0.001)
    raise AssertionError("partition bucket never locked")


def _feed(p, nbytes, seconds_by_codec, rounds=8):
    for _ in range(rounds):
        kw = p.plan_compression(nbytes)
        key = (kw or {}).get("compressor", "none")
        p.observe_compression(nbytes, key, seconds_by_codec(key))


def test_ladder_locks_none_small_quantized_large():
    """The acceptance demo: under a synthetic slow-wire regime the large
    bucket's quantized candidate wins the wall-time race while the small
    bucket's codec compute dominates — so the planner locks `none` small
    and `onebit` large, with the decision visible in telemetry."""
    p = _planner()
    small, large = 40_000, 4_000_000
    _lock_partition(p, small)
    _lock_partition(p, large)
    _feed(p, small, lambda k: 0.001 if k == "none" else 0.010)
    _feed(p, large, lambda k: 0.002 if k == "onebit" else 0.020)
    assert p.compress_locked(small) and p.compress_locked(large)
    assert p.plan_compression(small) is None
    assert p.plan_compression(large)["compressor"] == "onebit"
    snap = p.snapshot()["compression"]
    assert snap["buckets"][str(small.bit_length())]["locked_codec"] \
        == "none"
    b_large = snap["buckets"][str(large.bit_length())]
    assert b_large["locked_codec"] == "onebit"
    assert set(b_large["explored"]) == {k for k, _ in COMPRESS_LADDER}
    assert b_large["golden_error"]["onebit"] > 0
    assert counters.get("compression.planner_locked") == 2
    locked_gauges = [k for k in gauges.snapshot()
                     if k.startswith("compression.codec_locked{")]
    assert any('codec="onebit"' in k for k in locked_gauges)


def test_ladder_waits_for_partition_lock():
    p = _planner()
    assert p.plan_compression(4_000_000) is None
    assert p.snapshot()["compression"]["buckets"] == {}


def test_ladder_error_ceiling_excludes_candidates():
    """Quality gate: with a 0.2 ceiling, onebit (golden ~0.27) and
    randomk (~0.47) are excluded UP FRONT — never explored — while topk
    (~0.17) stays in the race."""
    p = _planner(ceiling=0.2)
    n = 4_000_000
    _lock_partition(p, n)
    seen = set()
    for _ in range(8):
        kw = p.plan_compression(n)
        key = (kw or {}).get("compressor", "none")
        seen.add(key)
        p.observe_compression(n, key, 0.01)
    assert seen <= {"none", "topk"}
    assert "onebit" not in seen and "randomk" not in seen


def test_ladder_below_cutoff_never_explores():
    """The compression cutoff is checked per TENSOR, not per bucket: a
    below-cutoff tensor is never planned a codec (the engine would
    strip it and re-carve bounds every push), never creates ladder
    state, and reads as locked (nothing to explore)."""
    p = _planner(min_compress=10**9)
    n = 4_000_000
    _lock_partition(p, n)
    assert p.plan_compression(n) is None
    assert p.compress_locked(n)
    p.observe_compression(n, "none", 0.01)       # refused, not recorded
    assert p.snapshot()["compression"]["buckets"] == {}


def test_ladder_bucket_straddling_cutoff():
    """Two tensors in ONE size bucket, one above and one below the
    cutoff: the above-cutoff tensor explores and locks; the
    below-cutoff one keeps planning None throughout (its pushes must
    not churn codecs or pollute the bucket's samples)."""
    p = _planner(min_compress=100_000)
    above, below = 120_000, 80_000               # same bit_length bucket
    assert above.bit_length() == below.bit_length()
    _lock_partition(p, above)
    for _ in range(8):
        assert p.plan_compression(below) is None
        kw = p.plan_compression(above)
        key = (kw or {}).get("compressor", "none")
        p.observe_compression(above, key, 0.01)
        p.observe_compression(below, "none", 0.001)   # refused
    assert p.compress_locked(above)
    assert p.compress_locked(below)              # under cutoff: trivially
    assert p.plan_compression(below) is None


def test_ladder_multiprocess_inert():
    p = _planner(procs=2)
    assert not p.compress_active
    assert p.plan_compression(4_000_000) is None
    assert p.compress_locked(4_000_000)


def test_ladder_off_by_default():
    assert Config().compress_autotune is False
    p = _planner(autotune=False)
    assert not p.compress_active
    assert p.plan_compression(4_000_000) is None


def test_explicit_kwargs_pin_never_tuned(bps_ladder):
    """Pin semantics: a tensor pushed with explicit ``compression=``
    kwargs keeps its codec forever — the ladder never touches it, even
    across later bare pushes."""
    eng = bps.core.api._engine
    x = np.random.RandomState(1).randn(40_000).astype(np.float32)
    eng.push_pull_async(_stacked(x), "pin/c", op="sum",
                        out_shape=(40_000,), compression=ONEBIT_EF).wait()
    ctx = eng.registry.get("pin/c")
    assert ctx.compression_tuned is False
    eng.push_pull_async(_stacked(x), "pin/c", op="sum",
                        out_shape=(40_000,)).wait()
    assert ctx.compression_kwargs == ONEBIT_EF


def test_explicit_kwargs_repin_ladder_owned_tensor(bps_ladder):
    """The converse pin: a tensor FIRST pushed bare (ladder-owned) that
    later receives explicit ``compression=`` kwargs is re-pinned to the
    caller's codec — the planner must not keep retuning a tensor whose
    caller just named a codec (the push would silently ship different
    gradient semantics than asked)."""
    eng = bps.core.api._engine
    x = np.random.RandomState(3).randn(40_000).astype(np.float32)
    eng.push_pull_async(_stacked(x), "repin/c", op="sum",
                        out_shape=(40_000,)).wait()
    ctx = eng.registry.get("repin/c")
    assert ctx.compression_tuned is True
    eng.push_pull_async(_stacked(x), "repin/c", op="sum",
                        out_shape=(40_000,), compression=ONEBIT_EF).wait()
    assert ctx.compression_tuned is False
    assert ctx.compression_kwargs == ONEBIT_EF
    # and it STAYS pinned across later bare pushes
    eng.push_pull_async(_stacked(x), "repin/c", op="sum",
                        out_shape=(40_000,)).wait()
    assert ctx.compression_kwargs == ONEBIT_EF


def test_explicit_kwargs_pin_survives_inflight_push(bps_ladder):
    """A re-pin arriving while another push of the tensor is in flight
    must not be lost: ownership flips immediately, the codec itself is
    recorded as pending and applied at the next idle push."""
    eng = bps.core.api._engine
    x = np.random.RandomState(4).randn(40_000).astype(np.float32)
    eng.push_pull_async(_stacked(x), "repin/f", op="sum",
                        out_shape=(40_000,)).wait()
    ctx = eng.registry.get("repin/f")
    assert ctx.compression_tuned is True
    with ctx.lock:
        ctx.inflight += 1          # a concurrent push holds a claim
    try:
        eng.push_pull_async(_stacked(x), "repin/f", op="sum",
                            out_shape=(40_000,),
                            compression=ONEBIT_EF).wait()
        assert ctx.compression_tuned is False
        assert ctx.compression_pin == ONEBIT_EF      # deferred, not lost
    finally:
        with ctx.lock:
            ctx.inflight -= 1
    eng.push_pull_async(_stacked(x), "repin/f", op="sum",
                        out_shape=(40_000,)).wait()
    assert ctx.compression_pin is None
    assert ctx.compression_kwargs == ONEBIT_EF


def test_refresh_gauges_zeroes_retired_codec(bps_chunked):
    """A ladder retune must not leave the previous codec's
    ``compression.active`` series at 1.0 — the bps_top CODEC column
    would show a codec the tensor no longer uses."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.bps_top import _codec_cell
    eng = bps.core.api._engine
    x = np.random.RandomState(5).randn(40_000).astype(np.float32)
    eng.push_pull_async(_stacked(x), "gz/c", op="sum",
                        out_shape=(40_000,), compression=ONEBIT_EF).wait()
    eng.refresh_compression_gauges()
    assert _codec_cell(gauges.snapshot()) == "onebit"
    ctx = eng.registry.get("gz/c")
    with ctx.lock:
        eng.registry.retune_compression_locked(
            ctx, {"compressor": "topk", "k": "0.25", "ef": "vanilla"},
            eng.cfg.partition_bytes)
    eng._ensure_compression(ctx, np.float32)
    eng.refresh_compression_gauges()
    snap = gauges.snapshot()
    assert snap['compression.active{codec="onebit",tensor="gz/c"}'] == 0.0
    assert snap['compression.active{codec="topk",tensor="gz/c"}'] == 1.0
    assert _codec_cell(snap) == "topk"


def test_engine_ladder_explores_and_applies_locked_codec(bps_ladder):
    """Integration: a bare tensor under the ladder explores every
    candidate across real pushes (codec swapped between pushes at
    inflight == 0), locks, and later pushes carry the locked codec."""
    eng = bps.core.api._engine
    rng = np.random.RandomState(0)
    n = 40_000
    nbytes = n * 4
    for _ in range(80):
        eng.push_pull_local(rng.randn(n).astype(np.float32), "tune/w")
        if (eng.planner.locked(nbytes)
                and eng.planner.compress_locked(nbytes)):
            break
    assert eng.planner.compress_locked(nbytes)
    snap = eng.planner.snapshot()["compression"]["buckets"][
        str(nbytes.bit_length())]
    assert set(snap["explored"]) == {k for k, _ in COMPRESS_LADDER}
    locked = snap["locked_codec"]
    eng.push_pull_local(rng.randn(n).astype(np.float32), "tune/w")
    ctx = eng.registry.get("tune/w")
    got = (ctx.compression_kwargs.get("compressor", "none")
           if ctx.compression_kwargs else "none")
    assert got == locked


# ------------------------------------------------- elastic world changes


@pytest.mark.chaos
def test_compressed_push_crossing_world_change_drops_not_sums(bps_session):
    """A compressed chunk enqueued before a membership epoch change must
    be dropped with ABORTED, exactly like the uncompressed path — its
    quantized contribution must never be summed into the new world."""
    from byteps_tpu.fault import membership as mm
    eng = bps.core.api._engine
    ep0 = mm.current_epoch()
    try:
        eng.pause_dispatch()
        x = np.ones((bps.size(), 65536), np.float32)
        h = eng.push_pull_async(x, "cz/el", op="sum",
                                compression=ONEBIT_EF)
        mm.set_epoch(ep0 + 1)
        eng.resume_dispatch()
        with pytest.raises(RuntimeError, match="stale membership epoch"):
            h.wait(timeout=30)
        assert counters.get("membership.stale_chunks_dropped") >= 1
    finally:
        eng.resume_dispatch()
        mm._reset_epoch_for_tests()


@pytest.mark.chaos
def test_server_compressed_push_stale_mepoch_dropped():
    """ServerEngine.push_compressed stamped with a dead membership epoch
    is dropped at the door — before the wire decode even runs — and the
    round completes from current-epoch pushes alone."""
    import jax.numpy as jnp

    from byteps_tpu.compression import create as create_compressor
    from byteps_tpu.server.engine import ServerEngine
    eng = ServerEngine(num_threads=1)
    try:
        kw = {"compressor": "onebit"}
        eng.register_compression("ck", kw, 64)
        wc = create_compressor(kw, 64)
        p, _ = wc.compress(jnp.asarray(np.ones(64, np.float32)),
                           wc.init_state())
        wire = wc.wire_encode(p)
        c0 = counters.get("membership.stale_pushes_dropped")
        eng.push_compressed("ck", wire, 0, 1,
                            mepoch=eng.membership_epoch + 5)
        assert counters.get("membership.stale_pushes_dropped") == c0 + 1
        eng.push_compressed("ck", wire, 0, 1,
                            mepoch=eng.membership_epoch)
        out = eng.pull("ck", timeout=10)
        assert np.isfinite(out).all()
    finally:
        eng.shutdown()


# ---------------------------------------------------------- observability


def test_compression_counters_and_gauges(bps_chunked):
    eng = bps.core.api._engine
    x = np.random.RandomState(2).randn(40_000).astype(np.float32)
    eng.push_pull_async(_stacked(x), "obs/c", op="sum",
                        out_shape=(40_000,), compression=ONEBIT_EF).wait()
    assert counters.get("compression.wire_bytes") > 0
    assert counters.get("compression.bytes_saved") > 0
    # onebit at 160 KB: payload is ~1/32 of raw — saved dwarfs shipped
    assert counters.get("compression.bytes_saved") \
        > 10 * counters.get("compression.wire_bytes")
    eng.refresh_compression_gauges()
    snap = gauges.snapshot()
    assert any(k.startswith("compression.active{") and "onebit" in k
               for k in snap)
    norms = [v for k, v in snap.items()
             if k.startswith("compression.ef_norm{")]
    assert norms and norms[0] > 0


def test_bps_top_codec_column():
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools import bps_top
    cluster = {"epoch": 0, "world": [0, 1], "ranks": {
        0: {"age_s": 0.1, "metrics": {
            "gauges": {'compression.codec_locked{bucket="22",'
                       'codec="onebit"}': 1.0},
            "counters": {}, "step": {}}},
        1: {"age_s": 0.1, "metrics": {
            "gauges": {}, "counters": {}, "step": {}}}}}
    text = bps_top.render(cluster)
    assert "CODEC" in text
    rows = text.splitlines()
    assert any("onebit" in r for r in rows)      # rank 0 shows its codec
    r1 = next(r for r in rows if r.strip().startswith("1 "))
    assert " - " in r1 or r1.split()[7] == "-"   # rank 1 shows '-'
