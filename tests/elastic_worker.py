"""Worker body for the elastic-membership chaos tests (test_elastic.py).

Three (or two) real processes, each with its own engine on the virtual
CPU mesh, share one heartbeat endpoint and one membership bus.  Each
"training" step: the local gradient — a rank-distinct constant, so the
cross-rank mean *changes* when the world changes — rides the engine's
``push_pull_local`` (exercising enqueue/dispatch under every epoch),
then ``membership.step_sync`` all-gathers the per-rank grads over the
bus and every member applies the mean.  The data plane across
*processes* is thus the membership bus at toy scale; that is deliberate
(same reasoning as chaos_worker.py: an initialized JAX backend cannot
drop a dead peer, so real cross-host collectives cannot shrink
in-process — what these tests pin is the membership machinery itself:
epoch agreement, stale-work drops, suspend/resume at the new size, and
rejoin-with-state).

Scenarios, driven by env:

- **victim**: ``BYTEPS_FAULT_SPEC=kill:rank=R:step=K`` makes the
  injector kill this process at its K-th push — mid-train, no cleanup.
  ``kill:site=coordinator:step=K`` kills whichever process hosts the
  membership control plane at its K-th push (ISSUE 8 coordinator lanes).
- **survivor**: heartbeat detects, ``ElasticMembership.on_failure``
  shrinks in place; the worker keeps stepping to the final step and
  prints ``FINAL <epoch> <world> <w[0]>``.  Heartbeats are
  membership-managed (``host_heartbeat``): the UDP server follows the
  coordinator through world changes, so killing rank 0 leaves a world
  that still detects the next failure.
- **die-on-detect** (``BYTEPS_ELASTIC_DIE_ON_DETECT=1``): exits the
  moment its detector fires — manufactures a double failure *during*
  the survivors' shrink window (or, when the victim is the coordinator,
  the standby dying mid-failover).
- **rejoiner** (``BYTEPS_ELASTIC_REJOIN=1``): comes up fresh, parks on
  the bus, and resumes from the survivor-broadcast epoch/keys/params.
- **stale probes** (``BYTEPS_ELASTIC_STALE_PROBE=1``): after training,
  deterministically manufactures a stale-epoch chunk (pause dispatch →
  enqueue → advance epoch → resume) and a stale-epoch server push, and
  asserts both are dropped, not delivered/summed.
- **wedge** (``BYTEPS_ELASTIC_WEDGE_STEP=K`` [+ ``_WEDGE_S``]): at step
  K this rank's engine sync blocks for WEDGE_S seconds — the simulated
  wedged collective.  With ``BYTEPS_SYNC_DEADLINE_S`` armed the engine
  deadline fires, the installed failure action runs a membership
  *reconcile* (never ``os._exit``), and training continues; the worker
  prints ``DEADLINE-TRIPS``/``RECONCILES`` counters before FINAL.
- **partition** (``BYTEPS_ELASTIC_PARTITION_SPEC=partition:ranks=A|B...``
  + ``BYTEPS_ELASTIC_PARTITION_STEP=K``): at step K every rank arms the
  edge-cut spec locally (same step boundary, deterministic).  The
  majority side detects the severed coordinator (instant
  ``_BusUnreachable`` per attempt), shrinks through the failover
  ladder, and keeps training; the minority side's shrink proposal fails
  the quorum gate and raises ``PartitionMinority`` — the worker prints
  ``PARKED <rank> <epoch> <step>``, dumps its flight ring, stops the
  old membership, and loops ``ElasticMembership.rejoin`` (host-map bus
  discovery) until the ``ms=`` heal lets it back in, then resumes
  training to FINAL.  Every rank in partition mode dumps its flight
  ring before exiting and prints ``FLIGHT <path>`` so the test can
  assert the split-brain proof from both sides' records.

``BYTEPS_ELASTIC_BUS`` may be EMPTY in partition runs: the membership
then resolves the bus from ``BYTEPS_MEMBERSHIP_HOSTS`` per view, so a
failover successor binds its OWN entry (rank 0's process is still
alive across the cut, holding its port).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

LR = 0.1
DIM = 8

# Sharded-update leg (BYTEPS_ELASTIC_SHARDED=1, ISSUE 20 chaos proof):
# a second model trained through declare_update/push_pull_update on the
# local engine.  SU_DIM is deliberately NOT divisible by the 2-device
# local mesh, so every restore exercises the re-pad (C=6, n_pad=12).
SU_NAME = "wsh"
SU_DIM = 11


def _grad(rank: int) -> np.ndarray:
    # rank-distinct so shrink/grow changes the mean: {1,4,9}/3 vs {1,9}/2
    return np.full(DIM, float((rank + 1) ** 2), np.float32)


def _su_tx():
    import optax

    # momentum → a real padded-length trace leaf that must survive the
    # elastic re-shard bit-for-bit
    return optax.sgd(learning_rate=LR, momentum=0.9)


def _su_slot(api, m, mm):
    """A live sharded-update slot on the CURRENT engine.  After a world
    change tore the engine down, declare_update consumes the suspend()
    stash — master + momentum re-padded onto the rebuilt mesh — and the
    worker prints ``RESHARDED <applied> <owner,owner>`` as the restore
    evidence (a fresh slot would have applied == 0)."""
    retries = 0
    while True:
        try:
            eng = api._require()
            slot = eng.update_slots.get(SU_NAME)
            if slot is None:
                api.declare_update(SU_NAME, (SU_DIM,), tx=_su_tx(),
                                   init_value=np.zeros(SU_DIM, np.float32))
                slot = eng.update_slots[SU_NAME]
                if slot.applied:
                    owners = ",".join(str(o) for o, _, _ in
                                      slot.export_shards())
                    print("RESHARDED", slot.applied, owners, flush=True)
            return slot
        except RuntimeError:
            retries += 1
            if retries > 200:
                raise
            m.wait_ready(mm.current_epoch(), timeout=30)
            time.sleep(0.05)


def _su_step(api, m, mm, g0, target):
    """One exactly-once sharded-update dispatch: push this step's mean
    gradient (scaled onto a fixed basis vector), commit exactly one
    owner-resident optax update.

    ``target`` is how many updates must have committed once this call
    returns.  A mid-dispatch engine teardown (the kill's shrink) loses
    the handle but not the state: suspend() exported the slot WITH its
    ``applied`` count, so after the re-declare the counter arbitrates
    the torn step — already ``target`` means it committed before the
    drain (skip; a redispatch would double-apply), ``target - 1`` means
    the unit was dropped as stale (redispatch).  Never lost, never
    double-applied."""
    g = np.float32(g0) * np.arange(1, SU_DIM + 1, dtype=np.float32)
    retries = 0
    while True:
        slot = _su_slot(api, m, mm)
        if slot.applied >= target:
            assert slot.applied == target, (slot.applied, target)
            return
        try:
            api._require().push_pull_update(g, SU_NAME)
        except (RuntimeError, ValueError):
            # engine torn down / rebuilt mid-dispatch (ValueError: the
            # rebuilt engine has no slot yet — next _su_slot re-declares)
            retries += 1
            if retries > 200:
                raise
            m.wait_ready(mm.current_epoch(), timeout=30)
            time.sleep(0.05)
            continue
        assert slot.applied == target, (slot.applied, target)
        return


def _stale_probes(api, mm) -> int:
    """Deterministic stale-epoch drop checks (rank 0, after training)."""
    from byteps_tpu.common.telemetry import counters
    from byteps_tpu.server.engine import ServerEngine

    # 1. stale CHUNK: enqueued under the current epoch, epoch advances
    #    before dispatch → dropped with an ABORTED status, not delivered
    eng = api._require()
    before = counters.get("membership.stale_chunks_dropped")
    eng.pause_dispatch()
    h = eng.push_pull_local_async(np.ones(DIM, np.float32), "stale_probe",
                                  op="sum")
    mm.advance_epoch()
    eng.resume_dispatch()
    try:
        h.wait(timeout=20)
        print("STALE-CHUNK-DELIVERED", flush=True)
        return 5
    except RuntimeError as e:
        if "stale membership epoch" not in str(e):
            print("STALE-CHUNK-WRONG-ERROR", e, flush=True)
            return 5
    if counters.get("membership.stale_chunks_dropped") <= before:
        print("STALE-CHUNK-NO-COUNTER", flush=True)
        return 5
    print("STALE-CHUNK-DROPPED", flush=True)

    # 2. stale PUSH: a server push stamped with the pre-shrink epoch is
    #    dropped at the door — the next merge must NOT include it
    srv = ServerEngine(num_threads=1)
    srv.set_membership_epoch(mm.current_epoch())
    srv.push("digest", np.ones(4, np.float32), 0, 1,
             mepoch=mm.current_epoch())
    v1 = srv.pull("digest", timeout=10)
    assert float(v1[0]) == 1.0, v1
    srv.push("digest", np.full(4, 100.0, np.float32), 0, 1,
             mepoch=mm.current_epoch() - 1)          # residue: dropped
    srv.push("digest", np.full(4, 2.0, np.float32), 0, 1,
             mepoch=mm.current_epoch())
    v2 = srv.pull("digest", timeout=10)
    srv.shutdown()
    if float(v2[0]) != 2.0:   # 102.0 would mean the stale push summed
        print("STALE-PUSH-SUMMED", float(v2[0]), flush=True)
        return 5
    if counters.get("membership.stale_pushes_dropped") < 1:
        print("STALE-PUSH-NO-COUNTER", flush=True)
        return 5
    print("STALE-PUSH-DROPPED", flush=True)
    return 0


def _parked_rejoin(mm, m, rank, w):
    """Minority-park flow (partition scenario): dump the flight ring —
    the split-brain proof reads membership.partition_minority and the
    ABSENCE of any agreed epoch from it — stop the parked membership,
    then retry :meth:`ElasticMembership.rejoin` (host-map bus discovery)
    until the partition heals and the majority's bus admits this rank.
    Returns ``(membership, w, next_step)`` resumed from the survivors'
    broadcast state."""
    import numpy as np

    from byteps_tpu.common import flight_recorder as _flight
    from byteps_tpu.fault.membership import ElasticMembership
    from byteps_tpu.utils.failure_detector import install_failure_action

    print("PARKED", rank, mm.current_epoch(), flush=True)
    print("FLIGHT", _flight.dump("parked"), flush=True)
    m.stop()
    bus = os.environ["BYTEPS_ELASTIC_BUS"] or None
    deadline = time.monotonic() + 120.0
    while True:
        try:
            m2, step0, state = ElasticMembership.rejoin(rank, bus,
                                                        timeout=5.0)
            break
        except Exception as e:  # noqa: BLE001 — severed until the heal
            if time.monotonic() > deadline:
                print("REJOIN-DEADLINE", repr(e), flush=True)
                raise
            time.sleep(0.5)
    install_failure_action(m2.on_failure)
    w = np.asarray(state["w"], np.float32)
    print("REJOINED", mm.current_epoch(),
          ",".join(map(str, m2.view().world)), step0, flush=True)
    return m2, w, int(step0) + 1


def main() -> int:
    rank = int(os.environ["BYTEPS_ELASTIC_RANK"])
    world = [int(r) for r in os.environ["BYTEPS_ELASTIC_WORLD"].split(",")]
    # empty → view-aware resolution (BYTEPS_MEMBERSHIP_HOSTS): partition
    # runs need the failover bus bound at the SUCCESSOR's own entry
    bus = os.environ["BYTEPS_ELASTIC_BUS"] or None
    hb_port = os.environ.get("BYTEPS_ELASTIC_HB_PORT", "")
    n_steps = int(os.environ["BYTEPS_ELASTIC_STEPS"])
    start_step = int(os.environ.get("BYTEPS_ELASTIC_START_STEP", "1"))
    init_w = float(os.environ.get("BYTEPS_ELASTIC_INIT_W", "0"))
    sleep_s = float(os.environ.get("BYTEPS_ELASTIC_STEP_SLEEP", "0.05"))
    rejoining = os.environ.get("BYTEPS_ELASTIC_REJOIN", "") == "1"
    sharded = os.environ.get("BYTEPS_ELASTIC_SHARDED", "") == "1"
    die_on_detect = os.environ.get("BYTEPS_ELASTIC_DIE_ON_DETECT", "") == "1"
    wedge_step = int(os.environ.get("BYTEPS_ELASTIC_WEDGE_STEP", "0"))
    wedge_s = float(os.environ.get("BYTEPS_ELASTIC_WEDGE_S", "4"))
    partition_spec = os.environ.get("BYTEPS_ELASTIC_PARTITION_SPEC", "")
    partition_step = int(os.environ.get("BYTEPS_ELASTIC_PARTITION_STEP",
                                        "0"))

    import jax

    jax.config.update("jax_platforms", "cpu")

    import byteps_tpu.core.api as api
    from byteps_tpu.fault import membership as mm
    from byteps_tpu.fault.membership import (ElasticMembership,
                                             MembershipTimeout,
                                             PartitionMinority, WorldChanged)
    from byteps_tpu.utils.failure_detector import install_failure_action

    if rejoining:
        # fresh process: park on the bus, adopt epoch/keys/params from a
        # survivor, resume mid-run
        m, step0, state = ElasticMembership.rejoin(rank, bus)
        w = np.asarray(state["w"], np.float32)
        start_step = int(step0) + 1
        on_failure = m.on_failure
        print("REJOINED", mm.current_epoch(),
              ",".join(map(str, m.view().world)), step0, flush=True)
    else:
        api.init()   # arms the injector from BYTEPS_FAULT_SPEC (victim)
        m = ElasticMembership(rank, world, bus).start()
        w = np.full(DIM, init_w, np.float32)
        if die_on_detect:
            def on_failure(stale):
                print("DIED-ON-DETECT", sorted(stale), flush=True)
                os._exit(1)
        else:
            on_failure = m.on_failure
    # route every default failure path (heartbeat, step watchdog, the
    # engine's sync deadline) through the elastic layer
    install_failure_action(on_failure)
    if hb_port:
        # membership-managed heartbeats: the UDP server follows the
        # coordinator through every world change (ISSUE 8) — the fixed
        # 127.0.0.1 endpoint pins only host:port, not WHO serves it
        m.host_heartbeat(interval=0.08, timeout=0.7, grace=60.0,
                         addr="127.0.0.1:" + hb_port,
                         on_failure=on_failure)
    # observability plane (test_observability.py): announce the obs
    # endpoint's resolved port when BYTEPS_OBS_PORT armed one — the
    # server outlives suspend/resume, so the port stays valid across
    # elastic transitions
    from byteps_tpu.common import obs_server as _obs
    if _obs.get_server() is not None:
        print("OBS", rank, _obs.get_server().port, flush=True)
    print("START", rank, flush=True)

    step = start_step
    retries = 0
    wedged = False
    partition_armed = False
    conn_errs = 0
    su_target = 0   # sharded-update commits expected so far (exactly-once)
    while step <= n_steps:
        if retries > 200:   # a real wedge must fail loudly, not spin
            print("RETRY-BUDGET-EXHAUSTED at", step, flush=True)
            return 6
        if (partition_spec and partition_step and step == partition_step
                and not partition_armed):
            # every rank severs the same edges at the same step boundary
            # — a deterministic network split, no global trigger needed
            from byteps_tpu.fault import injector as fault_injector
            partition_armed = True
            # persist: the cut must survive the suspend/resume cycles of
            # the very shrink/park it provokes — only ms= heals it
            fault_injector.arm(partition_spec, rank=rank, persist=True)
            print("PARTITION-ARMED", rank, "at", step, flush=True)
        try:
            eng = api._require()
            if wedge_step and step == wedge_step and not wedged:
                # simulated wedged collective: the NEXT unit the syncer
                # retires blocks wedge_s seconds inside the engine's
                # block hook (one-shot; restores itself).  The sync
                # deadline must fire and route through reconcile.
                wedged = True
                orig = eng._block

                def _wedge_once(x, _orig=orig, _eng=eng):
                    _eng._block = _orig
                    print("WEDGING", rank, flush=True)
                    time.sleep(wedge_s)
                    return _orig(x)
                eng._block = _wedge_once
            red = np.asarray(eng.push_pull_local(_grad(rank), "grad",
                                                 op="sum"))
        except RuntimeError:
            # engine torn down / rebuilt by a concurrent world change on
            # the detector thread — wait for the transition, retry
            retries += 1
            m.wait_ready(mm.current_epoch(), timeout=30)
            time.sleep(0.05)
            continue
        try:
            _, payloads = m.step_sync(step, payload=red,
                                      state={"w": w, "step": step - 1})
        except WorldChanged as e:
            print("WORLD", e.view.epoch,
                  ",".join(map(str, e.view.world)), "at", step, flush=True)
            continue   # engine already on the new world; retry the step
        except MembershipTimeout:
            retries += 1
            continue
        except PartitionMinority:
            # this side mustered only a minority: park, wait out the
            # heal, and return through the ordinary rejoin path
            m, w, step = _parked_rejoin(mm, m, rank, w)
            retries = conn_errs = 0
            continue
        except ConnectionError:
            if not partition_armed:
                raise
            conn_errs += 1
            if conn_errs < 2:
                # one unreachable round is not yet failure evidence (a
                # failover bind or a just-healed rejoin still settling)
                time.sleep(0.5)
                continue
            # the bus host is across the cut: name the coordinator as
            # failed and take the failover shrink (quorum-gated)
            try:
                view = m.shrink({m.view().coordinator})
            except PartitionMinority:
                m, w, step = _parked_rejoin(mm, m, rank, w)
                retries = conn_errs = 0
                continue
            conn_errs = 0
            print("WORLD", view.epoch,
                  ",".join(map(str, view.world)), "at", step, flush=True)
            continue
        retries = 0
        conn_errs = 0
        grads = [np.asarray(p) for p in payloads.values()]
        g = (np.sum(grads, axis=0, dtype=np.float32)
             / np.float32(len(grads)))
        w = w - np.float32(LR) * g
        if sharded:
            su_target += 1
            _su_step(api, m, mm, float(g[0]), su_target)
        step += 1
        time.sleep(sleep_s)

    assert np.all(w == w[0]), w   # uniform by construction
    rc = 0
    if os.environ.get("BYTEPS_ELASTIC_STALE_PROBE", "") == "1":
        rc = _stale_probes(api, mm)
    if wedge_step:
        from byteps_tpu.common.telemetry import counters as _counters
        print("DEADLINE-TRIPS", _counters.get("engine.sync_deadline_trips"),
              "RECONCILES", _counters.get("membership.reconcile_started"),
              flush=True)
    if sharded:
        # read the master back through export() (logical length): the
        # test replays the mean-gradient sequence with eager optax and
        # asserts this line bit-for-bit, plus applied == steps (no lost
        # or double-applied update across the mid-step teardown)
        slot = _su_slot(api, m, mm)
        vals = ",".join(repr(float(v)) for v in slot.export()["master"])
        print("FINAL-SHARDED", slot.applied, vals, flush=True)
    view = m.view()
    print("FINAL", view.epoch, ",".join(map(str, view.world)),
          repr(float(w[0])), flush=True)
    if partition_armed:
        # both sides of the split ship their evidence: the test asserts
        # the no-second-epoch proof from every rank's flight records
        from byteps_tpu.common import flight_recorder as _flight
        print("FLIGHT", _flight.dump("partition_done"), flush=True)
    install_failure_action(None)
    m.stop()   # stops the managed heartbeat too
    api.shutdown()
    return rc


if __name__ == "__main__":
    sys.exit(main())
