"""Native C++ core tests: the ctypes-loaded scheduler/partitioner/reducer
must agree with the pure-Python implementations (the reference's analogous
split is C++ core + numpy test replications, SURVEY.md §4)."""

import numpy as np
import pytest

from byteps_tpu import native
from byteps_tpu.common.partitioner import chunk_bounds as py_bounds
from byteps_tpu.common.scheduler import ChunkScheduler
from byteps_tpu.common.types import ChunkTask

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def _task(name, key, priority, nbytes):
    return ChunkTask(name=name, key=key, priority=priority, version=0,
                     offset_elems=0, num_elems=nbytes // 4, nbytes=nbytes,
                     total_parts=1)


def test_key_encoding_matches_python():
    lib = native.load()
    for declared, part in [(0, 0), (1, 2), (77, 65535), (65535, 1)]:
        assert native.make_key(declared, part) == (declared << 16) | part
        assert lib.bps_key_declared(native.make_key(declared, part)) == \
            declared
        assert lib.bps_key_part(native.make_key(declared, part)) == part


@pytest.mark.parametrize("num_elems,itemsize,pbytes", [
    (0, 4, 4096), (1, 4, 4096), (1024, 4, 4096), (1025, 4, 4096),
    (10_000_000, 4, 4096000), (123_457, 2, 1000), (512, 8, 512),
])
def test_chunk_bounds_matches_python(num_elems, itemsize, pbytes):
    assert native.chunk_bounds(num_elems, itemsize, pbytes) == \
        py_bounds(num_elems, itemsize, pbytes)


def test_scheduler_priority_and_key_order():
    for sched in (native.NativeChunkScheduler(0), ChunkScheduler(0)):
        sched.add_task(_task("c", 30, -3, 100))
        sched.add_task(_task("a", 10, -1, 100))
        sched.add_task(_task("b2", 21, -2, 100))
        sched.add_task(_task("b1", 20, -2, 100))
        order = [sched.get_task().name for _ in range(4)]
        assert order == ["a", "b1", "b2", "c"], type(sched).__name__


def test_scheduler_credit_window():
    sched = native.NativeChunkScheduler(credit_bytes=250)
    sched.add_task(_task("x", 1, 0, 100))
    sched.add_task(_task("y", 2, 0, 100))
    sched.add_task(_task("z", 3, 0, 100))
    assert sched.get_task().name == "x"
    assert sched.get_task().name == "y"
    # window full: 200 in flight + 100 > 250
    assert sched.get_task() is None
    assert sched.bytes_in_flight == 200
    sched.report_finish(100)
    assert sched.get_task().name == "z"


def test_scheduler_oversized_task_allowed_when_idle():
    sched = native.NativeChunkScheduler(credit_bytes=64)
    sched.add_task(_task("huge", 1, 0, 10_000))
    assert sched.get_task().name == "huge"  # window empty -> clamp through
    sched.add_task(_task("next", 2, 0, 10))
    assert sched.get_task() is None         # oversized still in flight
    sched.report_finish(10_000)
    assert sched.get_task().name == "next"


def test_scheduler_blocking_get_wakes_on_add():
    import threading
    sched = native.NativeChunkScheduler(0)
    got = []

    def consumer():
        got.append(sched.get_task(block=True, timeout=5.0))

    t = threading.Thread(target=consumer)
    t.start()
    sched.add_task(_task("late", 1, 0, 8))
    t.join(timeout=10)
    assert not t.is_alive() and got[0].name == "late"


def test_scheduler_drain_returns_remaining():
    sched = native.NativeChunkScheduler(0)
    for i in range(5):
        sched.add_task(_task(f"t{i}", i, -i, 10))
    assert sched.get_task().name == "t0"
    names = [t.name for t in sched.drain()]
    assert sorted(names) == ["t1", "t2", "t3", "t4"]
    assert sched.pending == 0


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32,
                                   np.int64])
def test_inplace_add_matches_numpy(dtype):
    rng = np.random.default_rng(0)
    if np.issubdtype(dtype, np.floating):
        a = rng.standard_normal(1 << 20).astype(dtype)
        b = rng.standard_normal(1 << 20).astype(dtype)
    else:
        a = rng.integers(-1000, 1000, 1 << 20).astype(dtype)
        b = rng.integers(-1000, 1000, 1 << 20).astype(dtype)
    expect = a + b
    out = native.inplace_add(a.copy(), b)
    np.testing.assert_array_equal(out, expect)


def test_inplace_scaled_add():
    rng = np.random.default_rng(1)
    a = rng.standard_normal(100_003).astype(np.float32)
    b = rng.standard_normal(100_003).astype(np.float32)
    expect = a + 0.25 * b
    out = native.inplace_scaled_add(a.copy(), b, 0.25)
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_bf16_reduce_round_to_nearest_even():
    lib = native.load()
    import ctypes
    try:
        import ml_dtypes
    except ImportError:
        pytest.skip("ml_dtypes unavailable")
    rng = np.random.default_rng(2)
    a32 = rng.standard_normal(4096).astype(np.float32)
    b32 = rng.standard_normal(4096).astype(np.float32)
    a = a32.astype(ml_dtypes.bfloat16)
    b = b32.astype(ml_dtypes.bfloat16)
    expect = (a.astype(np.float32) + b.astype(np.float32)) \
        .astype(ml_dtypes.bfloat16)
    dst = a.view(np.uint16).copy()
    src = b.view(np.uint16).copy()
    lib.bps_reduce_sum_bf16(
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        dst.size, 2)
    np.testing.assert_array_equal(dst.view(ml_dtypes.bfloat16), expect)


def test_engine_uses_native_scheduler_by_default():
    import jax
    if jax.default_backend() != "cpu":
        pytest.skip("mesh fixture is CPU-only")
    import byteps_tpu as bps
    from byteps_tpu.core import api as _api
    bps.init()
    try:
        name = type(_api._require().scheduler).__name__
        assert name == "NativeChunkScheduler"
        x = np.random.randn(bps.size(), 1024).astype(np.float32)
        out = bps.push_pull(x, "native_path")
        np.testing.assert_allclose(np.asarray(out), x.mean(0), rtol=1e-5,
                                   atol=1e-6)
    finally:
        bps.shutdown()
