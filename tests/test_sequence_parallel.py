"""Sequence-parallel attention vs single-device reference.

The harness shape follows SURVEY.md §4: an 8-way virtual CPU mesh stands in
for a TPU slice; correctness is checked against an exact single-device
computation (here full softmax attention) the way the reference checks
push_pull against numpy sums (reference tests/test_mxnet.py:40-80).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.parallel import (full_attention, make_sp_attention,
                                 make_sp_mesh, ring_attention,
                                 ulysses_attention)


def _qkv(key, b=2, t=32, h=8, d=8, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, t, h, d)
    return (jax.random.normal(kq, shape, dtype),
            jax.random.normal(kk, shape, dtype),
            jax.random.normal(kv, shape, dtype))


@pytest.mark.parametrize("kind", ["ring", "ulysses", "striped"])
@pytest.mark.parametrize("causal", [False, True])
def test_sp_attention_matches_full(kind, causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    ref = full_attention(q, k, v, causal=causal)
    mesh = make_sp_mesh(n_sp=8)
    attn = make_sp_attention(mesh, kind, causal=causal)
    out = attn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("kind", ["ring", "ulysses", "striped"])
@pytest.mark.slow  # heaviest grads-match pair: tier-1 budget on small CPU hosts
def test_sp_attention_grads_match(kind):
    q, k, v = _qkv(jax.random.PRNGKey(1), t=16, h=8, d=4)
    mesh = make_sp_mesh(n_sp=4)
    attn = make_sp_attention(mesh, kind, causal=True)

    def loss_sp(q, k, v):
        return jnp.sum(attn(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    g_sp = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_sp, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_ring_attention_dp_times_sp():
    """2-way dp x 4-way sp on the same 8 devices."""
    q, k, v = _qkv(jax.random.PRNGKey(2), b=4, t=16)
    ref = full_attention(q, k, v, causal=True)
    mesh = make_sp_mesh(n_sp=4)
    assert mesh.devices.shape == (2, 4)
    attn = make_sp_attention(mesh, "ring", causal=True)
    out = attn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_bf16():
    q, k, v = _qkv(jax.random.PRNGKey(3), dtype=jnp.bfloat16)
    mesh = make_sp_mesh(n_sp=8)
    attn = make_sp_attention(mesh, "ring", causal=False)
    out = attn(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = full_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                         v.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out).astype(np.float32),
                               np.asarray(ref), rtol=0.05, atol=0.05)


def test_full_attention_causal_decode_alignment():
    """causal with Tq < Tk aligns q at the *end* of the key sequence."""
    q, k, v = _qkv(jax.random.PRNGKey(7), t=16)
    q1 = q[:, -1:]  # last-token decode against the full key cache
    full = full_attention(q, k, v, causal=True)
    dec = full_attention(q1, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(full[:, -1:]),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_rejects_indivisible_heads():
    q, k, v = _qkv(jax.random.PRNGKey(4), h=3)
    mesh = make_sp_mesh(n_sp=8)
    attn = make_sp_attention(mesh, "ulysses")
    with pytest.raises(ValueError, match="divisible"):
        attn(q, k, v)


def test_sp_mesh_from_comm_bridge():
    """SP mesh carved out of a bootstrapped (dcn, ici) CommContext."""
    import jax as _jax

    from byteps_tpu.comm.mesh import CommContext, _build_mesh

    devices = _jax.devices()[:8]
    comm = CommContext(mesh=_build_mesh(devices, 2), n_dcn=2, n_ici=4)
    from byteps_tpu.parallel import sp_mesh_from_comm

    mesh = sp_mesh_from_comm(comm, n_sp=4)
    assert mesh.devices.shape == (2, 4)
    q, k, v = _qkv(jax.random.PRNGKey(6), b=4, t=16)
    attn = make_sp_attention(mesh, "ring", causal=True)
    out = attn(q, k, v)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    with pytest.raises(ValueError, match="divisible"):
        sp_mesh_from_comm(comm, n_sp=3)


def test_inner_collectives_direct_shard_map():
    """ring/ulysses callable directly inside a user shard_map body."""
    from jax.sharding import PartitionSpec as P

    q, k, v = _qkv(jax.random.PRNGKey(5), t=16)
    mesh = make_sp_mesh(n_sp=8)
    spec = P(None, "sp", None, None)

    def body(q, k, v):
        r = ring_attention(q, k, v, "sp", causal=True)
        u = ulysses_attention(q, k, v, "sp", causal=True)
        return r, u

    r, u = jax.shard_map(body, mesh=mesh, in_specs=(spec,) * 3,
                         out_specs=(spec, spec), check_vma=False)(q, k, v)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(r), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(u), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_stripe_batch_round_trip_and_layout():
    from byteps_tpu.parallel import stripe_batch, unstripe_batch
    x = jnp.arange(2 * 16 * 1 * 1, dtype=jnp.float32).reshape(2, 16, 1, 1)
    s = stripe_batch(x, 4)
    # contiguous shard r of the striped layout holds tokens r, r+4, ...
    tokens = np.asarray(s)[0, :, 0, 0]
    assert tokens[:4].tolist() == [0, 4, 8, 12]      # rank 0's stripe
    assert tokens[4:8].tolist() == [1, 5, 9, 13]     # rank 1's stripe
    np.testing.assert_array_equal(np.asarray(unstripe_batch(s, 4)),
                                  np.asarray(x))
    with pytest.raises(ValueError):
        stripe_batch(x[:, :15], 4)                   # T % n != 0


def test_striped_causal_work_is_balanced_across_ranks():
    """The point of striping (Brandon et al. 2023): with contiguous
    shards the per-(rank, step) causal-visible entry counts range from 0
    to a full block; with stripes every pair does near-identical work.
    Computed from the mask definitions, no timing involved."""
    n, t = 8, 64                                     # t per shard
    full = t * t

    def contiguous_visible(my, src):
        qp = my * t + np.arange(t)
        kp = src * t + np.arange(t)
        return int((qp[:, None] >= kp[None, :]).sum())

    def striped_visible(my, src):
        lq = np.arange(t)[:, None]
        lk = np.arange(t)[None, :]
        return int(((lq > lk) | ((lq == lk) & (my >= src))).sum())

    def rank_totals(visible):
        return [sum(visible(my, (my - s) % n) for s in range(n))
                for my in range(n)]

    cont = rank_totals(contiguous_visible)
    stri = rank_totals(striped_visible)
    # contiguous: rank 0 attends one block, rank n-1 all n — each ring
    # step runs at the slowest rank, so this spread is wasted wall-clock
    assert max(cont) - min(cont) == (n - 1) * full
    # striped: ranks differ only by how many diagonals they own — one
    # diagonal (t entries) per rank index, a (n-1)*t spread: t (=64x
    # here) less imbalance, growing with the shard length
    assert max(stri) - min(stri) == (n - 1) * t
    assert (max(cont) - min(cont)) // (max(stri) - min(stri)) == t
    # and per-STEP work is a fixed near-half block for EVERY (rank, step)
    assert all(abs(striped_visible(my, src) - full // 2) <= t
               for my in range(n) for src in range(n))
