"""Sequence-parallel attention vs single-device reference.

The harness shape follows SURVEY.md §4: an 8-way virtual CPU mesh stands in
for a TPU slice; correctness is checked against an exact single-device
computation (here full softmax attention) the way the reference checks
push_pull against numpy sums (reference tests/test_mxnet.py:40-80).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.parallel import (full_attention, make_sp_attention,
                                 make_sp_mesh, ring_attention,
                                 ulysses_attention)


def _qkv(key, b=2, t=32, h=8, d=8, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, t, h, d)
    return (jax.random.normal(kq, shape, dtype),
            jax.random.normal(kk, shape, dtype),
            jax.random.normal(kv, shape, dtype))


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_sp_attention_matches_full(kind, causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    ref = full_attention(q, k, v, causal=causal)
    mesh = make_sp_mesh(n_sp=8)
    attn = make_sp_attention(mesh, kind, causal=causal)
    out = attn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
def test_sp_attention_grads_match(kind):
    q, k, v = _qkv(jax.random.PRNGKey(1), t=16, h=8, d=4)
    mesh = make_sp_mesh(n_sp=4)
    attn = make_sp_attention(mesh, kind, causal=True)

    def loss_sp(q, k, v):
        return jnp.sum(attn(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    g_sp = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_sp, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_ring_attention_dp_times_sp():
    """2-way dp x 4-way sp on the same 8 devices."""
    q, k, v = _qkv(jax.random.PRNGKey(2), b=4, t=16)
    ref = full_attention(q, k, v, causal=True)
    mesh = make_sp_mesh(n_sp=4)
    assert mesh.devices.shape == (2, 4)
    attn = make_sp_attention(mesh, "ring", causal=True)
    out = attn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_bf16():
    q, k, v = _qkv(jax.random.PRNGKey(3), dtype=jnp.bfloat16)
    mesh = make_sp_mesh(n_sp=8)
    attn = make_sp_attention(mesh, "ring", causal=False)
    out = attn(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = full_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                         v.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out).astype(np.float32),
                               np.asarray(ref), rtol=0.05, atol=0.05)


def test_full_attention_causal_decode_alignment():
    """causal with Tq < Tk aligns q at the *end* of the key sequence."""
    q, k, v = _qkv(jax.random.PRNGKey(7), t=16)
    q1 = q[:, -1:]  # last-token decode against the full key cache
    full = full_attention(q, k, v, causal=True)
    dec = full_attention(q1, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(full[:, -1:]),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_rejects_indivisible_heads():
    q, k, v = _qkv(jax.random.PRNGKey(4), h=3)
    mesh = make_sp_mesh(n_sp=8)
    attn = make_sp_attention(mesh, "ulysses")
    with pytest.raises(ValueError, match="divisible"):
        attn(q, k, v)


def test_sp_mesh_from_comm_bridge():
    """SP mesh carved out of a bootstrapped (dcn, ici) CommContext."""
    import jax as _jax

    from byteps_tpu.comm.mesh import CommContext, _build_mesh

    devices = _jax.devices()[:8]
    comm = CommContext(mesh=_build_mesh(devices, 2), n_dcn=2, n_ici=4)
    from byteps_tpu.parallel import sp_mesh_from_comm

    mesh = sp_mesh_from_comm(comm, n_sp=4)
    assert mesh.devices.shape == (2, 4)
    q, k, v = _qkv(jax.random.PRNGKey(6), b=4, t=16)
    attn = make_sp_attention(mesh, "ring", causal=True)
    out = attn(q, k, v)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    with pytest.raises(ValueError, match="divisible"):
        sp_mesh_from_comm(comm, n_sp=3)


def test_inner_collectives_direct_shard_map():
    """ring/ulysses callable directly inside a user shard_map body."""
    from jax.sharding import PartitionSpec as P

    q, k, v = _qkv(jax.random.PRNGKey(5), t=16)
    mesh = make_sp_mesh(n_sp=8)
    spec = P(None, "sp", None, None)

    def body(q, k, v):
        r = ring_attention(q, k, v, "sp", causal=True)
        u = ulysses_attention(q, k, v, "sp", causal=True)
        return r, u

    r, u = jax.shard_map(body, mesh=mesh, in_specs=(spec,) * 3,
                         out_specs=(spec, spec), check_vma=False)(q, k, v)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(r), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(u), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
