"""bpslint: the project-invariant analyzer (tools/bpslint, ISSUE 13).

Per rule family: a fixture snippet proving the rule FIRES (positive) and
that an ``# bpslint: ignore[rule] reason=...`` pragma suppresses it
(negative), plus config validation and the tier-1 acceptance pin
``test_tree_is_clean`` — the analyzer runs over this very repository and
must exit 0.
"""

import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from tools.bpslint import (BpslintConfig, BpslintConfigError, load_config,
                           run)
from tools.bpslint.rules_env import doc_rows
from tools.bpslint.rules_metrics import doc_names

REPO = Path(__file__).resolve().parents[1]

_ENV_DOC = """\
# Env

| Variable | Default | Meaning |
|---|---|---|
| `BYTEPS_GOOD_KNOB` | 0 | a documented, validated, consumed knob |
"""

_OBS_DOC = """\
# Obs

| Name | Kind | Meaning |
|---|---|---|
| `good.metric` | counter | a documented, emitted metric |
"""

_CONFIG_SRC = """\
import os
GOOD = os.environ.get("BYTEPS_GOOD_KNOB")
"""

_INJECTOR_SRC = """\
VALID_SITES = (
    "good_site",
)
"""

_BASE_SRC = """\
import os
from x import counters, _fault

def baseline():
    os.environ.get("BYTEPS_GOOD_KNOB")
    counters.inc("good.metric")
    _fault.fire("good_site")
"""


def make_tree(tmp_path, extra=None, env_doc=_ENV_DOC, obs_doc=_OBS_DOC,
              injector=_INJECTOR_SRC, config_src=_CONFIG_SRC):
    pkg = tmp_path / "mypkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "config.py").write_text(config_src)
    (pkg / "injector.py").write_text(injector)
    (pkg / "base.py").write_text(_BASE_SRC)
    docs = tmp_path / "docs"
    docs.mkdir(exist_ok=True)
    (docs / "env.md").write_text(env_doc)
    (docs / "obs.md").write_text(obs_doc)
    for name, src in (extra or {}).items():
        (pkg / name).write_text(textwrap.dedent(src))
    return BpslintConfig(
        paths=["mypkg", "docs"], package="mypkg",
        config_module="mypkg/config.py", env_doc="docs/env.md",
        metrics_doc="docs/obs.md", injector_module="mypkg/injector.py")


def lint(tmp_path, **kw):
    cfg = make_tree(tmp_path, **kw)
    return run(tmp_path, cfg)


def rules_of(findings):
    return sorted({f.rule for f in findings})


def test_clean_fixture_tree_is_clean(tmp_path):
    assert lint(tmp_path) == []


# -- env-knob ---------------------------------------------------------------

def test_env_knob_fires_on_unvalidated_and_undocumented(tmp_path):
    fs = lint(tmp_path, extra={"bad.py": """\
        import os
        V = os.environ.get("BYTEPS_ROGUE_KNOB")
    """})
    msgs = [f.message for f in fs if f.rule == "env-knob"]
    assert any("never validated" in m for m in msgs)
    assert any("no row" in m for m in msgs)
    assert all(f.path == "mypkg/bad.py" for f in fs)


def test_env_knob_dead_doc_row_fires(tmp_path):
    doc = _ENV_DOC + "| `BYTEPS_DEAD_KNOB` | 0 | consumed by nothing |\n"
    fs = lint(tmp_path, env_doc=doc)
    assert len(fs) == 1 and fs[0].rule == "env-knob"
    assert fs[0].path == "docs/env.md" and "dead doc row" in fs[0].message


def test_env_knob_pragma_with_reason_suppresses(tmp_path):
    fs = lint(tmp_path, extra={"ok.py": """\
        import os
        # bpslint: ignore[env-knob] reason=marker var written for a child process
        V = os.environ.get("BYTEPS_ROGUE_KNOB")
    """})
    assert fs == []


def test_pragma_without_reason_is_itself_a_finding(tmp_path):
    fs = lint(tmp_path, extra={"bad.py": """\
        import os
        # bpslint: ignore[env-knob]
        V = os.environ.get("BYTEPS_ROGUE_KNOB")
    """})
    assert "pragma" in rules_of(fs)
    assert any("no reason" in f.message for f in fs)


def test_pragma_unknown_rule_is_a_finding(tmp_path):
    fs = lint(tmp_path, extra={"bad.py": """\
        X = 1  # bpslint: ignore[not-a-rule] reason=whatever
    """})
    assert rules_of(fs) == ["pragma"]
    assert "unknown rule" in fs[0].message


def test_pragma_syntax_inside_docstring_is_not_a_pragma(tmp_path):
    # regression: the scanner reads COMMENT tokens, so documentation
    # QUOTING the pragma grammar must neither suppress nor be flagged
    fs = lint(tmp_path, extra={"doc.py": '''\
        def f():
            """Use `# bpslint: ignore[env-knob] reason=...` to suppress."""
            return 1
    '''})
    assert fs == []


def test_env_knob_message_strings_are_not_consumption(tmp_path):
    # a knob NAMED inside a longer message string is not a read: the
    # doc row for it still counts as dead
    doc = _ENV_DOC + "| `BYTEPS_NAMED_KNOB` | 0 | named in an error |\n"
    fs = lint(tmp_path, env_doc=doc, extra={"msg.py": """\
        ERR = "set BYTEPS_NAMED_KNOB to a positive value"
    """})
    assert len(fs) == 1 and "dead doc row" in fs[0].message


def test_env_doc_parser_skips_disposition_table():
    lines = [
        "| Variable | Meaning |", "|---|---|",
        "| `BYTEPS_LIVE` | live |", "",
        "| Reference variable | Status | Notes |", "|---|---|---|",
        "| `BYTEPS_HISTORICAL` | dropped | gone |",
    ]
    rows = doc_rows(lines)
    assert "BYTEPS_LIVE" in rows and "BYTEPS_HISTORICAL" not in rows


# -- metric-name ------------------------------------------------------------

def test_metric_name_fires_on_undocumented_emission(tmp_path):
    fs = lint(tmp_path, extra={"bad.py": """\
        from x import gauges
        gauges.set("rogue.gauge", 1.0)
    """})
    assert rules_of(fs) == ["metric-name"]
    assert "no row" in fs[0].message and fs[0].line == 2


def test_metric_name_dead_doc_row_fires(tmp_path):
    doc = _OBS_DOC + "| `ghost.metric` | counter | emitted by nothing |\n"
    fs = lint(tmp_path, obs_doc=doc)
    assert len(fs) == 1 and fs[0].path == "docs/obs.md"
    assert "dead doc row" in fs[0].message


def test_metric_name_literal_name_table_satisfies_doc_row(tmp_path):
    # dynamic emitters are covered by a module-level literal name table
    # (the step.attrib_* pattern in common/telemetry.py)
    doc = _OBS_DOC + "| `dyn.metric_a` / `dyn.metric_b` | gauge | dyn |\n"
    fs = lint(tmp_path, obs_doc=doc, extra={"dyn.py": """\
        from x import gauges
        NAMES = {"a": "dyn.metric_a", "b": "dyn.metric_b"}
        def publish(k, v):
            gauges.set(NAMES[k], v)
    """})
    assert fs == []


def test_metric_name_pragma_suppresses(tmp_path):
    fs = lint(tmp_path, extra={"ok.py": """\
        from x import counters
        # bpslint: ignore[metric-name] reason=test-only canary series
        counters.inc("rogue.counter")
    """})
    assert fs == []


def test_metric_doc_parser_expands_row_prefix_shorthand():
    lines = [
        "| Name | Kind | Meaning |", "|---|---|---|",
        "| `integrity.rejected` / `skipped` / `zeroed` | counter | x |",
        "| `slowness.score{site=,rank=}` | gauge | labeled |",
        "| `wire_bytes` / `wire_bytes_wasted` | counter | no prefix |",
    ]
    names = doc_names(lines)
    assert {"integrity.rejected", "integrity.skipped",
            "integrity.zeroed", "slowness.score", "wire_bytes",
            "wire_bytes_wasted"} <= set(names)


# -- chaos-site -------------------------------------------------------------

def test_chaos_site_fires_on_unknown_site(tmp_path):
    fs = lint(tmp_path, extra={"bad.py": """\
        from x import _fault
        _fault.fire("typo_site")
    """})
    assert rules_of(fs) == ["chaos-site"]
    assert "typo_site" in fs[0].message


def test_chaos_site_fires_on_unwoven_valid_site(tmp_path):
    inj = 'VALID_SITES = (\n    "good_site",\n    "orphan_site",\n)\n'
    fs = lint(tmp_path, injector=inj)
    assert rules_of(fs) == ["chaos-site"]
    assert fs[0].path == "mypkg/injector.py" and fs[0].line == 3
    assert "never woven" in fs[0].message


def test_chaos_site_pragma_on_tuple_line_suppresses(tmp_path):
    inj = ('VALID_SITES = (\n    "good_site",\n'
           '    # bpslint: ignore[chaos-site] reason=kill-only predicate\n'
           '    "orphan_site",\n)\n')
    assert lint(tmp_path, injector=inj) == []


def test_chaos_site_pragma_at_call_suppresses(tmp_path):
    fs = lint(tmp_path, extra={"ok.py": """\
        from x import _fault
        # bpslint: ignore[chaos-site] reason=site registered by a plugin at runtime
        _fault.fire("typo_site")
    """})
    assert fs == []


# -- lock-discipline --------------------------------------------------------

def test_lock_discipline_fires_on_sleep_under_lock(tmp_path):
    fs = lint(tmp_path, extra={"bad.py": """\
        import time, threading
        _lock = threading.Lock()
        def f():
            with _lock:
                time.sleep(1)
    """})
    assert rules_of(fs) == ["lock-discipline"]
    assert "time.sleep" in fs[0].message and fs[0].line == 5


def test_lock_discipline_fires_on_callback_under_lock(tmp_path):
    fs = lint(tmp_path, extra={"bad.py": """\
        class S:
            def notify(self):
                with self._lock:
                    for fn in self._subs:
                        fn(1, 2)
    """})
    assert rules_of(fs) == ["lock-discipline"]
    assert "user callback fn" in fs[0].message


def test_lock_discipline_ignores_calls_outside_and_deferred(tmp_path):
    fs = lint(tmp_path, extra={"ok.py": """\
        import time, threading
        _lock = threading.Lock()
        def f():
            with _lock:
                subs = list(range(3))
            time.sleep(0)            # outside the body: fine
            with _lock:
                def later():          # deferred body: fine
                    time.sleep(1)
                return later
    """})
    assert fs == []


def test_lock_discipline_condvar_wait_not_flagged(tmp_path):
    fs = lint(tmp_path, extra={"ok.py": """\
        import threading
        class S:
            def f(self):
                with self._cv:
                    self._cv.wait_for(lambda: True, timeout=1)
    """})
    assert fs == []


def test_lock_discipline_nested_locks_report_once(tmp_path):
    # review regression: a blocking call under TWO nested lock-shaped
    # `with` blocks is one defect, not one finding per enclosing lock
    fs = lint(tmp_path, extra={"bad.py": """\
        import time, threading
        _a_lock = threading.Lock()
        _b_lock = threading.Lock()
        def f():
            with _a_lock:
                with _b_lock:
                    time.sleep(1)
    """})
    assert len(fs) == 1 and fs[0].rule == "lock-discipline"
    assert "_a_lock" in fs[0].message   # attributed to the outermost


def test_lock_discipline_pragma_suppresses(tmp_path):
    fs = lint(tmp_path, extra={"ok.py": """\
        import time, threading
        _lock = threading.Lock()
        def f():
            with _lock:
                # bpslint: ignore[lock-discipline] reason=0s sleep is a scheduler yield, lock is leaf
                time.sleep(0)
    """})
    assert fs == []


# -- health-rule ------------------------------------------------------------

_HEALTH_SRC = """\
RULE_IDS = (
    "overlap_floor",
    "ef_growth",
)
"""

_OBS_DOC_HEALTH = _OBS_DOC + """
| Rule | Breaches when | Knob |
|---|---|---|
| `overlap_floor` | overlap low while steps complete | `X` |
| `ef_growth` | error-feedback norm grows | — |
"""


def _health_cfg(tmp_path, health_src=_HEALTH_SRC, obs_doc=_OBS_DOC_HEALTH):
    cfg = make_tree(tmp_path, obs_doc=obs_doc,
                    extra={"health.py": health_src})
    cfg.health_module = "mypkg/health.py"
    return cfg


def test_health_rule_clean_when_in_sync(tmp_path):
    assert run(tmp_path, _health_cfg(tmp_path)) == []


def test_health_rule_fires_on_undocumented_rule(tmp_path):
    src = ('RULE_IDS = (\n    "overlap_floor",\n    "ef_growth",\n'
           '    "ghost_rule",\n)\n')
    fs = run(tmp_path, _health_cfg(tmp_path, health_src=src))
    assert len(fs) == 1 and fs[0].rule == "health-rule"
    assert fs[0].path == "mypkg/health.py" and fs[0].line == 4
    assert "ghost_rule" in fs[0].message


def test_health_rule_fires_on_dead_doc_row(tmp_path):
    doc = _OBS_DOC_HEALTH + "| `retired_rule` | fires on nothing | — |\n"
    fs = run(tmp_path, _health_cfg(tmp_path, obs_doc=doc))
    assert len(fs) == 1 and fs[0].path == "docs/obs.md"
    assert "retired_rule" in fs[0].message
    assert "dead doc row" in fs[0].message


def test_health_rule_missing_table_is_one_finding(tmp_path):
    # rules declared, no `| Rule |` table anywhere: one doc finding,
    # not one per declared id
    fs = run(tmp_path, _health_cfg(tmp_path, obs_doc=_OBS_DOC))
    assert len(fs) == 1 and fs[0].path == "docs/obs.md"
    assert "health-rule table" in fs[0].message


def test_health_rule_missing_rule_ids_is_a_finding(tmp_path):
    fs = run(tmp_path, _health_cfg(tmp_path, health_src="X = 1\n"))
    assert len(fs) == 1 and fs[0].path == "mypkg/health.py"
    assert "RULE_IDS" in fs[0].message


def test_health_rule_inert_without_health_module(tmp_path):
    # a Rule table with no health module configured under the tree is
    # documentation, not drift (and the metric-name parser must not eat
    # its backtick spans as metric rows)
    cfg = make_tree(tmp_path, obs_doc=_OBS_DOC_HEALTH)
    assert run(tmp_path, cfg) == []


def test_health_rule_pragma_suppresses(tmp_path):
    src = ('RULE_IDS = (\n    "overlap_floor",\n    "ef_growth",\n'
           '    # bpslint: ignore[health-rule] reason=staged rollout, the doc row lands with the engine change\n'
           '    "ghost_rule",\n)\n')
    assert run(tmp_path, _health_cfg(tmp_path, health_src=src)) == []


# -- configuration ----------------------------------------------------------

def test_config_unknown_key_rejected(tmp_path):
    (tmp_path / "pyproject.toml").write_text(
        "[tool.bpslint]\nwrong-key = true\n")
    with pytest.raises(BpslintConfigError, match="unknown key.*wrong-key"):
        load_config(tmp_path)


def test_config_unknown_rule_in_disable_rejected(tmp_path):
    (tmp_path / "pyproject.toml").write_text(
        '[tool.bpslint]\ndisable = ["not-a-rule"]\n')
    with pytest.raises(BpslintConfigError, match="unknown rule"):
        load_config(tmp_path)


def test_config_type_error_rejected(tmp_path):
    (tmp_path / "pyproject.toml").write_text(
        '[tool.bpslint]\npaths = "byteps_tpu"\n')
    with pytest.raises(BpslintConfigError, match="array of strings"):
        load_config(tmp_path)


def test_config_disable_disables_rule(tmp_path):
    cfg = make_tree(tmp_path, extra={"bad.py": """\
        from x import _fault
        _fault.fire("typo_site")
    """})
    cfg.disable = ["chaos-site"]
    assert run(tmp_path, cfg) == []


def test_config_malformed_toml_is_a_config_error(tmp_path):
    # review regression: on 3.11+ a TOML syntax error anywhere in
    # pyproject.toml must exit 2 (config error), not traceback as
    # findings; the 3.10 mini parser only reads [tool.bpslint*] tables
    # so a global syntax error outside them is invisible there
    try:
        import tomllib  # noqa: F401
    except ModuleNotFoundError:
        pytest.skip("no tomllib: the mini parser only sees bpslint tables")
    (tmp_path / "pyproject.toml").write_text(
        "[tool.other\nbroken = \n")
    with pytest.raises(BpslintConfigError, match="not valid TOML"):
        load_config(tmp_path)


def test_repo_config_parses_with_mini_parser():
    # the repo's own [tool.bpslint] section must stay inside the
    # 3.10-compatible TOML subset the fallback parser reads
    from tools.bpslint.config import _parse_tables_mini
    tables = _parse_tables_mini((REPO / "pyproject.toml").read_text())
    assert tables[""]["paths"] == ["byteps_tpu", "docs", "tools"]
    assert "sleep" in tables["lock-discipline"]["blocking-calls"]


def test_path_subset_run_seeds_consumption_from_config_paths(tmp_path):
    # review regression: `bpslint some/file.py` must not report every
    # doc row as dead and every site as unwoven just because the
    # consumers live outside the requested subset — the bidirectional
    # sets are seeded from the CONFIGURED paths, findings restricted to
    # the requested files
    cfg = make_tree(tmp_path)
    assert run(tmp_path, cfg, paths=["mypkg/config.py"]) == []
    # and a real violation inside the subset still fires
    (tmp_path / "mypkg" / "viol.py").write_text(
        'import os\nV = os.environ.get("BYTEPS_ROGUE_KNOB")\n')
    fs = run(tmp_path, cfg, paths=["mypkg/viol.py"])
    assert fs and all(f.path == "mypkg/viol.py" for f in fs)
    # while a violation OUTSIDE the subset stays silent on this run
    assert run(tmp_path, cfg, paths=["mypkg/config.py"]) == []


def test_path_subset_suppresses_reverse_direction_findings(tmp_path):
    # review regression: dead doc rows and unwoven sites live on files
    # OUTSIDE a `bpslint some/file.py` subset — they must not leak into
    # its report (the full run still catches them)
    env_doc = _ENV_DOC + "| `BYTEPS_DEAD_KNOB` | 0 | consumed by " \
                         "nothing |\n"
    inj = 'VALID_SITES = (\n    "good_site",\n    "orphan_site",\n)\n'
    cfg = make_tree(tmp_path, env_doc=env_doc, injector=inj)
    full = run(tmp_path, cfg)
    assert {f.path for f in full} == {"docs/env.md", "mypkg/injector.py"}
    assert run(tmp_path, cfg, paths=["mypkg/base.py"]) == []


def test_explicit_non_py_path_is_usage_error(tmp_path):
    # review regression: an explicitly requested non-.py FILE used to be
    # silently skipped — rc 0 looked like "linted clean"
    cfg = make_tree(tmp_path)
    with pytest.raises(FileNotFoundError, match="not a Python source"):
        run(tmp_path, cfg, paths=["docs/env.md"])


# -- the acceptance pin -----------------------------------------------------

def test_tree_is_clean():
    """`python -m tools.bpslint` on this repository exits 0: every
    contract the analyzer enforces holds on the tree that ships it."""
    r = subprocess.run(
        [sys.executable, "-m", "tools.bpslint"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"bpslint findings:\n{r.stdout}\n{r.stderr}"


def test_cli_exit_codes(tmp_path):
    make_tree(tmp_path, extra={"bad.py": """\
        from x import _fault
        _fault.fire("typo_site")
    """})
    (tmp_path / "pyproject.toml").write_text(
        '[tool.bpslint]\npaths = ["mypkg", "docs"]\n'
        'package = "mypkg"\nconfig-module = "mypkg/config.py"\n'
        'env-doc = "docs/env.md"\nmetrics-doc = "docs/obs.md"\n'
        'injector-module = "mypkg/injector.py"\n')
    env = {"PYTHONPATH": str(REPO)}
    r = subprocess.run([sys.executable, "-m", "tools.bpslint",
                        "--root", str(tmp_path)],
                       capture_output=True, text=True, timeout=120,
                       cwd=REPO, env={**__import__("os").environ, **env})
    assert r.returncode == 1 and "typo_site" in r.stdout
    (tmp_path / "pyproject.toml").write_text(
        "[tool.bpslint]\nbogus = 1\n")
    r2 = subprocess.run([sys.executable, "-m", "tools.bpslint",
                         "--root", str(tmp_path)],
                        capture_output=True, text=True, timeout=120,
                        cwd=REPO, env={**__import__("os").environ, **env})
    assert r2.returncode == 2 and "configuration error" in r2.stderr
