"""2-process CPU-backend integration test (round-1 VERDICT item 4).

Reference anchor: the reference validates its distributed path on one
machine by spawning scheduler/server processes and running the worker
against them (reference tests/meta_test.py:27-85).  The TPU-native
equivalent is two real JAX processes rendezvousing through
``jax.distributed.initialize`` (wired from the same DMLC_* env names) and
reducing over a (dcn=2, ici=2) global mesh whose shards are mutually
non-addressable — the configuration single-process tests cannot reach.
"""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

from .conftest import free_port as _free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_two_process_push_pull_matches_single_process():
    port = _free_port()
    # a separately-reserved UDP endpoint for the auto-armed heartbeat:
    # the default (rendezvous port + 1) is never reserved and can collide
    hb_port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_NUM_WORKER": "2",
            "DMLC_WORKER_ID": str(pid),
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            # small bound -> the big tensor partitions into ~7 chunks, so
            # the scheduler/dispatch path runs multi-chunk across processes
            "BYTEPS_PARTITION_BYTES": "65536",
            "BYTEPS_LOG_LEVEL": "WARNING",
            # exercise the auto-armed liveness path (healthy run: the
            # monitors must arm at init, stay quiet, stop at shutdown)
            "BYTEPS_HEARTBEAT_ON": "1",
            "BYTEPS_HEARTBEAT_TIMEOUT": "60",
            "BYTEPS_HEARTBEAT_PORT": str(hb_port),
        })
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "mp_worker.py")],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("2-process workers timed out (rendezvous or collective "
                    "deadlock); partial output: " +
                    "".join(o[-1500:] for o in outs))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"MP_OK {pid}" in out, f"worker {pid} output:\n{out[-4000:]}"
