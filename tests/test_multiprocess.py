"""2-process CPU-backend integration test (round-1 VERDICT item 4).

Reference anchor: the reference validates its distributed path on one
machine by spawning scheduler/server processes and running the worker
against them (reference tests/meta_test.py:27-85).  The TPU-native
equivalent is two real JAX processes rendezvousing through
``jax.distributed.initialize`` (wired from the same DMLC_* env names) and
reducing over a (dcn=2, ici=2) global mesh whose shards are mutually
non-addressable — the configuration single-process tests cannot reach.

CPU-backend capability: XLA's CPU backend does not implement
cross-process collectives ("Multiprocess computations aren't implemented
on the CPU backend").  The probe IS the attempt — when both workers die
on exactly that error, the test SKIPS with the backend limitation named
instead of standing red forever; any other failure still fails.  The
transport-backed sibling below exercises the same 2-process world over
REAL sockets (comm/transport.py), so the scenario is no longer untested
on hosts without cross-process XLA.
"""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np
import pytest

from .conftest import free_port as _free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the CPU backend's cross-process collective gap, verbatim (jax raises
# it from the first multi-process psum); matching on it is the
# capability probe
_CPU_BACKEND_GAP = "Multiprocess computations aren't implemented"


@pytest.mark.slow
def test_two_process_push_pull_matches_single_process():
    port = _free_port()
    # a separately-reserved UDP endpoint for the auto-armed heartbeat:
    # the default (rendezvous port + 1) is never reserved and can collide
    hb_port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_NUM_WORKER": "2",
            "DMLC_WORKER_ID": str(pid),
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            # small bound -> the big tensor partitions into ~7 chunks, so
            # the scheduler/dispatch path runs multi-chunk across processes
            "BYTEPS_PARTITION_BYTES": "65536",
            "BYTEPS_LOG_LEVEL": "WARNING",
            # exercise the auto-armed liveness path (healthy run: the
            # monitors must arm at init, stay quiet, stop at shutdown)
            "BYTEPS_HEARTBEAT_ON": "1",
            "BYTEPS_HEARTBEAT_TIMEOUT": "60",
            "BYTEPS_HEARTBEAT_PORT": str(hb_port),
        })
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "mp_worker.py")],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("2-process workers timed out (rendezvous or collective "
                    "deadlock); partial output: " +
                    "".join(o[-1500:] for o in outs))
    if (all(p.returncode != 0 for p in procs)
            and any(_CPU_BACKEND_GAP in o for o in outs)):
        # capability-probed skip: the attempt itself established that
        # THIS host's XLA CPU backend cannot run cross-process
        # collectives — the loud reason names the limitation so the
        # skip can never silently mask a real regression elsewhere
        pytest.skip(
            "XLA CPU backend capability gap: cross-process collectives "
            f"are unimplemented on this host ({_CPU_BACKEND_GAP!r}); "
            "the same 2-process world runs over real sockets in "
            "test_two_process_world_over_tcp_transport — on a TPU/GPU "
            "backend this test runs in full")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"MP_OK {pid}" in out, f"worker {pid} output:\n{out[-4000:]}"


def test_two_process_world_over_tcp_transport():
    """The transport-backed sibling: the SAME 2-process world, its
    cross-process reduction riding the supervised TCP transport's
    sealed envelopes instead of XLA collectives — one server process
    merging both workers' pushes, both pulling the identical merged
    round — so the 2-process scenario is exercised on every host,
    whatever its XLA backend implements (ISSUE satellite: zero standing
    reds outside tier-1)."""
    port = _free_port()
    worker = os.path.join(REPO, "tests", "transport_worker.py")
    steps, nworkers = 6, 2
    procs = {}
    for rank in range(nworkers + 1):   # rank 0 = the server process
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env["BYTEPS_TW_MODE"] = "bitflip"   # the worker body; no fault
        env["BYTEPS_TW_RANK"] = str(rank)
        env["BYTEPS_TW_PORT"] = str(port)
        env["BYTEPS_TW_STEPS"] = str(steps)
        env["BYTEPS_TW_NWORKERS"] = str(nworkers)
        env["BYTEPS_LOG_LEVEL"] = "ERROR"
        env.pop("BYTEPS_FAULT_SPEC", None)
        procs[rank] = subprocess.Popen([sys.executable, worker], env=env,
                                       cwd=REPO, stdout=subprocess.PIPE,
                                       stderr=subprocess.STDOUT, text=True)
    outs = {}
    try:
        for rank, p in procs.items():
            outs[rank], _ = p.communicate(timeout=240)
    except subprocess.TimeoutExpired:
        for p in procs.values():
            p.kill()
        pytest.fail("transport 2-process workers hung; partial output: "
                    + "".join(o[-1500:] for o in outs.values()))
    for rank, p in procs.items():
        assert p.returncode == 0, f"rank {rank}:\n{outs[rank][-4000:]}"
    digests = {}
    for rank in (1, 2):
        for line in outs[rank].splitlines():
            if line.startswith("DIGEST "):
                digests[rank] = line.split()[2]
    assert len(set(digests.values())) == 1, digests
    # bit-identical to the single-process replay of the same seeds
    import hashlib

    from tests.transport_worker import LR, N, _grad
    params = np.zeros(N, np.float32)
    for step in range(steps):
        merged = np.sum([_grad(step, w) for w in range(nworkers)],
                        axis=0, dtype=np.float32)
        params -= LR * merged
    assert digests[1] == hashlib.sha256(params.tobytes()).hexdigest()
