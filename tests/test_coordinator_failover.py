"""Coordinator failover (ISSUE 8): replicated bus state, standby
takeover with seeded state, view-aware bus resolution, heartbeat
re-hosting, the dead-successor escalation ladder, and the
``kill:site=coordinator`` chaos predicate.

The multiprocess acceptance pins:

- ``test_coordinator_kill_shrink_matches_clean_run`` — rank 0 (bus +
  heartbeat host) is chaos-killed mid-step; the survivors fail over the
  bus to the standby, shrink in place, finish training, and match a
  fault-free 2-process run from the same state.
- ``test_coordinator_kill_rejoin_through_successor_bus`` — the killed
  coordinator restarts and is admitted by the SUCCESSOR bus at a step
  boundary; every member finishes at the same state.
- ``test_coordinator_double_failure_standby_dies_mid_failover`` — the
  standby dies the moment its detector fires (mid-failover); the last
  survivor escalates down the rank ladder, hosts the bus itself, and
  completes alone — never wedging past the rendezvous window.
- ``test_sync_deadline_wedge_reconciles_world_no_exit`` — a wedged
  collective on one rank trips ``BYTEPS_SYNC_DEADLINE_S``; the evidence
  routes through a membership *reconcile* (not ``os._exit``) and the
  full world keeps training.

All chaos-marked; ``tools/run_chaos.sh coordinator`` runs this file
plus the sync-deadline units under the hard per-test timeout.
"""

from __future__ import annotations

import os
import socket
import threading
import time

import numpy as np
import pytest

import byteps_tpu.core.api as api
from byteps_tpu.common.config import reset_config
from byteps_tpu.common.telemetry import counters
from byteps_tpu.fault import injector as fault_injector
from byteps_tpu.fault import membership as mm
from byteps_tpu.fault.membership import (ElasticMembership, MembershipView,
                                         _BusServer, bus_request,
                                         resolve_bus_addr)
from byteps_tpu.utils.failure_detector import HeartbeatMonitor

from .conftest import free_port as _free_port
from .test_elastic import _communicate, _final, _simulate, _spawn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_epoch():
    mm._reset_epoch_for_tests()
    yield
    if api.initialized():
        api.shutdown()
    api._declared_order = []
    mm._reset_epoch_for_tests()


def _req(port, msg, timeout=20.0):
    return bus_request(("127.0.0.1", port), msg, timeout=timeout)


# -- view-aware address resolution ------------------------------------------


def test_resolve_bus_addr_is_view_aware_after_coordinator_change(monkeypatch):
    monkeypatch.setenv("BYTEPS_MEMBERSHIP_HOSTS",
                       "10.0.0.5:7000, 10.0.0.6:7100, 10.0.0.7")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", "9000")
    monkeypatch.delenv("BYTEPS_MEMBERSHIP_PORT", raising=False)
    reset_config()
    # explicit arg always wins
    assert resolve_bus_addr("1.2.3.4:5") == ("1.2.3.4", 5)
    # no view: static env resolution (DMLC root + port+2)
    assert resolve_bus_addr() == ("127.0.0.1", 9002)
    # the view's coordinator picks the host-map entry — a failover from
    # rank 0 to rank 1 MOVES the resolved address
    assert resolve_bus_addr(view=MembershipView(0, (0, 1, 2))) == \
        ("10.0.0.5", 7000)
    assert resolve_bus_addr(view=MembershipView(1, (1, 2))) == \
        ("10.0.0.6", 7100)
    # an entry without a port uses the default membership port
    assert resolve_bus_addr(view=MembershipView(2, (2,))) == \
        ("10.0.0.7", 9002)
    # coordinator outside the map: static fallback
    assert resolve_bus_addr(view=MembershipView(3, (7,))) == \
        ("127.0.0.1", 9002)


# -- bus replication ---------------------------------------------------------


def test_bus_ping_and_standby_replication():
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0, 1, 2)),
                     rendezvous_timeout_s=2.0, sync_timeout_s=5.0,
                     host_rank=0)
    try:
        ping = _req(port, {"op": "ping"})
        assert ping["ok"] and ping["epoch"] == 0
        assert ping["coordinator"] == 0 and ping["standby"] == 1
        assert ping["bus_rank"] == 0
        # replies to the STANDBY piggyback the replica; other ranks'
        # replies do not
        r1 = _req(port, {"op": "metrics_put", "rank": 1, "metrics": {"x": 1}})
        r0 = _req(port, {"op": "metrics_put", "rank": 0, "metrics": {"x": 0}})
        assert "replica" in r1 and "replica" not in r0
        rep = r1["replica"]
        assert rep["epoch"] == 0 and rep["world"] == [0, 1, 2]
        # the explicit replicate verb answers anyone (a rank that just
        # BECAME standby bootstraps through it)
        rep2 = _req(port, {"op": "replicate", "rank": 2})["replica"]
        assert rep2["metrics"][1][1] == {"x": 1}
    finally:
        bus.close()


def test_bus_seeded_from_replica_resumes_parked_joiner():
    """The failover headline at bus granularity: a replica taken from a
    bus with a PARKED joiner seeds a successor that still advertises the
    admission — the joiner survives the coordinator's death parked, and
    the next state-carrying quorum admits it."""
    port_a = _free_port()
    bus_a = _BusServer(("127.0.0.1", port_a), MembershipView(1, (1, 2)),
                       rendezvous_timeout_s=2.0, sync_timeout_s=30.0,
                       host_rank=1)
    out = {}
    try:
        # a joiner parks on bus A...
        tj = threading.Thread(target=lambda: out.update(
            joinA=_req(port_a, {"op": "rejoin", "rank": 0}, timeout=5.0)))
        tj.start()
        time.sleep(0.3)   # until the rejoin op is registered
        rep = _req(port_a, {"op": "replicate", "rank": 2})["replica"]
        assert rep["join_wait"] == [0]
    finally:
        bus_a.close()     # ...and the coordinator dies
    tj.join(timeout=10)

    # the standby binds a successor seeded with the replica
    port_b = _free_port()
    bus_b = _BusServer(("127.0.0.1", port_b), MembershipView(1, (1, 2)),
                       rendezvous_timeout_s=2.0, sync_timeout_s=30.0,
                       seed=rep, host_rank=2)
    try:
        assert bus_b.view() == MembershipView(1, (1, 2))
        from byteps_tpu.utils.checkpoint import pack_state
        state = pack_state({"w": np.ones(3, np.float32)})

        def member(r, step, with_state):
            msg = {"op": "sync", "rank": r, "epoch": 1, "step": step,
                   "payload": None}
            if with_state:
                msg["state"] = state
                msg["declared"] = ["g"]
            out[(r, step)] = _req(port_b, msg, timeout=40.0)

        # first quorum: no state attached, but the seeded park means the
        # reply already advertises join_waiting
        ts = [threading.Thread(target=member, args=(r, 7, False))
              for r in (1, 2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=20)
        assert out[(1, 7)]["ok"] and out[(1, 7)]["join_waiting"], out
        # the joiner re-parks on the successor (its old connection died
        # with bus A) and the next state-carrying quorum admits it
        tj2 = threading.Thread(target=lambda: out.update(
            joinB=_req(port_b, {"op": "rejoin", "rank": 0}, timeout=40.0)))
        tj2.start()
        time.sleep(0.2)
        ts = [threading.Thread(target=member, args=(r, 8, True))
              for r in (1, 2)]
        for t in ts:
            t.start()
        for t in ts + [tj2]:
            t.join(timeout=30)
        join = out["joinB"]
        assert join["ok"] and join["epoch"] == 2
        assert join["world"] == [0, 1, 2] and join["declared"] == ["g"]
    finally:
        bus_b.close()


def test_elastic_failover_seeds_bus_and_records_flight():
    """Two in-process members: the standby holds a replica, the
    coordinator's bus dies, and the standby's shrink re-binds the SAME
    address seeded with the replicated state — recorded as
    ``membership.coordinator_failover``."""
    from byteps_tpu.common import flight_recorder as _flight
    port = _free_port()
    bus = f"127.0.0.1:{port}"
    m0 = ElasticMembership(0, [0, 1], bus, rendezvous_timeout_s=2.0,
                           sync_timeout_s=5.0).start()
    m1 = ElasticMembership(1, [0, 1], bus, rendezvous_timeout_s=2.0,
                           sync_timeout_s=5.0).start()
    try:
        assert m0.hosting_bus and not m1.hosting_bus
        assert m1.standby_rank == 1 and m1._pull_replica()
        assert m1._replica["epoch"] == 0
        # the coordinator dies (its bus with it)
        m0._bus.close()
        m0._bus = None
        view = m1.shrink({0})
        assert view == MembershipView(1, (1,))
        assert m1.hosting_bus
        assert counters.get("membership.coordinator_failover") >= 1
        kinds = [e["kind"] for e in _flight.recorder.snapshot()]
        assert "membership.coordinator_failover" in kinds
        # the successor bus answers with the agreed view
        ping = _req(port, {"op": "ping"})
        assert ping["epoch"] == 1 and ping["world"] == [1]
        assert ping["bus_rank"] == 1
    finally:
        m1.stop()
        m0.stop()


def test_ensure_bus_bind_failure_is_loud_not_silent():
    """Satellite: a bind that stays refused with NOTHING serving the
    address is a busless world — counter + flight event + raise, not a
    log-and-continue."""
    blocker = socket.socket()
    blocker.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)   # accepts nothing, speaks nothing
    port = blocker.getsockname()[1]
    try:
        m = ElasticMembership(0, [0], f"127.0.0.1:{port}",
                              rendezvous_timeout_s=1.0, sync_timeout_s=2.0)
        with pytest.raises(OSError):
            m.start()
        assert counters.get("membership.bus_bind_failed") >= 1
        from byteps_tpu.common import flight_recorder as _flight
        kinds = [e["kind"] for e in _flight.recorder.snapshot()]
        assert "membership.bus_bind_failed" in kinds
    finally:
        blocker.close()


# -- heartbeat re-hosting ----------------------------------------------------


def test_heartbeat_server_follows_server_rank_and_detects_its_death():
    """A monitor hosted on an arbitrary server_rank (not rank 0), with
    explicit world-set ranks; a client that has HEARD the server once
    detects its death within `timeout` even though the startup grace is
    much larger."""
    port = _free_port()
    fired = []
    server = HeartbeatMonitor(2, coordinator=f"127.0.0.1:{port}",
                              interval=0.05, timeout=5.0, grace=30.0,
                              ranks={0, 2}, server_rank=2,
                              on_failure=lambda s: None).start()
    client = HeartbeatMonitor(0, coordinator=f"127.0.0.1:{port}",
                              interval=0.05, timeout=0.5, grace=30.0,
                              ranks={0, 2}, server_rank=2,
                              on_failure=lambda s: fired.append(set(s)))
    client.start()
    try:
        deadline = time.monotonic() + 5.0
        while not client._got_reply and time.monotonic() < deadline:
            time.sleep(0.02)
        assert client._got_reply, "client never heard the server"
        server.stop()   # the server dies mid-run
        deadline = time.monotonic() + 5.0
        while not fired and time.monotonic() < deadline:
            time.sleep(0.02)
        # detected in ~timeout seconds despite grace=30: the grace gate
        # opens permanently after the first reply
        assert fired and fired[0] == {2}, fired
    finally:
        client.stop()
        server.stop()


# -- observability surfaces --------------------------------------------------


def test_healthz_and_debug_state_name_the_control_plane():
    port = _free_port()
    m = ElasticMembership(0, [0, 1, 2], f"127.0.0.1:{port}",
                          rendezvous_timeout_s=1.0,
                          sync_timeout_s=2.0).start()
    try:
        from byteps_tpu.common.obs_server import debug_state, healthz
        doc = healthz()["membership"]
        assert doc["coordinator"] == 0 and doc["standby"] == 1
        assert doc["is_coordinator"] and doc["hosting_bus"]
        assert doc["bus_addr"].endswith(f":{port}")
        dbg = debug_state()["membership"]
        assert dbg["coordinator"] == 0 and dbg["standby"] == 1
        assert dbg["replica"] == {"held": False, "epoch": None}
        assert m._pull_replica()
        assert debug_state()["membership"]["replica"]["held"]
    finally:
        m.stop()
    # with the membership stopped the sections disappear again
    from byteps_tpu.common.obs_server import healthz
    assert "membership" not in healthz()


def test_bps_top_renders_coordinator_and_failover_header():
    import importlib
    bps_top = importlib.import_module("tools.bps_top")
    live = bps_top.render({"epoch": 1, "world": [1, 2],
                           "coordinator": 1, "standby": 2,
                           "ranks": {}})
    assert "coordinator=1 standby=2" in live.splitlines()[0]
    failover = bps_top.render({"epoch": 1, "world": [1, 2],
                               "coordinator": 1, "standby": 2,
                               "local_only": True,
                               "failover_in_progress": True,
                               "ranks": {}})
    assert "FAILOVER IN PROGRESS" in failover.splitlines()[0]
    plain = bps_top.render({"epoch": 0, "world": [0], "local_only": True,
                            "ranks": {}})
    assert "local-only view: no membership bus" in plain.splitlines()[0]


# -- kill:site=coordinator ---------------------------------------------------


def test_kill_site_coordinator_spec_validation():
    rules = fault_injector.parse_spec("kill:site=coordinator:step=3")
    assert rules[0].kind == "kill" and rules[0].site == "coordinator"
    with pytest.raises(ValueError, match="site=coordinator"):
        fault_injector.parse_spec("kill:site=dcn:step=3")
    with pytest.raises(ValueError, match="kill-only"):
        fault_injector.parse_spec("delay:site=coordinator:ms=5")


def test_kill_site_coordinator_fires_only_on_the_coordinator(monkeypatch):
    exits = []
    monkeypatch.setattr(fault_injector, "_exit",
                        lambda code: exits.append(code))
    port = _free_port()
    m = ElasticMembership(1, [1, 2], f"127.0.0.1:{port}",
                          rendezvous_timeout_s=1.0,
                          sync_timeout_s=2.0).start()
    try:
        # this process is rank 2 of the active membership: NOT the
        # coordinator, so the kill predicate must not fire...
        m.rank = 2
        fault_injector._reset_lifetime_for_tests()
        inj = fault_injector.arm("kill:site=coordinator:step=1", rank=2)
        inj.on_step()
        assert exits == []
        # ...while the coordinator at the same step dies
        m.rank = 1
        fault_injector._reset_lifetime_for_tests()
        inj2 = fault_injector.arm("kill:site=coordinator:step=1", rank=1)
        inj2.on_step()
        assert exits == [1]
        # and a re-armed schedule (elastic suspend/resume) never
        # cascade-kills: the lifetime counter is already past the step
        fault_injector.arm("kill:site=coordinator:step=1",
                           rank=1).on_step()
        assert exits == [1]
    finally:
        fault_injector.disarm()
        m.stop()


# -- multiprocess acceptance pins -------------------------------------------


@pytest.mark.chaos
def test_coordinator_kill_shrink_matches_clean_run():
    """THE headline: rank 0 — bus host AND heartbeat server — is
    chaos-killed mid-step.  The standby (rank 1) re-binds the bus seeded
    with its replica, survivors shrink to {1, 2} in place (no process
    exit), re-host the heartbeat, finish training, and their final state
    equals a fault-free 2-process {1, 2} run from the shrink-boundary
    state."""
    n, kill_at = 9, 4
    bus, hb = str(_free_port()), str(_free_port())
    procs = {
        r: _spawn(r, "0,1,2", bus, hb, n, extra={
            "BYTEPS_FAULT_SPEC": f"kill:site=coordinator:step={kill_at}",
            "BYTEPS_FAULT_SEED": "7"})
        for r in (0, 1, 2)}
    outs = _communicate(procs)

    # exactly the coordinator died (same spec armed on EVERY rank: the
    # site predicate selects the bus host, and the successor — whose
    # step counter is already past kill_at — is never cascade-killed)
    assert procs[0].returncode == 1, outs[0][-3000:]
    assert "FINAL" not in outs[0]
    finals = {}
    for r in (1, 2):
        assert procs[r].returncode == 0, outs[r][-3000:]
        assert "WORLD 1 1,2" in outs[r], outs[r][-3000:]
        finals[r] = _final(outs[r])
        assert finals[r][0] == 1 and finals[r][1] == "1,2", finals[r]
    assert finals[1][2] == pytest.approx(finals[2][2], abs=1e-6)

    # fault-free 2-process run from the same state
    w_shrink = _simulate(0.0, (0, 1, 2), kill_at - 1)
    bus2 = str(_free_port())
    procs2 = {
        r: _spawn(r, "1,2", bus2, "", n, extra={
            "BYTEPS_ELASTIC_START_STEP": str(kill_at),
            "BYTEPS_ELASTIC_INIT_W": repr(w_shrink)})
        for r in (1, 2)}
    outs2 = _communicate(procs2)
    for r in (1, 2):
        assert procs2[r].returncode == 0, outs2[r][-3000:]
    clean = _final(outs2[1])
    assert clean[0] == 0 and clean[1] == "1,2"
    assert finals[1][2] == pytest.approx(clean[2], abs=1e-5), (
        finals, clean, w_shrink)


@pytest.mark.chaos
def test_coordinator_kill_rejoin_through_successor_bus():
    """After the coordinator kill, the dead rank restarts and rejoins
    through the SUCCESSOR bus (rank 1's, at the same address): admitted
    at a step boundary with epoch/keys/params, and every member finishes
    at the same state."""
    n, kill_at = 30, 4
    bus, hb = str(_free_port()), str(_free_port())
    procs = {
        r: _spawn(r, "0,1,2", bus, hb, n, extra={
            "BYTEPS_ELASTIC_STEP_SLEEP": "0.3",
            "BYTEPS_FAULT_SPEC": f"kill:site=coordinator:step={kill_at}",
            "BYTEPS_FAULT_SEED": "7"})
        for r in (0, 1, 2)}
    out_victim, _ = procs[0].communicate(timeout=120)
    assert procs[0].returncode == 1, out_victim[-3000:]
    # the rejoiner gets the heartbeat port too: admitted as the new
    # coordinator it re-hosts the UDP server (taking the port over from
    # rank 1's interim server), so liveness detection stays armed after
    # the rejoin
    rejoiner = _spawn(0, "0,1,2", bus, hb, n, extra={
        "BYTEPS_ELASTIC_REJOIN": "1",
        "BYTEPS_ELASTIC_STEP_SLEEP": "0.3"})
    outs = _communicate({1: procs[1], 2: procs[2], "rj": rejoiner})

    assert rejoiner.returncode == 0, outs["rj"][-3000:]
    rejoin_line = next(line for line in outs["rj"].splitlines()
                       if line.startswith("REJOINED "))
    _, epoch, world, step0 = rejoin_line.split()
    assert int(epoch) == 2 and world == "0,1,2", rejoin_line
    assert kill_at - 1 <= int(step0) < n, rejoin_line
    finals = {}
    for r in (1, 2):
        assert procs[r].returncode == 0, outs[r][-3000:]
        assert "WORLD 1 1,2" in outs[r], outs[r][-3000:]
        assert "WORLD 2 0,1,2" in outs[r], outs[r][-3000:]
        finals[r] = _final(outs[r])
        assert finals[r][0] == 2 and finals[r][1] == "0,1,2", finals[r]
    fin_rj = _final(outs["rj"])
    assert fin_rj[0] == 2 and fin_rj[1] == "0,1,2", fin_rj
    assert finals[1][2] == pytest.approx(finals[2][2], abs=1e-6)
    assert finals[1][2] == pytest.approx(fin_rj[2], abs=1e-6)


@pytest.mark.chaos
def test_coordinator_double_failure_standby_dies_mid_failover():
    """Kill the coordinator, then lose the standby INSIDE the failover
    window (it exits the moment its detector fires, before binding the
    successor bus).  The last survivor must not wedge: its hello to the
    never-bound bus exhausts the rendezvous window, rank 1 is presumed
    dead too, and rank 2 hosts the bus itself and completes alone."""
    n, kill_at = 9, 4
    bus, hb = str(_free_port()), str(_free_port())
    procs = {
        r: _spawn(r, "0,1,2", bus, hb, n, extra=(
            {"BYTEPS_FAULT_SPEC": f"kill:site=coordinator:step={kill_at}",
             "BYTEPS_FAULT_SEED": "7"} if r == 0 else
            {"BYTEPS_ELASTIC_DIE_ON_DETECT": "1"} if r == 1 else None))
        for r in (0, 1, 2)}
    outs = _communicate(procs)

    assert procs[0].returncode == 1, outs[0][-3000:]
    assert procs[1].returncode == 1, outs[1][-3000:]
    assert "DIED-ON-DETECT" in outs[1], outs[1][-3000:]
    assert procs[0].returncode == 1
    # the survivor either finished alone (the escalation ladder bound
    # the bus on the third-lowest rank) or exited restartable — never a
    # wedge (the _communicate timeout would have tripped)
    if procs[2].returncode == 0:
        epoch, world, w0 = _final(outs[2])
        assert world == "2" and epoch >= 1, (epoch, world)
        expected = _simulate(_simulate(0.0, (0, 1, 2), kill_at - 1),
                             (2,), n - kill_at + 1)
        assert w0 == pytest.approx(expected, abs=1e-5), (w0, expected)
    else:
        assert procs[2].returncode == 17, outs[2][-3000:]


@pytest.mark.chaos
def test_sync_deadline_wedge_reconciles_world_no_exit():
    """The second acceptance lane: rank 1's engine wedges for 4s at step
    5 with BYTEPS_SYNC_DEADLINE_S=1.  The deadline fires, the installed
    action runs a membership reconcile (epoch +1, world unchanged — the
    wedge resolves, nobody is actually dead), members parked in the step
    sync JOIN the rendezvous, and the run finishes on the FULL world
    with the exact fault-free result.  No process exits."""
    n, wedge_at = 9, 5
    bus = str(_free_port())
    procs = {
        r: _spawn(r, "0,1,2", bus, "", n, extra={
            "BYTEPS_SYNC_DEADLINE_S": "1.0",
            "BYTEPS_MEMBERSHIP_RENDEZVOUS_TIMEOUT": "8",
            **({"BYTEPS_ELASTIC_WEDGE_STEP": str(wedge_at),
                "BYTEPS_ELASTIC_WEDGE_S": "4"} if r == 1 else {})})
        for r in (0, 1, 2)}
    outs = _communicate(procs)

    assert "WEDGING 1" in outs[1], outs[1][-3000:]
    trips = next(line for line in outs[1].splitlines()
                 if line.startswith("DEADLINE-TRIPS "))
    assert int(trips.split()[1]) >= 1, trips        # the deadline fired
    assert int(trips.split()[3]) >= 1, trips        # ...into a reconcile
    finals = {}
    for r in (0, 1, 2):
        # rc 0 everywhere IS the os._exit proof: the old escalation (17)
        # would show up as a nonzero exit
        assert procs[r].returncode == 0, outs[r][-3000:]
        finals[r] = _final(outs[r])
        assert finals[r][0] >= 1 and finals[r][1] == "0,1,2", finals[r]
    expected = _simulate(0.0, (0, 1, 2), n)         # world never changed
    for r in (0, 1, 2):
        assert finals[r][2] == pytest.approx(expected, abs=1e-5), (
            finals, expected)
