"""Retry/backoff layer (common/retry.py) + fault-tolerance counters
(common/telemetry.py Counters)."""

from __future__ import annotations

import random
import time

import pytest

from byteps_tpu.common.retry import RetryPolicy
from byteps_tpu.common.telemetry import Counters, counters


def _policy(**kw):
    kw.setdefault("base_delay_s", 0.0)
    kw.setdefault("max_delay_s", 0.0)
    return RetryPolicy(**kw)


def test_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert _policy(max_attempts=5).call(flaky) == "ok"
    assert len(calls) == 3


def test_attempt_budget_exhausted_reraises_last():
    calls = []

    def always():
        calls.append(1)
        raise ValueError(f"attempt {len(calls)}")

    with pytest.raises(ValueError, match="attempt 3"):
        _policy(max_attempts=3).call(always)
    assert len(calls) == 3


def test_non_matching_exception_propagates_immediately():
    calls = []

    def wrong_kind():
        calls.append(1)
        raise KeyError("not retryable")

    with pytest.raises(KeyError):
        _policy(max_attempts=5, retry_on=(OSError,)).call(wrong_kind)
    assert len(calls) == 1


def test_deadline_cuts_attempt_budget_short():
    calls = []

    def slow_fail():
        calls.append(1)
        time.sleep(0.05)
        raise OSError("down")

    with pytest.raises(OSError):
        _policy(max_attempts=50, deadline_s=0.01).call(slow_fail)
    assert len(calls) == 1  # elapsed >= deadline after the first attempt


def test_full_jitter_bounded_and_seeded():
    p = RetryPolicy(max_attempts=5, base_delay_s=0.1, max_delay_s=0.8,
                    rng=random.Random(42))
    q = RetryPolicy(max_attempts=5, base_delay_s=0.1, max_delay_s=0.8,
                    rng=random.Random(42))
    for attempt in range(1, 8):
        cap = min(0.8, 0.1 * 2 ** (attempt - 1))
        d = p.backoff(attempt)
        assert 0.0 <= d <= cap
        assert d == q.backoff(attempt)  # same seed, same schedule


def test_sleep_injectable_and_called_between_attempts():
    slept = []

    def flaky():
        if len(slept) < 2:
            raise OSError("x")
        return 1

    p = RetryPolicy(max_attempts=5, base_delay_s=0.25, max_delay_s=0.25,
                    rng=random.Random(0), sleep=slept.append)
    assert p.call(flaky) == 1
    assert len(slept) == 2 and all(0.0 <= s <= 0.25 for s in slept)


def test_from_config_reads_env_knobs(monkeypatch):
    from byteps_tpu.common.config import Config
    monkeypatch.setenv("BYTEPS_RETRY_MAX_ATTEMPTS", "7")
    monkeypatch.setenv("BYTEPS_RETRY_BASE_DELAY", "0.5")
    monkeypatch.setenv("BYTEPS_RETRY_MAX_DELAY", "9")
    monkeypatch.setenv("BYTEPS_RETRY_DEADLINE", "123")
    p = RetryPolicy.from_config(Config.from_env(), retry_on=(OSError,))
    assert p.max_attempts == 7
    assert p.base_delay_s == 0.5
    assert p.max_delay_s == 9.0
    assert p.deadline_s == 123.0
    assert p.retry_on == (OSError,)


def test_invalid_policy_rejected():
    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        RetryPolicy(base_delay_s=-1)


def test_retry_counters_flow():
    counters.reset()

    def flaky(state=[]):  # noqa: B006
        state.append(1)
        if len(state) < 2:
            raise OSError("x")

    _policy(max_attempts=3).call(flaky)
    assert counters.get("retry.attempt") == 1
    with pytest.raises(OSError):
        _policy(max_attempts=2).call(
            lambda: (_ for _ in ()).throw(OSError("y")))
    assert counters.get("retry.gave_up") == 1


def test_counters_unit():
    c = Counters()
    c.inc("a")
    c.inc("a", 2)
    assert c.get("a") == 3 and c.get("missing") == 0
    assert c.snapshot() == {"a": 3}
    c.reset()
    assert c.snapshot() == {}
