"""Distributed serving tier tests (server/serving_tier.py + serve_ring.py
+ serve_autoscaler.py, ISSUE 15).

What is pinned here:

- the consistent-hash ring: deterministic across instances/processes,
  adding/removing a host remaps ONLY its arc (~1/N of the key space),
  arc shares sum to 1;
- admission control: token bucket + queue watermark shed verdicts, shed
  only while the client stays inside its staleness bound
  (``serve.shed`` / ``serve.shed_bypass``), the shed reply keeps the
  client's freshness clock honest;
- the host core's two-phase publication: stage (idempotent) → commit
  (atomic ring swap, dedup by snapshot id, carry-forward of unchanged
  keys, loud drop of unshippable ones);
- the publisher: ships only owned+changed keys per host (delta bytes
  scale with churn), retires a host after consecutive ship failures
  (directory ban — no flap-back), follows directory membership;
- the router + PullClient: owner-routed groups, failover along the
  replica arc, the ISSUE satellite fix (a refresh hitting
  ``ServeUnavailable`` re-resolves the ring via ``reroute()`` instead
  of retrying the dead host), opt-in stale-on-error degradation;
- codec keys travel wire-encoded with the TRAINING codec end to end;
- the bus directory verbs: register/TTL/unregister/ban/generation,
  autoscaler target, replica-snapshot survival;
- the autoscaler's pure ``decide`` (up on shed, down when idle with
  probation-first victims, placement excluding probationed hosts);
- the acceptance storm: ≥3 REAL serving-host processes behind the TCP
  transport under a concurrent pull storm with one host chaos-killed
  (``kill:site=serve_host``) and one partitioned mid-storm
  (``serve_ctl`` → ``chaos_arm``): ZERO failed reads, the ring heals
  through the bus, staleness re-bounds after heal, finals exact.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from byteps_tpu.common.telemetry import counters
from byteps_tpu.fault import injector as inj
from byteps_tpu.fault.membership import (SERVE_RANK_BASE, MembershipView,
                                         _BusServer, bus_request)
from byteps_tpu.server.kv_store import KVStore
from byteps_tpu.server.serve_autoscaler import TierAutoscaler
from byteps_tpu.server.serve_ring import ServeRing
from byteps_tpu.server.serving import ServeReply, ServeUnavailable
from byteps_tpu.server.serving_tier import (AdmissionControl,
                                            ServingHostCore, ServingTier,
                                            TierDirectory, TierRouter,
                                            inproc_host)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh():
    yield
    inj.disarm()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _store(keys, numel=8):
    s = KVStore()
    for i, k in enumerate(keys):
        s.init_key(k, np.full(numel, float(i), np.float32))
    return s


def _inproc_tier(n_hosts=3, keys=(), replicas=2, **kw):
    d = TierDirectory(static_hosts={i: ("127.0.0.1", i + 1)
                                    for i in range(n_hosts)})
    cores = [inproc_host(ServingHostCore(host_id=i))
             for i in range(n_hosts)]
    store = _store(keys)
    tier = ServingTier(store, directory=d, replicas=replicas,
                       cut_interval_s=None, **kw)
    return store, tier, cores


KEYS = [f"t{i}" for i in range(12)]


# -- the ring ---------------------------------------------------------------

def test_ring_deterministic_and_distinct_replicas():
    a = ServeRing([3, 1, 2], vnodes=32)
    b = ServeRing([1, 2, 3], vnodes=32)
    for k in KEYS:
        assert a.owner(k) == b.owner(k)
        rs = a.replica_hosts(k, 2)
        assert rs == b.replica_hosts(k, 2)
        assert len(set(rs)) == 2 and rs[0] == a.owner(k)
    # n clamps to the host count
    assert len(a.replica_hosts("x", 99)) == 3


def test_ring_change_remaps_only_the_moved_arc():
    keys = [f"k{i}" for i in range(400)]
    r3 = ServeRing([0, 1, 2], vnodes=64)
    r4 = ServeRing([0, 1, 2, 3], vnodes=64)
    moved = r4.moved_keys(keys, r3, 1)
    # adding 1 host to 3 should move ~1/4 of the space, never half
    assert 0 < len(moved) / len(keys) < 0.45
    # every moved key moved TO the new host; unmoved keys kept owners
    for k in keys:
        if k in moved:
            assert r4.owner(k) == 3
        else:
            assert r4.owner(k) == r3.owner(k)
    # removing it again restores the exact old routing
    r4.remove(3)
    assert not r4.moved_keys(keys, r3, 1)


def test_ring_arc_share_and_empty_ring():
    r = ServeRing([0, 1, 2, 3], vnodes=64)
    shares = r.arc_share()
    assert abs(sum(shares.values()) - 1.0) < 1e-9
    assert set(shares) == {0, 1, 2, 3}
    assert all(s > 0.05 for s in shares.values())   # vnodes smooth it
    with pytest.raises(LookupError):
        ServeRing([], vnodes=8).owner("x")


# -- admission control -------------------------------------------------------

def test_admission_token_bucket_and_queue_watermark():
    ac = AdmissionControl(rate=10.0, burst=2.0, queue_high=3)
    assert ac.admit() and ac.admit()        # burst spent
    assert not ac.admit()                   # bucket dry
    time.sleep(0.25)                        # ~2.5 tokens refill
    assert ac.admit()
    # queue watermark sheds regardless of tokens
    ac2 = AdmissionControl(rate=0.0, queue_high=2)
    assert ac2.admit()
    for _ in range(3):
        ac2.enter()
    assert not ac2.admit()
    ac2.exit()
    assert ac2.admit()


# -- host core: stage/commit/shed -------------------------------------------

def _stage(core, key, value, version):
    core.receive_key(key, np.asarray(value, np.float32),
                     {"version": version, "codec": None})


def test_host_stage_commit_publish_and_carry_forward():
    core = ServingHostCore(host_id=5)
    _stage(core, "a", [1.0], 1)
    _stage(core, "b", [2.0], 1)
    out = core.commit({"snapshot_id": 1, "gen": 0,
                       "versions": {"a": 1, "b": 1}})
    assert out["keys"] == 2 and out["missing"] == 0
    r = core.pull()
    assert r.full and set(r.items) == {"a", "b"}
    # next cut changes only "a": "b" carries forward, unchanged travels 0
    _stage(core, "a", [9.0], 2)
    core.commit({"snapshot_id": 2, "gen": 0,
                 "versions": {"a": 2, "b": 1}})
    r2 = core.pull(since_id=r.snapshot_id)
    assert not r2.full and set(r2.items) == {"a"}
    assert float(np.asarray(r2.items["a"].payload)[0]) == 9.0
    # commit is idempotent by snapshot id (transport retransmit)
    dup = core.commit({"snapshot_id": 2, "gen": 0,
                       "versions": {"a": 2, "b": 1}})
    assert dup.get("dup") is True


def test_host_commit_missing_key_drops_loudly():
    core = ServingHostCore(host_id=1)
    _stage(core, "a", [1.0], 1)
    c0 = counters.get("serve.tier_missing_keys")
    out = core.commit({"snapshot_id": 1, "gen": 0,
                       "versions": {"a": 1, "ghost": 3}})
    assert out["missing"] == 1 and out["keys"] == 1
    assert counters.get("serve.tier_missing_keys") == c0 + 1
    # the published cut serves what it has; ghost is simply absent
    assert set(core.pull().items) == {"a"}


def test_host_sheds_only_inside_the_clients_bound():
    core = ServingHostCore(host_id=0)
    _stage(core, "a", [1.0], 1)
    core.commit({"snapshot_id": 1, "gen": 0, "versions": {"a": 1}})
    base = core.pull().snapshot_id
    _stage(core, "a", [2.0], 2)
    core.commit({"snapshot_id": 2, "gen": 0, "versions": {"a": 2}})
    # drain the bucket so every admit() says shed
    core.admission = AdmissionControl(rate=1e-9, burst=1e-9,
                                      queue_high=1000)
    c_shed = counters.get("serve.shed")
    c_byp = counters.get("serve.shed_bypass")
    # inside the bound: shed (empty reply pinned at the client's base)
    r = core.pull(since_id=base, max_stale_s=60.0)
    assert r.shed and not r.items and r.snapshot_id == base
    assert counters.get("serve.shed") == c_shed + 1
    # outside the bound (base older than 0s): served anyway
    r2 = core.pull(since_id=base, max_stale_s=0.0)
    assert not r2.shed and set(r2.items) == {"a"}
    assert counters.get("serve.shed_bypass") == c_byp + 1
    # no base at all: never shed — there is no cache to serve from
    r3 = core.pull(max_stale_s=60.0)
    assert not r3.shed and r3.full


def test_shed_reply_keeps_client_freshness_clock():
    store, tier, cores = _inproc_tier(1, KEYS[:3], replicas=1)
    tier.cut()
    client = tier.client(max_staleness_s=0.05)
    client.pull()
    fetched = client._fetched_at
    cores[0].admission = AdmissionControl(rate=1e-9, burst=1e-9,
                                          queue_high=1000)
    time.sleep(0.08)            # stale now
    c0 = counters.get("serve.shed_served")
    vals = client.pull()        # refresh -> shed -> stale cache served
    assert set(vals) == set(KEYS[:3])
    assert counters.get("serve.shed_served") == c0 + 1
    # the freshness clock did NOT advance: the next pull retries
    assert client._fetched_at == fetched
    tier.close()


# -- publisher ---------------------------------------------------------------

def test_tier_ships_owned_changed_only_and_delta_bytes():
    store, tier, cores = _inproc_tier(3, KEYS, replicas=2)
    tier.cut()
    # every key landed on exactly its replica set
    for k in KEYS:
        owners = set(tier.ring.replica_hosts(k, 2))
        holders = {c.host_id for c in cores
                   if k in (c.ring.latest().versions
                            if c.ring.latest() else {})}
        assert holders == owners
    recv0 = counters.get("serve.tier_recv_keys")
    store.push_delta(KEYS[0], np.ones(8, np.float32))
    tier.cut()
    # one changed key -> shipped once per replica holder, nothing else
    assert counters.get("serve.tier_recv_keys") - recv0 == 2


def test_tier_codec_key_ships_wire_encoded():
    store = _store(["c0"], numel=256)
    store.register_compression("c0", {"compressor": "onebit"}, 256,
                               np.float32)
    d = TierDirectory(static_hosts={0: ("127.0.0.1", 1)})
    inproc_host(ServingHostCore(host_id=0))
    tier = ServingTier(store, directory=d, replicas=1,
                       cut_interval_s=None)
    store.push_delta("c0", np.ones(256, np.float32))
    b0 = counters.get("serve.tier_recv_bytes")
    tier.cut()
    wire = counters.get("serve.tier_recv_bytes") - b0
    assert 0 < wire < 256 * 4       # onebit beats raw f32
    client = tier.client(max_staleness_s=0.0)
    vals = client.pull(["c0"])
    # onebit is lossy-but-signed: the decoded value is the codec's
    # round-trip of the stored value, exactly what the in-process
    # plane's clients decode
    assert vals["c0"].shape == (256,)
    assert client.bytes_received == wire / 1  # same encoded bytes
    tier.close()


class _FailingEndpoint:
    def __init__(self):
        self.calls = 0

    def serve_cut(self, *a, **kw):
        self.calls += 1
        raise ServeUnavailable("dead host")

    def serve_commit(self, *a, **kw):
        raise ServeUnavailable("dead host")

    def close(self, drain=True):
        pass


def test_tier_retires_host_after_ship_failures():
    store, tier, cores = _inproc_tier(3, KEYS[:6], replicas=2,
                                      fail_streak=2)
    tier.cut()
    with tier._lock:
        tier._eps[1] = _FailingEndpoint()
    c0 = counters.get("serve.tier_ship_failures")
    store.push_delta(KEYS[0], np.ones(8, np.float32))
    tier.cut()      # failure 1
    assert 1 in tier.ring.hosts()
    store.push_delta(KEYS[1], np.ones(8, np.float32))
    tier.cut()      # failure 2 -> retired
    assert 1 not in tier.ring.hosts()
    assert counters.get("serve.tier_ship_failures") >= c0 + 2
    assert counters.get("serve.tier_retired") >= 1
    # reads still work: the arc remapped to survivors and was re-shipped
    vals = tier.client(max_staleness_s=0.0).pull()
    assert set(vals) == set(KEYS[:6])
    tier.close()


def test_restarted_host_gets_its_holes_reshipped_next_cut():
    """Review regression: the publisher must ack only what a commit
    actually PUBLISHED.  A host that restarts within its TTL (same id,
    empty state) drops every unchanged key at its first commit (nothing
    staged, nothing to carry forward); acking the full owned map would
    leave those holes un-shipped until the keys next changed — here the
    NEXT cut must re-ship them even though no version advanced."""
    store, tier, cores = _inproc_tier(2, KEYS[:6], replicas=1)
    tier.cut()
    # "restart" host 0: same id, all state gone
    fresh = inproc_host(ServingHostCore(host_id=0))
    with tier._lock:
        tier._eps.pop(0, None)      # re-resolve to the fresh core
    owned0 = [k for k in KEYS[:6] if tier.ring.owner(k) == 0]
    assert owned0, "hash landed every key on host 1; pick more keys"
    # one key changes; the restarted host's first commit drops the rest
    store.push_delta(KEYS[0], np.ones(8, np.float32))
    m0 = counters.get("serve.tier_missing_keys")
    tier.cut()
    assert counters.get("serve.tier_missing_keys") > m0
    # NO further writes: the next cut must still re-ship the holes
    tier.cut()
    held = fresh.ring.latest().versions
    assert set(owned0).issubset(set(held))
    # and a client read of host 0's arc succeeds with exact values
    vals = tier.client(max_staleness_s=0.0).pull(owned0)
    assert set(vals) == set(owned0)
    tier.close()


def test_probation_excludes_host_from_router_and_publisher_rings():
    """Review regression: probation must reach CLIENT rings too — the
    one-sided version (publisher stops shipping, router keeps reading)
    pins clients to a host whose snapshot never advances again, serving
    unboundedly stale data as fresh."""
    store, tier, cores = _inproc_tier(3, KEYS[:6], replicas=1)
    tier.cut()
    client = tier.client(max_staleness_s=0.0)
    client.pull()
    router = client._plane
    tier.set_probation({1})
    tier.cut()                       # arcs re-ship to the healthy hosts
    assert 1 not in tier.ring.hosts()
    time.sleep(0.3)                  # past the router's sync interval
    vals = client.pull()             # router re-syncs (gen bumped)
    assert set(vals) == set(KEYS[:6])
    assert 1 not in router.ring.hosts()
    assert router.host_pulls.get(1, 0) <= 2   # nothing new routed there
    # probation lifts: the host returns to BOTH rings without
    # re-registering
    tier.set_probation(set())
    tier.cut()
    assert 1 in tier.ring.hosts()
    time.sleep(0.3)
    client.pull()
    assert 1 in router.ring.hosts()
    tier.close()


def test_tier_follows_directory_membership():
    store, tier, cores = _inproc_tier(2, KEYS[:6], replicas=1)
    tier.cut()
    assert sorted(tier.ring.hosts()) == [0, 1]
    inproc_host(ServingHostCore(host_id=2))
    tier.directory.register(("127.0.0.1", 3), host_id=2)
    tier.cut()
    assert sorted(tier.ring.hosts()) == [0, 1, 2]
    # the new host holds exactly its arc
    snap2 = cores[0].ring.latest()
    assert snap2 is not None
    moved = [k for k in KEYS[:6] if tier.ring.owner(k) == 2]
    c2 = inproc_host(host_id=2)
    if moved:
        held = c2.ring.latest().versions
        assert set(moved).issubset(set(held))
    tier.close()


# -- router + client ---------------------------------------------------------

def test_router_fails_over_along_the_replica_arc():
    store, tier, cores = _inproc_tier(3, KEYS[:8], replicas=2)
    tier.cut()
    client = tier.client(max_staleness_s=0.0)
    assert set(client.pull()) == set(KEYS[:8])
    # kill one host's serving endpoint (data plane only)
    cores[1].server.kill()
    f0 = counters.get("serve.tier_failover")
    vals = client.pull()
    assert set(vals) == set(KEYS[:8])
    assert counters.get("serve.tier_failover") > f0
    tier.close()


class _FlakyPlane:
    """Raises ServeUnavailable until reroute() is called — the dead-host
    shape the satellite fix exists for."""

    accepts_max_stale = True

    def __init__(self):
        self.rerouted = 0
        self.pulls = 0

    def reroute(self):
        self.rerouted += 1

    def pull(self, since_id=None, keys=None, record=True, hedge=None,
             max_stale_s=None):
        self.pulls += 1
        if not self.rerouted:
            raise ServeUnavailable("dead host")
        return ServeReply(snapshot_id=1, full=True,
                          items={}, wire_bytes=0, server_id=0)


def test_pull_client_refresh_reroutes_on_serve_unavailable():
    from byteps_tpu.server.serve_client import PullClient
    plane = _FlakyPlane()
    client = PullClient(plane, max_staleness_s=0.0)
    client.pull()     # would raise forever without the reroute fix
    assert plane.rerouted == 1
    assert plane.pulls == 2   # failed attempt + post-reroute retry


def test_client_stale_on_error_degradation():
    store, tier, cores = _inproc_tier(2, KEYS[:4], replicas=1)
    tier.cut()
    client = tier.client(max_staleness_s=0.0)
    vals = client.pull()
    for c in cores:
        c.server.kill()
    c0 = counters.get("serve.stale_on_error")
    stale = client.pull()     # every candidate dead -> stale cache
    assert stale.keys() == vals.keys()
    assert counters.get("serve.stale_on_error") == c0 + 1
    # without the opt-in, the same failure raises
    strict = tier.client(max_staleness_s=0.0, stale_on_error=False)
    with pytest.raises(ServeUnavailable):
        strict.pull()
    tier.close()


def test_router_routes_known_keys_to_owner_only():
    store, tier, cores = _inproc_tier(3, KEYS, replicas=2)
    tier.cut()
    client = tier.client(max_staleness_s=0.0)
    client.pull()             # hydration learns the key universe
    b0 = client.bytes_received
    store.push_delta(KEYS[3], np.ones(8, np.float32))
    tier.cut()
    client.pull()
    delta = client.bytes_received - b0
    # owner-routed: the changed key travels once (or twice when the
    # rotating discovery slice also mirrors it) — never once per host
    assert delta in (32, 64)
    tier.close()


# -- bus directory -----------------------------------------------------------

@pytest.mark.chaos
def test_bus_serve_directory_register_ttl_ban_and_failover_seed():
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0,)),
                     5.0, 5.0)
    try:
        d = TierDirectory(bus=f"127.0.0.1:{port}", ttl_s=1.2,
                          poll_interval_s=0.0)
        hid = d.register(("127.0.0.1", 1000))
        assert hid == 0
        assert d.register(("127.0.0.1", 1001), host_id=7) == 7
        gen, hosts = d.hosts(force=True)
        assert hosts == {0: ("127.0.0.1", 1000), 7: ("127.0.0.1", 1001)}
        # re-registration refreshes without a gen bump
        d.register(("127.0.0.1", 1000), host_id=0)
        gen2, _ = d.hosts(force=True)
        assert gen2 == gen
        # unregister with ban: immediate removal, re-register refused
        d.unregister(7, ban_s=30.0)
        gen3, hosts3 = d.hosts(force=True)
        assert gen3 > gen2 and 7 not in hosts3
        with pytest.raises(ConnectionError, match="banned"):
            d.register(("127.0.0.1", 1001), host_id=7)
        # the directory survives a coordinator failover via the replica
        d.register(("127.0.0.1", 1000), host_id=0)   # fresh TTL stamp
        rep = bus_request(("127.0.0.1", port), {"op": "replicate"})
        seed = rep["replica"]
        port2 = _free_port()
        bus2 = _BusServer(("127.0.0.1", port2), MembershipView(0, (0,)),
                          5.0, 5.0, seed=seed)
        try:
            d2 = TierDirectory(bus=f"127.0.0.1:{port2}", ttl_s=1.2,
                               poll_interval_s=0.0)
            _, hosts_f = d2.hosts(force=True)
            assert 0 in hosts_f
        finally:
            bus2.close()
        # TTL expiry prunes host 0 (no heartbeat past 1.2s)
        time.sleep(1.5)
        _, hosts4 = d.hosts(force=True)
        assert hosts4 == {}
        # the autoscaler target rides the same channel
        d.set_target(5)
        assert d.target() == 5
    finally:
        bus.close()


# -- autoscaler --------------------------------------------------------------

def _sig(hosts, *, shed=0.0, pulls=0.0, slow=(), share=None):
    return {"hosts": list(hosts), "gen": 1,
            "rates": {h: {"pulls_per_s": pulls / max(len(hosts), 1),
                          "shed_per_s": 0.0} for h in hosts},
            "pulls_per_s": pulls, "shed_per_s": shed,
            "slow": {h: (9.0 if h in slow else 0.0) for h in hosts},
            "phi_threshold": 8.0,
            "arc_share": share or {h: 1.0 / max(len(hosts), 1)
                                   for h in hosts},
            "hot_keys": ["hk0", "hk1"]}


def test_autoscaler_decide_up_down_hold_and_placement():
    store, tier, _ = _inproc_tier(3, KEYS[:4], replicas=2)
    asc = TierAutoscaler(tier, min_hosts=1, max_hosts=4, cooldown_s=0.0,
                         low_pulls_per_s=50.0)
    # shedding -> scale up
    d = asc.decide(_sig([0, 1, 2], shed=3.0, pulls=500.0))
    assert d.action == "up" and d.target == 4
    # idle -> scale down, smallest arc is the victim
    d2 = asc.decide(_sig([0, 1, 2], pulls=30.0,
                         share={0: 0.5, 1: 0.2, 2: 0.3}))
    assert d2.action == "down" and d2.victims == [1]
    # probationed host is the preferred victim AND leaves placement
    d3 = asc.decide(_sig([0, 1, 2], pulls=30.0, slow=(2,)))
    assert d3.action == "down" and d3.victims == [2]
    assert d3.probation == [2]
    for hosts in d3.placement.values():
        assert 2 not in hosts
    # busy but not shedding, inside bounds -> hold
    d4 = asc.decide(_sig([0, 1, 2], pulls=1000.0))
    assert d4.action == "hold"
    # ceiling respected
    d5 = asc.decide(_sig([0, 1, 2, 3], shed=5.0, pulls=500.0))
    assert d5.action == "hold"
    tier.close()


def test_autoscaler_step_retires_victim_and_posts_target():
    store, tier, cores = _inproc_tier(3, KEYS[:6], replicas=2)
    tier.cut()
    asc = TierAutoscaler(tier, min_hosts=1, max_hosts=4, cooldown_s=0.0,
                         low_pulls_per_s=50.0)
    c0 = counters.get("serve.tier_scale_down")
    # review regression: the FIRST step sees structural zero rates (no
    # deltas yet) and must HOLD — retiring a host on no data would ban
    # a healthy one mid-traffic
    first = asc.step(force=True)
    assert first is not None and first.action == "hold"
    assert "warming" in first.reason
    assert len(tier.ring.hosts()) == 3
    decision = asc.step(force=True)   # warmed: genuinely idle -> down
    assert decision is not None and decision.action == "down"
    assert counters.get("serve.tier_scale_down") == c0 + 1
    assert len(tier.ring.hosts()) == 2
    assert tier.directory.target() == 2
    # reads survive the retirement (arc remapped + re-shipped)
    tier.cut()
    vals = tier.client(max_staleness_s=0.0).pull()
    assert set(vals) == set(KEYS[:6])
    tier.close()


# -- debug/obs surfaces ------------------------------------------------------

def test_debug_state_serving_tier_section_and_bps_top_rows():
    store, tier, cores = _inproc_tier(2, KEYS[:4], replicas=1)
    tier.cut()
    tier.client(max_staleness_s=0.0).pull()
    from byteps_tpu.common import obs_server
    doc = obs_server.debug_state()
    kinds = {d["kind"] for d in doc["serving_tier"]}
    assert {"serving_tier", "serving_host"} <= kinds
    # bps_top: serve hosts render as first-class rows
    from tools import bps_top
    cluster = {"epoch": 0, "world": [0], "coordinator": 0,
               "ranks": {0: {"age_s": 0.1, "metrics": {}}},
               "serve_gen": 3,
               "serve_hosts": {0: {"addr": ["127.0.0.1", 1]},
                               1: {"addr": ["127.0.0.1", 2]}},
               "serve_ranks": {0: {"age_s": 0.2, "metrics": {
                   "counters": {"serve.pulls": 90, "serve.shed": 10}}}}}
    text = bps_top.render(cluster)
    assert "ROLE" in text and "SHED%" in text and "ARC" in text
    assert "s0" in text and "s1" in text and "serve" in text
    assert "10%" in text          # 10 shed / 100 answered
    assert "coordinator" in text
    assert "serve tier: 2 host(s), gen 3" in text
    tier.close()


# -- ring-aware chaos: site=serve_host ---------------------------------------

@pytest.mark.chaos
def test_kill_site_serve_host_validation_and_counter():
    with pytest.raises(ValueError, match="serve_host"):
        inj.parse_spec("kill:step=3:site=sync")
    rules = inj.parse_spec("kill:step=3:site=serve_host")
    assert rules[0].site == "serve_host"
    # the serve counter, not the push counter, matches the rule
    killed = []
    inj.arm("kill:step=2:site=serve_host", rank=0)
    orig = inj._exit
    inj._exit = lambda code: killed.append(code)
    try:
        inj.on_step()      # pushes do NOT consume serve_host kills
        inj.on_step()
        inj.on_step()
        assert not killed
        inj.on_serve()
        assert not killed
        inj.on_serve()     # the 2nd answered pull
        assert killed
    finally:
        inj._exit = orig
        inj.disarm()


# -- the acceptance storm ----------------------------------------------------


def _spawn_host(i, bus_port, ttl=3.0, spec=""):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               BYTEPS_SERVE_TIER_BUS=f"127.0.0.1:{bus_port}",
               BYTEPS_SERVE_HOST_ID=str(i),
               BYTEPS_SERVE_TIER_TTL=str(ttl),
               BYTEPS_LOG_LEVEL="ERROR",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    if spec:
        env["BYTEPS_FAULT_SPEC"] = spec
    else:
        env.pop("BYTEPS_FAULT_SPEC", None)
    return subprocess.Popen(
        [sys.executable, "-m", "byteps_tpu.server.serve_host"],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)


def _drain(proc):
    """Read HOST-UP, then keep the pipe drained: a chaos-noisy host
    must not block mid-log on a full 64 KiB pipe and wedge the storm."""
    line = proc.stdout.readline()
    threading.Thread(target=lambda f=proc.stdout: f.read(),
                     daemon=True, name="serve-host-drain").start()
    return line


@pytest.mark.chaos
def test_serve_dist_storm_kill_and_partition_4hosts():
    """THE acceptance pin (ISSUE 15): 4 real serving-host processes
    behind the TCP transport serve a concurrent pull storm while

    - host 1 dies at its 300th answered pull (``kill:site=serve_host``
      — deterministic, mid-storm), and
    - host 2 is partitioned mid-storm via the ring-aware chaos channel
      (``serve_ctl`` → ``chaos_arm partition:site=transport``),

    and the tier keeps its promises: ZERO failed reads (failover +
    reroute + stale-on-error), the ring heals through the bus (both
    corpses retired by the publisher's ship-failure streak), staleness
    re-bounds after the heal, and every client's final blocking pull
    equals the store exactly."""
    nkeys = 8
    keys = [f"d{i}" for i in range(nkeys)]
    bound = 0.25
    bus_port = _free_port()
    bus = _BusServer(("127.0.0.1", bus_port), MembershipView(0, (0,)),
                     5.0, 5.0)
    procs = {}
    tier = None
    stop = threading.Event()
    try:
        for i in range(4):
            procs[i] = _spawn_host(
                i, bus_port,
                spec=("kill:step=60:site=serve_host" if i == 1 else ""))
        for i, p in procs.items():
            line = _drain(p)
            assert "HOST-UP" in line, line

        store = KVStore()
        rng = np.random.RandomState(0)
        for k in keys:
            store.init_key(k, rng.randn(64).astype(np.float32))
        tier = ServingTier(store, bus=f"127.0.0.1:{bus_port}",
                           replicas=2, cut_interval_s=None,
                           ship_deadline_s=0.75, fail_streak=2,
                           conn_kw={"send_deadline_s": 0.75,
                                    "keepalive_s": 1.0})
        tier.cut()

        # the publisher's version->publish-time history for the
        # staleness audit (stamped when the cut RETURNS = shipped)
        pub_lock = threading.Lock()
        pub_times = {}          # version of keys[0] -> monotonic

        def pusher():
            step = 0
            while not stop.is_set():
                step += 1
                store.push_delta(keys[0],
                                 np.ones(64, np.float32))
                for k in keys[1:]:
                    store.push_delta(k, np.ones(64, np.float32) * 1e-3)
                snap = tier.cut()
                if snap is not None:
                    with pub_lock:
                        pub_times[snap.versions[keys[0]]] = \
                            time.monotonic()
                time.sleep(0.12)

        samples = []            # (t, seen version of keys[0])
        errors = []

        def puller(idx):
            client = tier.client(max_staleness_s=bound,
                                 pull_deadline_s=0.75)
            try:
                while not stop.is_set():
                    try:
                        client.pull()
                    except Exception as e:  # noqa: BLE001 — THE assertion
                        errors.append((idx, repr(e)))
                        continue
                    with pub_lock:
                        samples.append((time.monotonic(),
                                        client.version(keys[0])))
                    time.sleep(0.01)
            finally:
                client.close()

        push_t = threading.Thread(target=pusher, daemon=True)
        pull_ts = [threading.Thread(target=puller, args=(i,),
                                    daemon=True) for i in range(4)]
        push_t.start()
        for t in pull_ts:
            t.start()

        time.sleep(1.5)                     # healthy storm
        # mid-storm: partition host 2's data plane (ring-aware chaos);
        # the ack is blackholed by the partition itself — expected
        from byteps_tpu.common import integrity as _integrity
        from byteps_tpu.comm.transport import (TcpEndpoint,
                                               TransportError)
        _, addrs = tier.directory.hosts(force=True)
        t_chaos = time.monotonic()
        if 2 in addrs:
            ctl = TcpEndpoint(addrs[2], peer=SERVE_RANK_BASE + 2,
                              send_deadline_s=1.0, keepalive_s=0.0)
            try:
                ctl.serve_ctl(cmd="chaos_arm",
                              spec="partition:site=transport")
            except (_integrity.AckLost, TransportError):
                pass
            ctl.close(drain=False)
        # host 1's chaos kill fires on its own pull counter around now
        time.sleep(6.0)                     # chaos + heal + steady
        t_heal = time.monotonic()
        time.sleep(3.0)                     # post-heal steady state
        stop.set()
        push_t.join(timeout=20)
        for t in pull_ts:
            t.join(timeout=20)

        # 1) ZERO failed reads through kill + partition
        assert not errors, errors[:5]
        # 2) the kill fired: host 1 is dead with the injector's exit
        assert procs[1].poll() is not None, "host 1 was never killed"
        # 3) the ring healed THROUGH the bus: both corpses are out —
        # the partitioned host only ever leaves via the publisher's
        # retire+ban (its control plane keeps heartbeating), the killed
        # one via retire or TTL expiry, whichever won the race
        live = set(tier.ring.hosts())
        assert live and not ({1, 2} & live), live
        assert counters.get("serve.tier_retired") >= 1
        # failovers actually exercised
        assert counters.get("serve.tier_failover") > 0
        # 4) bounded staleness after heal: every post-heal sample saw
        # at least the newest version published (bound + slack) before
        slack = 0.8
        with pub_lock:
            history = sorted(pub_times.items())
        checked = 0
        for t_s, seen in samples:
            if t_s < t_heal:
                continue
            floor_v = 0
            for v, t_pub in history:
                if t_pub <= t_s - bound - slack:
                    floor_v = max(floor_v, v)
            assert seen >= floor_v, (t_s, seen, floor_v)
            checked += 1
        assert checked > 10, "no post-heal staleness samples"
        # 5) finals exact: a fresh blocking pull equals the store
        tier.cut()
        fc = tier.client(max_staleness_s=0.0, pull_deadline_s=2.0)
        final = fc.pull()
        fc.close()
        for k in keys:
            np.testing.assert_array_equal(final[k], store.pull(k))
    finally:
        stop.set()
        if tier is not None:
            tier.close()
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        bus.close()


@pytest.mark.chaos
def test_serve_dist_slow_socket_host_storm_zero_failed_reads():
    """One host under ``slow_socket`` chaos (every send throttled 20ms):
    the storm completes with zero failed reads and the throttled host's
    own counters prove the fault actually fired (queried over the bus —
    the host publishes metrics like any rank)."""
    bus_port = _free_port()
    bus = _BusServer(("127.0.0.1", bus_port), MembershipView(0, (0,)),
                     5.0, 5.0)
    procs = {}
    tier = None
    try:
        for i in range(3):
            procs[i] = _spawn_host(
                i, bus_port, ttl=2.0,
                spec=("slow_socket:site=transport:ms=20:p=0.5"
                      if i == 0 else ""))
        for p in procs.values():
            assert "HOST-UP" in _drain(p)
        store = _store([f"s{i}" for i in range(6)], numel=64)
        tier = ServingTier(store, bus=f"127.0.0.1:{bus_port}",
                           replicas=2, cut_interval_s=None,
                           ship_deadline_s=3.0)
        tier.cut()
        client = tier.client(max_staleness_s=0.0, pull_deadline_s=3.0)
        for _ in range(30):
            vals = client.pull()
            assert len(vals) == 6
        client.close()
        # the fault fired in host 0 (its bus-published counters say so)
        deadline = time.monotonic() + 8.0
        fired = 0
        while time.monotonic() < deadline:
            reply = bus_request(("127.0.0.1", bus_port), {"op": "metrics"})
            row = (reply.get("ranks") or {}).get(SERVE_RANK_BASE + 0)
            if row:
                fired = ((row["metrics"].get("counters") or {})
                         .get("fault.slow_socket", 0))
                if fired:
                    break
            time.sleep(0.5)
        assert fired > 0
    finally:
        if tier is not None:
            tier.close()
        for p in procs.values():
            p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        bus.close()
