"""Failure-detector tests: heartbeat liveness + step watchdog.

The reference has no in-tree failure detector (SURVEY.md §5) — liveness
lives in ps-lite's scheduler heartbeats.  These tests pin the TPU-native
replacement, including a real 2-process kill: one worker dies mid-run
and the survivor's detector must fire within the timeout instead of
hanging the way a DCN collective would.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

import pytest

from byteps_tpu.utils import failure_detector as fd_mod
from byteps_tpu.utils.failure_detector import (HeartbeatMonitor,
                                               StepWatchdog,
                                               install_failure_action)

from .conftest import free_port as _free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_healthy_cluster_no_fire():
    port = _free_port()
    fired = []
    mons = [HeartbeatMonitor(r, 2, f"127.0.0.1:{port}", interval=0.1,
                             timeout=1.0, on_failure=fired.append).start()
            for r in range(2)]
    time.sleep(1.5)
    for m in mons:
        m.stop()
    assert not fired


def test_missing_rank_detected_after_grace():
    port = _free_port()
    fired = []
    done = threading.Event()

    def on_failure(stale):
        fired.append(stale)
        done.set()

    # rank 1 never starts; rank 0's monitor must report it after grace
    m = HeartbeatMonitor(0, 2, f"127.0.0.1:{port}", interval=0.1,
                         timeout=0.8, grace=0.8, on_failure=on_failure)
    m.start()
    assert done.wait(5.0), "detector did not fire"
    m.stop()
    assert fired == [{1}]


def test_dead_coordinator_detected():
    port = _free_port()  # nothing listens here
    fired = []
    done = threading.Event()

    def on_failure(stale):
        fired.append(stale)
        done.set()

    m = HeartbeatMonitor(1, 2, f"127.0.0.1:{port}", interval=0.1,
                         timeout=0.6, on_failure=on_failure)
    m.start()
    assert done.wait(5.0), "client did not detect silent coordinator"
    m.stop()
    assert fired == [{0}]


def test_on_failure_fires_once():
    port = _free_port()
    fired = []
    m = HeartbeatMonitor(0, 3, f"127.0.0.1:{port}", interval=0.05,
                         timeout=0.4, grace=0.4, on_failure=fired.append)
    m.start()
    time.sleep(2.0)
    m.stop()
    assert len(fired) == 1  # both missing ranks reported in ONE call
    assert fired[0] == {1, 2}


def test_step_watchdog_stall_and_feed():
    stalls = []
    wd = StepWatchdog(timeout=0.5, on_stall=stalls.append)
    wd.start()
    for _ in range(4):  # regular feeding keeps it quiet
        time.sleep(0.2)
        wd.feed()
    assert not stalls
    time.sleep(1.2)  # stop feeding -> stall
    wd.stop()
    assert len(stalls) == 1 and stalls[0] > 0.5


def test_custom_on_failure_suppresses_exit(monkeypatch):
    """Satellite: a custom on_failure callback fully replaces the exit
    path — os._exit is never reached, the survivor stays alive."""
    exits = []
    monkeypatch.setattr(fd_mod, "_exit", exits.append)
    port = _free_port()
    fired = []
    done = threading.Event()

    def on_failure(stale):
        fired.append(stale)
        done.set()

    m = HeartbeatMonitor(0, 2, f"127.0.0.1:{port}", interval=0.1,
                         timeout=0.5, grace=0.5, on_failure=on_failure)
    m.start()
    assert done.wait(5.0)
    m.stop()
    assert fired == [{1}]
    assert exits == []           # the process would have survived


def test_custom_on_stall_suppresses_exit(monkeypatch):
    exits = []
    monkeypatch.setattr(fd_mod, "_exit", exits.append)
    stalls = []
    wd = StepWatchdog(timeout=0.3, on_stall=stalls.append)
    wd.start()
    time.sleep(0.9)
    wd.stop()
    assert len(stalls) == 1
    assert exits == []


def test_default_on_failure_exits_restartable(monkeypatch):
    """The DEFAULT action still exits with the configured restartable
    code when nothing is installed."""
    monkeypatch.setenv("BYTEPS_FAILURE_EXIT_CODE", "23")
    exits = []
    monkeypatch.setattr(fd_mod, "_exit", exits.append)
    fd_mod._default_on_failure({1})
    assert exits == [23]


def test_install_failure_action_rewires_the_default(monkeypatch):
    """install_failure_action lets an elastic layer own the DEFAULT
    escalation (covers the auto-armed monitor) without any exit."""
    exits = []
    monkeypatch.setattr(fd_mod, "_exit", exits.append)
    seen = []
    prev = install_failure_action(seen.append)
    try:
        fd_mod._default_on_failure({2, 3})
        assert seen == [{2, 3}]
        assert exits == []
    finally:
        install_failure_action(prev)
    # restored: the default exits again
    fd_mod._default_on_failure({1})
    assert len(exits) == 1


def test_failure_exit_code_rejects_non_restartable_codes(monkeypatch):
    """Satellite: BYTEPS_FAILURE_EXIT_CODE parsing rejects codes the
    --restart supervision could not distinguish from normal exits, with
    an error that says why."""
    from byteps_tpu.common.config import Config
    for bad in ("0", "1", "256", "-3"):
        monkeypatch.setenv("BYTEPS_FAILURE_EXIT_CODE", bad)
        with pytest.raises(ValueError, match="not restartable"):
            Config.from_env()
    monkeypatch.setenv("BYTEPS_FAILURE_EXIT_CODE", "copper")
    with pytest.raises(ValueError, match="integer"):
        Config.from_env()
    monkeypatch.setenv("BYTEPS_FAILURE_EXIT_CODE", "23")
    assert Config.from_env().failure_exit_code == 23


_WORKER = r"""
import sys, time
from byteps_tpu.utils.failure_detector import HeartbeatMonitor
rank = int(sys.argv[1]); port = sys.argv[2]; die_after = float(sys.argv[3])

def on_failure(stale):
    print("DETECTED", sorted(stale), flush=True)
    raise SystemExit(0)

m = HeartbeatMonitor(rank, 2, "127.0.0.1:" + port, interval=0.2,
                     timeout=2.0, on_failure=on_failure)
m.start()
t0 = time.time()
while time.time() - t0 < 20:
    if die_after > 0 and time.time() - t0 > die_after:
        print("DYING", flush=True)
        import os; os._exit(1)  # simulated crash, no cleanup
    time.sleep(0.1)
print("TIMEOUT-NO-DETECT", flush=True)
"""


@pytest.mark.slow
def test_two_process_worker_death_detected():
    port = str(_free_port())
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    survivor = subprocess.Popen(
        [sys.executable, "-c", _WORKER, "0", port, "0"],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    victim = subprocess.Popen(
        [sys.executable, "-c", _WORKER, "1", port, "1.5"],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        out_s, _ = survivor.communicate(timeout=30)
        out_v, _ = victim.communicate(timeout=30)
    except subprocess.TimeoutExpired:
        survivor.kill()
        victim.kill()
        raise
    assert "DYING" in out_v
    assert "DETECTED [1]" in out_s, out_s[-2000:]
