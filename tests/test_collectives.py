"""Collective-layer tests on the virtual 8-device CPU mesh.

Covers the op-correctness ground the reference's tests/test_mxnet.py covers
(push_pull sums 1-3D tensors over dtypes against numpy, SURVEY.md §4), plus
the hierarchical two-level path the reference exercises via its NCCL+PS
pipeline."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.comm import mesh as mesh_mod
from byteps_tpu.comm.collectives import (
    all_reduce,
    broadcast,
    hierarchical_all_reduce,
    push_pull_array,
)
from byteps_tpu.comm.mesh import CommContext, _build_mesh


@pytest.fixture
def comm():
    return CommContext(mesh=_build_mesh(jax.devices(), 1), n_dcn=1, n_ici=8)


@pytest.fixture
def comm2d():
    return CommContext(mesh=_build_mesh(jax.devices(), 2), n_dcn=2, n_ici=4)


def _stacked(shape, dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    if np.issubdtype(np.dtype(dtype), np.integer):
        return jnp.asarray(rng.randint(-100, 100, (8,) + shape).astype(dtype))
    return jnp.asarray(rng.randn(8, *shape).astype(dtype))


@pytest.mark.parametrize("shape", [(7,), (32, 5), (4, 3, 2)])
@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32])
def test_all_reduce_sum_matches_numpy(comm, shape, dtype):
    x = _stacked(shape, dtype)
    out = all_reduce(comm, x, op="sum")
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).sum(0),
                               rtol=1e-5, atol=1e-6)


def test_all_reduce_average(comm):
    x = _stacked((16,))
    out = all_reduce(comm, x, op="average")
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).mean(0),
                               rtol=1e-5, atol=1e-6)


def test_all_reduce_bfloat16(comm):
    x = jnp.ones((8, 128), dtype=jnp.bfloat16) * 0.5
    out = all_reduce(comm, x, op="sum")
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32), 4.0)


@pytest.mark.parametrize("n", [8, 16, 17, 1000])  # incl. non-divisible sizes
def test_hierarchical_matches_flat(comm, n):
    x = _stacked((n,))
    flat = all_reduce(comm, x, op="sum")
    hier = hierarchical_all_reduce(comm, x, op="sum")
    np.testing.assert_allclose(np.asarray(hier), np.asarray(flat), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("n", [16, 17, 333])
def test_hierarchical_two_level(comm2d, n):
    # dcn=2 x ici=4: reduce-scatter inside each "slice", psum across, stitch
    x = _stacked((n,), seed=3)
    out = hierarchical_all_reduce(comm2d, x, op="average")
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).mean(0),
                               rtol=1e-5, atol=1e-6)


def test_hierarchical_2d_tensor(comm2d):
    x = _stacked((10, 3), seed=4)
    out = hierarchical_all_reduce(comm2d, x, op="sum")
    assert out.shape == (10, 3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).sum(0),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast(comm, root):
    x = _stacked((9,), seed=root)
    out = broadcast(comm, x, root=root)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x)[root])


def test_broadcast_2d_mesh(comm2d):
    x = _stacked((9,), seed=9)
    out = broadcast(comm2d, x, root=5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x)[5])


def test_push_pull_array_picks_topology(comm, comm2d):
    x = _stacked((33,), seed=5)
    for c in (comm, comm2d):
        out = push_pull_array(c, x, op="sum")
        np.testing.assert_allclose(np.asarray(out), np.asarray(x).sum(0),
                                   rtol=1e-5, atol=1e-6)


def test_wrong_rank_axis_raises(comm):
    with pytest.raises(ValueError):
        all_reduce(comm, jnp.ones((4, 3)), op="sum")
