"""Persistent compiled programs + auto-tuned planner tests (ISSUE 5).

The tentpole's contract, pinned here:

- **zero compiles at steady state**: a push_pull stream of declared
  tensors triggers no new XLA compiles after warmup — the compiled chunk
  programs persist in ``comm.jit_cache`` and the planner's locked choice
  stops the program set from growing;
- **declare-time AOT warm**: ``bps.declare(name, shape=...)``
  pre-compiles the tensor's whole steady-state program set, so even the
  FIRST push compiles nothing;
- **the planner**: explores its candidate ladder, locks a winner per
  size bucket, never moves a pinned knob, never tunes multi-process, and
  discards samples polluted by a compile;
- **the event-driven scheduler**: interrupt/wake/set_credit on both the
  Python and native backends, and the pause_dispatch handshake that
  replaced the polling quantum;
- **repartition safety**: chunk bounds only move between pushes, and
  compressed tensors never repartition.
"""

import threading
import time

import numpy as np
import pytest

import byteps_tpu as bps
from byteps_tpu.common.config import Config, set_config
from byteps_tpu.common.scheduler import ChunkPlanner, ChunkScheduler
from byteps_tpu.common.telemetry import counters
from byteps_tpu.common.types import ChunkTask


@pytest.fixture
def bps_session():
    bps.init()
    yield bps
    bps.shutdown()


@pytest.fixture
def bps_autotune_small():
    # Small base bound: an 80 KB tensor is already "large" to the
    # planner, so exploration + lock complete in a handful of fast pushes
    # instead of needing megabyte tensors.
    set_config(Config(partition_bytes=16384, partition_pinned=False,
                      credit_pinned=False))
    bps.init()
    yield bps
    bps.shutdown()


def _task(key, nbytes=64, priority=0):
    return ChunkTask(name=f"t{key}", key=key, priority=priority, version=0,
                     offset_elems=0, num_elems=nbytes // 4, nbytes=nbytes,
                     total_parts=1)


def _schedulers():
    out = [("python", lambda: ChunkScheduler(credit_bytes=0))]
    try:
        from byteps_tpu.native import NativeChunkScheduler, load
        if load() is not None:
            out.append(("native",
                        lambda: NativeChunkScheduler(credit_bytes=0)))
    except Exception:  # noqa: BLE001 — toolchain absent
        pass
    return out


# ---------------------------------------------------------------- headline


def test_steady_state_stream_compiles_nothing(bps_autotune_small):
    """The regression test the tentpole names: after warmup (declare-time
    AOT + planner exploration), a steady stream of push_pulls over the
    declared set triggers ZERO new XLA compiles."""
    eng = bps.core.api._engine
    rng = np.random.RandomState(0)
    shapes = {"z/a": (40_000,),       # 160 KB: multi-chunk, planner-tuned
              "z/b": (300, 33),       # odd 2-D, sub-bound single chunk
              "z/c": (1024,)}         # small parts-mode tensor
    for n, s in shapes.items():
        bps.declare(n, shape=s, dtype=np.float32)
    assert counters.get("engine.aot_compile_failed") == 0
    # Warmup: run until the planner has locked every tuned bucket (it
    # needs a few completed pushes per candidate), bounded hard.
    for _ in range(40):
        for n, s in shapes.items():
            x = rng.randn(*s).astype(np.float32)
            out = eng.push_pull_local(x, n)
            np.testing.assert_allclose(np.asarray(out), x, rtol=1e-5,
                                       atol=1e-6)
        if all(eng.planner.locked(int(np.prod(s)) * 4)
               for s in shapes.values()):
            break
    assert all(eng.planner.locked(int(np.prod(s)) * 4)
               for s in shapes.values())
    m0 = counters.get("engine.compile_cache_miss")
    for _ in range(5):
        for n, s in shapes.items():
            x = rng.randn(*s).astype(np.float32)
            out = eng.push_pull_local(x, n)
            np.testing.assert_allclose(np.asarray(out), x, rtol=1e-5,
                                       atol=1e-6)
    assert counters.get("engine.compile_cache_miss") == m0


def test_declare_aot_first_push_compiles_nothing(bps_session):
    """With the planner quiet (tensor under the base bound is a single
    chunk — nothing to explore), declare-time AOT covers the ENTIRE
    program set: even the first push is compile-free."""
    eng = bps.core.api._engine
    bps.declare("aot/w", shape=(300_000,), dtype=np.float32)
    assert counters.get("engine.aot_compiled") > 0
    assert counters.get("engine.aot_compile_failed") == 0
    m0 = counters.get("engine.compile_cache_miss")
    x = np.random.RandomState(1).randn(300_000).astype(np.float32)
    out = eng.push_pull_local(x, "aot/w")
    np.testing.assert_allclose(np.asarray(out), x, rtol=1e-5, atol=1e-6)
    assert counters.get("engine.compile_cache_miss") == m0


def test_declare_aot_sum_op_first_push_compiles_nothing(bps_session):
    """op="sum" warm must model the LOCAL path's over-count division
    (a float sum push rides the fused-scale fast path with scale =
    1/local_size) — an average-only model would warm dead keys and the
    first sum push would compile mid-dispatch."""
    eng = bps.core.api._engine
    bps.declare("aot/s", shape=(300_000,), dtype=np.float32, op="sum")
    assert counters.get("engine.aot_compile_failed") == 0
    m0 = counters.get("engine.compile_cache_miss")
    x = np.random.RandomState(2).randn(300_000).astype(np.float32)
    out = eng.push_pull_local(x, "aot/s", op="sum")
    np.testing.assert_allclose(np.asarray(out), x, rtol=1e-5, atol=1e-6)
    assert counters.get("engine.compile_cache_miss") == m0


def test_declare_with_shape_returns_key_and_orders(bps_session):
    k1 = bps.declare("ord/a", shape=(64,))
    k2 = bps.declare("ord/b")          # plain reservation still works
    assert k1 < k2


# ---------------------------------------------------------------- planner


def test_planner_explores_then_locks():
    cfg = Config(partition_bytes=16384, partition_pinned=False,
                 credit_pinned=False)
    p = ChunkPlanner(cfg, num_procs=1)
    nbytes = 160_000
    seen = []
    # feed every candidate enough clean samples; fastest candidate wins
    for i in range(64):
        cand = p.plan_partition(nbytes)
        seen.append(cand)
        p.observe(nbytes, cand, seconds=0.001 if cand == 16384 else 0.01)
        if p.locked(nbytes):
            break
    assert p.locked(nbytes)
    assert p.plan_partition(nbytes) == 16384      # the fast candidate
    assert len(set(seen)) > 1                     # it really explored
    snap = p.snapshot()
    b = snap["buckets"][str(nbytes.bit_length())]
    assert b["locked_partition_bytes"] == 16384
    assert snap["credit_bytes"] == 4 * 16384


def test_planner_small_tensors_never_tuned():
    cfg = Config(partition_bytes=16384, partition_pinned=False)
    p = ChunkPlanner(cfg, num_procs=1)
    assert p.plan_partition(1000) == 16384
    assert p.locked(1000)                      # nothing to explore
    assert p.snapshot()["buckets"] == {}


def test_planner_pinned_partition_is_never_moved():
    cfg = Config(partition_bytes=8192, partition_pinned=True)
    p = ChunkPlanner(cfg, num_procs=1)
    for _ in range(20):
        assert p.plan_partition(1_000_000) == 8192
        p.observe(1_000_000, 8192, 0.001)
    assert p.credit_bytes() == 0


def test_planner_multiprocess_is_inert():
    cfg = Config(partition_bytes=8192, partition_pinned=False,
                 credit_pinned=False)
    p = ChunkPlanner(cfg, num_procs=2)
    assert not p.active
    assert p.plan_partition(1_000_000) == 8192
    p.observe(1_000_000, 8192, 0.001)
    assert p.snapshot()["buckets"] == {}


def test_planner_compile_polluted_sample_discarded():
    cfg = Config(partition_bytes=16384, partition_pinned=False)
    p = ChunkPlanner(cfg, num_procs=1)
    nbytes = 160_000
    cand = p.plan_partition(nbytes)
    for _ in range(10):  # compiled=True samples must never advance it
        p.observe(nbytes, cand, 5.0, compiled=True)
    assert p.plan_partition(nbytes) == cand
    assert not p.locked(nbytes)


def test_planner_stale_inflight_sample_ignored():
    """A push carved under an earlier candidate completing late must not
    credit its timing to the current candidate."""
    cfg = Config(partition_bytes=16384, partition_pinned=False)
    p = ChunkPlanner(cfg, num_procs=1)
    nbytes = 160_000
    cand = p.plan_partition(nbytes)
    p.observe(nbytes, cand + 4096, 0.001)     # not the current candidate
    st = p._buckets[nbytes.bit_length()]
    assert st["samples"].get(cand + 4096) is None


# ------------------------------------------------------------- scheduler


@pytest.mark.parametrize("name,mk", _schedulers())
def test_scheduler_interrupt_wakes_blocked_get(name, mk):
    s = mk()
    got = {}

    def worker():
        got["task"] = s.get_task(block=True)   # no timeout: event-driven

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.05)
    assert t.is_alive()                        # parked, not polling
    s.interrupt()
    t.join(timeout=5)
    assert not t.is_alive()
    assert got["task"] is None


@pytest.mark.parametrize("name,mk", _schedulers())
def test_scheduler_interrupt_is_one_shot(name, mk):
    s = mk()
    s.interrupt()                               # latched for the NEXT get
    assert s.get_task(block=True) is None       # consumed here
    s.add_task(_task(1))
    assert s.get_task(block=True) is not None   # back to normal popping


@pytest.mark.parametrize("name,mk", _schedulers())
def test_scheduler_set_credit_unblocks_waiter(name, mk):
    s = mk()
    s.set_credit_bytes(64)
    assert s.credit_bytes == 64
    s.add_task(_task(1, nbytes=64))
    s.add_task(_task(2, nbytes=64))
    assert s.get_task() is not None
    assert s.get_task() is None                 # window exhausted
    got = {}

    def worker():
        got["task"] = s.get_task(block=True)

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.05)
    s.set_credit_bytes(256)                     # widening window notifies
    t.join(timeout=5)
    assert not t.is_alive() and got["task"] is not None


@pytest.mark.parametrize("name,mk", _schedulers())
def test_scheduler_wake_is_latched(name, mk):
    s = mk()
    s.wake()
    assert s.get_task(block=True) is None       # returns without waiting
    assert s.get_task(block=True) is None       # and keeps returning


def test_pause_dispatch_parks_without_polling(bps_session):
    """The pause handshake: pause returns only once the dispatcher has
    parked, tasks enqueued while paused stay queued, resume drains them.
    (The old design slept a polling quantum and hoped.)"""
    eng = bps.core.api._engine
    eng.pause_dispatch()
    try:
        assert eng._parked.is_set()
        h = eng.push_pull_local_async(np.ones(256, np.float32), "pause/t")
        time.sleep(0.1)
        assert not h.poll()                     # nothing pops while paused
    finally:
        eng.resume_dispatch()
    np.testing.assert_allclose(np.asarray(h.wait(timeout=30)), 1.0)


# ----------------------------------------------------------- repartition


def test_repartition_moves_bounds_between_pushes(bps_autotune_small):
    eng = bps.core.api._engine
    from byteps_tpu.common.registry import TensorRegistry
    x = np.ones(40_000, np.float32)
    eng.push_pull_local(x, "rp/w")
    ctx = eng.registry.get("rp/w")
    with ctx.lock:
        assert ctx.inflight == 0
        changed = TensorRegistry.repartition_locked(ctx, 65536)
    assert changed and ctx.partition_bytes == 65536
    assert len(ctx.key_list) == len(ctx.chunk_bounds)
    out = eng.push_pull_local(2 * x, "rp/w")    # correct under new bounds
    np.testing.assert_allclose(np.asarray(out), 2.0)


def test_repartition_refuses_compressed(bps_session):
    eng = bps.core.api._engine
    from byteps_tpu.common.registry import TensorRegistry
    x = np.ones((8, 4096), np.float32)
    bps.push_pull(x, "rp/c", compression={"compressor": "onebit"})
    ctx = eng.registry.get("rp/c")
    bounds = list(ctx.chunk_bounds)
    with ctx.lock:
        assert not TensorRegistry.repartition_locked(ctx, 1 << 20)
    assert ctx.chunk_bounds == bounds
