"""MoE GPT over (dp, ep): the model-level expert-parallel composite.

Pins: (a) the MoE-GPT forward under ep equals the single-device model on
the same token shard with the full expert stacks, (b) the (dp, ep) LM
step trains and keeps expert stacks distributed, (c) dense configs are
unchanged (moe_experts=0 produces the round-1 param structure).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from byteps_tpu.models.gpt import GPT, GPTConfig, lm_loss
from byteps_tpu.parallel.long_context import synthetic_lm_batch
from byteps_tpu.parallel.moe_lm import (
    EP_AXIS, make_ep_mesh, make_moe_lm_train_step, moe_lm_pspec,
    shard_moe_lm_batch, shard_moe_lm_params)


def _cfg(experts=4):
    return GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=4, intermediate_size=64, max_position=64,
                     dtype=jnp.float32, moe_experts=experts, moe_every=2)


def test_dense_config_param_structure_unchanged():
    dense = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                      num_heads=2, intermediate_size=32, max_position=32,
                      dtype=jnp.float32)
    p = GPT(dense).init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    assert "mlp_in" in p["params"]["h0"] and "moe" not in p["params"]["h0"]


def test_moe_blocks_every_other_layer():
    cfg = _cfg()
    p = GPT(cfg).init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    assert "mlp_in" in p["params"]["h0"]     # layer 0: dense
    assert "moe" in p["params"]["h1"]        # layer 1: switch
    assert p["params"]["h1"]["moe"]["w1"].shape == (4, 32, 64)


def test_moe_forward_matches_single_device_per_shard():
    cfg = _cfg()
    mesh = make_ep_mesh(jax.devices()[:8], n_ep=4)  # dp=2 x ep=4
    rng = jax.random.PRNGKey(1)
    batch = synthetic_lm_batch(rng, cfg, batch=8, seq_len=16)
    variables = GPT(cfg).init(rng, batch["input_ids"][:1])

    ep_model = GPT(cfg, ep_axis=EP_AXIS)

    def fwd(v, ids):
        logits, _ = ep_model.apply(v, ids, mutable=["moe_aux"])
        return logits

    mapped = jax.jit(jax.shard_map(
        fwd, mesh=mesh,
        in_specs=(jax.tree_util.tree_map_with_path(moe_lm_pspec,
                                                   variables),
                  P(("dp", "ep"), None)),
        out_specs=P(("dp", "ep"), None)))
    out = np.asarray(mapped(shard_moe_lm_params(mesh, variables),
                            shard_moe_lm_batch(mesh,
                                               batch)["input_ids"]))

    ref_model = GPT(cfg)  # ep_axis=None: full stacks, no collective
    for g in range(8):
        ids_g = batch["input_ids"][g:g + 1]
        ref, _ = ref_model.apply(variables, ids_g, mutable=["moe_aux"])
        np.testing.assert_allclose(out[g:g + 1], np.asarray(ref),
                                   rtol=1e-5, atol=1e-5,
                                   err_msg=f"shard {g}")


def test_moe_lm_trains_and_stays_sharded():
    cfg = _cfg()
    mesh = make_ep_mesh(jax.devices()[:8], n_ep=4)
    rng = jax.random.PRNGKey(2)
    batch = synthetic_lm_batch(rng, cfg, batch=16, seq_len=16)
    variables = shard_moe_lm_params(
        mesh, GPT(cfg).init(rng, batch["input_ids"][:1]))
    tx = optax.adam(1e-2)
    opt_state = jax.jit(tx.init)(variables)
    step = make_moe_lm_train_step(mesh, cfg, tx)
    b = shard_moe_lm_batch(mesh, batch)
    losses = []
    for _ in range(10):
        variables, opt_state, loss = step(variables, opt_state, b)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses
    w1 = variables["params"]["h1"]["moe"]["w1"]
    assert w1.addressable_shards[0].data.shape[0] * 4 == w1.shape[0]
    r = variables["params"]["h1"]["moe"]["router"]
    assert r.addressable_shards[0].data.shape == r.shape  # replicated


def test_dense_step_rejects_moe_config():
    from byteps_tpu.parallel.pipeline import init_pipeline_params
    with pytest.raises(ValueError, match="homogeneous"):
        init_pipeline_params(_cfg(), jax.random.PRNGKey(0),
                             jnp.zeros((1, 8), jnp.int32))


def test_moe_every_zero_rejected():
    with pytest.raises(ValueError, match="moe_every"):
        GPTConfig(moe_experts=4, moe_every=0)


def test_moe_compute_dtype_follows_config():
    """bf16 configs must run the expert einsums in bf16 (the dense MLP
    path's discipline), not silently in f32."""
    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                    num_heads=2, intermediate_size=32, max_position=32,
                    dtype=jnp.bfloat16, moe_experts=4, moe_every=2)
    ids = jnp.zeros((2, 8), jnp.int32)
    v = GPT(cfg).init(jax.random.PRNGKey(0), ids)
    # params stay f32 (master weights)...
    assert v["params"]["h1"]["moe"]["w1"].dtype == jnp.float32
    # ...and the forward runs without error, producing f32 logits
    logits, _ = GPT(cfg).apply(v, ids, mutable=["moe_aux"])
    assert logits.dtype == jnp.float32
