"""Slowness scoring units (utils/slowness.py): phi-accrual behavior,
the latency-quantile helper behind adaptive hedge delays, the probation
recovery loop, and the engine/metrics feeds (ISSUE 10 tentpole part 1)."""

from __future__ import annotations

import time

import numpy as np
import pytest

import byteps_tpu.core.api as api
from byteps_tpu.common.config import Config
from byteps_tpu.common.telemetry import gauges
from byteps_tpu.utils.slowness import (PHI_MAX, LatencyQuantile,
                                       SlownessTracker, wait_recovered)
from byteps_tpu.utils import slowness as slowness_mod


# -- SlownessTracker ---------------------------------------------------------


def test_uniform_peers_score_low():
    tr = SlownessTracker(window=32)
    for _ in range(20):
        for r in (0, 1, 2):
            tr.observe(r, 0.010, site="sync")
    for r in (0, 1, 2):
        assert tr.score(r, site="sync") < 2.0, tr.scores(site="sync")


def test_one_slow_peer_scores_high_others_stay_low():
    tr = SlownessTracker(window=32)
    for _ in range(20):
        tr.observe(0, 0.010, site="sync")
        tr.observe(1, 0.011, site="sync")
        tr.observe(2, 0.350, site="sync")   # the straggler
    scores = tr.scores(site="sync")
    assert scores[2] >= 8.0, scores
    assert scores[0] < 2.0 and scores[1] < 2.0, scores
    # the straggler's median is visible too
    assert tr.latency(2, site="sync") == pytest.approx(0.35, rel=0.01)


def test_identical_baseline_clamps_at_phi_max():
    """A zero-variance healthy population makes any outlier
    astronomically improbable: the score must CLAMP, not overflow."""
    tr = SlownessTracker(window=32)
    for _ in range(16):
        tr.observe(0, 0.010, site="s")
        tr.observe(1, 0.010, site="s")
        tr.observe(2, 10.0, site="s")
    assert tr.score(2, site="s") == PHI_MAX


def test_single_peer_scores_against_own_history():
    """With no peers at a site, the baseline is the peer's own older
    half — a sudden sustained slowdown still scores."""
    tr = SlownessTracker(window=32)
    for _ in range(12):
        tr.observe(0, 0.010, site="solo")
    for _ in range(12):
        tr.observe(0, 0.400, site="solo")
    assert tr.score(0, site="solo") >= 8.0
    # and a peer with a steady history does not
    tr2 = SlownessTracker(window=32)
    for _ in range(24):
        tr2.observe(0, 0.010, site="solo")
    assert tr2.score(0, site="solo") < 2.0


def test_window_bound_and_recovery():
    """The bounded window forgets: after the slow phase ends, enough
    healthy samples bring the score back down (the readmission
    hysteresis depends on this)."""
    tr = SlownessTracker(window=16)
    for _ in range(16):
        tr.observe(0, 0.010, site="s")
        tr.observe(1, 0.400, site="s")
    assert tr.score(1, site="s") >= 8.0
    for _ in range(16):
        tr.observe(0, 0.010, site="s")
        tr.observe(1, 0.010, site="s")   # recovered
    assert tr.score(1, site="s") < 2.0


def test_score_without_site_takes_worst_site():
    tr = SlownessTracker(window=32)
    for _ in range(16):
        tr.observe(1, 0.010, site="a")
        tr.observe(2, 0.010, site="a")
        tr.observe(1, 0.500, site="b")
        tr.observe(2, 0.010, site="b")
    assert tr.score(1) >= 8.0          # slow at site b only
    assert tr.score(1, site="a") < 2.0


def test_snapshot_shape_and_gauges():
    tr = SlownessTracker(window=16)
    for _ in range(10):
        tr.observe(0, 0.010, site="sync")
        tr.observe(1, 0.300, site="sync")
    snap = tr.snapshot()
    assert set(snap) == {"sync"}
    assert set(snap["sync"]) == {0, 1}
    row = snap["sync"][1]
    assert set(row) == {"n", "median_ms", "score"}
    assert row["median_ms"] == pytest.approx(300.0, rel=0.01)
    tr.publish_gauges()
    assert gauges.get("slowness.max_score") >= 8.0
    assert gauges.get("slowness.score", site="sync", rank=1) >= 8.0
    assert gauges.get("slowness.score", site="sync", rank=0) < 2.0


def test_window_validation_and_reset():
    with pytest.raises(ValueError, match="window"):
        SlownessTracker(window=4)
    tr = SlownessTracker()
    tr.observe(0, 1.0)
    tr.reset()
    assert tr.scores() == {} and tr.latency(0) == 0.0


def test_module_tracker_honors_config_window(monkeypatch):
    slowness_mod._reset_for_tests()
    monkeypatch.setenv("BYTEPS_SLOWNESS_WINDOW", "16")
    from byteps_tpu.common.config import reset_config
    reset_config()
    assert slowness_mod.tracker().window == 16
    assert slowness_mod.tracker() is slowness_mod.tracker()  # singleton


# -- LatencyQuantile ---------------------------------------------------------


def test_latency_quantile_none_until_min_samples():
    q = LatencyQuantile(window=32, min_samples=8)
    for i in range(7):
        q.observe(0.001 * (i + 1))
        assert q.quantile(0.99) is None
    q.observe(0.008)
    assert q.quantile(0.99) == pytest.approx(0.008)


def test_latency_quantile_values():
    q = LatencyQuantile(window=100, min_samples=8)
    for i in range(1, 101):
        q.observe(i / 1000.0)
    assert q.quantile(0.5) == pytest.approx(0.050)
    assert q.quantile(0.99) == pytest.approx(0.099)
    assert len(q) == 100


# -- wait_recovered ----------------------------------------------------------


def test_wait_recovered_waits_out_the_fault():
    state = {"n": 0}

    def probe():
        state["n"] += 1
        if state["n"] <= 4:
            time.sleep(0.05)    # "slow" phase

    assert wait_recovered(probe, baseline_s=0.01, factor=2.0,
                          consecutive=3, interval_s=0.0, timeout_s=10.0)
    # 4 slow probes, then 3 consecutive healthy ones
    assert state["n"] == 7


def test_wait_recovered_times_out_on_a_sustained_fault():
    assert not wait_recovered(lambda: time.sleep(0.03), baseline_s=0.01,
                              factor=2.0, consecutive=2,
                              interval_s=0.0, timeout_s=0.3)


# -- the engine feed ---------------------------------------------------------


def test_engine_sync_loop_feeds_tracker():
    """Every retired sync unit lands one `sync`-site sample for this
    process's own rank — the self-reported half of gray-failure
    detection (the bus's step-barrier lags are the cross-rank half)."""
    slowness_mod._reset_for_tests()
    api.init(Config(telemetry_on=True))
    try:
        for i in range(4):
            api._require().push_pull_local(
                np.ones(8, np.float32), "slowfeed", op="sum")
        snap = slowness_mod.tracker().snapshot()
        assert "sync" in snap, snap
        rank = Config().host_id
        assert snap["sync"][rank]["n"] >= 4
        # a healthy local engine must not accuse itself
        assert snap["sync"][rank]["score"] < 8.0
        # and the non-light metrics snapshot carries the same view
        assert "slowness" in api.metrics_snapshot()
    finally:
        api.shutdown()
