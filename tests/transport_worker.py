"""Worker body for the TCP-transport chaos tests (test_transport_chaos.py).

Four real processes per run (one transport server + three pushers, or a
full elastic world), all cross-process bytes on the NEW supervised TCP
transport (comm/transport.py) — no in-process loopback anywhere on the
data plane.  Modes, selected by ``BYTEPS_TW_MODE``:

- **bitflip**: rank 0 hosts a ``ServerEngine`` behind a
  ``TransportServer``; ranks 1..N push integer-valued gradients (exact
  in float32 under ANY arrival order — TCP does not serialize workers
  the way the loopback harness did) to a per-step key and pull the
  merged round back over the same wire.  With
  ``bitflip:site=server_push`` armed in the WORKERS, every corrupted
  frame must be NACKed by the server and retransmitted from the sealed
  source copy, so the final parameters are BIT-IDENTICAL to the
  fault-free replay — the test's headline assertion.  Workers print
  ``DIGEST``; the server prints ``REJECTS``/``RETRANS``.

- **kvreset**: rank 0 hosts a ``KVStore``; ranks 1..3 push STEPS
  seq-tokened unit deltas each, retrying on ``AckLost``.  Rank 2 runs
  under ``conn_reset:site=transport`` chaos: its connection is RST mid
  send/recv, the supervisor reconnects, and the retransmit carries the
  SAME token — the server's dedup absorbs retries whose original
  landed.  Every worker also sends one deliberate duplicate of its
  first (provably landed) token, so the dedup counter is nonzero
  deterministically.  The server polls the store to EXACTLY 3*STEPS
  (one over would be a double-sum, one under a lost push) and prints
  ``SUM``/``DUP``; rank 2 prints ``RESETS``/``RECONNECTS``.

- **partition**: a 4-rank elastic world (membership bus + heartbeats,
  fault/membership.py) whose data plane pushes seq-tokened deltas to
  rank 0's store over the transport (rank 0 itself rides the
  ``LoopbackEndpoint`` same-process fast path behind the same
  ``Endpoint`` interface).  ``partition:rank=2:site=transport``
  blackholes rank 2's sockets: its pushes surface as ``AckLost`` at
  the send deadline (never a hang), and after a short streak the rank
  converts the evidence into a detected data-path failure — prints
  ``PARTITIONED <deadline trips>`` and exits with the restartable
  failure code.  The survivors' heartbeat detector turns that into an
  ordinary shrink-to-survivors; they finish every step at the shrunk
  world and print ``FINAL`` states the test replays exactly.  The
  store ends at EXACTLY 3*STEPS (survivor retries across the world
  change are dedup-absorbed; the partitioned rank lands nothing).
"""

from __future__ import annotations

import hashlib
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N = 257
LR = np.float32(0.05)


def _grad(step: int, wid: int) -> np.ndarray:
    # integer-valued floats: sums of a few of these are EXACT in f32,
    # so the merged value is order-independent — bit-identical finals
    # need no arrival-order choreography over a real wire
    return np.random.RandomState(7919 * step + wid) \
        .randint(-1024, 1025, N).astype(np.float32)


def _elastic_grad(rank: int) -> np.ndarray:
    return np.full(4, float((rank + 1) ** 2), np.float32)


def main() -> int:
    mode = os.environ["BYTEPS_TW_MODE"]
    rank = int(os.environ["BYTEPS_TW_RANK"])
    port = int(os.environ["BYTEPS_TW_PORT"])
    steps = int(os.environ.get("BYTEPS_TW_STEPS", "20"))
    nworkers = int(os.environ.get("BYTEPS_TW_NWORKERS", "3"))

    from byteps_tpu.common import integrity
    from byteps_tpu.common.telemetry import counters
    from byteps_tpu.comm import transport as tp
    from byteps_tpu.fault import injector as inj

    spec = os.environ.get("BYTEPS_FAULT_SPEC", "")
    if spec:
        inj.arm(spec, seed=int(os.environ.get("BYTEPS_FAULT_SEED",
                                              str(rank))), rank=rank)

    if mode == "bitflip":
        return _run_bitflip(tp, rank, port, steps, nworkers, counters)
    if mode == "kvreset":
        return _run_kvreset(tp, integrity, rank, port, steps, nworkers,
                            counters)
    if mode == "partition":
        return _run_partition(tp, integrity, rank, port, steps, counters)
    raise SystemExit(f"unknown BYTEPS_TW_MODE {mode!r}")


def _run_bitflip(tp, rank, port, steps, nworkers, counters) -> int:
    if rank == 0:
        from byteps_tpu.server.engine import ServerEngine
        eng = ServerEngine(num_threads=1)
        srv = tp.TransportServer(host="127.0.0.1", port=port, rank=0,
                                 engine=eng)
        print("SRV-UP", flush=True)
        try:
            # the workers push a sentinel round AFTER their last pull:
            # its completion is the "everyone is done" barrier
            eng.pull("done", timeout=180)
        finally:
            print("REJECTS", counters.get("integrity.crc_reject"),
                  flush=True)
            print("RETRANS", counters.get("integrity.retransmit"),
                  flush=True)
            time.sleep(0.5)   # let the last ACKs/pulls drain
            srv.close()
            eng.shutdown()
        return 0
    wid = rank - 1
    ep = tp.TcpEndpoint(("127.0.0.1", port), peer=0, rank=rank)
    params = np.zeros(N, np.float32)
    for step in range(steps):
        # per-step key: the merge round for key g<step> completes
        # exactly once, so every worker's parked pull answers with THAT
        # round — no cross-step read races over the async wire
        key = f"g{step}"
        ep.push(key, _grad(step, wid), wid, nworkers)
        merged = ep.pull(key, timeout=60)
        params -= LR * merged
    print("RETRANS", rank, counters.get("integrity.retransmit"),
          flush=True)
    print("DIGEST", rank, hashlib.sha256(params.tobytes()).hexdigest(),
          flush=True)
    ep.push("done", np.zeros(1, np.float32), wid, nworkers)
    ep.close()
    return 0


def _run_kvreset(tp, integrity, rank, port, steps, nworkers,
                 counters) -> int:
    if rank == 0:
        from byteps_tpu.server.kv_store import KVStore
        kv = KVStore()
        kv.init_key("acc", np.zeros(1, np.float32))
        srv = tp.TransportServer(host="127.0.0.1", port=port, rank=0,
                                 kv=kv)
        print("SRV-UP", flush=True)
        want = float(steps * nworkers)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if float(kv.pull("acc")[0]) >= want:
                break
            time.sleep(0.1)
        time.sleep(1.0)  # a straggling duplicate would land here
        print("SUM", repr(float(kv.pull("acc")[0])), flush=True)
        print("DUP", counters.get("integrity.dup_dropped"), flush=True)
        srv.close()
        return 0
    ep = tp.TcpEndpoint(("127.0.0.1", port), peer=0, rank=rank,
                        send_deadline_s=5.0)

    def push_tok(seq):
        while True:
            try:
                ep.push_delta("acc", np.ones(1, np.float32),
                              worker_id=rank, seq=seq)
                return
            except integrity.AckLost:
                continue  # same token: the dedup absorbs the retry

    for step in range(steps):
        push_tok(step + 1)
        if step == 0:
            # one DELIBERATE duplicate of the just-landed token: the
            # random resets may all fire before the server processed a
            # frame (every retransmit is then a FIRST landing and the
            # dup counter honestly stays 0) — this duplicate's original
            # provably landed, so the dedup MUST absorb it: DUP >= 1 is
            # deterministic, and a broken dedup still shows up as SUM
            # overshooting 3*STEPS
            push_tok(1)
    print("RESETS", rank, counters.get("transport.conn_resets"),
          flush=True)
    print("RECONNECTS", rank, ep.connection.reconnects, flush=True)
    ep.close()
    return 0


def _run_partition(tp, integrity, rank, port, steps, counters) -> int:
    world = [int(r) for r in os.environ["BYTEPS_TW_WORLD"].split(",")]
    bus = os.environ["BYTEPS_TW_BUS"]
    hb_port = os.environ.get("BYTEPS_TW_HB_PORT", "")
    fail_code = int(os.environ.get("BYTEPS_FAILURE_EXIT_CODE", "17"))

    from byteps_tpu.fault.membership import (ElasticMembership,
                                             MembershipTimeout,
                                             WorldChanged)
    from byteps_tpu.utils.failure_detector import install_failure_action

    kv = None
    if rank == 0:
        from byteps_tpu.server.kv_store import KVStore
        kv = KVStore()
        kv.init_key("acc", np.zeros(1, np.float32))
        tp.serve(rank=0, host="127.0.0.1", port=port, kv=kv)
    # ONE Endpoint interface: rank 0 takes the same-process loopback
    # fast path, everyone else the supervised TCP connection
    if rank == 0:
        ep = tp.LoopbackEndpoint(kv=kv)
    else:
        ep = tp.TcpEndpoint(("127.0.0.1", port), peer=0, rank=rank,
                            send_deadline_s=1.5, keepalive_s=0.0)
    m = ElasticMembership(rank, world, bus).start()
    install_failure_action(m.on_failure)
    if hb_port:
        m.host_heartbeat(interval=0.08, timeout=0.7, grace=60.0,
                         addr="127.0.0.1:" + hb_port,
                         on_failure=m.on_failure)
    print("START", rank, flush=True)

    w = np.zeros(4, np.float32)
    step = 1
    acklost_streak = 0
    retries = 0
    while step <= steps:
        if retries > 200:
            print("RETRY-BUDGET-EXHAUSTED at", step, flush=True)
            return 6
        try:
            ep.push_delta("acc", np.ones(1, np.float32), worker_id=rank,
                          seq=step)
            acklost_streak = 0
        except integrity.AckLost:
            # the partition evidence: per-send deadlines, never a hang.
            # A short streak converts "my data path is dead" into a
            # DETECTED failure — exit restartable; the survivors'
            # heartbeat loss turns it into an ordinary shrink.
            acklost_streak += 1
            if acklost_streak >= 2:
                print("PARTITIONED",
                      counters.get("transport.send_deadline_trips"),
                      flush=True)
                m.stop()
                return fail_code
            continue
        try:
            _, payloads = m.step_sync(step, payload=_elastic_grad(rank))
        except WorldChanged as e:
            print("WORLD", e.view.epoch,
                  ",".join(map(str, e.view.world)), "at", step, flush=True)
            continue  # re-push is same-token: dedup absorbs it
        except MembershipTimeout:
            retries += 1
            continue
        retries = 0
        grads = [np.asarray(p) for p in payloads.values()]
        w = w - LR * (np.sum(grads, axis=0, dtype=np.float32)
                      / np.float32(len(grads)))
        step += 1
        time.sleep(0.03)

    view = m.view()
    if rank == 0:
        time.sleep(1.0)  # let the other survivors' last deltas land
        print("SUM", repr(float(kv.pull("acc")[0])), flush=True)
    print("FINAL", view.epoch, ",".join(map(str, view.world)),
          repr(float(w[0])), flush=True)
    install_failure_action(None)
    m.stop()
    ep.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
