"""Worker body for the 2-process CPU integration test.

Launched by tests/test_multiprocess.py with the DMLC bootstrap env set
(the reference's rendezvous protocol, reference communicator.cc:60-96 /
docs/env.md:7-45).  Exercises, for real, the paths round-1 review flagged
as untested under jax.process_count() > 1:

- ``mesh.bootstrap``'s ``jax.distributed.initialize`` branch (DMLC env ->
  coordinator address),
- ``_as_stacked`` building global arrays from per-process host data
  (non-addressable shards),
- ``push_pull_local``'s cross-process denominator logic,
- the hierarchical (dcn = processes) reduction path end-to-end,
- ``broadcast_host`` from a root rank owned by one process.

Asserted against numpy computed locally — i.e. multi-process results must
equal what a single process would compute over the union of contributions.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    import jax

    # The env-var JAX_PLATFORMS can already be consumed by a sitecustomize
    # jax import (tests/conftest.py:13-16 documents the trap); config.update
    # is the reliable pin and must precede any backend/distributed touch.
    jax.config.update("jax_platforms", "cpu")

    import byteps_tpu.core.api as api
    from byteps_tpu.comm.collectives import broadcast_host
    from byteps_tpu.comm.mesh import get_comm

    api.init()
    n_proc = jax.process_count()
    assert n_proc == 2, f"expected 2 processes, got {n_proc}"
    pid = jax.process_index()
    comm = get_comm()
    assert comm.n_dcn == 2, f"dcn axis should equal process count: {comm.n_dcn}"
    eng = api._require()

    # --- push_pull_local: sum and average over processes --------------------
    n = 999  # odd: exercises ici padding in the hierarchical path
    x = np.arange(n, dtype=np.float32) + 1000.0 * (pid + 1)
    expect_sum = np.sum(
        [np.arange(n, dtype=np.float32) + 1000.0 * (p + 1)
         for p in range(n_proc)], axis=0)
    out = eng.push_pull_local(x, "mp.sum", op="sum")
    np.testing.assert_allclose(np.asarray(out), expect_sum, rtol=1e-6)
    out = eng.push_pull_local(x, "mp.avg", op="average")
    np.testing.assert_allclose(np.asarray(out), expect_sum / n_proc,
                               rtol=1e-6)

    # --- partitioned path: big tensor split into multiple chunks ------------
    big_n = 100_000  # 400 KB f32 over BYTEPS_PARTITION_BYTES=65536 -> ~7 chunks
    rng = np.random.RandomState(7)  # same stream on both processes
    base = rng.randn(big_n).astype(np.float32)
    big = base * (pid + 1)
    out = eng.push_pull_local(big, "mp.big", op="sum")
    np.testing.assert_allclose(np.asarray(out), base * 3.0, rtol=1e-5,
                               atol=1e-5)

    # --- broadcast from root rank 0 (owned by process 0) --------------------
    b = broadcast_host(comm, x, root=0)
    expect_b = np.arange(n, dtype=np.float32) + 1000.0
    np.testing.assert_allclose(np.asarray(b), expect_b, rtol=1e-6)

    # --- torch adapter surface over two real processes ----------------------
    try:
        import torch
        import byteps_tpu.torch as bps_torch
        t = torch.full((8,), float(pid + 1))
        tout = bps_torch.push_pull(t, average=True, name="mp.torch")
        np.testing.assert_allclose(tout.numpy(), np.full((8,), 1.5),
                                   rtol=1e-6)
    except ImportError:
        pass

    api.shutdown()
    print(f"MP_OK {pid}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
