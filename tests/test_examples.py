"""Smoke-run the example scripts on the CPU mesh (the reference's
example benchmarks double as its multi-node validation, SURVEY.md §4).
Each runs in-process with tiny step counts."""

import sys

import pytest
import runpy

from .conftest import legacy_skip


def _run(path, *argv):
    old = sys.argv
    sys.argv = [path, *argv]
    try:
        runpy.run_path(path, run_name="__main__")
    finally:
        sys.argv = old


@pytest.mark.parametrize("path,argv", [
    ("example/jax/train_mnist_mlp.py", ("--steps", "2", "--batch", "2")),
    ("example/jax/benchmark_bert.py", ("--steps", "1", "--batch", "1")),
    ("example/jax/benchmark_resnet.py",
     ("--model", "tiny", "--batch", "1", "--size", "16", "--steps", "1")),
    ("example/jax/train_llama.py",
     ("--steps", "8", "--batch", "8", "--seq", "16")),
    ("example/jax/train_parallel_axes.py",
     ("--mode", "tp", "--steps", "2", "--batch", "8", "--seq", "16")),
    ("example/jax/train_parallel_axes.py",
     ("--mode", "pp", "--steps", "2", "--batch", "8", "--seq", "16",
      "--microbatches", "2")),
    ("example/jax/train_parallel_axes.py",
     ("--mode", "ep", "--steps", "2", "--batch", "4", "--experts", "8")),
    ("example/jax/train_parallel_axes.py",
     ("--mode", "zero", "--steps", "2", "--batch", "8", "--seq", "16")),
    ("example/jax/train_parallel_axes.py",
     ("--mode", "fsdp", "--steps", "2", "--batch", "8", "--seq", "16")),
    pytest.param(
        "example/jax/train_parallel_axes.py",
        ("--mode", "3d", "--steps", "2", "--batch", "8", "--seq", "16",
         "--microbatches", "2"),
        marks=legacy_skip),  # 3d composite diverges on pre-VMA shard_map
    ("example/jax/train_long_context.py",
     ("--steps", "2", "--seq", "128", "--sp", "4", "--tiny",
      "--batch", "4")),
    ("example/jax/train_long_context.py",
     ("--steps", "2", "--seq", "128", "--sp", "4", "--tiny",
      "--batch", "4", "--attention", "ring_flash")),
    ("example/jax/train_long_context.py",
     ("--steps", "2", "--seq", "128", "--sp", "4", "--tiny",
      "--batch", "4", "--attention", "ulysses_flash")),
    ("example/pytorch/train_mnist_byteps.py", ("--steps", "2")),
    ("example/pytorch/benchmark_byteps.py",
     ("--num-iters", "1", "--num-tensors", "2", "--tensor-mb", "0.1")),
    ("example/pytorch/benchmark_byteps_ddp.py",
     ("--num-iters", "1", "--accumulate", "2", "--batch", "4")),
    ("example/pytorch/benchmark_cross_barrier_byteps.py",
     ("--num-iters", "2", "--batch", "4")),
    ("example/pytorch/elastic_benchmark_byteps.py", ()),
])
def test_example_smoke(path, argv):
    _run(path, *argv)


@pytest.mark.parametrize("path,argv", [
    ("example/tensorflow/tensorflow2_mnist.py", ("--steps", "2")),
    ("example/tensorflow/synthetic_benchmark_tf2.py",
     ("--num-iters", "1", "--num-tensors", "1", "--tensor-mb", "0.1")),
    ("example/tensorflow/tensorflow2_mnist_bps_MirroredStrategy.py",
     ("--steps", "2",)),
    ("example/keras/keras_mnist.py", ("--epochs", "1", "--batch", "256")),
])
def test_tf_example_smoke(path, argv):
    pytest.importorskip("tensorflow")
    _run(path, *argv)
