"""Model zoo for examples/benchmarks, mirroring the reference's example/
directory (PyTorch MNIST, synthetic ResNet-50, GluonNLP BERT-large —
SURVEY.md §6 configs)."""

from .llama import (  # noqa: F401
    Llama,
    LlamaConfig,
    llama3_8b,
    llama_tiny,
)
from .mlp import MLP, mnist_mlp  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet,
    VGG,
    resnet18,
    resnet50,
    resnet_tiny,
    vgg16,
)
