"""Model zoo for examples/benchmarks, mirroring the reference's example/
directory (PyTorch MNIST, synthetic ResNet-50, GluonNLP BERT-large —
SURVEY.md §6 configs)."""

from .mlp import MLP, mnist_mlp  # noqa: F401
