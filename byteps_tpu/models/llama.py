"""Llama-family decoder LM: RoPE, RMSNorm, SwiGLU, grouped-query attention.

The reference has no model zoo of its own — its benchmarks drive framework
models (BERT/ResNet/VGG via GluonNLP/torchvision, reference README.md:35-41,
docs/performance.md) — but BASELINE.json's stretch config names a modern
LLM ("Llama-3-8B via byteps/jax DistributedOptimizer") as the flagship
workload for the FSDP/TP machinery.  This is that family, TPU-first:

- bf16 compute over f32 params, MXU-aligned head dims, static shapes;
- RMSNorm statistics in f32 (bf16 mean-of-squares loses the small-residual
  regime);
- rotary embeddings computed in f32 and cast once;
- GQA: ``num_kv_heads < num_heads`` shrinks the KV projections; K/V heads
  are repeated to the query-head count before the attention callable, so
  the same parameters run with exact, flash, ring or Ulysses attention
  (the established pluggable-``attn_fn`` pattern, models/gpt.py).

Weights follow the Llama layout: no biases anywhere, untied embedding and
lm head, SwiGLU gate/up/down MLP.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from .gpt import lm_loss, token_nll  # shared loss (same LM contract)

__all__ = [
    "LlamaConfig", "Llama", "llama3_8b", "llama_tiny", "lm_loss",
    "token_nll", "rope_frequencies", "apply_rope",
]

AttnFn = Callable  # (q, k, v, *, causal, sm_scale) -> out


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8            # GQA group count
    intermediate_size: int = 14336   # SwiGLU width
    max_position: int = 8192
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = False

    def __post_init__(self):
        if self.num_heads % self.num_kv_heads:
            raise ValueError(
                f"num_heads ({self.num_heads}) must be divisible by "
                f"num_kv_heads ({self.num_kv_heads})")
        if self.hidden_size % self.num_heads:
            raise ValueError("hidden_size must be divisible by num_heads")


def llama3_8b() -> LlamaConfig:
    """Llama-3-8B geometry (the BASELINE.json configs[4] stretch target)."""
    return LlamaConfig()


def llama_tiny() -> LlamaConfig:
    """CPU-mesh tests / multichip dry-runs; keeps GQA non-trivial (4 q
    heads over 2 kv heads)."""
    return LlamaConfig(vocab_size=256, hidden_size=64, num_layers=2,
                       num_heads=4, num_kv_heads=2, intermediate_size=128,
                       max_position=512, rope_theta=10000.0)


def llama_tiny_f32() -> LlamaConfig:
    """Even smaller, f32 end to end: the parity tests need bit-comparable
    math (one definition so every test pins the same geometry)."""
    return LlamaConfig(vocab_size=128, hidden_size=32, num_layers=2,
                       num_heads=4, num_kv_heads=2, intermediate_size=64,
                       max_position=64, rope_theta=10000.0,
                       dtype=jnp.float32)


# ----------------------------------------------------------------- rotary

def rope_frequencies(head_dim: int, positions, theta: float):
    """(cos, sin) tables [*, T, head_dim/2] in f32 for the given absolute
    positions (sharded-sequence callers pass their own offsets, as with
    GPT's ``positions`` argument)."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                           / head_dim))
    ang = positions[..., None].astype(jnp.float32) * inv  # [*, T, D/2]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x, cos, sin):
    """Rotate pairs (x[i], x[i + D/2]) — the *rotate-half* convention used
    by Llama checkpoints as distributed (HF ``rotate_half``), so pretrained
    q/k projections import without permutation.  x is [B, T, H, D], tables
    broadcast over the head axis.  (The interleaved (x[2i], x[2i+1])
    convention is the same rotation under a fixed channel permutation; we
    pin the checkpoint-compatible one.)"""
    d2 = x.shape[-1] // 2
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :d2], xf[..., d2:]
    c, s = cos[:, :, None, :], sin[:, :, None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float
    dtype: Any

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           jnp.float32)
        xf = x.astype(jnp.float32)
        rms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True)
                            + self.eps)
        return (xf * rms * scale).astype(self.dtype)


class LlamaAttention(nn.Module):
    cfg: LlamaConfig
    attn_fn: Optional[AttnFn] = None

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        hd = cfg.hidden_size // cfg.num_heads
        groups = cfg.num_heads // cfg.num_kv_heads
        q = nn.DenseGeneral((cfg.num_heads, hd), use_bias=False,
                            dtype=cfg.dtype, name="q")(x)
        k = nn.DenseGeneral((cfg.num_kv_heads, hd), use_bias=False,
                            dtype=cfg.dtype, name="k")(x)
        v = nn.DenseGeneral((cfg.num_kv_heads, hd), use_bias=False,
                            dtype=cfg.dtype, name="v")(x)
        cos, sin = rope_frequencies(hd, positions, cfg.rope_theta)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        if groups > 1:
            # repeat KV heads to the query count: numerically identical to
            # grouped attention, and keeps the pluggable attn_fn contract
            # (flash/ring/Ulysses) head-uniform
            k = jnp.repeat(k, groups, axis=2)
            v = jnp.repeat(v, groups, axis=2)
        attn = self.attn_fn
        if attn is None:
            from ..parallel.sequence import full_attention as attn
        ctx = attn(q, k, v, causal=True, sm_scale=1.0 / math.sqrt(hd))
        return nn.DenseGeneral(cfg.hidden_size, axis=(-2, -1),
                               use_bias=False, dtype=cfg.dtype,
                               name="out")(ctx)


class LlamaMLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        g = nn.Dense(cfg.intermediate_size, use_bias=False, dtype=cfg.dtype,
                     name="gate")(x)
        u = nn.Dense(cfg.intermediate_size, use_bias=False, dtype=cfg.dtype,
                     name="up")(x)
        return nn.Dense(cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
                        name="down")(jax.nn.silu(g) * u)


class LlamaBlock(nn.Module):
    cfg: LlamaConfig
    attn_fn: Optional[AttnFn] = None

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        h = RMSNorm(cfg.rms_eps, cfg.dtype, name="attn_norm")(x)
        x = x + LlamaAttention(cfg, self.attn_fn, name="attn")(h, positions)
        h = RMSNorm(cfg.rms_eps, cfg.dtype, name="mlp_norm")(x)
        return x + LlamaMLP(cfg, name="mlp")(h)


class Llama(nn.Module):
    """Decoder-only Llama.  ``positions`` must be passed when the sequence
    axis is sharded (each shard holds positions [off, off + T/sp))."""

    cfg: LlamaConfig
    attn_fn: Optional[AttnFn] = None

    @nn.compact
    def __call__(self, input_ids, positions=None):
        cfg = self.cfg
        b, t = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(t)[None], (b, t))
        elif positions.ndim == 1:
            positions = jnp.broadcast_to(positions[None], (b, t))
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     name="wte")(input_ids)
        block = LlamaBlock
        if cfg.remat:
            block = nn.remat(LlamaBlock)
        for i in range(cfg.num_layers):
            x = block(cfg, self.attn_fn, name=f"h{i}")(x, positions)
        x = RMSNorm(cfg.rms_eps, cfg.dtype, name="norm_f")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                          name="lm_head")(x)
        return logits.astype(jnp.float32)
