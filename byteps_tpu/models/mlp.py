"""MNIST-class MLP — the minimum end-to-end model (SURVEY.md §7 step 4,
standing in for the reference's example/pytorch MNIST config)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class MLP(nn.Module):
    features: Sequence[int] = (128, 64, 10)

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        for i, f in enumerate(self.features):
            x = nn.Dense(f, name=f"dense_{i}")(x)
            if i < len(self.features) - 1:
                x = nn.relu(x)
        return x


def mnist_mlp() -> MLP:
    return MLP(features=(128, 64, 10))


def softmax_cross_entropy(logits, labels):
    logp = jax.nn.log_softmax(logits)
    onehot = jnp.eye(logits.shape[-1], dtype=logp.dtype)[labels]
    return -(onehot * logp).sum(-1).mean()
