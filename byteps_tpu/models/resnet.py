"""ResNet and VGG — the reference's benchmark vision family, TPU-first.

The reference's published perf table is ResNet-50 and VGG-16 images/s
(reference docs/performance.md:3-23, example/pytorch/
train_imagenet_resnet50_byteps.py, keras_imagenet_resnet50.py).  These are
re-designed for TPU rather than ported from torchvision:

- **NHWC** layout throughout — the layout XLA:TPU convolutions natively
  tile; NCHW would insert transposes at every conv.
- **bf16 compute, f32 params**: convolutions/matmuls run in bfloat16 on
  the MXU (``compute_dtype=jnp.bfloat16``); parameters, batch statistics
  and the softmax stay f32.
- **Cross-replica BatchNorm**: ``axis_name`` threads the mesh axes into
  the batch-stat reduction, so statistics are computed over the *global*
  batch under data parallelism (the sync-BN the reference delegates to
  the frameworks).  Running stats then update identically on every
  replica — no extra broadcast needed.
- Pure-functional state: batch statistics live in a ``batch_stats``
  collection threaded by ``parallel.make_dp_train_step_with_state``.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class ConvBN(nn.Module):
    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    compute_dtype: Any = jnp.bfloat16
    axis_name: Optional[Any] = None
    act: bool = True

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.features, self.kernel, self.strides, padding="SAME",
                    use_bias=False, dtype=self.compute_dtype,
                    param_dtype=jnp.float32)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=jnp.float32,
                         axis_name=self.axis_name)(x.astype(jnp.float32))
        x = x.astype(self.compute_dtype)
        return nn.relu(x) if self.act else x


class Bottleneck(nn.Module):
    """ResNet-v1.5 bottleneck: 1x1 reduce, 3x3 (stride here, as v1.5),
    1x1 expand, residual add."""

    features: int
    strides: Tuple[int, int] = (1, 1)
    compute_dtype: Any = jnp.bfloat16
    axis_name: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cb = partial(ConvBN, compute_dtype=self.compute_dtype,
                     axis_name=self.axis_name)
        residual = x
        y = cb(self.features, (1, 1))(x, train)
        y = cb(self.features, (3, 3), self.strides)(y, train)
        y = cb(self.features * 4, (1, 1), act=False)(y, train)
        if residual.shape != y.shape:
            residual = cb(self.features * 4, (1, 1), self.strides,
                          act=False)(residual, train)
        return nn.relu(y + residual)


class BasicBlock(nn.Module):
    """ResNet-18/34 block: two 3x3 convs + residual."""

    features: int
    strides: Tuple[int, int] = (1, 1)
    compute_dtype: Any = jnp.bfloat16
    axis_name: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cb = partial(ConvBN, compute_dtype=self.compute_dtype,
                     axis_name=self.axis_name)
        residual = x
        y = cb(self.features, (3, 3), self.strides)(x, train)
        y = cb(self.features, (3, 3), act=False)(y, train)
        if residual.shape != y.shape:
            residual = cb(self.features, (1, 1), self.strides,
                          act=False)(residual, train)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """NHWC ResNet; ``stage_sizes``/``block`` select the depth."""

    stage_sizes: Sequence[int]
    block: Callable
    num_classes: int = 1000
    width: int = 64
    compute_dtype: Any = jnp.bfloat16
    axis_name: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cb = partial(ConvBN, compute_dtype=self.compute_dtype,
                     axis_name=self.axis_name)
        x = x.astype(self.compute_dtype)
        x = cb(self.width, (7, 7), (2, 2))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block(self.width * 2 ** i, strides,
                               compute_dtype=self.compute_dtype,
                               axis_name=self.axis_name)(x, train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x.astype(jnp.float32))
        return x


def resnet50(num_classes: int = 1000, axis_name=None,
             compute_dtype=jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block=Bottleneck,
                  num_classes=num_classes, axis_name=axis_name,
                  compute_dtype=compute_dtype)


def resnet18(num_classes: int = 1000, axis_name=None,
             compute_dtype=jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), block=BasicBlock,
                  num_classes=num_classes, axis_name=axis_name,
                  compute_dtype=compute_dtype)


def resnet_tiny(num_classes: int = 10, axis_name=None,
                compute_dtype=jnp.float32) -> ResNet:
    """CI-sized: one block per stage, width 8 (CPU-mesh tests)."""
    return ResNet(stage_sizes=(1, 1), block=BasicBlock, width=8,
                  num_classes=num_classes, axis_name=axis_name,
                  compute_dtype=compute_dtype)


class VGG(nn.Module):
    """VGG-16 (configuration D), NHWC, bf16 compute.  The reference's
    bandwidth-bound benchmark model (docs/performance.md:9 — VGG's 138M
    dense parameters made it BytePS's best case)."""

    cfg: Sequence = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
                     512, 512, 512, "M", 512, 512, 512, "M")
    num_classes: int = 1000
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.compute_dtype)
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding="SAME",
                            dtype=self.compute_dtype,
                            param_dtype=jnp.float32)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        for feats in (4096, 4096):
            x = nn.Dense(feats, dtype=self.compute_dtype,
                         param_dtype=jnp.float32)(x)
            x = nn.relu(x)
            # real dropout when training: needs a "dropout" RNG in apply()
            x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32)(x.astype(jnp.float32))


def vgg16(num_classes: int = 1000,
          compute_dtype=jnp.bfloat16) -> VGG:
    return VGG(num_classes=num_classes, compute_dtype=compute_dtype)


def softmax_cross_entropy(logits, labels) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None],
                                         axis=1).squeeze(1))


def synthetic_images(rng, batch: int, size: int = 224,
                     num_classes: int = 1000):
    """Synthetic NHWC image batch (the reference benchmarks on synthetic
    data too, example/pytorch/benchmark_byteps.py)."""
    krng, lrng = jax.random.split(rng)
    return {
        "images": jax.random.normal(krng, (batch, size, size, 3),
                                    jnp.float32),
        "labels": jax.random.randint(lrng, (batch,), 0, num_classes),
    }


def make_vision_trainer(comm, model, tx, init_batch, rng):
    """Shared DP training scaffolding for the vision models: returns
    ``(step, state)`` with ``step(state, batch) -> (state, loss)``.

    Handles both variable layouts — BatchNorm models (ResNet: mutable
    ``batch_stats`` threaded through ``make_dp_train_step_with_state``)
    and stateless ones (VGG: plain ``make_dp_train_step``) — and threads
    a dropout RNG (VGG trains with real dropout; the key is folded per
    call site, fixed across steps, which is the right trade for
    synthetic throughput benchmarks).  Used by bench.py's resnet section
    and example/jax/benchmark_resnet.py, so the two cannot drift.
    """
    from ..parallel import (make_dp_train_step,
                            make_dp_train_step_with_state, replicate)

    variables = model.init(rng, init_batch["images"][:2], train=False)
    has_bn = "batch_stats" in variables
    drop_rng = jax.random.fold_in(rng, 1)

    if has_bn:
        def loss_fn(p, state, b):
            logits, mut = model.apply(
                {"params": p, "batch_stats": state}, b["images"],
                train=True, mutable=["batch_stats"],
                rngs={"dropout": drop_rng})
            return (softmax_cross_entropy(logits, b["labels"]),
                    mut["batch_stats"])

        inner = make_dp_train_step_with_state(comm, loss_fn, tx)
        state = (replicate(comm, variables["params"]),
                 replicate(comm, variables["batch_stats"]),
                 replicate(comm, tx.init(variables["params"])))
    else:
        def loss_fn(p, b):
            logits = model.apply({"params": p}, b["images"], train=True,
                                 rngs={"dropout": drop_rng})
            return softmax_cross_entropy(logits, b["labels"])

        inner = make_dp_train_step(comm, loss_fn, tx)
        state = (replicate(comm, variables["params"]),
                 replicate(comm, tx.init(variables["params"])))

    def step(state, batch):
        *new_state, loss = inner(*state, batch)
        return tuple(new_state), loss

    return step, state
