"""Causal transformer LM with pluggable attention — the long-context
flagship.

The reference has no sequence dimension anywhere (SURVEY.md §5
"Long-context: absent"); this model exists to exercise the framework's
sequence-parallel attention (parallel/sequence.py) end to end: the
attention callable is injected, so the same parameters run with exact
full attention on one device or ring/Ulysses attention over an sp mesh
axis — outputs match to float tolerance (tests/test_long_context.py).

TPU-first: bf16 compute / f32 params, MXU-aligned dims, static shapes,
optional per-layer remat.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

AttnFn = Callable  # (q, k, v, *, causal, sm_scale) -> out


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 32768
    hidden_size: int = 512
    num_layers: int = 8
    num_heads: int = 8
    intermediate_size: int = 2048
    max_position: int = 32768        # long-context by default
    dtype: Any = jnp.bfloat16
    remat: bool = False


def gpt_small() -> GPTConfig:
    return GPTConfig()


def gpt_tiny() -> GPTConfig:
    """CPU-mesh tests / multichip dry-runs."""
    return GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                     num_heads=4, intermediate_size=128, max_position=512)


class CausalSelfAttention(nn.Module):
    cfg: GPTConfig
    attn_fn: Optional[AttnFn] = None

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_heads
        qkv = nn.DenseGeneral((3, cfg.num_heads, head_dim), dtype=cfg.dtype,
                              name="qkv")(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = self.attn_fn
        if attn is None:
            # lazy: parallel/__init__ imports models.gpt (long_context),
            # so a top-level import back into parallel would be circular
            from ..parallel.sequence import full_attention as attn
        ctx = attn(q, k, v, causal=True,
                   sm_scale=1.0 / math.sqrt(head_dim))
        return nn.DenseGeneral(cfg.hidden_size, axis=(-2, -1),
                               dtype=cfg.dtype, name="out")(ctx)


class Block(nn.Module):
    cfg: GPTConfig
    attn_fn: Optional[AttnFn] = None

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln1")(x)
        x = x + CausalSelfAttention(cfg, self.attn_fn, name="attn")(h)
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln2")(x)
        h = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype, name="mlp_in")(h)
        h = jax.nn.gelu(h)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlp_out")(h)
        return x + h


class GPT(nn.Module):
    """Decoder-only LM.  ``positions`` must be passed when the sequence
    axis is sharded (each shard holds positions [off, off + T/sp))."""

    cfg: GPTConfig
    attn_fn: Optional[AttnFn] = None

    @nn.compact
    def __call__(self, input_ids, positions=None):
        cfg = self.cfg
        b, t = input_ids.shape
        if positions is None:
            positions = jnp.arange(t)[None]
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     name="wte")(input_ids)
        x = x + nn.Embed(cfg.max_position, cfg.hidden_size, dtype=cfg.dtype,
                         name="wpe")(positions)
        block = Block
        if cfg.remat:
            block = nn.remat(Block)
        for i in range(cfg.num_layers):
            x = block(cfg, self.attn_fn, name=f"h{i}")(x)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype, name="lm_head")(x)
        return logits.astype(jnp.float32)


def token_nll(logits, labels, ignore: int = -1):
    """(sum of per-token NLL over valid positions, valid-token count).
    Shared by local (:func:`lm_loss`) and mesh-global (psum'd,
    parallel/long_context.py) normalizations."""
    valid = labels != ignore
    safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits)
    ll = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    w = valid.astype(jnp.float32)
    return -(ll * w).sum(), w.sum()


def lm_loss(logits, labels, ignore: int = -1):
    """Next-token cross-entropy; ``labels == ignore`` positions skipped.
    Callers shift: labels[t] is the target for logits[t]."""
    s, c = token_nll(logits, labels, ignore)
    return s / jnp.maximum(c, 1.0)
