"""Causal transformer LM with pluggable attention — the long-context
flagship.

The reference has no sequence dimension anywhere (SURVEY.md §5
"Long-context: absent"); this model exists to exercise the framework's
sequence-parallel attention (parallel/sequence.py) end to end: the
attention callable is injected, so the same parameters run with exact
full attention on one device or ring/Ulysses attention over an sp mesh
axis — outputs match to float tolerance (tests/test_long_context.py).

TPU-first: bf16 compute / f32 params, MXU-aligned dims, static shapes,
optional per-layer remat.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

AttnFn = Callable  # (q, k, v, *, causal, sm_scale) -> out


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 32768
    hidden_size: int = 512
    num_layers: int = 8
    num_heads: int = 8
    intermediate_size: int = 2048
    max_position: int = 32768        # long-context by default
    dtype: Any = jnp.bfloat16
    remat: bool = False
    # Mixture-of-experts (switch) MLPs: 0 = dense everywhere; >0 turns
    # every ``moe_every``-th block's MLP into a switch layer with that
    # many experts (parallel/expert.py moe_mlp; ep-shardable)
    moe_experts: int = 0
    moe_every: int = 2
    moe_capacity: float = 1.25

    def __post_init__(self):
        if self.moe_experts > 0 and self.moe_every < 1:
            raise ValueError(
                "moe_every must be >= 1 when moe_experts > 0 (a value "
                "of 0 would silently produce a fully dense model)")


def gpt_small() -> GPTConfig:
    return GPTConfig()


def gpt_tiny() -> GPTConfig:
    """CPU-mesh tests / multichip dry-runs."""
    return GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                     num_heads=4, intermediate_size=128, max_position=512)


class CausalSelfAttention(nn.Module):
    cfg: GPTConfig
    attn_fn: Optional[AttnFn] = None

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_heads
        qkv = nn.DenseGeneral((3, cfg.num_heads, head_dim), dtype=cfg.dtype,
                              name="qkv")(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = self.attn_fn
        if attn is None:
            # lazy: parallel/__init__ imports models.gpt (long_context),
            # so a top-level import back into parallel would be circular
            from ..parallel.sequence import full_attention as attn
        ctx = attn(q, k, v, causal=True,
                   sm_scale=1.0 / math.sqrt(head_dim))
        return nn.DenseGeneral(cfg.hidden_size, axis=(-2, -1),
                               dtype=cfg.dtype, name="out")(ctx)


class MoEMLP(nn.Module):
    """Switch-MoE MLP block: parameters are the FULL expert stacks at
    init; under an ep mesh each device's slice flows through apply (flax
    only checks shapes at init, the same trick pipeline.py uses for
    stage-local layer slices).  The aux load-balance loss is sown into
    the ``moe_aux`` collection — train steps apply with
    ``mutable=["moe_aux"]`` and fold the sown values into the loss."""

    cfg: GPTConfig
    ep_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        from jax import lax as _lax
        from ..parallel.expert import moe_mlp
        cfg = self.cfg
        h, f, e = cfg.hidden_size, cfg.intermediate_size, cfg.moe_experts
        # declared expert-stack size: the LOCAL shard when running under
        # an ep axis (flax validates self.param shapes at apply; sharded
        # leaves carry e/ep experts), the full stack otherwise (init and
        # single-device reference both use ep_axis=None)
        e_decl = e if self.ep_axis is None \
            else e // _lax.axis_size(self.ep_axis)
        params = {
            "router": self.param("router", nn.initializers.lecun_normal(),
                                 (h, e), jnp.float32),
            "w1": self.param("w1", nn.initializers.lecun_normal(),
                             (e_decl, h, f), jnp.float32),
            "b1": self.param("b1", nn.initializers.zeros, (e_decl, f),
                             jnp.float32),
            "w2": self.param("w2", nn.initializers.lecun_normal(),
                             (e_decl, f, h), jnp.float32),
            "b2": self.param("b2", nn.initializers.zeros, (e_decl, h),
                             jnp.float32),
        }
        # compute in cfg.dtype like the dense MLP path (params stay f32;
        # moe_mlp casts expert inputs to the weight dtype, so casting the
        # stacks here puts both big einsums on the bf16 MXU path)
        params = {k: (v if k == "router" else v.astype(cfg.dtype))
                  for k, v in params.items()}
        b, t, _ = x.shape
        out, aux = moe_mlp(x.reshape(b * t, h), params, e,
                           cfg.moe_capacity, axis_name=self.ep_axis)
        self.sow("moe_aux", "aux", aux)
        return out.reshape(b, t, h)


class Block(nn.Module):
    cfg: GPTConfig
    attn_fn: Optional[AttnFn] = None
    moe: bool = False
    ep_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln1")(x)
        x = x + CausalSelfAttention(cfg, self.attn_fn, name="attn")(h)
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln2")(x)
        if self.moe:
            return x + MoEMLP(cfg, self.ep_axis, name="moe")(h)
        h = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype, name="mlp_in")(h)
        h = jax.nn.gelu(h)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlp_out")(h)
        return x + h


class GPT(nn.Module):
    """Decoder-only LM.  ``positions`` must be passed when the sequence
    axis is sharded (each shard holds positions [off, off + T/sp))."""

    cfg: GPTConfig
    attn_fn: Optional[AttnFn] = None

    ep_axis: Optional[str] = None

    @nn.compact
    def __call__(self, input_ids, positions=None):
        cfg = self.cfg
        b, t = input_ids.shape
        if positions is None:
            positions = jnp.arange(t)[None]
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     name="wte")(input_ids)
        x = x + nn.Embed(cfg.max_position, cfg.hidden_size, dtype=cfg.dtype,
                         name="wpe")(positions)
        block = Block
        if cfg.remat:
            block = nn.remat(Block)
        for i in range(cfg.num_layers):
            moe = (cfg.moe_experts > 0
                   and i % cfg.moe_every == cfg.moe_every - 1)
            x = block(cfg, self.attn_fn, moe=moe, ep_axis=self.ep_axis,
                      name=f"h{i}")(x)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype, name="lm_head")(x)
        return logits.astype(jnp.float32)


def token_nll(logits, labels, ignore: int = -1):
    """(sum of per-token NLL over valid positions, valid-token count).
    Shared by local (:func:`lm_loss`) and mesh-global (psum'd,
    parallel/long_context.py) normalizations."""
    valid = labels != ignore
    safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits)
    ll = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    w = valid.astype(jnp.float32)
    return -(ll * w).sum(), w.sum()


def lm_loss(logits, labels, ignore: int = -1):
    """Next-token cross-entropy; ``labels == ignore`` positions skipped.
    Callers shift: labels[t] is the target for logits[t]."""
    s, c = token_nll(logits, labels, ignore)
    return s / jnp.maximum(c, 1.0)
