"""BERT encoder (flax) — the flagship benchmark model.

The reference's headline number is BERT-large scaling efficiency with
GluonNLP on 256 GPUs (reference README.md:35-41; BASELINE.md).  This is a
TPU-first reimplementation of that workload's model: bf16 compute / f32
params, MXU-aligned dims (1024/4096 hidden, 64-dim heads), optional
rematerialization of encoder layers to trade FLOPs for HBM, and static
shapes throughout so XLA tiles everything onto the systolic array.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30528          # 30522 rounded up to a multiple of 64
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int = 4096
    max_position: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.0        # benchmarks run dropout-free
    dtype: Any = jnp.bfloat16        # compute dtype; params stay f32
    remat: bool = False              # jax.checkpoint each layer


def bert_large() -> "BertConfig":
    return BertConfig()


def bert_base() -> "BertConfig":
    return BertConfig(hidden_size=768, num_layers=12, num_heads=12,
                      intermediate_size=3072)


def bert_tiny() -> "BertConfig":
    """For CPU-mesh tests and multichip dry-runs."""
    return BertConfig(vocab_size=512, hidden_size=128, num_layers=2,
                      num_heads=2, intermediate_size=256, max_position=128)


class SelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask):
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (cfg.num_heads, head_dim), dtype=cfg.dtype, name=name)
        q = dense("query")(x)
        k = dense("key")(x)
        v = dense("value")(x)
        scale = jnp.asarray(head_dim, cfg.dtype) ** -0.5
        # [B, H, T, T] logits on the MXU; additive mask
        logits = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
        logits = logits + mask[:, None, None, :]
        probs = jax.nn.softmax(logits.astype(jnp.float32)).astype(cfg.dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        return nn.DenseGeneral(cfg.hidden_size, axis=(-2, -1),
                               dtype=cfg.dtype, name="out")(ctx)


class EncoderLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask):
        cfg = self.cfg
        a = SelfAttention(cfg, name="attention")(x, mask)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_att")(x + a)
        h = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype, name="mlp_in")(x)
        h = jax.nn.gelu(h)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlp_out")(h)
        return nn.LayerNorm(dtype=cfg.dtype, name="ln_mlp")(x + h)


class BertEncoder(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        cfg = self.cfg
        b, t = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((b, t), jnp.int32)
        if token_type_ids is None:
            token_type_ids = jnp.zeros((b, t), jnp.int32)
        emb = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                       dtype=cfg.dtype, name="word_embeddings")(input_ids)
        pos = nn.Embed(cfg.max_position, cfg.hidden_size, dtype=cfg.dtype,
                       name="position_embeddings")(jnp.arange(t)[None])
        typ = nn.Embed(cfg.type_vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                       name="token_type_embeddings")(token_type_ids)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_emb")(emb + pos + typ)
        # additive attention mask: 0 keep, -1e9 drop
        mask = (1.0 - attention_mask.astype(jnp.float32)) * -1e9
        mask = mask.astype(cfg.dtype)
        layer_cls = EncoderLayer
        if cfg.remat:
            layer_cls = nn.remat(EncoderLayer)
        for i in range(cfg.num_layers):
            x = layer_cls(cfg, name=f"layer_{i}")(x, mask)
        return x


class BertForMLM(nn.Module):
    """Masked-LM head — the pretraining objective of the headline bench.

    With ``masked_positions`` ([B, P] indices) the head runs only on the
    masked tokens: the vocab projection and softmax shrink from [B, T, V]
    to [B, P, V] — at 15% masking that is ~6x less head FLOPs and HBM
    traffic (the [B, T, 30k] f32 logits tensor never exists).  Without it,
    the full-sequence logits are returned (HF-compatible shape)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None,
                 masked_positions=None):
        cfg = self.cfg
        x = BertEncoder(cfg, name="encoder")(input_ids, attention_mask)
        if masked_positions is not None:
            x = jnp.take_along_axis(
                x, masked_positions[..., None].astype(jnp.int32), axis=1)
        x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlm_transform")(x)
        x = jax.nn.gelu(x)
        x = nn.LayerNorm(dtype=cfg.dtype, name="mlm_ln")(x)
        logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype, name="mlm_out")(x)
        return logits.astype(jnp.float32)


def mlm_loss(logits, labels, weights=None):
    """Cross-entropy over masked positions (labels < 0 are unmasked)."""
    valid = (labels >= 0)
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits)
    ll = jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    w = valid.astype(jnp.float32)
    if weights is not None:
        w = w * weights
    return -(ll * w).sum() / jnp.maximum(w.sum(), 1.0)


def synthetic_batch(rng: "jax.Array", cfg: BertConfig, batch: int,
                    seq_len: int, mask_frac: float = 0.15):
    """Deterministic fake pretraining batch (reference benchmarks use
    synthetic data too, example/pytorch/benchmark_byteps.py).

    Masks exactly ``P = max(1, int(seq_len * mask_frac))`` positions per
    example so the gathered-head path has static shapes: the returned
    ``masked_positions``/``masked_labels`` ([B, P]) feed
    ``BertForMLM(..., masked_positions=...)``; the full-length ``labels``
    (-1 on unmasked) remain for the ungathered path."""
    k1, k2 = jax.random.split(rng, 2)
    ids = jax.random.randint(k1, (batch, seq_len), 0, cfg.vocab_size)
    n_pred = max(1, int(seq_len * mask_frac))
    perm = jax.vmap(lambda k: jax.random.permutation(k, seq_len))(
        jax.random.split(k2, batch))
    positions = jnp.sort(perm[:, :n_pred].astype(jnp.int32), axis=1)
    is_masked = jnp.zeros((batch, seq_len), bool)
    is_masked = jax.vmap(lambda m, p: m.at[p].set(True))(is_masked,
                                                         positions)
    labels = jnp.where(is_masked, ids, -1)
    input_ids = jnp.where(is_masked, jnp.zeros_like(ids), ids)
    masked_labels = jnp.take_along_axis(ids, positions, axis=1)
    return {"input_ids": input_ids, "labels": labels,
            "attention_mask": jnp.ones((batch, seq_len), jnp.int32),
            "masked_positions": positions, "masked_labels": masked_labels}
