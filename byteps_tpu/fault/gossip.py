"""SWIM-style gossip membership plane (ISSUE 17, ROADMAP 1(b)).

Every rank keeps a local **membership table**

    rank -> {incarnation, state in (alive | suspect | dead | parked),
             heartbeat counter}

and disseminates it by anti-entropy: each gossip period the agent picks
``gossip_fanout`` random live peers and exchanges full digests (the
tables are tiny — a few dozen bytes per rank — so full-state exchange
beats delta bookkeeping at the 64-rank scale this plane targets).  The
wire is pluggable: production rides the membership bus ``gossip`` verb
(fault/membership.py), so envelopes/CRC/frame clamps are reused rather
than reinvented; tests use :class:`InMemoryWire` to run 64 ranks in one
process.

State machine per remote rank (local clock, monotonic):

    alive --no hb progress for gossip_suspect_s--> suspect
    suspect --gossip_dead_s more without progress--> dead
    suspect/dead --higher incarnation from the rank itself--> alive

**Refutation**: a rank that sees ITSELF suspected/declared dead in a
merged digest bumps its own incarnation past the accusation and
re-asserts ``alive`` — a slow-but-live rank un-suspects itself instead
of being shot (``gossip.refutations`` counter + flight event).  Merge
precedence: higher incarnation wins outright; at equal incarnation the
more-damning state wins (dead > parked > suspect > alive), and at equal
state the higher heartbeat counter wins.

World *agreement* stays epoch-based (fault/membership.py) but becomes
quorum-gated when ``BYTEPS_GOSSIP_ON`` is set: :func:`quorum_ok` is the
one shared predicate — a shrink proposal commits only when a STRICT
majority of the last agreed world is reachable.  The minority side of a
partition parks (engine suspended, ``membership.partition_minority``)
and rejoins through the ordinary rejoin path when the partition heals;
two disjoint minorities can never both hold a strict majority of the
same last world, so two epochs can never advance concurrently.

Piggybacked **payloads** (serve_dir, metrics/history windows) ride the
same digests as ``(version, value)`` pairs merged by highest version,
so ``cluster_metrics()`` / ``bps_top`` / ``bps_doctor`` can be answered
from any rank's local table with no bus round-trip fan-in.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ..common import flight_recorder as _flight
from ..common import health as _health
from ..common.config import get_config
from ..common.lock_witness import named_lock
from ..common.logging import get_logger
from ..common.telemetry import counters
from . import injector as _fault

log = get_logger()

__all__ = [
    "ALIVE", "SUSPECT", "DEAD", "PARKED",
    "GossipTable", "GossipAgent", "InMemoryWire", "quorum_ok",
]

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"
PARKED = "parked"

# Merge precedence at EQUAL incarnation: the more-damning claim wins.
# A rank escapes a damning state only by raising its incarnation
# (refutation), never by re-gossiping a stale happy claim.
_PRECEDENCE = {ALIVE: 0, SUSPECT: 1, PARKED: 2, DEAD: 3}
_STATES = tuple(_PRECEDENCE)


def quorum_ok(proposed_world: Iterable[int],
              last_world: Iterable[int]) -> bool:
    """Strict-majority gate for epoch agreement: the proposed world must
    hold MORE than half of the last agreed world.  Strictness is the
    split-brain proof for even splits: 2-of-4 is not a quorum, so
    neither half of an even partition can commit."""
    return 2 * len(tuple(proposed_world)) > len(tuple(last_world))


class GossipTable:
    """The per-rank membership table plus piggybacked payloads.

    Thread-safe; every mutation happens under one lock.  Time is always
    passed in (``now``) so tests drive the state machine with a fake
    clock — nothing in here reads the wall clock.
    """

    def __init__(self, rank: int, world: Iterable[int], *,
                 suspect_s: Optional[float] = None,
                 dead_s: Optional[float] = None,
                 now: Optional[float] = None):
        cfg = get_config()
        self.rank = int(rank)
        self.suspect_s = float(suspect_s if suspect_s is not None
                               else cfg.gossip_suspect_s)
        self.dead_s = float(dead_s if dead_s is not None
                            else cfg.gossip_dead_s)
        now = time.monotonic() if now is None else now
        self._lock = named_lock("gossip.table")
        # rank -> {"inc": int, "state": str, "hb": int}
        self._entries: Dict[int, Dict[str, Any]] = {}
        # rank -> local monotonic time of last observed hb progress
        self._progress: Dict[int, float] = {}
        # rank -> monotonic time the rank entered suspect (refutation
        # window anchor); cleared on any progress
        self._suspect_at: Dict[int, float] = {}
        # (rank, kind) -> (version, value): serve_dir / metrics / history
        self._payloads: Dict[Tuple[int, str], Tuple[int, Any]] = {}
        for r in world:
            self._entries[int(r)] = {"inc": 0, "state": ALIVE, "hb": 0}
            self._progress[int(r)] = now

    # ------------------------------------------------------------- read

    def snapshot(self) -> Dict[int, Dict[str, Any]]:
        with self._lock:
            return {r: dict(e) for r, e in self._entries.items()}

    def state_of(self, rank: int) -> Optional[str]:
        with self._lock:
            e = self._entries.get(rank)
            return None if e is None else e["state"]

    def alive_ranks(self) -> List[int]:
        """Ranks currently believed reachable-and-well (alive only —
        a suspect rank is still *reachable* for quorum purposes, see
        :meth:`reachable_ranks`)."""
        with self._lock:
            return sorted(r for r, e in self._entries.items()
                          if e["state"] == ALIVE)

    def reachable_ranks(self) -> List[int]:
        """Ranks that count toward quorum: alive or merely suspect.
        Suspicion is a *refutable accusation*, not evidence of
        unreachability — counting suspects keeps a gray blip from
        parking a healthy majority."""
        with self._lock:
            return sorted(r for r, e in self._entries.items()
                          if e["state"] in (ALIVE, SUSPECT))

    def payload(self, rank: int, kind: str) -> Optional[Any]:
        with self._lock:
            ent = self._payloads.get((rank, kind))
            return None if ent is None else ent[1]

    def payloads_of_kind(self, kind: str) -> Dict[int, Any]:
        with self._lock:
            return {r: v for (r, k), (_, v) in self._payloads.items()
                    if k == kind}

    # ------------------------------------------------------------ write

    def beat(self, now: Optional[float] = None) -> None:
        """Advance the local rank's heartbeat counter (self-progress)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            e = self._entries.setdefault(
                self.rank, {"inc": 0, "state": ALIVE, "hb": 0})
            e["hb"] += 1
            if e["state"] in (SUSPECT, DEAD):
                # refute an accusation that arrived while we slept
                e["inc"] += 1
                e["state"] = ALIVE
            self._progress[self.rank] = now
            self._suspect_at.pop(self.rank, None)

    def set_payload(self, kind: str, value: Any) -> None:
        """Attach/refresh this rank's payload of ``kind``; version bumps
        monotonically so remote merges converge on the newest value."""
        with self._lock:
            old = self._payloads.get((self.rank, kind))
            ver = (old[0] + 1) if old else 1
            self._payloads[(self.rank, kind)] = (ver, value)

    def add_rank(self, rank: int, now: Optional[float] = None) -> None:
        """A join observed out-of-band (rejoin admitted by the bus)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            e = self._entries.get(rank)
            if e is None or e["state"] in (DEAD, PARKED):
                inc = (e["inc"] + 1) if e else 0
                self._entries[rank] = {"inc": inc, "state": ALIVE, "hb": 0}
                self._progress[rank] = now
                self._suspect_at.pop(rank, None)

    def mark(self, rank: int, state: str,
             now: Optional[float] = None) -> None:
        """Out-of-band state assertion (e.g. the local rank parking, or
        a kill observed by the bus).  Bumps the incarnation so the claim
        beats any alive claim already circulating."""
        if state not in _PRECEDENCE:
            raise ValueError(f"unknown gossip state {state!r}")
        now = time.monotonic() if now is None else now
        with self._lock:
            e = self._entries.setdefault(
                rank, {"inc": 0, "state": ALIVE, "hb": 0})
            if e["state"] != state:
                e["inc"] += 1
                e["state"] = state
            if state == ALIVE:
                self._progress[rank] = now
                self._suspect_at.pop(rank, None)

    # ------------------------------------------------------ anti-entropy

    def digest(self) -> Dict[str, Any]:
        """The full wire image: entries + payloads.  Small by design —
        O(world) dict-of-smallints plus the bounded payload windows."""
        with self._lock:
            return {
                "from": self.rank,
                "entries": {r: dict(e) for r, e in self._entries.items()},
                "payloads": {f"{r}/{k}": list(v)
                             for (r, k), v in self._payloads.items()},
            }

    def merge(self, digest: Dict[str, Any],
              now: Optional[float] = None) -> List[int]:
        """Merge a remote digest; returns the ranks whose entry changed.

        Refutation happens here: a remote claim that THIS rank is
        suspect/dead (at our incarnation or higher) is answered by
        bumping our incarnation past it and re-asserting alive — the
        next exchanges carry the refutation outward.
        """
        now = time.monotonic() if now is None else now
        changed: List[int] = []
        entries = digest.get("entries") or {}
        with self._lock:
            for r, remote in entries.items():
                r = int(r)
                state = remote.get("state")
                if state not in _PRECEDENCE:
                    continue
                inc = int(remote.get("inc", 0))
                hb = int(remote.get("hb", 0))
                if r == self.rank:
                    if (state in (SUSPECT, DEAD)
                            and inc >= self._entries[r]["inc"]
                            and self._entries[r]["state"] != PARKED):
                        # somebody is accusing us and their claim would
                        # win a merge elsewhere: out-bid it
                        me = self._entries[r]
                        me["inc"] = inc + 1
                        me["state"] = ALIVE
                        self._progress[r] = now
                        self._suspect_at.pop(r, None)
                        counters.inc("gossip.refutations")
                        _flight.record("gossip.refuted", rank=self.rank,
                                       accused_state=state,
                                       new_incarnation=me["inc"])
                        changed.append(r)
                    continue
                mine = self._entries.get(r)
                if mine is None:
                    self._entries[r] = {"inc": inc, "state": state,
                                        "hb": hb}
                    self._progress[r] = now
                    if state == SUSPECT:
                        self._suspect_at[r] = now
                    changed.append(r)
                    continue
                take = False
                if inc > mine["inc"]:
                    take = True
                elif inc == mine["inc"]:
                    if _PRECEDENCE[state] > _PRECEDENCE[mine["state"]]:
                        take = True
                    elif (state == mine["state"] and hb > mine["hb"]):
                        take = True
                if not take:
                    continue
                state_changed = (state != mine["state"]
                                 or inc != mine["inc"])
                hb_progress = hb > mine["hb"]
                mine.update(inc=inc, state=state, hb=hb)
                if hb_progress or state == ALIVE:
                    self._progress[r] = now
                    self._suspect_at.pop(r, None)
                if state == SUSPECT and r not in self._suspect_at:
                    self._suspect_at[r] = now
                if state_changed:
                    changed.append(r)
            # payloads: highest version wins
            for key, pair in (digest.get("payloads") or {}).items():
                try:
                    r_s, kind = str(key).split("/", 1)
                    r, ver, val = int(r_s), int(pair[0]), pair[1]
                except (ValueError, IndexError, TypeError):
                    continue
                cur = self._payloads.get((r, kind))
                if cur is None or ver > cur[0]:
                    self._payloads[(r, kind)] = (ver, val)
        return changed

    def sweep(self, now: Optional[float] = None) -> Dict[int, str]:
        """Apply the suspicion/death timeouts; returns {rank: new state}
        for every transition made this sweep."""
        now = time.monotonic() if now is None else now
        out: Dict[int, str] = {}
        with self._lock:
            for r, e in self._entries.items():
                if r == self.rank or e["state"] in (DEAD, PARKED):
                    continue
                seen = self._progress.get(r, now)
                if e["state"] == ALIVE:
                    if now - seen >= self.suspect_s:
                        e["inc"] += 0  # accusation rides OUR next digest
                        e["state"] = SUSPECT
                        self._suspect_at[r] = now
                        out[r] = SUSPECT
                elif e["state"] == SUSPECT:
                    since = self._suspect_at.get(r, seen)
                    if now - since >= self.dead_s:
                        e["state"] = DEAD
                        out[r] = DEAD
        for r, st in out.items():
            if st == SUSPECT:
                counters.inc("gossip.suspect")
            else:
                counters.inc("gossip.dead")
            _flight.record("gossip.state", rank=r, state=st,
                           by=self.rank)
        return out


class InMemoryWire:
    """Test wire: N tables in one process, exchange = direct merge.
    ``cut(a_side, b_side)`` models a partition (symmetric blackhole)."""

    def __init__(self):
        self.tables: Dict[int, GossipTable] = {}
        self._cut: Optional[Tuple[frozenset, frozenset]] = None

    def register(self, table: GossipTable) -> None:
        self.tables[table.rank] = table

    def cut(self, side_a: Iterable[int], side_b: Iterable[int]) -> None:
        self._cut = (frozenset(side_a), frozenset(side_b))

    def heal(self) -> None:
        self._cut = None

    def _severed(self, a: int, b: int) -> bool:
        if self._cut is None:
            return False
        sa, sb = self._cut
        return (a in sa and b in sb) or (a in sb and b in sa)

    def exchange(self, src: int, dst: int, digest: Dict[str, Any],
                 now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Push ``digest`` from src to dst; returns dst's digest back
        (the anti-entropy round trip), or None when unreachable."""
        if self._severed(src, dst):
            return None
        peer = self.tables.get(dst)
        if peer is None:
            return None
        peer.merge(digest, now=now)
        return peer.digest()


class GossipAgent:
    """Drives one rank's table: beat, pick k peers, exchange, sweep.

    ``wire(peer, digest) -> reply digest | None`` abstracts the
    transport; production passes a closure over the membership bus
    ``gossip`` verb, tests pass :class:`InMemoryWire.exchange`.
    ``step(now)`` is the whole period, callable directly (deterministic
    tests); ``start()`` runs it on a daemon thread every
    ``gossip_interval_s``.
    """

    def __init__(self, table: GossipTable,
                 wire: Callable[[int, Dict[str, Any]],
                                Optional[Dict[str, Any]]],
                 *, fanout: Optional[int] = None,
                 interval_s: Optional[float] = None,
                 seed: Optional[int] = None,
                 world_fn: Optional[Callable[[], Iterable[int]]] = None,
                 payload_fn: Optional[Callable[[], Dict[str, Any]]]
                 = None):
        cfg = get_config()
        self.table = table
        self.wire = wire
        self.fanout = int(fanout if fanout is not None
                          else cfg.gossip_fanout)
        self.interval_s = float(interval_s if interval_s is not None
                                else cfg.gossip_interval_s)
        # deterministic peer choice: seeded per rank, not wall-clock
        self._rng = random.Random(f"gossip/{table.rank}/"
                                  f"{seed if seed is not None else 0}")
        # the quorum denominator: the LAST AGREED world (membership
        # epoch view), not the gossip table — agreement gates against
        # what was committed, not against rumors
        self._world_fn = world_fn
        # {kind: value} refresher called once per period so serve_dir /
        # metrics / history windows ride the digests as payloads
        self._payload_fn = payload_fn
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # pin ONE bound-method object: accessing ``self.quorum_view``
        # builds a fresh bound method each time, and the health module
        # unregisters by identity
        self._provider_fn: Optional[Callable[[], Dict[str, int]]] = None

    # ------------------------------------------------------------ quorum

    def quorum_view(self) -> Dict[str, int]:
        """{"reachable": R, "world": W} for the health quorum_loss rule
        and the shrink gate: R = alive+suspect members of the last
        agreed world (self included), W = that world's size."""
        world = set(int(r) for r in (self._world_fn() if self._world_fn
                                     else self.table.snapshot()))
        reach = set(self.table.reachable_ranks()) | {self.table.rank}
        return {"reachable": len(reach & world) if world else len(reach),
                "world": len(world)}

    def register_health_provider(self) -> None:
        if self._provider_fn is None:
            self._provider_fn = self.quorum_view
        _health.set_quorum_provider(self._provider_fn)

    # -------------------------------------------------------------- run

    def step(self, now: Optional[float] = None) -> Dict[int, str]:
        """One gossip period: beat, exchange with k random peers, sweep.
        Returns the sweep's state transitions."""
        now = time.monotonic() if now is None else now
        if _fault.ENABLED:
            _fault.fire("gossip")
        self.table.beat(now=now)
        if self._payload_fn is not None:
            try:
                for kind, value in (self._payload_fn() or {}).items():
                    if value is not None:
                        self.table.set_payload(kind, value)
            except Exception:  # noqa: BLE001 — observability payloads
                pass           # must never stall the membership plane
        peers = [r for r in self.table.reachable_ranks()
                 if r != self.table.rank]
        self._rng.shuffle(peers)
        for peer in peers[:self.fanout]:
            if _fault.ENABLED and (_fault.should_drop("gossip")
                                   or _fault.edge_cut(peer)):
                continue
            try:
                reply = self.wire(peer, self.table.digest())
            except Exception:
                counters.inc("gossip.exchange_failed")
                continue
            if reply:
                self.table.merge(reply, now=now)
        return self.table.sweep(now=now)

    def start(self) -> "GossipAgent":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"gossip-r{self.table.rank}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)
        if self._provider_fn is not None:
            _health.clear_quorum_provider(self._provider_fn)
            self._provider_fn = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception:
                # the gossip plane must never take the process down
                log.exception("gossip step failed")
                counters.inc("gossip.step_error")
