"""Fault tolerance: deterministic chaos injection + supervised recovery.

The reference delegates liveness to ps-lite scheduler heartbeats and
treats every failure as "restart the role" (SURVEY.md §5).  Here the
failure path is a *tested code path*: :mod:`injector` plants seeded,
deterministic faults (kill/delay/bitflip/straggler/drop) at named sites
across the stack, and :mod:`recovery` turns a detected failure into an
automated drain → suspend → resume(survivors) → checkpoint-restore
sequence instead of a bare ``os._exit``.  :mod:`membership` goes one
step further: a monotonic membership epoch stamps every dispatch and
server push, survivors shrink to the agreed survivor world in place
(no process exit), and a restarted rank rejoins at a step boundary
with epoch/keys/parameters handed over by a survivor.
"""

from .injector import FaultInjector, arm, disarm  # noqa: F401
from .membership import (Demoted, ElasticMembership,  # noqa: F401
                         Evicted, MembershipTimeout, MembershipView,
                         WorldChanged)
from .recovery import RecoveryCoordinator, RecoveryResult  # noqa: F401
