"""Elastic membership: shrink-to-survivors and in-place rejoin.

The reference lets a recovered worker rejoin a running job in place
(``is_recovery``, reference global.cc:291-294, server.cc:486-489) but
offers no survivor-side story: a dead peer means the whole job restarts.
PR 2 built the ingredients — suspend/resume (core/api.py), the
``RecoveryCoordinator`` drain→restore flow, chaos injection, launcher
supervision.  This module composes them into a real membership layer:

- **Membership epoch** — a process-wide monotonic counter
  (:func:`current_epoch`).  Every engine dispatch stamps its pending
  tensor with the epoch at enqueue (core/engine.py) and every
  ServerEngine/KVStore push may carry one; work stamped with a dead
  epoch is *dropped, not summed* — the same residue-vs-fresh-round
  discipline as ``ServerEngine.reset_key``, applied to the whole world.
- **Shrink-to-survivors** — on heartbeat loss every survivor's
  ``on_failure`` runs :meth:`ElasticMembership.shrink`: advance the
  epoch (stale guard up), drain + ``suspend()``, agree on the new world
  through an epoch-tagged rendezvous on the membership bus, then
  ``resume()`` at the smaller size — re-declared keys in original
  order, re-sharded ``ServerAssigner``, training continues from
  in-memory state with **no process exit**.
- **In-place rejoin** — a restarted rank calls
  :meth:`ElasticMembership.rejoin`; it parks on the bus until the
  survivors complete a step-boundary sync, then receives the agreed
  epoch, the declared-key order, and the parameters packed by a
  survivor (``utils.checkpoint.pack_state`` — the wire form of the
  broadcast-after-restore contract) and resumes as a full member.

The **membership bus** is a tiny TCP control-plane endpoint hosted by
the lowest-ranked live member (the *membership coordinator*).  It
serves three verbs: ``sync`` (per-step barrier + small payload
all-gather, the vehicle for both failure evidence and join admission),
``hello`` (the shrink rendezvous), and ``rejoin``.  Clients reach it
with :class:`common.retry.RetryPolicy` full-jitter backoff, so a bus
that moves to a new coordinator mid-shrink is a transient, not an
error.  Control-plane only: gradients ride the XLA collectives; the
bus carries membership state, step digests, and the (rare) rejoin
parameter transfer.

Double failure during a shrink: the rendezvous waits
``membership_rendezvous_timeout_s`` for every proposed survivor; a
member that never checks in (it died after the first detection) is
dropped from the agreed world and the shrink completes without it.  A
member that finds itself outside the agreed world raises
:class:`Evicted` — under ``bpslaunch-dist --elastic`` it exits
restartable and comes back through the rejoin path.

Single-host note: the bus address is fixed (``BYTEPS_MEMBERSHIP_PORT``,
default coordinator port + 2), so coordinator failover — the next
lowest rank re-binding the same address — works wherever the survivors
share that address (the CPU chaos tests, single-host multi-process
runs).  A multi-host deployment keeps the bus on a supervised host
(worker 0 under launcher ``--elastic`` restart) exactly as the DMLC
root already must be.
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Set, Tuple

from ..common import flight_recorder as _flight
from ..common.logging import get_logger
from ..common.retry import RetryPolicy
from ..common.telemetry import counters

__all__ = [
    "MembershipView", "ElasticMembership", "WorldChanged", "Evicted",
    "MembershipTimeout", "current_epoch", "advance_epoch", "set_epoch",
    "resolve_bus_addr", "bus_request",
]


# -- the process-wide membership epoch --------------------------------------
#
# One integer, monotonic, shared by every layer that stamps or checks
# work: engine pendings (core/engine.py), server pushes
# (server/engine.py, server/kv_store.py), and the bus protocol below.
# Epoch 0 is the static world every non-elastic run lives in forever.

_epoch = 0
_epoch_lock = threading.Lock()


def current_epoch() -> int:
    """The membership epoch this process currently lives in."""
    return _epoch


def advance_epoch() -> int:
    """Bump the epoch by one (stale guards trip immediately)."""
    global _epoch
    with _epoch_lock:
        _epoch += 1
        return _epoch


def set_epoch(epoch: int) -> int:
    """Raise the epoch to ``epoch`` (monotonic: never regresses)."""
    global _epoch
    with _epoch_lock:
        if epoch > _epoch:
            _epoch = epoch
        return _epoch


def _reset_epoch_for_tests() -> None:
    global _epoch
    with _epoch_lock:
        _epoch = 0


@dataclasses.dataclass(frozen=True)
class MembershipView:
    """One agreed (epoch, world) pair; world is a sorted rank tuple."""

    epoch: int
    world: Tuple[int, ...]

    @property
    def num_workers(self) -> int:
        return len(self.world)

    @property
    def coordinator(self) -> int:
        return min(self.world)


class WorldChanged(RuntimeError):
    """The world moved under a step_sync; retry the step at the new
    epoch (the local engine has already been re-initialized)."""

    def __init__(self, view: MembershipView):
        super().__init__(f"membership changed: epoch {view.epoch}, "
                         f"world {list(view.world)}")
        self.view = view


class Evicted(RuntimeError):
    """This rank is not in the agreed world (the survivors shrank past
    it).  Exit restartable and come back through rejoin()."""


class MembershipTimeout(TimeoutError):
    """A bus request did not complete inside its window."""


class _BusUnreachable(ConnectionError):
    """Transient: the coordinator is dead/moving; retried with backoff."""


class _BusFrameError(_BusUnreachable):
    """A RECEIVED bus frame failed a sanity or integrity check.  The
    connection is failed loudly (server side logs and closes; client
    side retries with a fresh connection under the bounded backoff
    policy — the corruption is plausibly transient) — never acted on."""


class _BusFrameTooLarge(ValueError):
    """Deterministic sender-side refusal: the frame WE are about to send
    exceeds ``BYTEPS_BUS_MAX_FRAME``.  Deliberately NOT a
    :class:`_BusUnreachable` (nor an ``OSError``): retrying cannot
    succeed until the operator raises the env var, and each retry would
    re-pickle and re-CRC a multi-gigabyte rejoin state for nothing."""


# -- wire helpers (length-prefixed pickle over a trusted local socket,
#    CRC32C-enveloped when BYTEPS_INTEGRITY is armed) -----------------------

def _send_obj(sock: socket.socket, obj: Any) -> None:
    from ..common import integrity as _integrity
    from ..common.config import get_config
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sealing = _integrity.enabled()
    max_frame = get_config().bus_max_frame
    wire_len = len(data) + (
        _integrity.envelope_overhead("membership-bus") if sealing else 0)
    if wire_len > max_frame:
        # fail at the SENDER, before shipping gigabytes the receiver's
        # clamp would reject anyway (and misattribute to corruption) —
        # and before CRC'ing and copying them into an envelope this
        # refusal would only discard
        raise _BusFrameTooLarge(
            f"refusing to send a {wire_len}-byte bus frame > "
            f"BYTEPS_BUS_MAX_FRAME={max_frame}; legitimately large rejoin "
            "states need BYTEPS_BUS_MAX_FRAME raised on every member")
    if sealing:
        # membership frames carry epochs, worlds, and rejoin parameter
        # blobs — a silently corrupt one could commit a wrong world or
        # hand a joiner bad weights, so they ride the same envelope as
        # every other host hop
        data = _integrity.seal_bytes(data, key="membership-bus")
    # 8-byte length prefix: a rejoin state payload is a whole model's
    # parameters and can exceed the 4 GiB a 32-bit prefix could frame
    sock.sendall(struct.pack("!Q", len(data)) + data)


def _recv_obj(sock: socket.socket) -> Any:
    from ..common import integrity as _integrity
    from ..common.config import get_config
    buf = b""
    while len(buf) < 8:
        chunk = sock.recv(8 - len(buf))
        if not chunk:
            raise _BusUnreachable("bus connection closed mid-frame")
        buf += chunk
    (n,) = struct.unpack("!Q", buf)
    max_frame = get_config().bus_max_frame
    if n > max_frame:
        # an 8-byte prefix is the first thing a corrupt stream mangles:
        # trusting it unconditionally would park this thread on a
        # multi-petabyte recv.  Clamp and fail the connection instead.
        raise _BusFrameError(
            f"bus frame length {n} exceeds BYTEPS_BUS_MAX_FRAME="
            f"{max_frame} — corrupt length prefix or misbehaving peer "
            "(senders clamp too, so a legitimately large rejoin state "
            "would have failed at its sender: raise BYTEPS_BUS_MAX_FRAME "
            "on every member); failing the connection")
    data = b""
    while len(data) < n:
        chunk = sock.recv(min(65536, n - len(data)))
        if not chunk:
            raise _BusUnreachable("bus connection closed mid-frame")
        data += chunk
    if _integrity.is_frame(data):
        try:
            data, _ = _integrity.open_bytes(data)
        except _integrity.IntegrityError as e:
            counters.inc("integrity.crc_reject")
            raise _BusFrameError(
                f"bus frame failed integrity verification: {e}") from None
    try:
        return pickle.loads(data)
    except Exception as e:
        # a flip in the envelope's 4 magic bytes defeats the is_frame
        # sniff and lands the raw envelope (or otherwise-corrupt bytes)
        # here — that is still wire corruption and must fail through the
        # retriable _BusFrameError path, not an unclassified
        # UnpicklingError that skips the caller's backoff/close handling
        counters.inc("integrity.crc_reject")
        raise _BusFrameError(f"bus frame failed to unpickle: {e}") from None


def resolve_bus_addr(bus: Optional[str] = None) -> Tuple[str, int]:
    """``host:port`` of the membership bus — explicit arg, or the same
    DMLC-root + BYTEPS_MEMBERSHIP_PORT resolution
    :class:`ElasticMembership` uses."""
    from ..common.config import get_config
    if bus is not None:
        host, port_s = bus.rsplit(":", 1)
        return host, int(port_s)
    cfg = get_config()
    host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    port = cfg.membership_port or (
        int(os.environ.get("DMLC_PS_ROOT_PORT", "9000")) + 2)
    return host, port


def bus_request(addr: Tuple[str, int], msg: dict,
                timeout: float = 10.0) -> dict:
    """One single-attempt request/reply round trip to the bus (no
    backoff — read-only observability callers like
    ``core/api.cluster_metrics`` / ``tools/bps_top.py`` decide their own
    retry cadence).  Raises :class:`_BusUnreachable` (a
    ``ConnectionError``) when nothing answers."""
    try:
        s = socket.create_connection(addr, timeout=min(timeout, 3.0))
    except OSError as e:
        raise _BusUnreachable(f"bus {addr}: {e}") from None
    try:
        s.settimeout(timeout)
        _send_obj(s, msg)
        return _recv_obj(s)
    except socket.timeout:
        raise MembershipTimeout(
            f"bus {msg.get('op')} timed out after {timeout:.1f}s") from None
    except _BusUnreachable:
        raise
    except OSError as e:
        raise _BusUnreachable(f"bus {addr}: {e}") from None
    finally:
        s.close()


class _BusServer:
    """The coordinator-side membership endpoint.

    State: the agreed ``(epoch, world)``, per-(epoch, step) sync
    payloads, per-epoch shrink hellos, and parked join requests.  Every
    verb parks its connection thread on one condition variable; any
    state transition (quorum complete, epoch advanced, join admitted)
    wakes everyone and each waiter re-evaluates its own predicate —
    the same pop-time re-evaluation discipline as the server engine's
    PriorityQueue.
    """

    def __init__(self, addr: Tuple[str, int], view: MembershipView,
                 rendezvous_timeout_s: float, sync_timeout_s: float):
        self.addr = addr
        self.epoch = view.epoch
        self.world: Set[int] = set(view.world)
        self._rdv_timeout = rendezvous_timeout_s
        self._sync_timeout = sync_timeout_s
        self._cv = threading.Condition()
        # (epoch, step) -> {rank: payload}
        self._sync: Dict[Tuple[int, int], Dict[int, Any]] = {}
        # (epoch, step) -> (state bytes, declared names, state's step)
        self._snapshots: Dict[Tuple[int, int], Tuple[bytes, List[str], int]] = {}
        # proposed epoch -> {rank: proposed world}
        self._hellos: Dict[int, Dict[int, frozenset]] = {}
        # rank -> None (parked) | admission info dict
        self._join_wait: Dict[int, Optional[dict]] = {}
        # rank -> (wall time, metrics snapshot): the cross-rank
        # observability cache — members attach a compact snapshot to
        # every sync (and may metrics_put explicitly); the metrics verb
        # answers from here in one round-trip (core/api.cluster_metrics)
        self._metrics: Dict[int, Tuple[float, Any]] = {}
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(addr)
        self._sock.listen(32)
        self._sock.settimeout(0.25)
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="bps-membership-bus")
        self._thread.start()

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        self._thread.join(timeout=2)
        self._sock.close()

    def view(self) -> MembershipView:
        with self._cv:
            return MembershipView(self.epoch, tuple(sorted(self.world)))

    # -- serving -----------------------------------------------------------

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True, name="bps-membership-conn")
            t.start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(self._sync_timeout + self._rdv_timeout + 30.0)
            msg = _recv_obj(conn)
            op = msg.get("op")
            if op == "sync":
                reply = self._do_sync(msg)
            elif op == "hello":
                reply = self._do_hello(msg)
            elif op == "rejoin":
                reply = self._do_rejoin(msg)
            elif op == "metrics_put":
                reply = self._do_metrics_put(msg)
            elif op == "metrics":
                reply = self._do_metrics()
            else:
                reply = {"ok": False, "error": f"unknown op {op!r}"}
            try:
                _send_obj(conn, reply)
            except _BusFrameTooLarge as e:
                # the reply (e.g. a rejoin state snapshot) exceeds the
                # coordinator's BYTEPS_BUS_MAX_FRAME: a silent close
                # would have the joiner retry a deterministic failure
                # under backoff — answer with a small error naming the
                # knob instead, so the client fails fast and loudly
                get_logger().warning(
                    "membership bus: reply for op %r too large: %s", op, e)
                _send_obj(conn, {"ok": False, "error": str(e)})
        except Exception:  # noqa: BLE001 — a broken/dead client connection
            # must not take the bus down; the client side has its own
            # retry/timeout story
            get_logger().debug("membership bus: connection handler failed",
                               exc_info=True)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _stale_reply(self) -> dict:
        return {"ok": False, "stale": True, "epoch": self.epoch,
                "world": sorted(self.world)}

    # -- verb: sync (step barrier + payload all-gather + join admission) ---

    def _do_sync(self, msg: dict) -> dict:
        rank, epoch, step = msg["rank"], msg["epoch"], msg["step"]
        deadline = time.monotonic() + self._sync_timeout
        with self._cv:
            if msg.get("metrics") is not None:
                # observability piggyback: cached even for a stale-epoch
                # sync — a rank mid-transition is exactly one an operator
                # wants to see
                self._metrics[rank] = (time.time(), msg["metrics"])
            if epoch != self.epoch:
                return self._stale_reply()
            key = (epoch, step)
            self._sync.setdefault(key, {})[rank] = msg.get("payload")
            if msg.get("state") is not None:
                # the state a member carries at step s is its state
                # AFTER step s-1 — what a joiner admitted at this
                # boundary resumes from
                self._snapshots[key] = (msg["state"],
                                        list(msg.get("declared") or ()),
                                        step - 1)
            # memory hygiene: completed rounds more than a few steps old
            # can never gain another waiter
            for k in [k for k in self._sync if k[1] < step - 4]:
                self._sync.pop(k, None)
                self._snapshots.pop(k, None)
            self._cv.notify_all()
            while not self._stop.is_set():
                if self.epoch != epoch:
                    # a shrink or an admission moved the world while this
                    # round was parked: the payloads are void, retry the
                    # step at the new epoch
                    return self._stale_reply()
                got = self._sync.get(key, {})
                joins_parked = any(v is None
                                   for v in self._join_wait.values())
                if set(got) >= self.world:
                    if joins_parked and key in self._snapshots:
                        self._admit(key)
                        continue  # epoch changed: loop → stale reply
                    # join_waiting tells members to attach state on the
                    # NEXT boundary — so the (expensive) state transfer
                    # happens only when someone is actually rejoining
                    return {"ok": True, "epoch": epoch,
                            "payloads": dict(got),
                            "join_waiting": joins_parked}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # quorum never completed: the missing members are
                    # failure evidence (the detector may be inert after
                    # its one firing) — the client turns this into a
                    # shrink
                    return {"ok": False, "timeout": True,
                            "missing": sorted(self.world - set(got)),
                            "epoch": self.epoch,
                            "world": sorted(self.world)}
                self._cv.wait(min(remaining, 0.25))
        return self._stale_reply()

    def _admit(self, key: Tuple[int, int]) -> None:
        """Admit every parked joiner at this completed step boundary
        (caller holds the condition)."""
        state, declared, state_step = self._snapshots[key]
        joiners = sorted(r for r, v in self._join_wait.items() if v is None)
        self.epoch += 1
        self.world |= set(joiners)
        info = {"epoch": self.epoch, "world": sorted(self.world),
                "declared": declared, "step": state_step, "state": state}
        for r in joiners:
            self._join_wait[r] = dict(info)
        counters.inc("membership.rejoin_admitted", len(joiners))
        get_logger().warning(
            "membership bus: admitted rank(s) %s at step boundary %d — "
            "epoch %d, world %s", joiners, key[1], self.epoch,
            sorted(self.world))
        # void the old epoch's parked rounds
        self._sync = {k: v for k, v in self._sync.items()
                      if k[0] >= self.epoch}
        self._cv.notify_all()

    # -- verb: hello (the shrink rendezvous) -------------------------------

    def _do_hello(self, msg: dict) -> dict:
        rank = msg["rank"]
        proposed_epoch = msg["epoch"]
        proposed_world = frozenset(msg["world"])
        deadline = time.monotonic() + self._rdv_timeout
        with self._cv:
            if proposed_epoch <= self.epoch:
                # agreement already happened (or a stray old proposal):
                # the current view IS the answer
                return {"ok": True, "epoch": self.epoch,
                        "world": sorted(self.world)}
            self._hellos.setdefault(proposed_epoch, {})[rank] = proposed_world
            self._cv.notify_all()
            while not self._stop.is_set():
                if self.epoch >= proposed_epoch:
                    return {"ok": True, "epoch": self.epoch,
                            "world": sorted(self.world)}
                got = self._hellos.get(proposed_epoch, {})
                # the ranks every proposal agrees are alive must all
                # check in; a rank someone still believes dead but that
                # hellos anyway is alive by definition and joins the
                # agreed world
                expected = frozenset.intersection(*got.values())
                if set(got) >= expected:
                    self._agree(proposed_epoch, sorted(got))
                    return {"ok": True, "epoch": self.epoch,
                            "world": sorted(self.world)}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # double failure during the shrink: whoever never
                    # helloed inside the window is dropped too
                    get_logger().error(
                        "membership: rendezvous for epoch %d timed out "
                        "waiting for %s — proceeding with responders %s",
                        proposed_epoch, sorted(expected - set(got)),
                        sorted(got))
                    self._agree(proposed_epoch, sorted(got))
                    return {"ok": True, "epoch": self.epoch,
                            "world": sorted(self.world)}
                self._cv.wait(min(remaining, 0.25))
        return self._stale_reply()

    def _agree(self, epoch: int, world: List[int]) -> None:
        """Commit a shrink agreement (caller holds the condition)."""
        self.epoch = epoch
        self.world = set(world)
        self._hellos.pop(epoch, None)
        # release every sync round parked under the dead epoch
        self._sync = {k: v for k, v in self._sync.items() if k[0] >= epoch}
        counters.inc("membership.shrink_agreed")
        get_logger().warning("membership bus: agreed epoch %d, world %s",
                             epoch, world)
        self._cv.notify_all()

    # -- verb: rejoin ------------------------------------------------------

    def _do_rejoin(self, msg: dict) -> dict:
        rank = msg["rank"]
        deadline = time.monotonic() + self._sync_timeout
        with self._cv:
            self._join_wait[rank] = None
            self._cv.notify_all()
            while not self._stop.is_set():
                info = self._join_wait.get(rank)
                if info is not None:
                    del self._join_wait[rank]
                    return {"ok": True, **info}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._join_wait.pop(rank, None)
                    return {"ok": False, "timeout": True}
                self._cv.wait(min(remaining, 0.25))
        return {"ok": False, "timeout": True}

    # -- verbs: metrics (cross-rank observability) -------------------------

    def _do_metrics_put(self, msg: dict) -> dict:
        """Store one rank's snapshot (the explicit form of the sync
        piggyback — background publishers and one-shot tools)."""
        with self._cv:
            self._metrics[msg["rank"]] = (time.time(), msg.get("metrics"))
            return {"ok": True, "epoch": self.epoch,
                    "world": sorted(self.world)}

    def _do_metrics(self) -> dict:
        """Every live rank's latest snapshot in one reply.  Ranks outside
        the current world are pruned (their cache entries are residue of
        a shrink); age lets the caller judge freshness."""
        now = time.time()
        with self._cv:
            self._metrics = {r: v for r, v in self._metrics.items()
                             if r in self.world}
            return {"ok": True, "epoch": self.epoch,
                    "world": sorted(self.world),
                    "ranks": {r: {"age_s": round(now - t, 3), "metrics": m}
                              for r, (t, m) in self._metrics.items()}}


# -- the per-process membership object --------------------------------------


class ElasticMembership:
    """One process's handle on the elastic world.

    Parameters
    ----------
    rank : this process's membership rank (the launcher's
        ``DMLC_WORKER_ID`` numbering — a per-process identity that
        exists before any JAX state, same convention as the fault
        injector).
    world : the initial member ranks.
    bus : ``host:port`` of the membership bus; defaults to
        ``DMLC_PS_ROOT_URI`` with ``BYTEPS_MEMBERSHIP_PORT`` (or
        coordinator port + 2).  The lowest-ranked live member hosts it.
    devices : devices for resumed meshes (passed through to
        ``api.resume``).
    assigner / server_engine / kv_store : optional attached components
        re-synced on every world change (``ServerAssigner.reshard``,
        ``set_membership_epoch``).
    on_world_change : callback run with the new :class:`MembershipView`
        after each applied change (keep it short — it can run on the
        detector thread).
    """

    def __init__(self, rank: int, world: Iterable[int],
                 bus: Optional[str] = None, *,
                 devices=None,
                 assigner=None, server_engine=None, kv_store=None,
                 on_world_change: Optional[Callable[[MembershipView],
                                                    None]] = None,
                 rendezvous_timeout_s: Optional[float] = None,
                 sync_timeout_s: Optional[float] = None,
                 retry: Optional[RetryPolicy] = None,
                 _view: Optional[MembershipView] = None):
        from ..common.config import get_config
        cfg = get_config()
        self.rank = int(rank)
        self._view = _view or MembershipView(
            current_epoch(), tuple(sorted(int(r) for r in world)))
        if self.rank not in self._view.world:
            raise ValueError(f"rank {self.rank} not in world "
                             f"{list(self._view.world)}")
        self.bus_addr = resolve_bus_addr(bus)
        self.devices = devices
        self.assigner = assigner
        self.server_engine = server_engine
        self.kv_store = kv_store
        self.on_world_change = on_world_change
        self.rendezvous_timeout_s = (
            cfg.membership_rendezvous_timeout_s
            if rendezvous_timeout_s is None else rendezvous_timeout_s)
        self.sync_timeout_s = (cfg.membership_sync_timeout_s
                               if sync_timeout_s is None else sync_timeout_s)
        self._retry = retry or RetryPolicy.from_config(
            cfg, retry_on=(_BusUnreachable,))
        self._apply_lock = threading.Lock()
        self._ready_cv = threading.Condition()
        self._bus: Optional[_BusServer] = None
        # True once a sync reply advertised a parked joiner: the next
        # step_sync attaches the (expensive) state payload
        self._join_hint = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ElasticMembership":
        """Adopt the initial view; host the bus when this rank is the
        coordinator."""
        set_epoch(self._view.epoch)
        self._ensure_bus(self._view)
        return self

    def stop(self) -> None:
        if self._bus is not None:
            self._bus.close()
            self._bus = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def view(self) -> MembershipView:
        return self._view

    @property
    def is_coordinator(self) -> bool:
        return self.rank == self._view.coordinator

    def _ensure_bus(self, view: MembershipView) -> None:
        """Host the bus iff this rank is the coordinator of ``view``
        and no bus is running here yet (idempotent; retried because a
        just-dead predecessor's socket may linger in TIME_WAIT).

        A bind that stays refused is NOT fatal: after a coordinator
        failover the old minimum rank can rejoin a world whose bus a
        surviving member already hosts at the fixed address — the
        rejoiner must join as a client of that bus, not die on
        EADDRINUSE after it was already admitted."""
        if self.rank != min(view.world) or self._bus is not None:
            return
        def _bind():
            return _BusServer(self.bus_addr, view,
                              self.rendezvous_timeout_s,
                              self.sync_timeout_s)
        try:
            self._bus = RetryPolicy.from_config(
                retry_on=(OSError,)).call(_bind,
                                          describe="membership bus bind")
        except OSError:
            get_logger().warning(
                "membership: rank %d is the coordinator of %s but the bus "
                "address %s:%d is already served (coordinator failover "
                "kept it) — continuing as a bus client",
                self.rank, list(view.world), *self.bus_addr)
            return
        get_logger().info("membership: rank %d hosting the bus at %s:%d",
                          self.rank, *self.bus_addr)

    # -- bus client --------------------------------------------------------

    def _request(self, msg: dict, timeout: float) -> dict:
        """One request/reply round trip.  Connection-level failures (the
        coordinator died; its successor is still binding) are retried
        with full-jitter backoff; a read that exceeds ``timeout`` is a
        :class:`MembershipTimeout` and is NOT retried — the server
        answers its own timeouts explicitly."""
        def once():
            try:
                s = socket.create_connection(self.bus_addr, timeout=3.0)
            except OSError as e:
                raise _BusUnreachable(f"bus {self.bus_addr}: {e}") from None
            try:
                s.settimeout(timeout)
                _send_obj(s, msg)
                return _recv_obj(s)
            except socket.timeout:
                raise MembershipTimeout(
                    f"membership {msg.get('op')} timed out after "
                    f"{timeout:.1f}s") from None
            except _BusUnreachable:
                raise
            except OSError as e:
                raise _BusUnreachable(f"bus {self.bus_addr}: {e}") from None
            finally:
                s.close()
        return self._retry.call(once,
                                describe=f"membership {msg.get('op')}")

    def _declared_order(self) -> List[str]:
        from ..core import api
        if api.initialized():
            return api._require().registry.names_in_declaration_order()
        return list(api._declared_order)

    # -- cross-rank observability ------------------------------------------

    def _local_metrics(self) -> Optional[dict]:
        """The compact snapshot every sync piggybacks (None when
        telemetry is off or the snapshot itself fails — observability
        must never fail a step barrier)."""
        try:
            from ..common.config import get_config
            if not get_config().telemetry_on:
                return None
            from ..core import api
            return api.metrics_snapshot(light=True)
        except Exception:  # noqa: BLE001
            return None

    def publish_metrics(self) -> bool:
        """Best-effort explicit snapshot push (``metrics_put``) for
        processes between step barriers; returns False instead of
        raising when the bus is unreachable."""
        try:
            from ..core import api
            bus_request(self.bus_addr,
                        {"op": "metrics_put", "rank": self.rank,
                         "metrics": api.metrics_snapshot(light=True)},
                        timeout=5.0)
            return True
        except Exception:  # noqa: BLE001
            return False

    # -- the step barrier / all-gather ------------------------------------

    def step_sync(self, step: int, payload: Any = None,
                  state: Any = None) -> Tuple[MembershipView, Dict[int, Any]]:
        """Synchronize step ``step`` with every live member.

        Returns ``(view, payloads)`` where ``payloads`` maps rank →
        the small control-plane payload each member posted.  ``state``
        (a checkpoint-style pytree, or pre-packed bytes) is what a
        parked rejoiner would be admitted with; pass it every step to
        make any step a potential rejoin barrier.  It is only
        materialized and shipped when the bus has advertised a parked
        joiner (the previous sync reply's ``join_waiting``), so the
        per-step cost of the offer is one ignored keyword — the real
        pack/transfer happens on the one boundary that needs it
        (admission therefore lands on the *second* quorum after a
        rejoin request).

        Raises :class:`WorldChanged` when the epoch moved — by then the
        local engine has already been suspended/resumed onto the new
        world, so the caller just retries the step.  A quorum timeout
        with missing members is treated as failure evidence and turned
        into a shrink (the heartbeat detector fires only once; this is
        the detection path for failures *after* the first).
        """
        view = self._view
        msg: Dict[str, Any] = {"op": "sync", "rank": self.rank,
                               "epoch": view.epoch, "step": step,
                               "payload": payload,
                               "metrics": self._local_metrics()}
        if state is not None and self._join_hint:
            if not isinstance(state, bytes):
                from ..utils.checkpoint import pack_state
                # seal=False: the bus frame (_send_obj) already envelopes
                # this whole message — double-sealing a multi-GB state
                # would double the rejoin's CRC and copy cost
                state = pack_state(state, seal=False)
            msg["state"] = state
            msg["declared"] = self._declared_order()
        reply = self._request(msg, timeout=self.sync_timeout_s + 15.0)
        if reply.get("ok"):
            self._join_hint = bool(reply.get("join_waiting"))
            return self._view, reply["payloads"]
        if reply.get("stale"):
            new = MembershipView(reply["epoch"], tuple(reply["world"]))
            if self.rank not in new.world:
                raise Evicted(
                    f"rank {self.rank} is outside the agreed world "
                    f"{list(new.world)} (epoch {new.epoch})")
            self._maybe_apply(new)
            raise WorldChanged(new)
        if reply.get("timeout"):
            missing = set(reply.get("missing") or ())
            if missing:
                get_logger().error(
                    "membership: step %d sync timed out; missing rank(s) "
                    "%s treated as failed", step, sorted(missing))
                return_view = self.shrink(missing)
                raise WorldChanged(return_view)
            raise MembershipTimeout(f"step {step} sync timed out")
        raise RuntimeError(f"membership sync failed: {reply!r}")

    # -- shrink ------------------------------------------------------------

    def on_failure(self, stale: Set[int]) -> None:
        """``HeartbeatMonitor.on_failure`` action: shrink in place;
        escalate to the restartable exit only when the shrink itself
        fails (launcher supervision is the outer loop, as with
        ``RecoveryCoordinator``)."""
        try:
            self.shrink(stale)
        except Exception:  # noqa: BLE001 — end of the in-process line
            counters.inc("membership.shrink_failed")
            from ..utils.failure_detector import _failure_exit_code
            code = _failure_exit_code()
            get_logger().error(
                "elastic shrink failed — exiting %d so the launcher can "
                "restart", code, exc_info=True)
            _exit(code)

    def shrink(self, stale: Set[int]) -> MembershipView:
        """Drop ``stale`` ranks: epoch guard up → drain/suspend →
        epoch-tagged rendezvous → resume at the survivor world."""
        view = self._view
        stale = set(stale) & set(view.world)
        if not stale:
            # a late detection of ranks an earlier shrink already
            # removed — the world is current, nothing to do
            return view
        proposed_world = tuple(r for r in view.world if r not in stale)
        proposed_epoch = view.epoch + 1
        if self.rank not in proposed_world:
            raise Evicted(f"rank {self.rank} was declared stale by its "
                          "own detector input")
        counters.inc("membership.shrink_started")
        _flight.record("membership.shrink_started", stale=sorted(stale),
                       proposed_epoch=proposed_epoch,
                       proposed_world=list(proposed_world))
        t0 = time.monotonic()
        get_logger().error(
            "membership: rank(s) %s lost — shrinking to %s (epoch %d)",
            sorted(stale), list(proposed_world), proposed_epoch)
        # Guard first: from here every in-flight chunk is stale and gets
        # dropped at dispatch/finish instead of delivered, so the drain
        # below is fast and the results of a half-dead collective never
        # reach a callback.
        set_epoch(proposed_epoch)
        from ..core import api
        if api.initialized():
            api.suspend()
        # Coordinator failover: if the dead set includes the old
        # coordinator, the lowest surviving rank hosts the bus before
        # helloing (to itself); everyone else's connect is retried with
        # backoff until the new bus is up.
        self._ensure_bus(MembershipView(view.epoch, proposed_world))
        reply = self._request(
            {"op": "hello", "rank": self.rank, "epoch": proposed_epoch,
             "world": list(proposed_world)},
            timeout=self.rendezvous_timeout_s + 15.0)
        agreed = MembershipView(reply["epoch"], tuple(reply["world"]))
        if self.rank not in agreed.world:
            raise Evicted(f"rank {self.rank} is outside the agreed world "
                          f"{list(agreed.world)}")
        out = self._maybe_apply(agreed)
        get_logger().warning(
            "membership: shrink complete in %.2fs — epoch %d, world %s",
            time.monotonic() - t0, out.epoch, list(out.world))
        return out

    # -- applying an agreed view ------------------------------------------

    def _maybe_apply(self, view: MembershipView) -> MembershipView:
        """Re-point this process at ``view``: advance the epoch, rebuild
        mesh+engine on the new world size, re-shard attached components.
        Idempotent and monotonic — concurrent appliers (detector thread
        vs a trainer thread that saw a stale sync reply) serialize here
        and the second is a no-op."""
        with self._apply_lock:
            old = self._view
            if view.epoch <= old.epoch:
                return old
            t0 = time.monotonic()
            grew = len(view.world) > len(old.world)
            set_epoch(view.epoch)
            from ..core import api
            if api.initialized():
                api.suspend()
            _resume_for_world(view, self.devices)
            self._view = view
            if self.assigner is not None:
                try:
                    self.assigner.reshard(view.num_workers)
                except Exception:  # noqa: BLE001 — a shape the shrunk
                    # world can't satisfy must not kill a healthy
                    # survivor; routing keeps the old map, service
                    # survives (mixed-mode assigners need an explicit
                    # reshard(num_servers, num_workers) from
                    # on_world_change — the split is deployment-specific)
                    get_logger().error(
                        "membership: ServerAssigner reshard to %d failed; "
                        "keeping the previous assignment (drive "
                        "reshard() from on_world_change for mixed mode)",
                        view.num_workers, exc_info=True)
            if self.server_engine is not None:
                self.server_engine.set_membership_epoch(view.epoch)
            if self.kv_store is not None:
                self.kv_store.set_membership_epoch(view.epoch)
            self._ensure_bus(view)
            counters.inc("membership.grow" if grew else "membership.shrink")
            _flight.record("membership.applied", epoch=view.epoch,
                           world=list(view.world), grew=grew)
            self._record_span("rejoin" if grew else "shrink", t0, view)
            get_logger().warning(
                "membership: now epoch %d, world %s (%d worker(s))",
                view.epoch, list(view.world), view.num_workers)
        with self._ready_cv:
            self._ready_cv.notify_all()
        if self.on_world_change is not None:
            try:
                self.on_world_change(view)
            except Exception:  # noqa: BLE001 — the transition itself
                # succeeded; a broken user callback must not undo that
                get_logger().error("on_world_change callback raised",
                                   exc_info=True)
        return view

    def wait_ready(self, epoch: int,
                   timeout: Optional[float] = None) -> MembershipView:
        """Block until the local view reaches ``epoch`` (trainer-side
        helper for exception paths where the applying thread is
        elsewhere)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._ready_cv:
            while self._view.epoch < epoch:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise MembershipTimeout(
                        f"world change to epoch {epoch} not applied "
                        f"locally within {timeout:.1f}s")
                self._ready_cv.wait(0.1 if remaining is None
                                    else min(remaining, 0.1))
        return self._view

    def _record_span(self, name: str, t0: float,
                     view: MembershipView) -> None:
        """Membership transition span into the *resumed* engine's tracer
        (same placement as RecoveryCoordinator._record_span)."""
        try:
            from ..core import api
            eng = api._require()
        except Exception:  # noqa: BLE001 — tracing is best-effort
            return
        eng.tracer.record_span(name, t0, time.monotonic(),
                               epoch=view.epoch, world=list(view.world))

    # -- rejoin ------------------------------------------------------------

    @classmethod
    def rejoin(cls, rank: int, bus: Optional[str] = None, *,
               devices=None, timeout: Optional[float] = None,
               **kwargs) -> Tuple["ElasticMembership", Optional[int], Any]:
        """Rejoin a running world from a fresh process.

        Parks on the bus until the survivors pass a step boundary, then
        adopts the agreed epoch, re-declares every tensor in the
        received declared-key order (identical key assignment), resumes
        the engine at the grown world size, and returns
        ``(membership, step, state)`` — ``state`` is the survivors'
        in-memory parameters (``utils.checkpoint.unpack_state``), the
        elastic counterpart of restore-then-broadcast, and ``step`` the
        training step it corresponds to.
        """
        counters.inc("membership.rejoin_requested")
        t0 = time.monotonic()
        probe = cls(rank, [rank], bus, devices=devices, **kwargs)
        wait_s = probe.sync_timeout_s if timeout is None else timeout
        reply = probe._request({"op": "rejoin", "rank": int(rank)},
                               timeout=wait_s + 15.0)
        if not reply.get("ok"):
            raise MembershipTimeout(
                f"rejoin of rank {rank} was not admitted: {reply!r}")
        view = MembershipView(reply["epoch"], tuple(reply["world"]))
        set_epoch(view.epoch)
        from ..core import api
        for name in reply.get("declared") or ():
            api.declare(name)   # original order ⇒ identical keys
        _resume_for_world(view, devices)
        probe._view = view
        probe._ensure_bus(view)   # no-op unless this rank is coordinator
        state = None
        if reply.get("state") is not None:
            from ..utils.checkpoint import unpack_state
            state = unpack_state(reply["state"])
        counters.inc("membership.rejoined")
        _flight.record("membership.rejoined", rank=int(rank),
                       epoch=view.epoch, world=list(view.world),
                       step=reply.get("step"))
        probe._record_span("rejoin", t0, view)
        get_logger().warning(
            "membership: rank %d rejoined at epoch %d, world %s, step %s",
            rank, view.epoch, list(view.world), reply.get("step"))
        return probe, reply.get("step"), state


def _resume_for_world(view: MembershipView, devices) -> None:
    """Resume the engine for the agreed world.

    Multi-host (a real ``jax.distributed`` run): the world size IS the
    DMLC host count, so it is exported through ``resume(num_workers=)``
    exactly as the reference's ``BytePSBasics.resume`` would — with the
    known caveat that an initialized JAX backend cannot drop a dead
    peer's devices, so callers pass ``devices=jax.local_devices()``
    (see RecoveryCoordinator's ``devices`` docstring).

    Single-controller (one process per member, each owning its own
    local mesh — the CPU chaos topology and any one-host elastic run):
    the membership world is a *bus-level* fact, not the local JAX
    topology; resume re-initializes the local mesh unchanged and must
    NOT rewrite ``DMLC_NUM_WORKER`` (that would send the next
    bootstrap down the multi-host rendezvous path)."""
    import jax
    from ..core import api
    if jax.process_count() > 1:
        api.resume(num_workers=view.num_workers, devices=devices)
    else:
        api.resume(devices=devices)


# monkeypatch point for tests (escalation must not kill the test runner)
_exit = os._exit
