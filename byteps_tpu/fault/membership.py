"""Elastic membership: shrink-to-survivors and in-place rejoin.

The reference lets a recovered worker rejoin a running job in place
(``is_recovery``, reference global.cc:291-294, server.cc:486-489) but
offers no survivor-side story: a dead peer means the whole job restarts.
PR 2 built the ingredients — suspend/resume (core/api.py), the
``RecoveryCoordinator`` drain→restore flow, chaos injection, launcher
supervision.  This module composes them into a real membership layer:

- **Membership epoch** — a process-wide monotonic counter
  (:func:`current_epoch`).  Every engine dispatch stamps its pending
  tensor with the epoch at enqueue (core/engine.py) and every
  ServerEngine/KVStore push may carry one; work stamped with a dead
  epoch is *dropped, not summed* — the same residue-vs-fresh-round
  discipline as ``ServerEngine.reset_key``, applied to the whole world.
- **Shrink-to-survivors** — on heartbeat loss every survivor's
  ``on_failure`` runs :meth:`ElasticMembership.shrink`: advance the
  epoch (stale guard up), drain + ``suspend()``, agree on the new world
  through an epoch-tagged rendezvous on the membership bus, then
  ``resume()`` at the smaller size — re-declared keys in original
  order, re-sharded ``ServerAssigner``, training continues from
  in-memory state with **no process exit**.
- **In-place rejoin** — a restarted rank calls
  :meth:`ElasticMembership.rejoin`; it parks on the bus until the
  survivors complete a step-boundary sync, then receives the agreed
  epoch, the declared-key order, and the parameters packed by a
  survivor (``utils.checkpoint.pack_state`` — the wire form of the
  broadcast-after-restore contract) and resumes as a full member.

The **membership bus** is a tiny TCP control-plane endpoint hosted by
the lowest-ranked live member (the *membership coordinator*).  It
serves three verbs: ``sync`` (per-step barrier + small payload
all-gather, the vehicle for both failure evidence and join admission),
``hello`` (the shrink rendezvous), and ``rejoin``.  Clients reach it
with :class:`common.retry.RetryPolicy` full-jitter backoff, so a bus
that moves to a new coordinator mid-shrink is a transient, not an
error.  Control-plane only: gradients ride the XLA collectives; the
bus carries membership state, step digests, and the (rare) rejoin
parameter transfer.

Double failure during a shrink: the rendezvous waits
``membership_rendezvous_timeout_s`` for every proposed survivor; a
member that never checks in (it died after the first detection) is
dropped from the agreed world and the shrink completes without it.  A
member that finds itself outside the agreed world raises
:class:`Evicted` — under ``bpslaunch-dist --elastic`` it exits
restartable and comes back through the rejoin path.

Coordinator survival (ISSUE 8): the coordinator is no longer a single
point of state.  Every bus mutation — the agreed (epoch, world),
per-step sync payloads, parked rejoin requests, and the cross-rank
metrics cache — is replicated to a **standby** (the
next-lowest live rank) by piggybacking a ``replica`` snapshot on every
reply the bus sends that rank (plus an explicit ``replicate`` verb for
a rank that just *became* standby).  When the coordinator dies, the
standby re-binds the bus — same address on a single host, or its own
``BYTEPS_MEMBERSHIP_HOSTS`` entry on multi-host (``resolve_bus_addr``
is view-aware) — **seeded with the replicated state**, so a mid-step
sync round and a parked joiner survive the failover instead of wedging
until timeout.  The heartbeat plane moves with it: under
:meth:`ElasticMembership.host_heartbeat` every applied world change
rebuilds the monitors with ``server_rank = view.coordinator``, so
"coordinator down" flows through the ordinary shrink path and detection
of *subsequent* failures keeps working.  If the would-be coordinator of
a shrink never serves the bus inside the rendezvous window, the
proposing survivor drops it too and escalates down the rank ladder
until it either reaches a live coordinator or hosts the bus itself —
a double failure during failover converges instead of wedging.

Failure evidence without a named suspect — a data-path deadline trip
(``BYTEPS_SYNC_DEADLINE_S``, core/engine.py), a step-watchdog stall —
arrives as :meth:`ElasticMembership.on_failure` with an *empty* stale
set and becomes a :meth:`reconcile`: a rendezvous over the CURRENT
world at the next epoch.  Members parked in a step sync are released
with ``reconcile=True`` and join it; whoever is wedged-dead never
hellos and is dropped by the rendezvous timeout.  The bus turns
"something is stuck" into "exactly who is gone".

Gray failures — probation-based demotion (ISSUE 10): a rank that is
slow-but-ALIVE (throttled chip, degraded NIC) completes every quorum,
just late, so nothing above ever fires while every barrier waits on it.
The bus is the one place that SEES this: :meth:`_BusServer._do_sync`
stamps each rank's arrival time per barrier, scores arrival lags with a
phi-accrual tracker (``utils/slowness.py``, ``site="step_sync"``), and
folds in self-reported sync-deadline trips from the metrics piggyback.
Under ``BYTEPS_STRAGGLER_POLICY=demote`` a rank slow for
``straggler_demote_after`` consecutive barriers is **demoted**: the
round answers every member with a ``demote`` signal — survivors reuse
shrink-to-survivors (:meth:`ElasticMembership.demote`), while the
straggler itself raises :class:`Demoted` (NOT :class:`Evicted`): it
stays alive on the bus's **probation list**, recovers at its own pace
(``utils.slowness.wait_recovered``), and returns through the ordinary
:meth:`ElasticMembership.rejoin` step-boundary admission, which clears
its probation entry.  The probation list replicates to the standby with
the rest of the bus state, and the current coordinator is exempt from
demotion (its slowness escalates through the crash-failover path
instead — demoting the process that hosts the bus would race its own
takeover).  See docs/gray_failures.md.
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import socket
import struct
import threading
import time
import weakref
from typing import Any, Callable, Dict, Iterable, List, Optional, Set, Tuple

from ..common import flight_recorder as _flight
from ..common.lock_witness import named_lock
from ..common.logging import get_logger
from ..common.retry import RetryPolicy
from ..common.telemetry import counters

__all__ = [
    "MembershipView", "ElasticMembership", "WorldChanged", "Evicted",
    "Demoted", "PartitionMinority", "MembershipTimeout", "current_epoch",
    "advance_epoch", "set_epoch", "resolve_bus_addr", "bus_request",
    "active_membership", "is_parked", "SERVE_RANK_BASE",
]

# Serving hosts (server/serving_tier.py) publish metrics snapshots into
# the same bus-side cache as trainer ranks, keyed at host_id + this base
# so the two id spaces can never collide (a tier of 3 hosts beside a
# 4-rank trainer world must not have host 2 shadow rank 2's row in
# bps_top).  Anything at or above the base is a serving host.
SERVE_RANK_BASE = 1 << 20


# The process's started ElasticMembership (weak: stop()/GC must not be
# blocked by observability readers).  cluster_metrics / the obs endpoint
# / the injector's kill:site=coordinator predicate read the CURRENT view
# through this instead of re-deriving a stale env-derived address.
_active_ref: Optional["weakref.ref[ElasticMembership]"] = None


def active_membership() -> Optional["ElasticMembership"]:
    """The live :class:`ElasticMembership` of this process, if one was
    started (None otherwise) — the handle observability callers use to
    re-resolve the bus from the current view after a coordinator
    change."""
    ref = _active_ref
    return ref() if ref is not None else None


def is_parked() -> bool:
    """True while this process sits parked on the minority side of a
    partition (quorum gate refused the epoch; engine suspended).  The
    engine checks this at enqueue so a parked rank fails loudly instead
    of queueing work no epoch will ever complete."""
    m = active_membership()
    return bool(m is not None and m.parked)


# -- the process-wide membership epoch --------------------------------------
#
# One integer, monotonic, shared by every layer that stamps or checks
# work: engine pendings (core/engine.py), server pushes
# (server/engine.py, server/kv_store.py), and the bus protocol below.
# Epoch 0 is the static world every non-elastic run lives in forever.

_epoch = 0
_epoch_lock = threading.Lock()


def current_epoch() -> int:
    """The membership epoch this process currently lives in."""
    return _epoch


def advance_epoch() -> int:
    """Bump the epoch by one (stale guards trip immediately)."""
    global _epoch
    with _epoch_lock:
        _epoch += 1
        return _epoch


def set_epoch(epoch: int) -> int:
    """Raise the epoch to ``epoch`` (monotonic: never regresses)."""
    global _epoch
    with _epoch_lock:
        if epoch > _epoch:
            _epoch = epoch
        return _epoch


def _reset_epoch_for_tests() -> None:
    global _epoch
    with _epoch_lock:
        _epoch = 0


@dataclasses.dataclass(frozen=True)
class MembershipView:
    """One agreed (epoch, world) pair; world is a sorted rank tuple."""

    epoch: int
    world: Tuple[int, ...]

    @property
    def num_workers(self) -> int:
        return len(self.world)

    @property
    def coordinator(self) -> int:
        return min(self.world)


class WorldChanged(RuntimeError):
    """The world moved under a step_sync; retry the step at the new
    epoch (the local engine has already been re-initialized)."""

    def __init__(self, view: MembershipView):
        super().__init__(f"membership changed: epoch {view.epoch}, "
                         f"world {list(view.world)}")
        self.view = view


class Evicted(RuntimeError):
    """This rank is not in the agreed world (the survivors shrank past
    it).  Exit restartable and come back through rejoin()."""


class Demoted(RuntimeError):
    """The bus demoted THIS rank onto the probation list — a sustained
    straggler under ``BYTEPS_STRAGGLER_POLICY=demote``.  Deliberately
    not an :class:`Evicted`: the rank is slow, not dead — stay alive,
    wait out the local condition (``utils.slowness.wait_recovered``
    against a small data-path probe), then come back through
    :meth:`ElasticMembership.rejoin` at a step boundary; admission
    clears the probation entry."""

    def __init__(self, view: MembershipView, probation):
        super().__init__(
            f"demoted to probation: the world moved on to epoch "
            f"{view.epoch + 1} without this rank (probation list: "
            f"{sorted(probation)}); recover, then rejoin()")
        self.view = view
        self.probation = sorted(probation)


class PartitionMinority(RuntimeError):
    """This rank is on the MINORITY side of a partition: a shrink it
    proposed (or joined) cannot reach a strict majority of the last
    agreed world, so committing it could split-brain the epoch
    (``BYTEPS_GOSSIP_ON`` quorum gate, fault/gossip.py quorum_ok).  The
    rank PARKS — engine suspended, no epoch advanced — and rejoins
    through the ordinary :meth:`ElasticMembership.rejoin` path once the
    partition heals.  Deliberately not an :class:`Evicted`: nobody
    agreed a world without this rank; there is, for now, no agreed new
    world at all on this side."""

    def __init__(self, view: MembershipView, reachable, proposed):
        super().__init__(
            f"partition minority: only {sorted(reachable)} of the last "
            f"agreed world {list(view.world)} (epoch {view.epoch}) are "
            f"reachable — no strict majority, parking instead of "
            f"committing epoch {view.epoch + 1}; rejoin() after the "
            f"partition heals")
        self.view = view
        self.reachable = sorted(reachable)
        self.proposed = sorted(proposed)


class MembershipTimeout(TimeoutError):
    """A bus request did not complete inside its window."""


class _BusUnreachable(ConnectionError):
    """Transient: the coordinator is dead/moving; retried with backoff."""


class _BusFrameError(_BusUnreachable):
    """A RECEIVED bus frame failed a sanity or integrity check.  The
    connection is failed loudly (server side logs and closes; client
    side retries with a fresh connection under the bounded backoff
    policy — the corruption is plausibly transient) — never acted on."""


class _BusFrameTooLarge(ValueError):
    """Deterministic sender-side refusal: the frame WE are about to send
    exceeds ``BYTEPS_BUS_MAX_FRAME``.  Deliberately NOT a
    :class:`_BusUnreachable` (nor an ``OSError``): retrying cannot
    succeed until the operator raises the env var, and each retry would
    re-pickle and re-CRC a multi-gigabyte rejoin state for nothing."""


# -- wire helpers (length-prefixed pickle over a trusted local socket,
#    CRC32C-enveloped when BYTEPS_INTEGRITY is armed) -----------------------

def _send_obj(sock: socket.socket, obj: Any) -> None:
    from ..common import integrity as _integrity
    from ..common.config import get_config
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sealing = _integrity.enabled()
    max_frame = get_config().bus_max_frame
    wire_len = len(data) + (
        _integrity.envelope_overhead("membership-bus") if sealing else 0)
    if wire_len > max_frame:
        # fail at the SENDER, before shipping gigabytes the receiver's
        # clamp would reject anyway (and misattribute to corruption) —
        # and before CRC'ing and copying them into an envelope this
        # refusal would only discard
        raise _BusFrameTooLarge(
            f"refusing to send a {wire_len}-byte bus frame > "
            f"BYTEPS_BUS_MAX_FRAME={max_frame}; legitimately large rejoin "
            "states need BYTEPS_BUS_MAX_FRAME raised on every member")
    if sealing:
        # membership frames carry epochs, worlds, and rejoin parameter
        # blobs — a silently corrupt one could commit a wrong world or
        # hand a joiner bad weights, so they ride the same envelope as
        # every other host hop
        data = _integrity.seal_bytes(data, key="membership-bus")
    # 8-byte length prefix: a rejoin state payload is a whole model's
    # parameters and can exceed the 4 GiB a 32-bit prefix could frame
    sock.sendall(struct.pack("!Q", len(data)) + data)


def _recv_obj(sock: socket.socket) -> Any:
    from ..common import integrity as _integrity
    from ..common.config import get_config
    buf = b""
    while len(buf) < 8:
        chunk = sock.recv(8 - len(buf))
        if not chunk:
            raise _BusUnreachable("bus connection closed mid-frame")
        buf += chunk
    (n,) = struct.unpack("!Q", buf)
    max_frame = get_config().bus_max_frame
    if n > max_frame:
        # an 8-byte prefix is the first thing a corrupt stream mangles:
        # trusting it unconditionally would park this thread on a
        # multi-petabyte recv.  Clamp and fail the connection instead.
        raise _BusFrameError(
            f"bus frame length {n} exceeds BYTEPS_BUS_MAX_FRAME="
            f"{max_frame} — corrupt length prefix or misbehaving peer "
            "(senders clamp too, so a legitimately large rejoin state "
            "would have failed at its sender: raise BYTEPS_BUS_MAX_FRAME "
            "on every member); failing the connection")
    data = b""
    while len(data) < n:
        chunk = sock.recv(min(65536, n - len(data)))
        if not chunk:
            raise _BusUnreachable("bus connection closed mid-frame")
        data += chunk
    if _integrity.is_frame(data):
        try:
            data, _ = _integrity.open_bytes(data)
        except _integrity.IntegrityError as e:
            counters.inc("integrity.crc_reject")
            raise _BusFrameError(
                f"bus frame failed integrity verification: {e}") from None
    try:
        return pickle.loads(data)
    except Exception as e:
        # a flip in the envelope's 4 magic bytes defeats the is_frame
        # sniff and lands the raw envelope (or otherwise-corrupt bytes)
        # here — that is still wire corruption and must fail through the
        # retriable _BusFrameError path, not an unclassified
        # UnpicklingError that skips the caller's backoff/close handling
        counters.inc("integrity.crc_reject")
        raise _BusFrameError(f"bus frame failed to unpickle: {e}") from None


def _membership_host_map() -> List[Tuple[str, Optional[int]]]:
    """BYTEPS_MEMBERSHIP_HOSTS parsed into per-rank ``(host, port)``
    entries (port None = use the default membership port).  Empty list
    when unset — the single-fixed-address deployments."""
    from ..common.config import get_config
    out: List[Tuple[str, Optional[int]]] = []
    for entry in get_config().membership_hosts.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if ":" in entry:
            host, port_s = entry.rsplit(":", 1)
            out.append((host, int(port_s)))
        else:
            out.append((entry, None))
    return out


def resolve_bus_addr(bus: Optional[str] = None,
                     view: Optional[MembershipView] = None) -> Tuple[str, int]:
    """``host:port`` of the membership bus — explicit arg, or resolved
    **from the view**: with ``BYTEPS_MEMBERSHIP_HOSTS`` set, the bus
    lives at the CURRENT coordinator's entry (so a coordinator failover
    moves the address with the coordinator); otherwise the static
    DMLC-root + BYTEPS_MEMBERSHIP_PORT resolution (single host: the
    successor re-binds the same address)."""
    from ..common.config import get_config
    if bus is not None:
        host, port_s = bus.rsplit(":", 1)
        return host, int(port_s)
    cfg = get_config()
    port = cfg.membership_port or (
        int(os.environ.get("DMLC_PS_ROOT_PORT", "9000")) + 2)
    if view is not None and view.world:
        hosts = _membership_host_map()
        coord = min(view.world)
        if hosts and coord < len(hosts):
            host, entry_port = hosts[coord]
            return host, (entry_port if entry_port is not None else port)
    return os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"), port


def bus_request(addr: Tuple[str, int], msg: dict,
                timeout: float = 10.0) -> dict:
    """One single-attempt request/reply round trip to the bus (no
    backoff — read-only observability callers like
    ``core/api.cluster_metrics`` / ``tools/bps_top.py`` decide their own
    retry cadence).  Raises :class:`_BusUnreachable` (a
    ``ConnectionError``) when nothing answers."""
    try:
        s = socket.create_connection(addr, timeout=min(timeout, 3.0))
    except OSError as e:
        raise _BusUnreachable(f"bus {addr}: {e}") from None
    try:
        s.settimeout(timeout)
        _send_obj(s, msg)
        return _recv_obj(s)
    except socket.timeout:
        raise MembershipTimeout(
            f"bus {msg.get('op')} timed out after {timeout:.1f}s") from None
    except _BusUnreachable:
        raise
    except OSError as e:
        raise _BusUnreachable(f"bus {addr}: {e}") from None
    finally:
        s.close()


def estimate_clock_offset(addr: Tuple[str, int],
                          samples: Optional[int] = None,
                          timeout: float = 2.0) -> Optional[Tuple[float,
                                                                  float]]:
    """NTP-style wall-clock offset of THIS process against the bus host
    (ISSUE 12, the merged cluster timeline): ``samples`` ping
    round-trips, each yielding ``offset = midpoint(local) - t_wall(bus)``;
    the minimum-RTT sample wins (its midpoint bounds the true offset
    tightest).  The estimate is published to
    :func:`byteps_tpu.common.tracing.set_clock_offset` so every trace
    file this process flushes carries it; returns ``(offset_s, err_s)``
    or None when no sample landed.  Cost: ``samples`` sub-ms TCP round
    trips — run at membership start and after coordinator changes, not
    per step."""
    from ..common import tracing as _tracing
    from ..common.config import get_config
    if samples is None:
        samples = get_config().clock_sync_samples
    best: Optional[Tuple[float, float]] = None   # (rtt, offset)
    for _ in range(max(0, samples)):
        t0 = time.time()
        try:
            reply = bus_request(tuple(addr), {"op": "ping"},
                                timeout=timeout)
        except (ConnectionError, MembershipTimeout):
            continue
        t1 = time.time()
        if not reply.get("ok") or "t_wall" not in reply:
            continue
        rtt = t1 - t0
        offset = (t0 + t1) / 2.0 - float(reply["t_wall"])
        if best is None or rtt < best[0]:
            best = (rtt, offset)
    if best is None:
        return None
    rtt, offset = best
    _tracing.set_clock_offset(offset, rtt / 2.0,
                              source="bus %s:%d" % tuple(addr))
    return offset, rtt / 2.0


class _BusServer:
    """The coordinator-side membership endpoint.

    State: the agreed ``(epoch, world)``, per-(epoch, step) sync
    payloads, per-epoch shrink hellos, and parked join requests.  Every
    verb parks its connection thread on one condition variable; any
    state transition (quorum complete, epoch advanced, join admitted)
    wakes everyone and each waiter re-evaluates its own predicate —
    the same pop-time re-evaluation discipline as the server engine's
    PriorityQueue.

    ``seed`` is a replica snapshot from a dead predecessor
    (:meth:`_replica_snapshot`): a bus born from one resumes at the
    replicated epoch/world with the replicated sync rounds,
    parked-joiner set, and metrics cache — the failover is a
    resumption, not a restart.  A stale seed (older epoch than the
    view being hosted) is ignored.
    """

    def __init__(self, addr: Tuple[str, int], view: MembershipView,
                 rendezvous_timeout_s: float, sync_timeout_s: float,
                 seed: Optional[dict] = None,
                 host_rank: Optional[int] = None):
        self.addr = addr
        self.host_rank = host_rank
        self.epoch = view.epoch
        self.world: Set[int] = set(view.world)
        self._rdv_timeout = rendezvous_timeout_s
        self._sync_timeout = sync_timeout_s
        self._cv = threading.Condition(
            named_lock("membership.bus", reentrant=True))
        # (epoch, step) -> {rank: payload}
        self._sync: Dict[Tuple[int, int], Dict[int, Any]] = {}
        # (epoch, step) -> (state bytes, declared names, state's step)
        self._snapshots: Dict[Tuple[int, int], Tuple[bytes, List[str], int]] = {}
        # proposed epoch -> {rank: proposed world}
        self._hellos: Dict[int, Dict[int, frozenset]] = {}
        # rank -> None (parked) | admission info dict
        self._join_wait: Dict[int, Optional[dict]] = {}
        # rank -> (wall time, metrics snapshot): the cross-rank
        # observability cache — members attach a compact snapshot to
        # every sync (and may metrics_put explicitly); the metrics verb
        # answers from here in one round-trip (core/api.cluster_metrics)
        self._metrics: Dict[int, Tuple[float, Any]] = {}
        # rank -> (wall time, windowed time-series summary): the history
        # cache (ISSUE 16) — compact window summaries piggybacked the
        # same way, so cluster_metrics grows a `history` view in the
        # SAME round-trip and the health engine's skew rule can compare
        # ranks without new verbs
        self._history: Dict[int, Tuple[float, Any]] = {}
        # -- gray-failure state (ISSUE 10, docs/gray_failures.md) ----------
        # The bus scores each rank's STEP-BARRIER ARRIVAL LAG: a
        # slow-but-alive rank completes every quorum, just last — the
        # one cross-rank signal that attributes "everyone waits on R".
        from ..common.config import get_config
        from ..utils.slowness import SlownessTracker
        cfg = get_config()
        # quorum-gated agreement (ISSUE 17): with the gossip plane on, a
        # shrink commits only when a strict majority of the last agreed
        # world answered the hello — the server-side half of the
        # split-brain gate (fault/gossip.py quorum_ok)
        self._quorum_gate = bool(getattr(cfg, "gossip_on", False))
        # the bus's gossip table (fault/gossip.py): every `gossip` verb
        # merges the caller's digest here and answers with the merged
        # table, so two ranks that never talk directly still converge
        # through the bus.  The hosting ElasticMembership installs its
        # own agent's table; a bare bus lazily builds a relay-only one.
        self.gossip_table = None
        self._straggler_policy = cfg.straggler_policy
        self._phi = cfg.slowness_phi
        self._demote_after = cfg.straggler_demote_after
        self._min_lag = cfg.straggler_min_lag_s
        self._slow = SlownessTracker(window=cfg.slowness_window)
        # (epoch, step) -> {rank: monotonic arrival}; rounds already
        # scored (scoring runs once per completed barrier)
        self._arrive: Dict[Tuple[int, int], Dict[int, float]] = {}
        self._scored: set = set()
        # (epoch, step) -> {rank: flow id}: causal-tracing ids members
        # attached to their syncs (ISSUE 12) — the bus closes each arc
        # when the barrier completes, so the merged cluster timeline
        # shows every rank's step flowing into ONE barrier span
        self._sync_trace: Dict[Tuple[int, int], Dict[int, int]] = {}
        self._slow_rounds: Dict[int, int] = {}   # consecutive slow barriers
        self._deadline_seen: Dict[int, int] = {}  # last seen trip counters
        # rank -> {"since": wall ts, "score": phi at demotion}: demoted
        # ranks awaiting recovery; cleared by rejoin admission
        self._probation: Dict[int, dict] = {}
        self._demote_pending: Optional[Tuple[int, int]] = None  # (epoch, rank)
        # -- serving-host directory (server/serving_tier.py) ---------------
        # host_id -> {"addr": (host, port), "ts": wall-clock refresh,
        # "ttl": seconds, "meta": {...}}.  A generation counter bumps on
        # every membership-visible change (join, leave, TTL expiry, addr
        # move) so ring consumers re-derive routing exactly when it
        # changed and never otherwise.  Wall-clock stamps deliberately:
        # the directory must survive a coordinator failover onto a
        # process with a different monotonic base.
        self._serve_hosts: Dict[int, dict] = {}
        self._serve_gen = 0
        self._serve_target: Optional[int] = None  # autoscaler proposal
        # gray-failing serving hosts the autoscaler excluded from
        # placement (SERVING-HOST ids — a different namespace from the
        # trainer-rank ``_probation`` above; the two must never leak
        # into each other).  Changing it bumps the generation so every
        # ring consumer re-routes the demoted arcs.
        self._serve_probation: set = set()
        # host ids mid graceful drain (launcher/reconciler.py): marked
        # by the host's own re-registration with draining=True; routers
        # and the publisher exclude them exactly like probation (the
        # transition bumps the generation), in-flight pulls finish, and
        # the final unregister clears the mark
        self._serve_draining: set = set()
        # scale-down victims the autoscaler PROPOSED (serve_scale
        # victims=[...]): the reconciler reads them from serve_dir and
        # drains exactly those hosts — cleared as each victim leaves
        self._serve_victims: set = set()
        # host_id -> wall time until which re-registration is refused: a
        # retired host whose CONTROL plane still heartbeats (the gray
        # failure: bus reachable, data plane dead) must not flap back
        # into every client's ring one beat after the publisher evicted
        # it
        self._serve_banned: Dict[int, float] = {}
        if seed and seed.get("epoch", -1) >= view.epoch:
            self.epoch = int(seed["epoch"])
            self.world = set(int(r) for r in (seed.get("world")
                                              or view.world))
            self._sync = {tuple(k): dict(v)
                          for k, v in (seed.get("sync") or {}).items()}
            # parked joiners re-park as None: their connections died with
            # the predecessor, but the ADMISSION intent survives — the
            # next sync reply advertises join_waiting and the retried
            # rejoin request lands on an already-armed bus.  (State
            # snapshots are deliberately not replicated — see
            # _replica_snapshot — so admission waits for the successor's
            # first state-carrying quorum.)
            self._join_wait = {int(r): None
                               for r in (seed.get("join_wait") or ())}
            self._metrics = {int(r): tuple(v)
                             for r, v in (seed.get("metrics") or {}).items()}
            # the history cache survives a coordinator failover with the
            # metrics cache — a postmortem that spans the failover must
            # still see the window leading into it
            self._history = {int(r): tuple(v)
                             for r, v in (seed.get("history") or {}).items()}
            # probation survives a coordinator failover: a demoted rank
            # must still be readmittable (and visible as demoted, not
            # forgotten) through the successor bus
            self._probation = {int(r): dict(v) for r, v in
                               (seed.get("probation") or {}).items()}
            # the serving-host directory survives the failover too — a
            # successor that forgot the tier would empty every client's
            # ring until each host's next re-registration
            srv = seed.get("serve") or {}
            self._serve_hosts = {int(h): dict(v) for h, v in
                                 (srv.get("hosts") or {}).items()}
            self._serve_gen = int(srv.get("gen", 0))
            self._serve_target = srv.get("target")
            self._serve_probation = {int(h) for h in
                                     (srv.get("probation") or ())}
            self._serve_draining = {int(h) for h in
                                    (srv.get("draining") or ())}
            self._serve_victims = {int(h) for h in
                                   (srv.get("victims") or ())}
            self._serve_banned = {int(h): float(t) for h, t in
                                  (srv.get("banned") or {}).items()}
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(addr)
        self._sock.listen(32)
        self._sock.settimeout(0.25)
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="bps-membership-bus")
        self._thread.start()
        # cross-rank judgment (common/health.py): the rank hosting this
        # bus holds every member's piggybacked window in-process, so the
        # skew rule runs here (and only here) with no extra round-trips
        from ..common import health as _health
        _health.set_cluster_history_provider(self._history_view)

    def _history_view(self) -> Dict[int, dict]:
        """``{rank: window summary}`` of the live world — the health
        engine's cluster-skew input (and the doctor's, over the bus)."""
        with self._cv:
            return {r: h for r, (_, h) in self._history.items()
                    if r in self.world and h}

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        from ..common import health as _health
        _health.clear_cluster_history_provider(self._history_view)
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        self._thread.join(timeout=2)
        self._sock.close()

    def view(self) -> MembershipView:
        with self._cv:
            return MembershipView(self.epoch, tuple(sorted(self.world)))

    # -- replication -------------------------------------------------------

    def _standby_rank(self) -> Optional[int]:
        """The next-lowest live rank — the replica target (caller holds
        the condition)."""
        w = sorted(self.world)
        return w[1] if len(w) > 1 else None

    def _replica_snapshot(self) -> dict:
        """Everything a successor needs to resume this bus (caller holds
        the condition) — a few KB of sync digests, the parked-joiner
        set, and the metrics cache.  Deliberately NOT the packed
        rejoin-state payloads (``_snapshots``): a whole model's
        parameters riding every standby reply would make a large state
        trip ``BYTEPS_BUS_MAX_FRAME`` and fail the healthy standby's
        step sync.  The successor instead re-advertises ``join_waiting``
        from the replicated park set and re-collects state at its next
        state-carrying quorum — the admission moves one boundary later,
        nothing is lost."""
        return {
            "epoch": self.epoch,
            "world": sorted(self.world),
            "sync": {k: dict(v) for k, v in self._sync.items()},
            "join_wait": sorted(r for r, v in self._join_wait.items()
                                if v is None),
            "metrics": dict(self._metrics),
            "history": dict(self._history),
            "probation": {r: dict(v) for r, v in self._probation.items()},
            "serve": {"hosts": {h: dict(v)
                                for h, v in self._serve_hosts.items()},
                      "gen": self._serve_gen,
                      "target": self._serve_target,
                      "probation": sorted(self._serve_probation),
                      # mid-drain marks and proposed victims survive a
                      # failover: a successor that forgot them would
                      # route new pulls back onto a host that is busy
                      # finishing its last ones and exiting
                      "draining": sorted(self._serve_draining),
                      "victims": sorted(self._serve_victims),
                      # wall-clock expiry stamps, valid on any host —
                      # without them a failover forgets the ban and a
                      # retired-but-heartbeating host flaps back into
                      # the ring through the successor bus
                      "banned": dict(self._serve_banned)},
        }

    # -- serving -----------------------------------------------------------

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True, name="bps-membership-conn")
            t.start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(self._sync_timeout + self._rdv_timeout + 30.0)
            msg = _recv_obj(conn)
            op = msg.get("op")
            # ranks-partition chaos: a caller across the cut never
            # reaches this bus — drop the request unanswered (the
            # client's connect/read timeout surfaces the silence), same
            # shape as a real severed control network
            from . import injector as _fault
            if (_fault.ENABLED and msg.get("rank") is not None
                    and _fault.edge_cut(int(msg["rank"]))):
                return
            if op == "sync":
                reply = self._do_sync(msg)
            elif op == "hello":
                reply = self._do_hello(msg)
            elif op == "rejoin":
                reply = self._do_rejoin(msg)
            elif op == "metrics_put":
                reply = self._do_metrics_put(msg)
            elif op == "metrics":
                reply = self._do_metrics()
            elif op == "replicate":
                reply = self._do_replicate()
            elif op == "ping":
                reply = self._do_ping()
            elif op == "serve_register":
                reply = self._do_serve_register(msg)
            elif op == "serve_unregister":
                reply = self._do_serve_unregister(msg)
            elif op == "serve_dir":
                reply = self._do_serve_dir()
            elif op == "serve_scale":
                reply = self._do_serve_scale(msg)
            elif op == "gossip":
                reply = self._do_gossip(msg)
            else:
                reply = {"ok": False, "error": f"unknown op {op!r}"}
            # replication piggyback: every reply to the STANDBY carries a
            # state snapshot — the standby pays one extra payload on
            # traffic it already sends, and the coordinator never opens a
            # connection of its own (no extra round trips, no push path
            # to keep alive)
            rank = msg.get("rank")
            if rank is not None and op != "replicate":
                with self._cv:
                    if rank == self._standby_rank():
                        reply = dict(reply)
                        reply["replica"] = self._replica_snapshot()
            try:
                _send_obj(conn, reply)
            except _BusFrameTooLarge as e:
                # the reply (e.g. a rejoin state snapshot) exceeds the
                # coordinator's BYTEPS_BUS_MAX_FRAME: a silent close
                # would have the joiner retry a deterministic failure
                # under backoff — answer with a small error naming the
                # knob instead, so the client fails fast and loudly
                get_logger().warning(
                    "membership bus: reply for op %r too large: %s", op, e)
                _send_obj(conn, {"ok": False, "error": str(e)})
        except Exception:  # noqa: BLE001 — a broken/dead client connection
            # must not take the bus down; the client side has its own
            # retry/timeout story
            get_logger().debug("membership bus: connection handler failed",
                               exc_info=True)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _stale_reply(self) -> dict:
        # probation rides every stale reply so a demoted rank that syncs
        # late (it raced the demote signal) learns it is demoted — not
        # evicted — from the same reply that tells it the world moved
        return {"ok": False, "stale": True, "epoch": self.epoch,
                "world": sorted(self.world),
                "probation": sorted(self._probation)}

    def _pending_rendezvous(self) -> Optional[int]:
        """The highest proposed epoch of an in-flight hello rendezvous
        (caller holds the condition), or None.  Members parked in a sync
        are released with ``reconcile=True`` so they JOIN the rendezvous
        instead of waiting out their quorum — failure evidence propagates
        through the bus faster than every member's own detector."""
        pending = [e for e in self._hellos if e > self.epoch]
        return max(pending) if pending else None

    # -- verb: sync (step barrier + payload all-gather + join admission) ---

    def _do_sync(self, msg: dict) -> dict:
        rank, epoch, step = msg["rank"], msg["epoch"], msg["step"]
        deadline = time.monotonic() + self._sync_timeout
        with self._cv:
            if msg.get("metrics") is not None:
                # observability piggyback: cached even for a stale-epoch
                # sync — a rank mid-transition is exactly one an operator
                # wants to see
                self._metrics[rank] = (time.time(), msg["metrics"])
            if msg.get("history") is not None:
                # the windowed time-series summary rides the same frame
                self._history[rank] = (time.time(), msg["history"])
            if epoch != self.epoch:
                return self._stale_reply()
            if (self._demote_pending is not None
                    and self._demote_pending[0] == self.epoch):
                # a demotion was decided this epoch: every member of the
                # doomed round (and any late sync) gets the signal
                return self._demote_reply()
            pe = self._pending_rendezvous()
            if pe is not None:
                # a shrink/reconcile rendezvous is in flight: this round
                # is doomed — tell the member to join the rendezvous now
                return {"ok": False, "reconcile": True, "pending_epoch": pe,
                        "epoch": self.epoch, "world": sorted(self.world)}
            key = (epoch, step)
            # arrival stamp: the straggler signal is WHEN each rank
            # reached this barrier relative to the round's first arrival
            self._arrive.setdefault(key, {})[rank] = time.monotonic()
            if msg.get("trace"):
                self._sync_trace.setdefault(key, {})[rank] = msg["trace"]
            self._sync.setdefault(key, {})[rank] = msg.get("payload")
            if msg.get("state") is not None:
                # the state a member carries at step s is its state
                # AFTER step s-1 — what a joiner admitted at this
                # boundary resumes from
                self._snapshots[key] = (msg["state"],
                                        list(msg.get("declared") or ()),
                                        step - 1)
            # memory hygiene: completed rounds more than a few steps old
            # can never gain another waiter
            for k in [k for k in self._sync if k[1] < step - 4]:
                self._sync.pop(k, None)
                self._snapshots.pop(k, None)
                self._arrive.pop(k, None)
                self._sync_trace.pop(k, None)
                self._scored.discard(k)
            self._cv.notify_all()
            while not self._stop.is_set():
                if self.epoch != epoch:
                    # a shrink or an admission moved the world while this
                    # round was parked: the payloads are void, retry the
                    # step at the new epoch
                    return self._stale_reply()
                if (self._demote_pending is not None
                        and self._demote_pending[0] == self.epoch):
                    return self._demote_reply()
                pe = self._pending_rendezvous()
                if pe is not None:
                    return {"ok": False, "reconcile": True,
                            "pending_epoch": pe, "epoch": self.epoch,
                            "world": sorted(self.world)}
                got = self._sync.get(key, {})
                joins_parked = any(v is None
                                   for v in self._join_wait.values())
                if set(got) >= self.world:
                    # gray-failure scoring on the COMPLETED barrier (one
                    # pass per round): may decide a demotion, in which
                    # case this round's reply IS the demote signal
                    self._score_round(key)
                    if (self._demote_pending is not None
                            and self._demote_pending[0] == self.epoch):
                        return self._demote_reply()
                    if joins_parked and key in self._snapshots:
                        self._admit(key)
                        continue  # epoch changed: loop → stale reply
                    # join_waiting tells members to attach state on the
                    # NEXT boundary — so the (expensive) state transfer
                    # happens only when someone is actually rejoining
                    return {"ok": True, "epoch": epoch,
                            "payloads": dict(got),
                            "join_waiting": joins_parked}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # quorum never completed: the missing members are
                    # failure evidence (the detector may be inert after
                    # its one firing) — the client turns this into a
                    # shrink
                    return {"ok": False, "timeout": True,
                            "missing": sorted(self.world - set(got)),
                            "epoch": self.epoch,
                            "world": sorted(self.world)}
                self._cv.wait(min(remaining, 0.25))
        return self._stale_reply()

    def _demote_reply(self) -> dict:
        """The demote signal every member of a doomed round receives
        (caller holds the condition): survivors turn it into
        ``ElasticMembership.demote`` (a shrink), the target into
        :class:`Demoted` (park on probation, recover, rejoin)."""
        return {"ok": False, "demote": self._demote_pending[1],
                "probation": sorted(self._probation),
                "epoch": self.epoch, "world": sorted(self.world)}

    def _emit_barrier_trace(self, key: Tuple[int, int]) -> None:
        """Close the round's cross-rank flow arcs (ISSUE 12): one
        ``bus.step_barrier`` span on this process's timeline covering
        first→last arrival, and a flow ``f`` per member that attached a
        trace id to its sync — the member emitted the matching ``s`` on
        ITS OWN timeline, so after ``tools/bps_trace.py`` merges the
        per-rank files each rank's step visibly flows into the one
        barrier that gated it.  Caller holds the condition; runs once
        per round (the ``_scored`` latch)."""
        flows = self._sync_trace.pop(key, None)
        if not flows:
            return
        try:
            from ..common import tracing as _tracing
            tr = _tracing.tracer()
            if not tr.active:
                return
            arrivals = self._arrive.get(key) or {}
            if not arrivals:
                return
            t_first = min(arrivals.values())
            t_last = max(max(arrivals.values()), t_first + 1e-5)
            epoch, step = key
            tr.record_traced(next(iter(flows.values())),
                             "bus.step_barrier", "bus/step_sync",
                             t_first, t_last, step=step, epoch=epoch,
                             ranks=sorted(flows))
            for r, fid in flows.items():
                ts = min(max(arrivals.get(r, t_last), t_first), t_last)
                tr.flow(fid, "f", "bus/step_sync", ts)
        except Exception:  # noqa: BLE001 — tracing is best-effort
            get_logger().debug("barrier trace emission failed",
                               exc_info=True)

    def _score_round(self, key: Tuple[int, int]) -> None:
        """Score one COMPLETED step barrier (caller holds the condition;
        runs once per round).

        Per-rank arrival lag against the round's first arrival feeds the
        bus-side phi tracker (``site="step_sync"``); the metrics
        piggyback contributes self-reported ``engine.sync_deadline_trips``
        deltas (a rank whose own units blow the data-path deadline is
        slow even if it somehow makes the barrier on time).  A rank is
        *slow this round* when its lag clears BOTH the absolute floor
        (``straggler_min_lag_s`` — phi self-calibrates, so an idle
        world's microsecond jitter must not score) and the phi threshold
        (``slowness_phi``), or when it reported fresh deadline trips.
        ``straggler_demote_after`` consecutive slow rounds under
        ``BYTEPS_STRAGGLER_POLICY=demote`` demote it — except the
        current coordinator (it hosts this bus; its slowness escalates
        through the crash-failover path instead)."""
        if key in self._scored:
            return
        self._scored.add(key)
        self._emit_barrier_trace(key)
        arrivals = self._arrive.get(key) or {}
        if len(arrivals) < 2:
            return
        first = min(arrivals.values())
        slow_now = set()
        for r, t in arrivals.items():
            lag = t - first
            self._slow.observe(r, lag, site="step_sync")
            ent = self._metrics.get(r)
            trips = 0
            if ent is not None and isinstance(ent[1], dict):
                trips = int((ent[1].get("counters") or {}).get(
                    "engine.sync_deadline_trips", 0) or 0)
            tripped = trips > self._deadline_seen.get(r, trips)
            if trips > self._deadline_seen.get(r, 0):
                self._deadline_seen[r] = trips
            if tripped or (lag >= self._min_lag
                           and self._slow.score(r, site="step_sync")
                           >= self._phi):
                slow_now.add(r)
        for r in arrivals:
            self._slow_rounds[r] = (self._slow_rounds.get(r, 0) + 1
                                    if r in slow_now else 0)
        if (self._straggler_policy != "demote"
                or self._demote_pending is not None
                or len(self.world) < 2):
            return
        coordinator = min(self.world)
        candidates = [r for r in sorted(slow_now)
                      if r in self.world and r != coordinator
                      and self._slow_rounds.get(r, 0) >= self._demote_after]
        if not candidates:
            return
        # one demotion at a time, worst straggler first: the world
        # change resets every counter, so a second straggler re-earns
        # its consecutive rounds under the new world
        target = max(candidates, key=lambda r: self._slow_rounds[r])
        score = round(self._slow.score(target, site="step_sync"), 2)
        self._probation[target] = {"since": time.time(), "score": score}
        self._demote_pending = (self.epoch, target)
        self._slow_rounds[target] = 0
        counters.inc("membership.straggler_demote_decided")
        _flight.record("membership.straggler_demote", rank=target,
                       epoch=self.epoch, score=score,
                       consecutive=self._demote_after)
        get_logger().error(
            "membership bus: rank %d is a sustained straggler (phi %.1f, "
            "%d consecutive slow barriers) — demoting to probation, world "
            "shrinks without it", target, score, self._demote_after)
        self._cv.notify_all()

    def _admit(self, key: Tuple[int, int]) -> None:
        """Admit every parked joiner at this completed step boundary
        (caller holds the condition)."""
        state, declared, state_step = self._snapshots[key]
        joiners = sorted(r for r, v in self._join_wait.items() if v is None)
        self.epoch += 1
        self.world |= set(joiners)
        info = {"epoch": self.epoch, "world": sorted(self.world),
                "declared": declared, "step": state_step, "state": state}
        for r in joiners:
            self._join_wait[r] = dict(info)
            # the joiner is a FRESH incarnation: its sync_deadline_trips
            # counter restarts, so the high-water mark from the dead
            # incarnation must go too — otherwise new trips stay masked
            # until they exceed the old lifetime total
            self._deadline_seen.pop(r, None)
            if self._probation.pop(r, None) is not None:
                # a demoted straggler came back healthy: readmission IS
                # the end of probation
                counters.inc("membership.probation_readmitted")
                _flight.record("membership.probation_readmitted",
                               rank=r, epoch=self.epoch)
                get_logger().warning(
                    "membership bus: rank %d readmitted from probation "
                    "(epoch %d)", r, self.epoch)
        counters.inc("membership.rejoin_admitted", len(joiners))
        # the admission moved the world: a stale pending demotion is
        # void and every consecutive-slow counter restarts (a rejoiner
        # must re-earn any accusation under the new world)
        if (self._demote_pending is not None
                and self.epoch > self._demote_pending[0]):
            self._demote_pending = None
        self._slow_rounds.clear()
        get_logger().warning(
            "membership bus: admitted rank(s) %s at step boundary %d — "
            "epoch %d, world %s", joiners, key[1], self.epoch,
            sorted(self.world))
        # void the old epoch's parked rounds
        self._sync = {k: v for k, v in self._sync.items()
                      if k[0] >= self.epoch}
        self._cv.notify_all()

    # -- verb: hello (the shrink rendezvous) -------------------------------

    def _do_hello(self, msg: dict) -> dict:
        rank = msg["rank"]
        proposed_epoch = msg["epoch"]
        proposed_world = frozenset(msg["world"])
        deadline = time.monotonic() + self._rdv_timeout
        with self._cv:
            if proposed_epoch <= self.epoch:
                # agreement already happened (or a stray old proposal):
                # the current view IS the answer
                return {"ok": True, "epoch": self.epoch,
                        "world": sorted(self.world)}
            self._hellos.setdefault(proposed_epoch, {})[rank] = proposed_world
            self._cv.notify_all()
            while not self._stop.is_set():
                if self.epoch >= proposed_epoch:
                    return {"ok": True, "epoch": self.epoch,
                            "world": sorted(self.world)}
                got = self._hellos.get(proposed_epoch, {})
                # the ranks every proposal agrees are alive must all
                # check in; a rank someone still believes dead but that
                # hellos anyway is alive by definition and joins the
                # agreed world
                expected = frozenset.intersection(*got.values())
                if set(got) >= expected:
                    if self._quorum_minority_locked(got):
                        return self._minority_reply(proposed_epoch, got)
                    self._agree(proposed_epoch, sorted(got))
                    return {"ok": True, "epoch": self.epoch,
                            "world": sorted(self.world)}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # double failure during the shrink: whoever never
                    # helloed inside the window is dropped too
                    if self._quorum_minority_locked(got):
                        return self._minority_reply(proposed_epoch, got)
                    get_logger().error(
                        "membership: rendezvous for epoch %d timed out "
                        "waiting for %s — proceeding with responders %s",
                        proposed_epoch, sorted(expected - set(got)),
                        sorted(got))
                    self._agree(proposed_epoch, sorted(got))
                    return {"ok": True, "epoch": self.epoch,
                            "world": sorted(self.world)}
                self._cv.wait(min(remaining, 0.25))
        return self._stale_reply()

    def _quorum_minority_locked(self, got) -> bool:
        """True when the quorum gate is armed and the rendezvous
        responders are NOT a strict majority of the last agreed world
        (caller holds the condition).  The server-side half of the
        split-brain proof: an agreement that this side of a partition
        could commit concurrently with the other side's is refused."""
        return self._quorum_gate and 2 * len(got) <= len(self.world)

    def _minority_reply(self, proposed_epoch: int, got) -> dict:
        """Refuse a minority agreement (caller holds the condition): no
        epoch advances; the caller parks (:class:`PartitionMinority`)."""
        counters.inc("membership.quorum_refused")
        _flight.record("membership.quorum_refused",
                       proposed_epoch=proposed_epoch,
                       responders=sorted(got),
                       world=sorted(self.world), epoch=self.epoch)
        get_logger().error(
            "membership bus: REFUSING epoch %d — responders %s are not "
            "a strict majority of the last agreed world %s (partition "
            "minority); parking instead of split-braining",
            proposed_epoch, sorted(got), sorted(self.world))
        return {"ok": False, "minority": True, "epoch": self.epoch,
                "world": sorted(self.world), "responders": sorted(got)}

    def _agree(self, epoch: int, world: List[int]) -> None:
        """Commit a shrink agreement (caller holds the condition)."""
        self.epoch = epoch
        self.world = set(world)
        # drop THIS agreement's proposals and any stragglers for already-
        # passed epochs — a lingering dead proposal would keep flagging
        # reconcile on every future sync
        self._hellos = {e: v for e, v in self._hellos.items() if e > epoch}
        # release every sync round parked under the dead epoch
        self._sync = {k: v for k, v in self._sync.items() if k[0] >= epoch}
        self._arrive = {k: v for k, v in self._arrive.items()
                        if k[0] >= epoch}
        self._sync_trace = {k: v for k, v in self._sync_trace.items()
                            if k[0] >= epoch}
        self._scored = {k for k in self._scored if k[0] >= epoch}
        # a pending demotion is consumed by the agreement that applied
        # it; consecutive-slow counters restart under the new world
        # (readmitted or resized worlds re-earn any accusation)
        if (self._demote_pending is not None
                and epoch > self._demote_pending[0]):
            self._demote_pending = None
        self._slow_rounds.clear()
        counters.inc("membership.shrink_agreed")
        get_logger().warning("membership bus: agreed epoch %d, world %s",
                             epoch, world)
        self._cv.notify_all()

    # -- verb: rejoin ------------------------------------------------------

    def _do_rejoin(self, msg: dict) -> dict:
        rank = msg["rank"]
        deadline = time.monotonic() + self._sync_timeout
        with self._cv:
            # (re)park — but never clobber an admission that already
            # landed: after a failover the seeded bus re-parks this
            # joiner from the replica, and a state-carrying quorum can
            # admit it BEFORE the retried rejoin reconnects.  The retry
            # must collect that admission (the wait loop below returns
            # it immediately), not overwrite it and stall the world on a
            # member that is still parked.
            self._join_wait.setdefault(rank, None)
            self._cv.notify_all()
            while not self._stop.is_set():
                info = self._join_wait.get(rank)
                if info is not None:
                    del self._join_wait[rank]
                    return {"ok": True, **info}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._join_wait.pop(rank, None)
                    return {"ok": False, "timeout": True}
                self._cv.wait(min(remaining, 0.25))
        return {"ok": False, "timeout": True}

    # -- verbs: metrics (cross-rank observability) -------------------------

    def _do_metrics_put(self, msg: dict) -> dict:
        """Store one rank's snapshot (the explicit form of the sync
        piggyback — background publishers and one-shot tools)."""
        with self._cv:
            self._metrics[msg["rank"]] = (time.time(), msg.get("metrics"))
            if msg.get("history") is not None:
                self._history[msg["rank"]] = (time.time(), msg["history"])
            return {"ok": True, "epoch": self.epoch,
                    "world": sorted(self.world)}

    def _do_metrics(self) -> dict:
        """Every live rank's latest snapshot in one reply.  Ranks outside
        the current world are pruned (their cache entries are residue of
        a shrink); age lets the caller judge freshness.  The reply names
        who hosts the control plane (coordinator / standby / the rank
        actually serving this bus) so ``bps_top`` can show it."""
        now = time.time()
        with self._cv:
            self._prune_serve_locked()
            # serving-host snapshots (rank >= SERVE_RANK_BASE) are kept
            # while their directory registration lives — they are not
            # members of the trainer world and must not be pruned as
            # shrink residue
            self._metrics = {
                r: v for r, v in self._metrics.items()
                if r in self.world
                or (r >= SERVE_RANK_BASE
                    and (r - SERVE_RANK_BASE) in self._serve_hosts)}
            return {"ok": True, "epoch": self.epoch,
                    "serve_gen": self._serve_gen,
                    # fleet reconciliation view (bps_top's banner and
                    # DRAINING rows): the autoscaler's target and who is
                    # mid-drain right now
                    "serve_target": self._serve_target,
                    "serve_draining": sorted(self._serve_draining),
                    "serve_hosts": {
                        h: {"addr": list(v["addr"]),
                            "age_s": round(now - v["ts"], 3)}
                        for h, v in self._serve_hosts.items()},
                    "world": sorted(self.world),
                    "coordinator": min(self.world) if self.world else None,
                    "standby": self._standby_rank(),
                    "bus_rank": self.host_rank,
                    # gray-failure view: per-rank step-barrier phi
                    # scores + who is demoted right now — bps_top's
                    # SLOW/STATE columns read these
                    "slow": {r: round(s, 2) for r, s in
                             self._slow.scores(site="step_sync").items()},
                    "probation": sorted(self._probation),
                    "ranks": {r: {"age_s": round(now - t, 3), "metrics": m}
                              for r, (t, m) in self._metrics.items()},
                    # the retention plane (ISSUE 16): each live rank's
                    # piggybacked window summary, same freshness rules
                    "history": {r: {"age_s": round(now - t, 3),
                                    "summary": h}
                                for r, (t, h) in self._history.items()
                                if r in self.world}}

    # -- verb: gossip (anti-entropy relay, fault/gossip.py) ----------------

    def _do_gossip(self, msg: dict) -> dict:
        """Merge the caller's gossip digest into the bus-side table and
        answer with the merged digest — one round trip is one
        anti-entropy exchange, so two ranks that never talk directly
        still converge through the bus.  Metrics/history payloads riding
        the digest seed the bus's own observability caches: the bus is a
        thin compatibility server FED from the gossip table, and
        ``cluster_metrics()``/``bps_top``/``bps_doctor`` keep working
        unchanged."""
        digest = msg.get("digest") or {}
        table = self.gossip_table
        if table is None:
            from .gossip import GossipTable
            with self._cv:
                if self.gossip_table is None:
                    rank = (self.host_rank if self.host_rank is not None
                            else (min(self.world) if self.world else 0))
                    self.gossip_table = GossipTable(rank,
                                                    sorted(self.world))
                table = self.gossip_table
        table.merge(digest)
        with self._cv:
            for kind, cache in (("metrics", self._metrics),
                                ("history", self._history)):
                for r, v in table.payloads_of_kind(kind).items():
                    if not isinstance(v, dict) or "t" not in v:
                        continue
                    cur = cache.get(r)
                    if cur is None or float(v["t"]) > cur[0]:
                        cache[r] = (float(v["t"]), v.get("v"))
        return {"ok": True, "epoch": self.epoch,
                "world": sorted(self.world), "digest": table.digest()}

    # -- verbs: replicate / ping (coordinator-failover support) ------------

    def _do_replicate(self) -> dict:
        """Explicit replica pull: a rank that just BECAME the standby
        (after a world change) bootstraps its copy instead of waiting for
        the next piggybacked reply."""
        with self._cv:
            return {"ok": True, "epoch": self.epoch,
                    "world": sorted(self.world),
                    "replica": self._replica_snapshot()}

    def _do_ping(self) -> dict:
        """Cheap liveness + control-plane identity probe (used by
        ``_ensure_bus`` to distinguish "someone already serves this
        address" from "the world is busless", and by tooling)."""
        with self._cv:
            return {"ok": True, "epoch": self.epoch,
                    "world": sorted(self.world),
                    "coordinator": min(self.world) if self.world else None,
                    "standby": self._standby_rank(),
                    "bus_rank": self.host_rank,
                    # wall-clock sample for the trace clock-offset
                    # estimator (ISSUE 12): stamped as late as possible
                    "t_wall": time.time(),
                    "probation": sorted(self._probation)}

    # -- verbs: serving-host directory (server/serving_tier.py) ------------

    def _prune_serve_locked(self) -> None:
        """Drop TTL-expired serving hosts (caller holds the condition).
        Expiry is a membership change: the generation bumps so every
        ring consumer re-routes the dead host's arc."""
        now = time.time()
        dead = [h for h, v in self._serve_hosts.items()
                if now - v["ts"] > v["ttl"]]
        for h in dead:
            del self._serve_hosts[h]
            # an expired host's drain/victim marks are residue — a
            # fresh registration under the same id must start clean
            self._serve_draining.discard(h)
            self._serve_victims.discard(h)
        if dead:
            self._serve_gen += 1

    def _do_serve_register(self, msg: dict) -> dict:
        """A serving host joins (or refreshes) the tier directory.
        ``host_id=None`` allocates the next free id; a re-registration
        at the same address refreshes the TTL without bumping the
        generation (steady-state heartbeats must not churn every
        client's ring)."""
        addr = tuple(msg["addr"])
        ttl = float(msg.get("ttl_s") or 10.0)
        now = time.time()
        with self._cv:
            self._prune_serve_locked()
            hid0 = msg.get("host_id")
            if hid0 is not None:
                until = self._serve_banned.get(int(hid0), 0.0)
                if until > now:
                    return {"ok": False, "banned": True,
                            "retry_after_s": round(until - now, 1),
                            "gen": self._serve_gen}
                self._serve_banned.pop(int(hid0), None)
            hid = msg.get("host_id")
            if hid is None:
                hid = (max(self._serve_hosts) + 1 if self._serve_hosts
                       else 0)
            hid = int(hid)
            prev = self._serve_hosts.get(hid)
            self._serve_hosts[hid] = {"addr": addr, "ts": time.time(),
                                      "ttl": ttl,
                                      "meta": dict(msg.get("meta") or {})}
            changed = prev is None or tuple(prev["addr"]) != addr
            # the drain mark rides the registration (the host flips
            # itself to DRAINING and keeps heartbeating the mark until
            # its final unregister); either transition is membership-
            # visible — routers must stop (or resume) sending new pulls
            # at the next gen-driven re-sync
            draining = bool(msg.get("draining"))
            if draining != (hid in self._serve_draining):
                if draining:
                    self._serve_draining.add(hid)
                else:
                    self._serve_draining.discard(hid)
                changed = True
            if changed:
                self._serve_gen += 1
            return {"ok": True, "host_id": hid, "gen": self._serve_gen,
                    "epoch": self.epoch}

    def _do_serve_unregister(self, msg: dict) -> dict:
        """A host leaves (clean shutdown, or the publisher/autoscaler
        retiring it after a failure streak) — its arc remaps NOW instead
        of at TTL expiry.  ``ban_s`` refuses re-registration for that
        window: an evicted-but-heartbeating host (data plane dead, bus
        reachable) must not flap straight back into the ring."""
        with self._cv:
            hid = int(msg["host_id"])
            if self._serve_hosts.pop(hid, None) is not None:
                self._serve_gen += 1
            # the drain handshake completes here: the departing host's
            # final unregister clears its mark (and its victim entry)
            self._serve_draining.discard(hid)
            self._serve_victims.discard(hid)
            ban = float(msg.get("ban_s") or 0.0)
            if ban > 0:
                self._serve_banned[hid] = time.time() + ban
            return {"ok": True, "gen": self._serve_gen}

    def _do_serve_dir(self) -> dict:
        """The tier directory in one round trip: generation, live hosts,
        the autoscaler's current target proposal, and the SERVING-HOST
        probation set (placement and routing exclude these — host ids,
        not trainer ranks)."""
        now = time.time()
        with self._cv:
            self._prune_serve_locked()
            return {"ok": True, "gen": self._serve_gen,
                    "epoch": self.epoch,
                    "target": self._serve_target,
                    "probation": sorted(self._serve_probation),
                    "draining": sorted(self._serve_draining),
                    "victims": sorted(self._serve_victims),
                    "hosts": {h: {"addr": list(v["addr"]),
                                  "age_s": round(now - v["ts"], 3),
                                  "meta": dict(v.get("meta") or {})}
                              for h, v in self._serve_hosts.items()}}

    def _do_serve_scale(self, msg: dict) -> dict:
        """Record the autoscaler's proposals: target tier size and/or
        the serving-host probation set.  The bus only CARRIES them —
        whoever launches host processes (an operator, serve_bench
        ``--hosts``, a k8s controller) reads the target from
        ``serve_dir`` and acts; routers and the publisher exclude the
        probationed hosts from their rings (the change bumps the
        generation so they all re-sync)."""
        with self._cv:
            if "target" in msg:
                t = msg["target"]
                self._serve_target = None if t is None else int(t)
            if "probation" in msg:
                new = {int(h) for h in (msg["probation"] or ())}
                if new != self._serve_probation:
                    self._serve_probation = new
                    self._serve_gen += 1
            if "victims" in msg:
                # scale-down victim PROPOSALS (autoscaler dispose mode):
                # carried, not acted on — the reconciler reads them from
                # serve_dir and runs the drain; no gen bump, routing
                # only changes when a victim actually flips to DRAINING
                self._serve_victims = {int(h)
                                       for h in (msg["victims"] or ())
                                       if int(h) in self._serve_hosts}
            return {"ok": True, "target": self._serve_target,
                    "probation": sorted(self._serve_probation),
                    "victims": sorted(self._serve_victims),
                    "gen": self._serve_gen}


# -- the per-process membership object --------------------------------------


class ElasticMembership:
    """One process's handle on the elastic world.

    Parameters
    ----------
    rank : this process's membership rank (the launcher's
        ``DMLC_WORKER_ID`` numbering — a per-process identity that
        exists before any JAX state, same convention as the fault
        injector).
    world : the initial member ranks.
    bus : ``host:port`` of the membership bus; defaults to
        ``DMLC_PS_ROOT_URI`` with ``BYTEPS_MEMBERSHIP_PORT`` (or
        coordinator port + 2).  The lowest-ranked live member hosts it.
    devices : devices for resumed meshes (passed through to
        ``api.resume``).
    assigner / server_engine / kv_store : optional attached components
        re-synced on every world change (``ServerAssigner.reshard``,
        ``set_membership_epoch``).
    on_world_change : callback run with the new :class:`MembershipView`
        after each applied change (keep it short — it can run on the
        detector thread).
    """

    def __init__(self, rank: int, world: Iterable[int],
                 bus: Optional[str] = None, *,
                 devices=None,
                 assigner=None, server_engine=None, kv_store=None,
                 on_world_change: Optional[Callable[[MembershipView],
                                                    None]] = None,
                 rendezvous_timeout_s: Optional[float] = None,
                 sync_timeout_s: Optional[float] = None,
                 retry: Optional[RetryPolicy] = None,
                 _view: Optional[MembershipView] = None):
        from ..common.config import get_config
        cfg = get_config()
        self.rank = int(rank)
        self._view = _view or MembershipView(
            current_epoch(), tuple(sorted(int(r) for r in world)))
        if self.rank not in self._view.world:
            raise ValueError(f"rank {self.rank} not in world "
                             f"{list(self._view.world)}")
        self._bus_arg = bus
        self.bus_addr = resolve_bus_addr(bus, self._view)
        # the rank serving bus_addr right now (moves with _ensure_bus's
        # re-resolution, AHEAD of the view during a failover rendezvous)
        self._bus_host_rank = self._view.coordinator
        self.devices = devices
        self.assigner = assigner
        self.server_engine = server_engine
        self.kv_store = kv_store
        self.on_world_change = on_world_change
        self.rendezvous_timeout_s = (
            cfg.membership_rendezvous_timeout_s
            if rendezvous_timeout_s is None else rendezvous_timeout_s)
        self.sync_timeout_s = (cfg.membership_sync_timeout_s
                               if sync_timeout_s is None else sync_timeout_s)
        # The bus client must ride out a coordinator FAILOVER: detection
        # (heartbeat timeout) + successor bind can span many short
        # connect-refused attempts, so the attempt budget is raised well
        # past the bootstrap default (BYTEPS_BUS_RETRIES — the
        # detection-vs-patience dial) and the retry deadline is the
        # real bound.
        self._retry = retry or RetryPolicy.from_config(
            cfg, retry_on=(_BusUnreachable,),
            max_attempts=max(cfg.retry_max_attempts, cfg.bus_retries))
        self._apply_lock = named_lock("membership.apply")
        self._ready_cv = threading.Condition()
        self._bus: Optional[_BusServer] = None
        # True once a sync reply advertised a parked joiner: the next
        # step_sync attaches the (expensive) state payload
        self._join_hint = False
        # the latest replica snapshot piggybacked to this rank while it
        # is the standby — the seed a failover bus resumes from
        self._replica: Optional[dict] = None
        # step_sync retries the trace clock-offset estimate while it is
        # missing, but BOUNDED: each failing attempt costs blocking ping
        # round trips, which must not tax every step barrier forever
        self._clock_retries = 0
        # membership-managed heartbeat (host_heartbeat): rebuilt on every
        # applied world change so the UDP server follows the coordinator
        self._hb = None
        self._hb_args: Optional[dict] = None
        # -- gossip plane (BYTEPS_GOSSIP_ON, fault/gossip.py) --------------
        self._gossip_on = bool(getattr(cfg, "gossip_on", False))
        self._gossip_table = None
        self._gossip_agent = None
        # True after a minority park: the engine is suspended and no
        # epoch was advanced on this side; cleared only by a successful
        # rejoin through a healed world
        self._parked = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ElasticMembership":
        """Adopt the initial view; host the bus when this rank is the
        coordinator."""
        global _active_ref
        set_epoch(self._view.epoch)
        self._ensure_bus(self._view)
        if self._gossip_on:
            self._start_gossip()
        _active_ref = weakref.ref(self)
        self._sync_clock()
        return self

    def stop(self) -> None:
        global _active_ref
        if self._gossip_agent is not None:
            self._gossip_agent.stop()
            self._gossip_agent = None
        if self._hb is not None:
            self._hb.stop()
            self._hb = None
        if self._bus is not None:
            self._bus.close()
            self._bus = None
        if _active_ref is not None and _active_ref() is self:
            _active_ref = None

    def _start_gossip(self) -> None:
        """Arm the SWIM plane: one table + one agent for this rank, the
        bus ``gossip`` verb as the wire, metrics/history snapshots as
        piggybacked payloads, and the health engine's ``quorum_loss``
        provider registered against the LAST AGREED world."""
        from .gossip import GossipAgent, GossipTable
        if self._gossip_table is None:
            self._gossip_table = GossipTable(self.rank, self._view.world)

        def wire(peer: int, digest: dict):
            # the bus is the exchange point; `peer` only scopes chaos
            # (a severed edge to the bus host is already honored in
            # _request) — the reply digest is the anti-entropy return
            reply = self._request({"op": "gossip", "rank": self.rank,
                                   "digest": digest},
                                  timeout=max(2.0, self.sync_timeout_s / 4))
            return reply.get("digest") if reply.get("ok") else None

        def payloads() -> dict:
            # refreshed once per gossip period; values are wall-stamped
            # ``{"t", "v"}`` pairs so _do_gossip can seed the bus caches
            # by freshness; None snapshots are skipped by the agent
            now = time.time()
            out = {}
            m = self._local_metrics()
            if m is not None:
                out["metrics"] = {"t": now, "v": m}
            h = self._local_history()
            if h is not None:
                out["history"] = {"t": now, "v": h}
            bus = self._bus
            if bus is not None:
                # the hosting rank also gossips the serving-tier
                # directory, so routers on the far side of a partition
                # can keep serving from the last-known host map
                try:
                    out["serve_dir"] = {"t": now, "v": bus._do_serve_dir()}
                except Exception:  # noqa: BLE001 — serving is optional
                    pass
            return out

        self._gossip_agent = GossipAgent(
            self._gossip_table, wire,
            world_fn=lambda: self._view.world,
            payload_fn=payloads)
        self._gossip_agent.register_health_provider()
        self._gossip_agent.start()
        if self._bus is not None:
            # the hosting rank's bus serves anti-entropy FROM this same
            # table: verb replies and the local agent converge as one
            self._bus.gossip_table = self._gossip_table

    @property
    def gossip(self):
        """The local :class:`~byteps_tpu.fault.gossip.GossipTable`
        (None unless BYTEPS_GOSSIP_ON armed it) — observability callers
        (cluster_metrics, bps_top) answer from it bus-free."""
        return self._gossip_table

    @property
    def parked(self) -> bool:
        """True while this rank sits parked on the minority side of a
        partition (engine suspended, no epoch agreed)."""
        return self._parked

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def view(self) -> MembershipView:
        return self._view

    def _sync_clock(self) -> None:
        """Trace-timeline clock alignment (ISSUE 12): estimate this
        rank's wall-clock offset against the bus host.  Gated on an
        active tracer — an untraced run must not pay the ping round
        trips — and entirely best-effort."""
        from ..common import tracing as _tracing
        from ..common.config import get_config
        try:
            if (get_config().clock_sync_samples <= 0
                    or not _tracing.tracer().active):
                return
            estimate_clock_offset(tuple(self.bus_addr))
        except Exception:  # noqa: BLE001 — alignment is best-effort
            get_logger().debug("clock-offset estimation failed",
                               exc_info=True)

    @property
    def is_coordinator(self) -> bool:
        return self.rank == self._view.coordinator

    @property
    def standby_rank(self) -> Optional[int]:
        """The next-lowest live rank — who takes over the bus if the
        coordinator dies (None in a solo world)."""
        w = self._view.world
        return w[1] if len(w) > 1 else None

    @property
    def hosting_bus(self) -> bool:
        """True when THIS process serves the membership bus."""
        return self._bus is not None

    @property
    def heartbeat(self):
        """The membership-managed :class:`HeartbeatMonitor`, if
        :meth:`host_heartbeat` armed one (rebuilt per world change)."""
        return self._hb

    def _ensure_bus(self, view: MembershipView,
                    prev_coordinator: Optional[int] = None) -> None:
        """Re-resolve the bus address for ``view`` (view-aware on
        multi-host, BYTEPS_MEMBERSHIP_HOSTS) and host the bus iff this
        rank is the coordinator of ``view`` and no bus is running here
        yet (idempotent; retried because a just-dead predecessor's
        socket may linger in TIME_WAIT).

        A new bus is **seeded with the replicated state** this rank
        collected as the standby — a coordinator failover resumes the
        mid-step sync round and the parked joiners instead of forgetting
        them — and the takeover is recorded
        (``membership.coordinator_failover`` counter + flight event).

        A bind that stays refused is not necessarily fatal: after a
        failover the old minimum rank can rejoin a world whose bus a
        surviving member already hosts at the fixed address — when the
        address *answers a ping*, this rank joins as a client.  When it
        does NOT answer, the world would be silently busless (every
        future request doomed to time out), so the failure is loud:
        counter + flight event + raise, letting the caller's escalation
        path (shrink failure → restartable exit) take over."""
        addr = resolve_bus_addr(self._bus_arg, view)
        self.bus_addr = addr
        self._bus_host_rank = min(view.world)
        if self.rank != min(view.world) or self._bus is not None:
            return
        if prev_coordinator is None:
            prev_coordinator = self._view.coordinator
        seed = self._replica

        def _bind():
            return _BusServer(addr, view,
                              self.rendezvous_timeout_s,
                              self.sync_timeout_s,
                              seed=seed, host_rank=self.rank)
        try:
            self._bus = RetryPolicy.from_config(
                retry_on=(OSError,)).call(_bind,
                                          describe="membership bus bind")
        except OSError as e:
            try:
                bus_request(addr, {"op": "ping"}, timeout=2.0)
                served = True
            except Exception:  # noqa: BLE001 — any failure means nobody
                served = False  # is usefully serving that address
            if served:
                _flight.record("membership.bus_already_served",
                               rank=self.rank, addr="%s:%d" % addr)
                get_logger().warning(
                    "membership: rank %d is the coordinator of %s but the "
                    "bus address %s:%d is already served (coordinator "
                    "failover kept it) — continuing as a bus client",
                    self.rank, list(view.world), *addr)
                return
            counters.inc("membership.bus_bind_failed")
            _flight.record("membership.bus_bind_failed", rank=self.rank,
                           addr="%s:%d" % addr, error=str(e))
            get_logger().error(
                "membership: rank %d could not bind the bus at %s:%d and "
                "nothing answers there — refusing to leave the world "
                "busless: %s", self.rank, addr[0], addr[1], e)
            raise
        if self._gossip_table is not None and self._bus is not None:
            # a failover successor's bus answers anti-entropy from the
            # SAME table its local agent already converged
            self._bus.gossip_table = self._gossip_table
        if prev_coordinator != self.rank:
            counters.inc("membership.coordinator_failover")
            _flight.record("membership.coordinator_failover",
                           new_coordinator=self.rank,
                           prev_coordinator=prev_coordinator,
                           seeded=seed is not None,
                           epoch=view.epoch, world=list(view.world))
            get_logger().warning(
                "membership: rank %d took over the bus at %s:%d from rank "
                "%s (%s replica state)", self.rank, *addr, prev_coordinator,
                "with" if seed is not None else "without")
        else:
            get_logger().info("membership: rank %d hosting the bus at "
                              "%s:%d", self.rank, *addr)

    # -- bus client --------------------------------------------------------

    def _request(self, msg: dict, timeout: float,
                 retry: Optional[RetryPolicy] = None) -> dict:
        """One request/reply round trip.  Connection-level failures (the
        coordinator died; its successor is still binding) are retried
        with full-jitter backoff; a read that exceeds ``timeout`` is a
        :class:`MembershipTimeout` and is NOT retried — the server
        answers its own timeouts explicitly.  ``retry`` overrides the
        default policy (the shrink path uses a rendezvous-bounded one so
        a dead successor is escalated, not waited out).

        Replica harvesting happens here: while this rank is the standby,
        every reply carries a piggybacked ``replica`` snapshot — it is
        stripped from the reply and cached as the failover seed."""
        def once():
            from . import injector as _fault
            # gate on the rank actually HOSTING the resolved address, not
            # the view's coordinator: during a failover shrink the hello
            # targets the PROPOSED successor while the view still names
            # the severed old coordinator — that edge must stay open
            if (_fault.ENABLED and self._bus is None
                    and _fault.edge_cut(self._bus_host_rank)):
                # ranks-partition chaos: the bus host is across the cut
                # — fail fast instead of waiting out a connect timeout
                # per retry (the real network would blackhole the SYN)
                raise _BusUnreachable(
                    f"bus {self.bus_addr}: severed by injected "
                    f"partition (chaos)")
            try:
                s = socket.create_connection(self.bus_addr, timeout=3.0)
            except OSError as e:
                raise _BusUnreachable(f"bus {self.bus_addr}: {e}") from None
            try:
                s.settimeout(timeout)
                _send_obj(s, msg)
                return _recv_obj(s)
            except socket.timeout:
                raise MembershipTimeout(
                    f"membership {msg.get('op')} timed out after "
                    f"{timeout:.1f}s") from None
            except _BusUnreachable:
                raise
            except OSError as e:
                raise _BusUnreachable(f"bus {self.bus_addr}: {e}") from None
            finally:
                s.close()
        reply = (retry or self._retry).call(
            once, describe=f"membership {msg.get('op')}")
        if isinstance(reply, dict) and "replica" in reply:
            self._replica = reply.pop("replica")
        return reply

    def _discover_bus(self) -> bool:
        """Multi-host rejoin helper: with BYTEPS_MEMBERSHIP_HOSTS set,
        probe entries in rank order and point ``bus_addr`` at the first
        one that answers a ping (the survivors' coordinator).  Returns
        False (keeping the static resolution) when no map is configured
        or nothing answers yet — the rejoin request's own backoff keeps
        retrying the resolved address."""
        _, default_port = resolve_bus_addr()   # the ONE port resolution
        for host_rank, (host, port) in enumerate(_membership_host_map()):
            addr = (host, port if port is not None else default_port)
            try:
                if bus_request(addr, {"op": "ping"}, timeout=2.0).get("ok"):
                    self.bus_addr = addr
                    self._bus_host_rank = host_rank
                    return True
            except Exception:  # noqa: BLE001 — dead entry, try the next
                continue
        return False

    def _pull_replica(self) -> bool:
        """Best-effort explicit replica fetch (the ``replicate`` verb) —
        run when this rank becomes the standby so the failover seed
        exists even before the next piggybacked reply."""
        try:
            reply = bus_request(self.bus_addr,
                                {"op": "replicate", "rank": self.rank},
                                timeout=3.0)
        except Exception:  # noqa: BLE001 — purely opportunistic
            return False
        if reply.get("ok") and reply.get("replica") is not None:
            self._replica = reply["replica"]
            return True
        return False

    def _declared_order(self) -> List[str]:
        from ..core import api
        if api.initialized():
            return api._require().registry.names_in_declaration_order()
        return list(api._declared_order)

    # -- cross-rank observability ------------------------------------------

    def _local_metrics(self) -> Optional[dict]:
        """The compact snapshot every sync piggybacks (None when
        telemetry is off or the snapshot itself fails — observability
        must never fail a step barrier)."""
        try:
            from ..common.config import get_config
            if not get_config().telemetry_on:
                return None
            from ..core import api
            return api.metrics_snapshot(light=True)
        except Exception:  # noqa: BLE001
            return None

    def _local_history(self) -> Optional[dict]:
        """The compact time-series window summary riding the same sync
        frame (ISSUE 16); None when the sampler is off or empty —
        history must never fail a step barrier either."""
        try:
            from ..common import timeseries as _ts
            store = _ts.get_store()
            if store is None:
                return None
            summ = store.summary()
            return summ if summ.get("n") else None
        except Exception:  # noqa: BLE001
            return None

    def publish_metrics(self) -> bool:
        """Best-effort explicit snapshot push (``metrics_put``) for
        processes between step barriers; returns False instead of
        raising when the bus is unreachable."""
        try:
            from ..core import api
            bus_request(self.bus_addr,
                        {"op": "metrics_put", "rank": self.rank,
                         "metrics": api.metrics_snapshot(light=True),
                         "history": self._local_history()},
                        timeout=5.0)
            return True
        except Exception:  # noqa: BLE001
            return False

    # -- heartbeat re-hosting ----------------------------------------------

    def host_heartbeat(self, interval: Optional[float] = None,
                       timeout: Optional[float] = None,
                       addr: Optional[str] = None,
                       grace: Optional[float] = None,
                       on_failure: Optional[Callable[[Set[int]], None]]
                       = None):
        """Arm membership-managed heartbeats: the CURRENT view's
        coordinator hosts the UDP server, every member beats to it, and
        after every applied world change the monitors are rebuilt for
        the new view — the new coordinator re-hosts the server,
        survivors re-point their beats, and the fired-once latch resets
        so the failure AFTER the failover is detected too.

        ``addr`` pins ``host:port`` (single-host deployments and tests);
        otherwise the host follows the coordinator's
        ``BYTEPS_MEMBERSHIP_HOSTS`` entry and the port is
        ``BYTEPS_HEARTBEAT_PORT`` (DMLC_PS_ROOT_PORT + 1).
        ``on_failure`` defaults to :meth:`on_failure` (shrink in
        place).  Returns the first monitor."""
        from ..common.config import get_config
        cfg = get_config()
        self._hb_args = {
            "interval": (cfg.heartbeat_interval_s if interval is None
                         else interval),
            "timeout": (cfg.heartbeat_timeout_s if timeout is None
                        else timeout),
            "grace": grace,
            "addr": addr,
            "on_failure": on_failure or self.on_failure,
        }
        self._restart_heartbeat(self._view)
        return self._hb

    def _heartbeat_addr(self, view: MembershipView) -> Tuple[str, int]:
        """The heartbeat endpoint for ``view``: host follows the
        coordinator (BYTEPS_MEMBERSHIP_HOSTS when set), port from the
        pinned ``addr`` or BYTEPS_HEARTBEAT_PORT."""
        host = port = None
        pinned = self._hb_args.get("addr") if self._hb_args else None
        if pinned:
            host, port_s = pinned.rsplit(":", 1)
            port = int(port_s)
        hosts = _membership_host_map()
        if hosts and view.coordinator < len(hosts):
            host = hosts[view.coordinator][0]
        if host is None:
            host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        if port is None:
            port = int(os.environ.get(
                # bpslint: ignore[env-knob] reason=default is derived from DMLC_PS_ROOT_PORT+1 per resolved view; a Config snapshot cannot express it and the bind validates the value
                "BYTEPS_HEARTBEAT_PORT",
                str(int(os.environ.get("DMLC_PS_ROOT_PORT", "9000")) + 1)))
        return host, port

    def _restart_heartbeat(self, view: MembershipView) -> None:
        """Rebuild the managed monitor for ``view`` (no-op unless
        :meth:`host_heartbeat` armed one).  Safe to call from the OLD
        monitor's own beat thread (the detector → shrink → apply path):
        ``stop()`` skips joining the calling thread."""
        if self._hb_args is None:
            return
        from ..utils.failure_detector import HeartbeatMonitor
        old, self._hb = self._hb, None
        if old is not None:
            old.stop()
        if view.num_workers < 2:
            # a solo world has no peer to watch; a rejoin re-arms via the
            # world-change path
            _flight.record("membership.heartbeat_idle", epoch=view.epoch)
            return
        host, port = self._heartbeat_addr(view)
        args = self._hb_args

        def _arm():
            return HeartbeatMonitor(
                self.rank, coordinator=f"{host}:{port}",
                interval=args["interval"], timeout=args["timeout"],
                grace=args["grace"], on_failure=args["on_failure"],
                ranks=view.world, server_rank=view.coordinator).start()
        # the UDP bind races the predecessor server's teardown (a peer
        # that has not applied the new view yet still holds the port);
        # ride it out with a persistent bounded retry
        self._hb = RetryPolicy.from_config(
            retry_on=(OSError,), max_attempts=50, deadline_s=10.0).call(
                _arm, describe="heartbeat rebind")
        counters.inc("membership.heartbeat_rehosted")
        _flight.record("membership.heartbeat_rehosted",
                       server_rank=view.coordinator, epoch=view.epoch,
                       addr=f"{host}:{port}")
        get_logger().warning(
            "membership: heartbeat re-hosted — rank %d serves %s:%d for "
            "world %s (epoch %d)", view.coordinator, host, port,
            list(view.world), view.epoch)

    # -- the step barrier / all-gather ------------------------------------

    def step_sync(self, step: int, payload: Any = None,
                  state: Any = None) -> Tuple[MembershipView, Dict[int, Any]]:
        """Synchronize step ``step`` with every live member.

        Returns ``(view, payloads)`` where ``payloads`` maps rank →
        the small control-plane payload each member posted.  ``state``
        (a checkpoint-style pytree, or pre-packed bytes) is what a
        parked rejoiner would be admitted with; pass it every step to
        make any step a potential rejoin barrier.  It is only
        materialized and shipped when the bus has advertised a parked
        joiner (the previous sync reply's ``join_waiting``), so the
        per-step cost of the offer is one ignored keyword — the real
        pack/transfer happens on the one boundary that needs it
        (admission therefore lands on the *second* quorum after a
        rejoin request).

        Raises :class:`WorldChanged` when the epoch moved — by then the
        local engine has already been suspended/resumed onto the new
        world, so the caller just retries the step.  A quorum timeout
        with missing members is treated as failure evidence and turned
        into a shrink (the heartbeat detector fires only once; this is
        the detection path for failures *after* the first).
        """
        view = self._view
        # causal tracing (ISSUE 12): attach a flow id so the bus can
        # close the arc when the barrier completes — the ONE hop that
        # genuinely crosses rank boundaries today
        from ..common import tracing as _tracing
        _tr = _tracing.tracer()
        _tctx = _tr.maybe_sample("step_sync") if _tr.active else None
        _t_sync0 = time.monotonic()
        msg: Dict[str, Any] = {"op": "sync", "rank": self.rank,
                               "epoch": view.epoch, "step": step,
                               "payload": payload,
                               "metrics": self._local_metrics(),
                               "history": self._local_history()}
        if _tctx is not None:
            msg["trace"] = _tctx.trace_id
        if state is not None and self._join_hint:
            if not isinstance(state, bytes):
                from ..utils.checkpoint import pack_state
                # seal=False: the bus frame (_send_obj) already envelopes
                # this whole message — double-sealing a multi-GB state
                # would double the rejoin's CRC and copy cost
                state = pack_state(state, seal=False)
            msg["state"] = state
            msg["declared"] = self._declared_order()
        reply = self._request(msg, timeout=self.sync_timeout_s + 15.0)
        if reply.get("ok"):
            self._join_hint = bool(reply.get("join_waiting"))
            if (_tr.active and self._clock_retries < 3
                    and _tracing.clock_offset()["offset_s"] is None):
                # start()'s estimate can race the coordinator's bus
                # bind (nothing answered pings yet); the bus just
                # answered a sync, so the estimate usually lands on the
                # first retry — bounded at 3 attempts so a network that
                # syncs-but-drops-pings cannot tax every later barrier
                # with the full ping budget
                self._clock_retries += 1
                self._sync_clock()
            if _tctx is not None:
                # emitted only for a COMPLETED round: the bus registered
                # the id and closed the arc with its ``f``, so the ``s``
                # here never dangles (a retried/stale sync gets a fresh
                # id next attempt)
                now = time.monotonic()
                _tr.record_traced(_tctx.trace_id, "membership.step_sync",
                                  "membership", _t_sync0, now, step=step,
                                  epoch=view.epoch, rank=self.rank)
                _tr.flow(_tctx.trace_id, "s", "membership", _t_sync0)
            return self._view, reply["payloads"]
        if reply.get("stale"):
            new = MembershipView(reply["epoch"], tuple(reply["world"]))
            if self.rank not in new.world:
                if self.rank in set(reply.get("probation") or ()):
                    # demoted, not dead: a probation rank that syncs
                    # again (it raced the demote signal, or retried)
                    # must not exit restartable — it recovers and
                    # rejoins instead
                    raise Demoted(new, reply.get("probation") or ())
                raise Evicted(
                    f"rank {self.rank} is outside the agreed world "
                    f"{list(new.world)} (epoch {new.epoch})")
            self._maybe_apply(new)
            raise WorldChanged(new)
        if reply.get("demote") is not None:
            # the bus demoted a sustained straggler out of this round:
            # nobody consumes the round's payloads — the target parks on
            # probation, every survivor applies the demotion (a shrink)
            # and retries the step at the new epoch
            target = int(reply["demote"])
            cur = MembershipView(reply["epoch"], tuple(reply["world"]))
            if target == self.rank:
                counters.inc("membership.demoted")
                _flight.record("membership.demoted", rank=self.rank,
                               epoch=cur.epoch,
                               probation=list(reply.get("probation") or ()))
                get_logger().error(
                    "membership: this rank (%d) was demoted to probation "
                    "as a sustained straggler — recover locally, then "
                    "rejoin()", self.rank)
                raise Demoted(cur, reply.get("probation") or ())
            raise WorldChanged(self.demote(target))
        if reply.get("reconcile"):
            # a shrink/reconcile rendezvous is already in flight on the
            # bus: join it instead of waiting out a doomed quorum — this
            # is how failure evidence reaches members whose own detector
            # has not fired (or already spent its one firing)
            get_logger().warning(
                "membership: step %d sync found a pending rendezvous for "
                "epoch %s — joining it", step, reply.get("pending_epoch"))
            new = self.reconcile()
            raise WorldChanged(new)
        if reply.get("timeout"):
            missing = set(reply.get("missing") or ())
            if missing:
                get_logger().error(
                    "membership: step %d sync timed out; missing rank(s) "
                    "%s treated as failed", step, sorted(missing))
                return_view = self.shrink(missing)
                raise WorldChanged(return_view)
            raise MembershipTimeout(f"step {step} sync timed out")
        raise RuntimeError(f"membership sync failed: {reply!r}")

    # -- shrink ------------------------------------------------------------

    def on_failure(self, stale: Set[int]) -> None:
        """Failure-action entry point (``HeartbeatMonitor.on_failure``,
        ``install_failure_action``): shrink in place when the evidence
        names ranks; an EMPTY set is wedge evidence without a suspect
        (a data-path deadline / step-watchdog trip,
        ``failure_detector.data_path_stalled``) and becomes a
        :meth:`reconcile` — the rendezvous identifies who is gone.
        Escalate to the restartable exit only when the transition itself
        fails (launcher supervision is the outer loop, as with
        ``RecoveryCoordinator``)."""
        try:
            if stale:
                self.shrink(set(stale))
            else:
                self.reconcile()
        except PartitionMinority:
            # NOT a failure exit: this rank parked on the minority side
            # of a partition (engine suspended, no epoch agreed).  The
            # training thread observes the park at its next step_sync;
            # the process stays up to rejoin when the partition heals.
            return
        except Exception:  # noqa: BLE001 — end of the in-process line
            counters.inc("membership.shrink_failed")
            from ..utils.failure_detector import _failure_exit_code
            code = _failure_exit_code()
            get_logger().error(
                "elastic transition failed — exiting %d so the launcher "
                "can restart", code, exc_info=True)
            _exit(code)

    def demote(self, rank: int) -> MembershipView:
        """Apply a bus-decided straggler demotion: move ``rank`` out of
        the data-path world onto probation, reusing shrink-to-survivors
        wholesale — the epoch guard, drain/suspend, rendezvous, and
        resume are exactly a shrink's.  The difference is entirely in
        bookkeeping and intent: the bus keeps the rank on its probation
        list (it is slow, not dead), the rank itself got :class:`Demoted`
        instead of :class:`Evicted`, and it returns through the ordinary
        :meth:`rejoin` admission once ``utils.slowness.wait_recovered``
        says its local data path is healthy again."""
        rank = int(rank)
        counters.inc("membership.straggler_demote")
        _flight.record("membership.straggler_demote_applied",
                       rank=rank, by=self.rank, epoch=self._view.epoch)
        get_logger().warning(
            "membership: demoting straggler rank %d to probation "
            "(shrink-to-survivors; it rejoins when healthy)", rank)
        return self.shrink({rank})

    def _park_minority(self, view: MembershipView, proposed_world,
                       reachable) -> None:
        """Park this rank on the minority side of a partition: engine
        suspended, ``membership.partition_minority`` counter + flight
        event, gossip state ``parked`` — and raise
        :class:`PartitionMinority`.  No epoch is agreed (or even
        proposed further) on this side; the rank returns through the
        ordinary :meth:`rejoin` path when the partition heals."""
        self._parked = True
        counters.inc("membership.partition_minority")
        _flight.record("membership.partition_minority",
                       rank=self.rank, epoch=view.epoch,
                       world=list(view.world),
                       reachable=sorted(reachable),
                       proposed=sorted(proposed_world))
        get_logger().error(
            "membership: rank %d is on the MINORITY side of a partition "
            "(reachable %s of last agreed world %s) — parking; rejoin "
            "when the partition heals", self.rank, sorted(reachable),
            list(view.world))
        if self._gossip_table is not None:
            from .gossip import PARKED
            self._gossip_table.mark(self.rank, PARKED)
        from ..core import api
        if api.initialized():
            api.suspend()
        raise PartitionMinority(view, reachable, proposed_world)

    def shrink(self, stale: Set[int]) -> MembershipView:
        """Drop ``stale`` ranks: epoch guard up → drain/suspend →
        epoch-tagged rendezvous → resume at the survivor world.

        Coordinator failover is part of the rendezvous: if the dead set
        includes the old coordinator, the lowest surviving rank hosts
        the bus (seeded with its standby replica) before helloing to
        itself; everyone else's connect rides backoff until the new bus
        is up.  If the would-be coordinator never serves the bus inside
        the rendezvous window — it died too, mid-failover — it is
        presumed dead, dropped from the proposal, and the ladder
        descends until this rank either reaches a live bus or hosts one
        itself.  (A presumed-dead rank that is merely slow self-heals:
        its own hello marks it alive and the agreement re-admits it.)"""
        view = self._view
        stale = set(stale) & set(view.world)
        if not stale:
            # a late detection of ranks an earlier shrink already
            # removed — the world is current, nothing to do
            return view
        proposed_world = tuple(r for r in view.world if r not in stale)
        proposed_epoch = view.epoch + 1
        if self.rank not in proposed_world:
            raise Evicted(f"rank {self.rank} was declared stale by its "
                          "own detector input")
        if self._gossip_on:
            # quorum gate, BEFORE the epoch guard goes up: a minority
            # proposal must not even stamp a local epoch — park instead
            # (the other side of the partition may be committing the
            # legitimate successor world right now)
            from .gossip import quorum_ok
            if not quorum_ok(proposed_world, view.world):
                self._park_minority(view, proposed_world, proposed_world)
        counters.inc("membership.shrink_started")
        _flight.record("membership.shrink_started", stale=sorted(stale),
                       proposed_epoch=proposed_epoch,
                       proposed_world=list(proposed_world))
        t0 = time.monotonic()
        get_logger().error(
            "membership: rank(s) %s lost — shrinking to %s (epoch %d)",
            sorted(stale), list(proposed_world), proposed_epoch)
        # Guard first: from here every in-flight chunk is stale and gets
        # dropped at dispatch/finish instead of delivered, so the drain
        # below is fast and the results of a half-dead collective never
        # reach a callback.
        set_epoch(proposed_epoch)
        from ..core import api
        if api.initialized():
            api.suspend()
        from ..common.config import get_config
        while True:
            self._ensure_bus(MembershipView(view.epoch, proposed_world),
                             prev_coordinator=view.coordinator)
            # bounded hello: the proposed coordinator gets one rendezvous
            # window to serve the bus; past it, unreachability IS the
            # evidence it died mid-failover
            hello_retry = RetryPolicy.from_config(
                get_config(), retry_on=(_BusUnreachable,),
                max_attempts=get_config().bus_retries,
                deadline_s=max(self.rendezvous_timeout_s, 2.0))
            try:
                reply = self._request(
                    {"op": "hello", "rank": self.rank,
                     "epoch": proposed_epoch,
                     "world": list(proposed_world)},
                    timeout=self.rendezvous_timeout_s + 15.0,
                    retry=hello_retry)
                break
            except _BusUnreachable:
                dead_coord = min(proposed_world)
                if dead_coord == self.rank:
                    # we host the bus ourselves and it is unreachable:
                    # nothing left to escalate to
                    raise
                counters.inc("membership.coordinator_presumed_dead")
                _flight.record("membership.coordinator_presumed_dead",
                               rank=dead_coord,
                               proposed_epoch=proposed_epoch)
                get_logger().error(
                    "membership: proposed coordinator %d never served the "
                    "bus within the rendezvous window — presuming it dead "
                    "too and escalating", dead_coord)
                stale.add(dead_coord)
                proposed_world = tuple(r for r in proposed_world
                                       if r != dead_coord)
                if self.rank not in proposed_world:
                    raise Evicted(
                        f"rank {self.rank} has no surviving world left "
                        f"(every lower rank is unreachable)")
                if self._gossip_on:
                    # the ladder descended below quorum: every bus this
                    # side can reach is gone — a partition, not a pile
                    # of dead coordinators; park instead of committing
                    from .gossip import quorum_ok
                    if not quorum_ok(proposed_world, view.world):
                        self._park_minority(view, proposed_world,
                                            proposed_world)
        if reply.get("minority"):
            # the server-side gate refused the agreement: our local
            # evidence said majority, the actual rendezvous responders
            # were not one (the backstop half of the split-brain proof)
            self._park_minority(view, proposed_world,
                                reply.get("responders") or ())
        agreed = MembershipView(reply["epoch"], tuple(reply["world"]))
        if self.rank not in agreed.world:
            raise Evicted(f"rank {self.rank} is outside the agreed world "
                          f"{list(agreed.world)}")
        out = self._maybe_apply(agreed)
        get_logger().warning(
            "membership: shrink complete in %.2fs — epoch %d, world %s",
            time.monotonic() - t0, out.epoch, list(out.world))
        return out

    def reconcile(self) -> MembershipView:
        """Failure evidence WITHOUT a named suspect (a data-path
        deadline trip, a wedged collective): re-run the rendezvous over
        the CURRENT world at the next epoch.  Every live member joins —
        parked step_syncs are released with ``reconcile=True`` and hello
        too — while a wedged-dead member never checks in and is dropped
        by the rendezvous timeout.  If everyone answers (a transient
        stall, a false alarm) the world re-agrees unchanged at the new
        epoch and training continues.

        The epoch guard goes up FIRST, so the wedged unit's eventual
        result (if it ever lands) is dropped as stale; the engine itself
        stays up through the rendezvous — suspending here would block on
        the very unit that is wedged — and :meth:`_maybe_apply` performs
        the bounded suspend/resume once the agreement is in hand.  Work
        enqueued during the window is stamped with the proposed epoch
        and rides the old mesh: harmless when the world re-agrees
        unchanged, part of the same wedge when it does not."""
        view = self._view
        proposed_epoch = view.epoch + 1
        if current_epoch() >= proposed_epoch:
            # another thread (a detector shrink, a peer-driven apply) is
            # already moving the world — follow it instead of competing
            return self.wait_ready(
                current_epoch(),
                timeout=self.rendezvous_timeout_s + self.sync_timeout_s)
        counters.inc("membership.reconcile_started")
        _flight.record("membership.reconcile_started",
                       epoch=proposed_epoch, world=list(view.world))
        get_logger().error(
            "membership: reconcile — re-agreeing world %s at epoch %d on "
            "data-path failure evidence", list(view.world), proposed_epoch)
        set_epoch(proposed_epoch)
        try:
            self._ensure_bus(view)
            reply = self._request(
                {"op": "hello", "rank": self.rank, "epoch": proposed_epoch,
                 "world": list(view.world)},
                timeout=self.rendezvous_timeout_s + 15.0)
        except (_BusUnreachable, OSError):
            # the bus itself is unreachable: the wedge evidence and the
            # dead coordinator point at the same process — name it and
            # take the shrink path (which owns the failover escalation)
            coord = view.coordinator
            if coord == self.rank:
                raise
            get_logger().error(
                "membership: reconcile could not reach the bus — treating "
                "coordinator %d as failed", coord)
            return self.shrink({coord})
        if reply.get("minority"):
            # the bus answered but refused: this side of a partition
            # mustered only a minority at the rendezvous — park
            self._park_minority(view, view.world,
                                reply.get("responders") or ())
        agreed = MembershipView(reply["epoch"], tuple(reply["world"]))
        if self.rank not in agreed.world:
            raise Evicted(f"rank {self.rank} is outside the agreed world "
                          f"{list(agreed.world)}")
        return self._maybe_apply(agreed)

    # -- applying an agreed view ------------------------------------------

    def _maybe_apply(self, view: MembershipView) -> MembershipView:
        """Re-point this process at ``view``: advance the epoch, rebuild
        mesh+engine on the new world size, re-shard attached components.
        Idempotent and monotonic — concurrent appliers (detector thread
        vs a trainer thread that saw a stale sync reply) serialize here
        and the second is a no-op."""
        with self._apply_lock:
            old = self._view
            if view.epoch <= old.epoch:
                return old
            t0 = time.monotonic()
            grew = len(view.world) > len(old.world)
            set_epoch(view.epoch)
            from ..core import api
            if api.initialized():
                api.suspend()
            _resume_for_world(view, self.devices)
            self._view = view
            if self.assigner is not None:
                try:
                    self.assigner.reshard(view.num_workers)
                except Exception:  # noqa: BLE001 — a shape the shrunk
                    # world can't satisfy must not kill a healthy
                    # survivor; routing keeps the old map, service
                    # survives (mixed-mode assigners need an explicit
                    # reshard(num_servers, num_workers) from
                    # on_world_change — the split is deployment-specific)
                    get_logger().error(
                        "membership: ServerAssigner reshard to %d failed; "
                        "keeping the previous assignment (drive "
                        "reshard() from on_world_change for mixed mode)",
                        view.num_workers, exc_info=True)
            if self.server_engine is not None:
                self.server_engine.set_membership_epoch(view.epoch)
            if self.kv_store is not None:
                self.kv_store.set_membership_epoch(view.epoch)
            # serving plane: re-clamp replica endpoints + rebuild
            # replica sets for the new world (a dead replica's hot keys
            # degrade to primary reads; never an erroring read path)
            from ..server import serving as _serving
            _serving.notify_world_change(view)
            self._ensure_bus(view, prev_coordinator=old.coordinator)
            if view.coordinator != old.coordinator:
                # the clock reference moved with the coordinator: later
                # trace flushes must carry the offset to the NEW bus
                self._sync_clock()
            # heartbeat re-hosting: the UDP server follows the NEW
            # coordinator and every survivor re-points its beats; fresh
            # monitors also reset the fired-once latch, so "rank 0 down"
            # leaves a world that still detects the NEXT failure
            self._restart_heartbeat(view)
            if self.rank == self.standby_rank:
                # just became (or stayed) the standby of a changed world:
                # bootstrap the replica now instead of waiting for the
                # next piggybacked reply
                self._pull_replica()
            counters.inc("membership.grow" if grew else "membership.shrink")
            _flight.record("membership.applied", epoch=view.epoch,
                           world=list(view.world), grew=grew)
            self._record_span("rejoin" if grew else "shrink", t0, view)
            get_logger().warning(
                "membership: now epoch %d, world %s (%d worker(s))",
                view.epoch, list(view.world), view.num_workers)
        with self._ready_cv:
            self._ready_cv.notify_all()
        if self.on_world_change is not None:
            try:
                self.on_world_change(view)
            except Exception:  # noqa: BLE001 — the transition itself
                # succeeded; a broken user callback must not undo that
                get_logger().error("on_world_change callback raised",
                                   exc_info=True)
        return view

    def wait_ready(self, epoch: int,
                   timeout: Optional[float] = None) -> MembershipView:
        """Block until the local view reaches ``epoch`` (trainer-side
        helper for exception paths where the applying thread is
        elsewhere)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._ready_cv:
            while self._view.epoch < epoch:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise MembershipTimeout(
                        f"world change to epoch {epoch} not applied "
                        f"locally within {timeout:.1f}s")
                self._ready_cv.wait(0.1 if remaining is None
                                    else min(remaining, 0.1))
        return self._view

    def _record_span(self, name: str, t0: float,
                     view: MembershipView) -> None:
        """Membership transition span into the *resumed* engine's tracer
        (same placement as RecoveryCoordinator._record_span)."""
        try:
            from ..core import api
            eng = api._require()
        except Exception:  # noqa: BLE001 — tracing is best-effort
            return
        eng.tracer.record_span(name, t0, time.monotonic(),
                               epoch=view.epoch, world=list(view.world))

    # -- rejoin ------------------------------------------------------------

    @classmethod
    def rejoin(cls, rank: int, bus: Optional[str] = None, *,
               devices=None, timeout: Optional[float] = None,
               **kwargs) -> Tuple["ElasticMembership", Optional[int], Any]:
        """Rejoin a running world from a fresh process.

        Parks on the bus until the survivors pass a step boundary, then
        adopts the agreed epoch, re-declares every tensor in the
        received declared-key order (identical key assignment), resumes
        the engine at the grown world size, and returns
        ``(membership, step, state)`` — ``state`` is the survivors'
        in-memory parameters (``utils.checkpoint.unpack_state``), the
        elastic counterpart of restore-then-broadcast, and ``step`` the
        training step it corresponds to.
        """
        counters.inc("membership.rejoin_requested")
        t0 = time.monotonic()
        probe = cls(rank, [rank], bus, devices=devices, **kwargs)
        if bus is None:
            # a rejoiner does not know the current coordinator (its solo
            # probe view resolves to its OWN host-map entry); with a host
            # map configured, ping entries in rank order and park on the
            # first bus that answers
            probe._discover_bus()
        wait_s = probe.sync_timeout_s if timeout is None else timeout
        reply = probe._request({"op": "rejoin", "rank": int(rank)},
                               timeout=wait_s + 15.0)
        if not reply.get("ok"):
            raise MembershipTimeout(
                f"rejoin of rank {rank} was not admitted: {reply!r}")
        view = MembershipView(reply["epoch"], tuple(reply["world"]))
        set_epoch(view.epoch)
        from ..core import api
        for name in reply.get("declared") or ():
            api.declare(name)   # original order ⇒ identical keys
        _resume_for_world(view, devices)
        probe._view = view
        probe._ensure_bus(view)   # no-op unless this rank is coordinator
        state = None
        if reply.get("state") is not None:
            from ..utils.checkpoint import unpack_state
            state = unpack_state(reply["state"])
        counters.inc("membership.rejoined")
        _flight.record("membership.rejoined", rank=int(rank),
                       epoch=view.epoch, world=list(view.world),
                       step=reply.get("step"))
        global _active_ref
        _active_ref = weakref.ref(probe)
        probe._record_span("rejoin", t0, view)
        get_logger().warning(
            "membership: rank %d rejoined at epoch %d, world %s, step %s",
            rank, view.epoch, list(view.world), reply.get("step"))
        return probe, reply.get("step"), state


def _resume_for_world(view: MembershipView, devices) -> None:
    """Resume the engine for the agreed world.

    Multi-host (a real ``jax.distributed`` run): the world size IS the
    DMLC host count, so it is exported through ``resume(num_workers=)``
    exactly as the reference's ``BytePSBasics.resume`` would — with the
    known caveat that an initialized JAX backend cannot drop a dead
    peer's devices, so callers pass ``devices=jax.local_devices()``
    (see RecoveryCoordinator's ``devices`` docstring).

    Single-controller (one process per member, each owning its own
    local mesh — the CPU chaos topology and any one-host elastic run):
    the membership world is a *bus-level* fact, not the local JAX
    topology; resume re-initializes the local mesh unchanged and must
    NOT rewrite ``DMLC_NUM_WORKER`` (that would send the next
    bootstrap down the multi-host rendezvous path)."""
    import jax
    from ..core import api
    if jax.process_count() > 1:
        api.resume(num_workers=view.num_workers, devices=devices)
    else:
        api.resume(devices=devices)


# monkeypatch point for tests (escalation must not kill the test runner)
_exit = os._exit
