"""Deterministic, seeded fault injection at named sites.

The subsystem exists so the recovery path (detector → suspend → resume →
restore, :mod:`byteps_tpu.fault.recovery`) can be *proved* to work: a
chaos run configures ``BYTEPS_FAULT_SPEC`` and the injector fires
scripted faults at well-known points of the stack.  Adaptive runtimes
treat degraded/late/lost participants as first-class states (PAPERS:
arxiv 2105.07829, 2412.14374); this is the harness that manufactures
those states on demand.

Spec grammar (``BYTEPS_FAULT_SPEC``, ``;``- or ``,``-separated faults)::

    kill:rank=1:step=40            die (os._exit) when this process's
                                   push_pull counter reaches step 40
    kill:site=coordinator:step=40  die at step 40 ONLY if this process
                                   is currently the membership
                                   coordinator (hosts the control
                                   plane) — chaos lanes kill "whoever
                                   coordinates" without hardcoding a
                                   rank.  Matches the PROCESS-LIFETIME
                                   push counter (which survives the
                                   disarm/re-arm of an elastic
                                   suspend/resume): a successor whose
                                   lifetime counter is already past the
                                   step is never cascade-killed by the
                                   re-armed schedule
    kill:site=serve_host_start:step=1   die at serve-host startup,
                                   BEFORE HOST-UP (step=N = the Nth
                                   start of this process; N=1 is the
                                   deterministic crash-looper the
                                   reconciler's flap ban is tested with)
    delay:site=dcn:p=0.01:ms=200   sleep 200ms with prob 0.01 per visit
    bitflip:site=server_push:p=0.001   flip one random bit of the pushed
                                   value with prob 0.001
    straggler:rank=2:ms=50         rank 2 sleeps 50ms at every dispatch
    drop:site=heartbeat:p=0.2      drop 20% of heartbeat sends
    slow:rank=1:site=sync:ms=300:n=20   GRAY failure: rank 1 sleeps
                                   300ms at EVERY visit of the sync
                                   site for its first 20 visits, then
                                   the fault clears (``n`` absent =
                                   slow forever).  Unlike ``delay``
                                   (probabilistic one-shots) this is a
                                   sustained per-rank throttle — the
                                   slow-but-alive condition the
                                   straggler chaos lane injects — and
                                   unlike ``straggler`` it has a
                                   bounded window, so recovery and
                                   probation readmission are testable
    partition:rank=2               SOCKET fault (site=transport, the
                                   default and only socket site): every
                                   transport socket operation on rank 2
                                   blackholes — connects refuse, sends
                                   vanish, received frames are
                                   discarded.  The per-send deadline
                                   surfaces the silence as ``AckLost``
                                   (never a hang); ``n=K`` bounds the
                                   partition to K socket ops (a healing
                                   partition), absent = partitioned
                                   forever
    conn_reset:p=0.05:n=3          SOCKET fault: the established
                                   connection is torn down with a real
                                   RST (SO_LINGER 0 close) mid
                                   send/recv with probability p; the
                                   supervisor reconnects and the sender
                                   retransmits from its sealed source
                                   copy (seq-token dedup absorbs a
                                   retry whose original landed).
                                   ``n=`` bounds total resets
    partial_write:p=0.05           SOCKET fault: a send writes only
                                   half its bytes, then RSTs — the
                                   receiver's length-prefixed read
                                   fails mid-frame and the connection
                                   dies exactly as a real half-written
                                   socket would
    slow_socket:ms=20:p=1          SOCKET fault: every matched send
                                   first sleeps ms — a sustained
                                   bandwidth/latency throttle on the
                                   wire, feeding the per-peer RTT
                                   histogram and the slowness tracker

Fields: ``rank`` (int, default: every rank), ``step`` (int, kill only),
``site`` (one of :data:`VALID_SITES`), ``p`` (probability in (0, 1],
default 1), ``ms`` (sleep milliseconds), ``n`` (visit budget, slow
only), ``code`` (kill exit code, default 1 — a *crash*, distinct from
the detector's restartable ``BYTEPS_FAILURE_EXIT_CODE``).  The set of
fields each kind accepts is exactly :data:`_KIND_FIELDS` — the master
table :data:`_FIELDS` is *derived* from it, so the two cannot drift
(pinned kind-by-field by tests/test_fault_injector.py).

Sites (where the hooks are woven):

- ``dispatch`` / ``sync`` — engine dispatcher pop / syncer completion
  (core/engine.py)
- ``dcn``    — collective dispatch (comm/collectives.py)
- ``server_push`` / ``server_pull`` — ServerEngine entry points
  (server/engine.py); ``bitflip`` corrupts the pushed value (or, with
  integrity envelopes armed, the sealed wire frame) here
- ``kv_push`` — KVStore delta pushes (server/kv_store.py); ``bitflip``
  corrupts the wire frame, ``drop`` loses the *acknowledgement* after
  the delta applied (the duplicate-retry scenario the seq dedup absorbs)
- ``serve_pull`` — the serving plane's pull-reply hop
  (server/serving.py); ``bitflip`` corrupts a reply frame (NACKed and
  retransmitted by the same envelope machine as pushes)
- ``heartbeat`` — the heartbeat client's UDP send
  (utils/failure_detector.py); ``drop`` suppresses the datagram

Determinism: every rule owns a :class:`random.Random` seeded from
``(BYTEPS_FAULT_SEED, rule index, kind, site)`` as a *string* — string
seeding is hash-randomization-free, so the same spec + seed produces the
identical injection schedule across processes and runs (pinned by
tests/test_fault_injector.py).

Disabled fast path: when no spec is armed, :data:`ENABLED` is ``False``
and every woven site is a single module-attribute check — nothing else
runs, no injector object exists, and the compiled collective programs
are byte-identical to a build without the hooks (the hooks live host-side,
never in-graph).
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional

from ..common.logging import get_logger
from ..common.telemetry import counters

# Module-level fast path: hot call sites guard with `if injector.ENABLED:`
# — one attribute load + truth test when chaos is off.
ENABLED = False
_active: Optional["FaultInjector"] = None

# Process-lifetime push counter: unlike FaultInjector._step it survives
# the disarm/re-arm cycle of an elastic suspend/resume.  site=coordinator
# kills match THIS counter — with the per-incarnation counter, the
# surviving successor's re-armed schedule would re-approach the same step
# from zero and cascade-kill the new coordinator.
_lifetime_step = 0

# Process-lifetime visit accounting for `slow` rules (keyed by the
# rule's identity): a gray fault is a property of the HOST, not of one
# engine incarnation — an elastic suspend/resume (a demoted rank's
# rejoin!) re-arms the injector from config, and without this a slow
# fault whose n= window had already CLEARED would come back fresh and
# immediately re-demote the readmitted rank.
_slow_consumed: Dict[str, int] = {}


def _reset_lifetime_for_tests() -> None:
    global _lifetime_step
    _lifetime_step = 0
    _slow_consumed.clear()

# monkeypatch point for tests (a real os._exit would take pytest with it)
_exit = os._exit

VALID_KINDS = ("bitflip", "conn_reset", "delay", "drop", "kill",
               "partial_write", "partition", "slow", "slow_socket",
               "straggler")
VALID_SITES = (
    # bpslint: ignore[chaos-site] reason=kill-only predicate matched in on_step (die while hosting the control plane), never a woven fire() site
    "coordinator",
    "dcn",
    # durable-plane disk faults (server/wal.py): disk_full fails an
    # append with ENOSPC; fsync drops the sync the policy promised;
    # wal_write tears the on-disk record short (drop) or flips a bit in
    # it (bitflip) — the torn-tail/corrupt-segment recovery pins
    "disk_full", "dispatch", "fsync", "gossip", "heartbeat", "kv_push",
    "serve_host",
    # bpslint: ignore[chaos-site] reason=kill-only predicate matched in on_serve_start (die at serve-host startup, before HOST-UP), never a woven fire() site
    "serve_host_start",
    "serve_pull", "server_pull", "server_push", "sync", "transport",
    "wal_write")
# sites where corrupt() is actually woven; a bitflip elsewhere would
# silently never fire, so validation rejects it
CORRUPT_SITES = ("kv_push", "serve_pull", "server_push", "wal_write")
# socket-level kinds (comm/transport.py chaos shim): they act on raw
# socket operations via socket_fault(), not on fire()/corrupt() hooks,
# so they are only meaningful at the socket site(s) below — validation
# pins them there (and defaults them there)
SOCKET_KINDS = ("conn_reset", "partial_write", "partition", "slow_socket")
SOCKET_SITES = ("transport",)
# fields each kind actually reads — anything else is rejected, not
# silently ignored (kill:p=0.1 must fail loudly, not kill
# deterministically while the operator believes it is probabilistic)
_KIND_FIELDS = {
    "kill": ("rank", "step", "site", "code"),
    "delay": ("rank", "site", "p", "ms"),
    "straggler": ("rank", "site", "ms"),
    "slow": ("rank", "site", "ms", "n"),
    "drop": ("rank", "site", "p"),
    "bitflip": ("rank", "site", "p"),
    "partition": ("rank", "site", "n", "ranks", "ms"),
    "conn_reset": ("rank", "site", "p", "n"),
    "partial_write": ("rank", "site", "p", "n"),
    "slow_socket": ("rank", "site", "p", "ms"),
}
# the master field set is DERIVED from the per-kind tables: a field a
# kind reads but the master list forgot (or vice versa) is structurally
# impossible, instead of a drift the parser rejects at runtime
_FIELDS = tuple(sorted({f for fs in _KIND_FIELDS.values() for f in fs}))
assert set(_KIND_FIELDS) == set(VALID_KINDS)


class FaultRule:
    """One parsed fault clause plus its private deterministic RNG.

    ``left`` is the mutable visit budget of a ``slow`` rule (counts down
    from ``n``; ``None`` = unbounded) — the one piece of rule state that
    changes over a run, guarded by the injector's lock."""

    __slots__ = ("kind", "site", "rank", "step", "p", "ms", "code", "n",
                 "left", "skey", "rng", "ranks", "cut_t0", "healed")

    def __init__(self, kind: str, site: Optional[str], rank: Optional[int],
                 step: Optional[int], p: float, ms: float, code: int,
                 n: Optional[int] = None, ranks=None):
        self.kind = kind
        self.site = site
        self.rank = rank
        self.step = step
        self.p = p
        self.ms = ms
        self.code = code
        self.n = n
        self.left = n
        self.skey: Optional[str] = None  # lifetime-budget key (slow only)
        self.rng: Optional[random.Random] = None  # bound by FaultInjector
        # ranks-partition state (kind=partition with ranks=A|B): the two
        # sides as frozensets, the monotonic time of the FIRST severed
        # edge (the heal clock's zero when ms= is set), and the healed
        # latch — a healed partition never cuts again
        self.ranks = ranks
        self.cut_t0: Optional[float] = None
        self.healed = False

    def __repr__(self) -> str:  # actionable in logs and error messages
        parts = [self.kind]
        for f in ("site", "rank", "step", "p", "ms", "n"):
            v = getattr(self, f)
            if v is not None:
                parts.append(f"{f}={v}")
        if self.ranks is not None:
            parts.append("ranks=%s|%s" % (
                ".".join(map(str, sorted(self.ranks[0]))),
                ".".join(map(str, sorted(self.ranks[1])))))
        return ":".join(parts)


def _is_coordinator() -> bool:
    """The ``kill:site=coordinator`` predicate: does THIS process
    currently host the membership control plane (the coordinator of the
    active :class:`~byteps_tpu.fault.membership.ElasticMembership`'s
    view)?  False when no elastic membership is running — the rule then
    never fires, matching "kill the coordinator" semantics for worlds
    that have none."""
    try:
        from .membership import active_membership
        m = active_membership()
        return m is not None and m.is_coordinator
    except Exception:  # noqa: BLE001 — the injector must never crash
        return False


def _fail(spec: str, clause: str, msg: str) -> ValueError:
    return ValueError(
        f"BYTEPS_FAULT_SPEC: bad clause {clause!r} in {spec!r}: {msg}")


def parse_spec(spec: str) -> List[FaultRule]:
    """Parse and *validate* a fault spec; raises ValueError with the list
    of valid kinds/sites on any unknown token (eager validation is the
    init()-time contract — a typo must fail the run, not silently inject
    nothing)."""
    rules: List[FaultRule] = []
    for clause in spec.replace(";", ",").split(","):
        clause = clause.strip()
        if not clause:
            continue
        kind, _, rest = clause.partition(":")
        kind = kind.strip()
        if kind not in VALID_KINDS:
            raise _fail(spec, clause,
                        f"unknown fault kind {kind!r}; valid kinds: "
                        f"{', '.join(VALID_KINDS)}")
        fields: Dict[str, str] = {}
        if rest:
            for item in rest.split(":"):
                key, sep, val = item.partition("=")
                key = key.strip()
                if not sep or key not in _FIELDS:
                    raise _fail(spec, clause,
                                f"unknown field {key!r}; valid fields: "
                                f"{', '.join(_FIELDS)}")
                if key not in _KIND_FIELDS[kind]:
                    raise _fail(spec, clause,
                                f"field {key!r} has no effect on "
                                f"{kind!r}; {kind} reads: "
                                f"{', '.join(_KIND_FIELDS[kind])}")
                fields[key] = val.strip()
        site = fields.get("site")
        if site is not None and site not in VALID_SITES:
            raise _fail(spec, clause,
                        f"unknown site {site!r}; valid sites: "
                        f"{', '.join(VALID_SITES)}")
        try:
            rank = int(fields["rank"]) if "rank" in fields else None
            step = int(fields["step"]) if "step" in fields else None
            p = float(fields.get("p", "1"))
            ms = float(fields.get("ms", "0"))
            code = int(fields.get("code", "1"))
            n = int(fields["n"]) if "n" in fields else None
        except ValueError:
            raise _fail(spec, clause, "rank/step/code/n must be integers, "
                                      "p/ms numbers") from None
        if not 0.0 < p <= 1.0:
            raise _fail(spec, clause, f"p={p} must be in (0, 1]")
        ranks = None
        if "ranks" in fields:
            # partition:ranks=A|B — two '.'-separated rank sets, e.g.
            # ranks=0|1.2 severs every edge between {0} and {1,2}
            sides = fields["ranks"].split("|")
            if len(sides) != 2:
                raise _fail(spec, clause,
                            "ranks must name exactly two sides as "
                            "A|B (ranks '.'-separated, e.g. 0|1.2)")
            try:
                a = frozenset(int(x) for x in sides[0].split(".") if x)
                b = frozenset(int(x) for x in sides[1].split(".") if x)
            except ValueError:
                raise _fail(spec, clause,
                            "ranks sides must be '.'-separated "
                            "integers") from None
            if not a or not b:
                raise _fail(spec, clause,
                            "both partition sides must be non-empty")
            if a & b:
                raise _fail(spec, clause,
                            f"partition sides overlap: "
                            f"{sorted(a & b)} on both")
            ranks = (a, b)
        if kind == "partition" and ms < 0:
            raise _fail(spec, clause,
                        "partition ms=N (heal-after window) must be "
                        ">= 0 (0 = never heals)")
        # per-kind requirements, checked here so a broken spec fails at
        # init() with an actionable message instead of never firing
        if kind == "kill" and step is None:
            raise _fail(spec, clause, "kill needs step=N (the push_pull "
                                      "count at which the process dies — "
                                      "the ANSWERED-PULL count for "
                                      "site=serve_host)")
        if kind == "kill" and site not in (None, "coordinator",
                                           "serve_host",
                                           "serve_host_start"):
            raise _fail(spec, clause,
                        "kill supports only site=coordinator (die only "
                        "while hosting the membership control plane), "
                        "site=serve_host (die at the Nth answered serving "
                        "pull — the ring-aware mid-storm host kill), or "
                        "site=serve_host_start (die at serve-host "
                        "startup, before HOST-UP — the launch crash the "
                        "reconciler's flap ban absorbs)")
        if kind != "kill" and site in ("coordinator", "serve_host_start"):
            raise _fail(spec, clause,
                        f"site={site} is a kill-only predicate, not a "
                        "woven code site")
        if kind in ("delay", "drop") and site is None:
            raise _fail(spec, clause,
                        f"{kind} needs site=S; valid sites: "
                        f"{', '.join(VALID_SITES)}")
        if kind == "bitflip":
            if site is None or site not in CORRUPT_SITES:
                raise _fail(spec, clause,
                            "bitflip needs site=S with S in "
                            f"{', '.join(CORRUPT_SITES)} (the sites where "
                            "value corruption is woven)")
        if kind == "straggler":
            if ms <= 0:
                raise _fail(spec, clause, "straggler needs ms=N > 0")
            site = site or "dispatch"
        if kind == "slow":
            if ms <= 0:
                raise _fail(spec, clause, "slow needs ms=N > 0 (the "
                                          "sustained per-visit delay)")
            if n is not None and n <= 0:
                raise _fail(spec, clause,
                            "slow n=N (visit budget) must be > 0")
            site = site or "dispatch"
        if kind in SOCKET_KINDS:
            # socket kinds act through the transport's socket shim
            # (comm/transport.py), not the fire()/corrupt() hooks — a
            # non-socket site would silently never fire
            site = site or "transport"
            if site not in SOCKET_SITES:
                raise _fail(spec, clause,
                            f"{kind} is a socket-level fault; site must "
                            f"be one of {', '.join(SOCKET_SITES)}")
            if kind == "slow_socket" and ms <= 0:
                raise _fail(spec, clause,
                            "slow_socket needs ms=N > 0 (the per-send "
                            "throttle)")
            if n is not None and n <= 0:
                raise _fail(spec, clause,
                            f"{kind} n=N (fault budget) must be > 0")
        rules.append(FaultRule(kind, site, rank, step, p, ms, code, n,
                               ranks=ranks))
    if not rules:
        raise ValueError(
            f"BYTEPS_FAULT_SPEC={spec!r} contains no fault clauses")
    return rules


class FaultInjector:
    """Deterministic fault schedule for one process.

    ``rank`` is the process identity faults match against (the launcher's
    DMLC_WORKER_ID / config.host_id — a per-process number that exists
    before any JAX state).  ``seed`` namespaces every rule's RNG; the
    schedule is a pure function of (spec, seed) and the visit sequence.
    """

    def __init__(self, spec: str, seed: int = 0, rank: int = 0):
        self.spec = spec
        self.seed = seed
        self.rank = rank
        self.rules = parse_spec(spec)
        for i, r in enumerate(self.rules):
            # string seeding: stable across processes (no hash salt)
            r.rng = random.Random(f"{seed}/{i}/{r.kind}/{r.site}")
            if r.n is not None and r.kind in ("slow",) + SOCKET_KINDS:
                # resume the lifetime visit budget: a re-armed schedule
                # (elastic suspend/resume) continues the SAME fault
                # window instead of restarting it
                r.skey = f"{seed}/{i}/{r.kind}/{r.site}/{r.rank}/" \
                         f"{r.ms}/{r.n}"
                r.left = max(0, r.n - _slow_consumed.get(r.skey, 0))
        self._by_site: Dict[str, List[FaultRule]] = {}
        for r in self.rules:
            if r.site is not None:
                self._by_site.setdefault(r.site, []).append(r)
        self._kills = [r for r in self.rules if r.kind == "kill"]
        # ranks-scoped partitions: consulted via edge_cut(peer) from any
        # peer-aware site (transport, heartbeat, bus, gossip), not via
        # the blanket socket_fault path
        self._edge_rules = [r for r in self.rules
                            if r.kind == "partition" and r.ranks is not None]
        self._step = 0
        self._serves = 0   # answered serving pulls (site=serve_host kills)
        self._serve_starts = 0   # serve-host startups (serve_host_start)
        # survives disarm(engine_scoped_only=True) — see module arm()
        self.persist = False
        self._lock = threading.Lock()

    # -- site hooks --------------------------------------------------------

    def on_step(self) -> None:
        """Advance the step counters (one per push_pull enqueue) and
        honor any matching kill rule — the simulated hard crash."""
        global _lifetime_step
        with self._lock:
            self._step += 1
            step = self._step
            _lifetime_step += 1
            life = _lifetime_step
        for r in self._kills:
            if r.rank is not None and r.rank != self.rank:
                continue
            if r.site == "serve_host":
                continue  # matched against the serve counter (on_serve)
            # coordinator kills count process-lifetime pushes (see the
            # module docstring: the per-incarnation counter restarts on
            # an elastic re-arm and would cascade-kill the successor)
            matched = life if r.site == "coordinator" else step
            if matched != r.step:
                continue
            if r.site == "coordinator" and not _is_coordinator():
                continue
            counters.inc("fault.kill")
            # log/record the counter the rule MATCHED (the lifetime one
            # for coordinator kills) so a postmortem can correlate the
            # black box with the spec's step=N
            get_logger().error(
                "fault injector: kill at step %d (rank %d) — exiting %d",
                matched, self.rank, r.code)
            # black-box parity with a real crash: the flight
            # recorder's tail (the events leading into this kill)
            # hits disk BEFORE the hard exit — os._exit runs no
            # atexit hooks, so this is the only chance
            from ..common import flight_recorder as _flight
            _flight.record("fault.kill", step=matched, rank=self.rank,
                           code=r.code)
            _flight.dump("chaos_kill")
            _exit(r.code)

    def on_serve(self) -> None:
        """Advance the serving-pull counter and honor ``site=serve_host``
        kill rules — the ring-aware chaos hook: a serving host dies
        deterministically at its Nth ANSWERED pull, i.e. mid-storm,
        without the test choreographing a wall-clock race."""
        with self._lock:
            self._serves += 1
            n = self._serves
        for r in self._kills:
            if r.site != "serve_host":
                continue
            if r.rank is not None and r.rank != self.rank:
                continue
            if n != r.step:
                continue
            counters.inc("fault.kill")
            get_logger().error(
                "fault injector: serve_host kill at pull %d (host %d) — "
                "exiting %d", n, self.rank, r.code)
            from ..common import flight_recorder as _flight
            _flight.record("fault.kill", step=n, rank=self.rank,
                           code=r.code, site="serve_host")
            _flight.dump("chaos_kill")
            _exit(r.code)

    def on_serve_start(self) -> None:
        """Advance the serve-host startup counter and honor
        ``site=serve_host_start`` kill rules — die BEFORE HOST-UP, the
        deterministic launch crash (``step=1`` = die at the first start
        of this process) the reconciler's crash-loop backoff and flap
        ban are tested against."""
        with self._lock:
            self._serve_starts += 1
            n = self._serve_starts
        for r in self._kills:
            if r.site != "serve_host_start":
                continue
            if r.rank is not None and r.rank != self.rank:
                continue
            if n != r.step:
                continue
            counters.inc("fault.kill")
            get_logger().error(
                "fault injector: serve_host_start kill at start %d "
                "(host %d) — exiting %d", n, self.rank, r.code)
            from ..common import flight_recorder as _flight
            _flight.record("fault.kill", step=n, rank=self.rank,
                           code=r.code, site="serve_host_start")
            _flight.dump("chaos_kill")
            _exit(r.code)

    def fire(self, site: str) -> None:
        """Visit a site: apply delay/straggler/slow sleeps scheduled
        there."""
        for r in self._by_site.get(site, ()):
            if r.kind == "delay":
                if r.rank is not None and r.rank != self.rank:
                    continue
                if r.p >= 1.0 or r.rng.random() < r.p:
                    counters.inc("fault.delay")
                    time.sleep(r.ms / 1000.0)
            elif r.kind == "straggler":
                if r.rank is None or r.rank == self.rank:
                    counters.inc("fault.straggler")
                    time.sleep(r.ms / 1000.0)
            elif r.kind == "slow":
                if r.rank is not None and r.rank != self.rank:
                    continue
                # sustained per-rank throttle with a bounded visit
                # budget: decremented under the lock (sites fire from
                # several threads), and its exhaustion — the gray fault
                # CLEARING — is announced once so the straggler lane
                # can pin "readmitted after the fault window ends"
                with self._lock:
                    if r.left is not None:
                        if r.left <= 0:
                            continue
                        r.left -= 1
                        if r.skey is not None:
                            _slow_consumed[r.skey] = \
                                _slow_consumed.get(r.skey, 0) + 1
                        cleared = r.left == 0
                    else:
                        cleared = False
                counters.inc("fault.slow")
                if cleared:
                    counters.inc("fault.slow_cleared")
                    from ..common import flight_recorder as _flight
                    _flight.record("fault.slow_cleared", site=site,
                                   rank=self.rank, n=r.n)
                    get_logger().warning(
                        "fault injector: slow fault at %s cleared after "
                        "%d visits (rank %d)", site, r.n, self.rank)
                time.sleep(r.ms / 1000.0)

    def _consume_budget(self, r: FaultRule) -> bool:
        """Spend one unit of a rule's ``n=`` budget (lifetime-accounted,
        like ``slow`` — an elastic re-arm resumes the window instead of
        resurrecting an exhausted fault).  True = the fault fires."""
        with self._lock:
            if r.left is None:
                return True
            if r.left <= 0:
                return False
            r.left -= 1
            if r.skey is not None:
                _slow_consumed[r.skey] = _slow_consumed.get(r.skey, 0) + 1
            return True

    def socket_fault(self, site: str, op: str) -> Optional[str]:
        """Socket-level chaos decision for ONE socket operation at
        ``site`` (``op``: ``connect`` | ``send`` | ``recv``) — the hook
        the transport's chaos shim (comm/transport.py) consults before
        touching a real socket, so partitions/resets are injectable
        without a cooperating peer.

        Returns the failure the shim must simulate — ``"partition"``
        (blackhole the operation), ``"conn_reset"`` (tear the
        connection down with a real RST), ``"partial_write"`` (send a
        truncated frame, then RST) — or ``None``.  ``slow_socket``
        sleeps inline on sends and returns None (the operation
        proceeds, late)."""
        for r in self._by_site.get(site, ()):
            if r.kind not in SOCKET_KINDS:
                continue
            if r.rank is not None and r.rank != self.rank:
                continue
            if r.kind == "slow_socket":
                if op == "send" and (r.p >= 1.0 or r.rng.random() < r.p):
                    counters.inc("fault.slow_socket")
                    time.sleep(r.ms / 1000.0)
                continue
            if r.kind == "partition":
                if r.ranks is not None:
                    continue  # edge-scoped: consulted via edge_cut(peer)
                # unconditional while the budget lasts: a partition is
                # a state, not a per-op coin flip
                if self._consume_budget(r):
                    counters.inc("fault.partition")
                    return "partition"
                continue
            if op == "connect":
                continue  # resets model an ESTABLISHED connection dying
            if r.kind == "partial_write" and op != "send":
                continue
            if r.p < 1.0 and r.rng.random() >= r.p:
                continue
            if not self._consume_budget(r):
                continue
            if r.kind == "conn_reset":
                counters.inc("fault.conn_reset")
                return "conn_reset"
            counters.inc("fault.partial_write")
            return "partial_write"
        return None

    def edge_cut(self, peer: int) -> bool:
        """True when a ``partition:ranks=A|B`` rule severs the edge
        between THIS process and ``peer`` right now — the symmetric
        blackhole every peer-aware site (transport sends/recvs/dials,
        heartbeat datagrams, bus requests, gossip exchanges) consults.

        The heal clock starts at the FIRST severed edge (``cut_t0``):
        with ``ms=N`` the partition heals N milliseconds later and never
        cuts again (``fault.partition`` / ``fault.partition_healed``
        flight events bracket the incident for bps_doctor).  An ``n=``
        budget bounds the number of blackholed operations instead."""
        if peer is None or peer < 0 or not self._edge_rules:
            return False
        now = time.monotonic()
        for r in self._edge_rules:
            if r.healed:
                continue
            a, b = r.ranks
            if not ((self.rank in a and peer in b)
                    or (self.rank in b and peer in a)):
                continue
            with self._lock:
                if r.healed:
                    continue
                if r.cut_t0 is None:
                    r.cut_t0 = now
                    counters.inc("fault.partition")
                    from ..common import flight_recorder as _flight
                    _flight.record("fault.partition", rank=self.rank,
                                   side_a=sorted(a), side_b=sorted(b),
                                   heal_ms=r.ms or None)
                    get_logger().warning(
                        "fault injector: partition %s|%s active "
                        "(rank %d)", sorted(a), sorted(b), self.rank)
                if r.ms > 0 and (now - r.cut_t0) * 1000.0 >= r.ms:
                    r.healed = True
                    counters.inc("fault.partition_healed")
                    from ..common import flight_recorder as _flight
                    _flight.record(
                        "fault.partition_healed", rank=self.rank,
                        side_a=sorted(a), side_b=sorted(b),
                        after_ms=round((now - r.cut_t0) * 1000.0, 1))
                    get_logger().warning(
                        "fault injector: partition %s|%s healed "
                        "(rank %d)", sorted(a), sorted(b), self.rank)
                    continue
                if r.left is not None:
                    if r.left <= 0:
                        continue
                    r.left -= 1
                    if r.skey is not None:
                        _slow_consumed[r.skey] = \
                            _slow_consumed.get(r.skey, 0) + 1
            counters.inc("fault.edge_cut")
            return True
        return False

    def should_drop(self, site: str) -> bool:
        """True when a drop rule says to suppress this message."""
        for r in self._by_site.get(site, ()):
            if r.kind == "drop" and (r.rank is None or r.rank == self.rank):
                if r.p >= 1.0 or r.rng.random() < r.p:
                    counters.inc("fault.drop")
                    return True
        return False

    def corrupt(self, site: str, arr):
        """Return ``arr`` with one random bit flipped when a bitflip rule
        fires here; otherwise the input, untouched (no copy)."""
        import numpy as np
        for r in self._by_site.get(site, ()):
            if r.kind != "bitflip":
                continue
            if r.rank is not None and r.rank != self.rank:
                continue
            if r.p < 1.0 and r.rng.random() >= r.p:
                continue
            counters.inc("fault.bitflip")
            a = np.array(arr, copy=True)
            raw = a.view(np.uint8).reshape(-1)
            byte = r.rng.randrange(raw.size)
            raw[byte] ^= np.uint8(1 << r.rng.randrange(8))
            from ..common import flight_recorder as _flight
            _flight.record("fault.bitflip", site=site, byte=byte)
            get_logger().warning(
                "fault injector: bit flipped at %s (byte %d)", site, byte)
            return a
        return arr

    @property
    def step_count(self) -> int:
        with self._lock:
            return self._step


# -- module-level arm/disarm (the init()/shutdown() contract) ---------------


def arm(spec: str, seed: int = 0, rank: int = 0, *,
        persist: bool = False) -> FaultInjector:
    """Validate ``spec`` and install the process-wide injector.  Raises
    ValueError (with the valid kind/site lists) on a malformed spec —
    called eagerly by ``bps.init()`` so chaos-run typos fail fast.

    ``persist=True`` pins the injector across the engine lifecycle:
    ``disarm(engine_scoped_only=True)`` — what ``api.suspend()`` /
    ``api.shutdown()`` issue — leaves it armed.  A ``partition:ranks``
    blackhole must survive the very suspend/resume transition it
    provokes: the network does not heal because the engine restarted,
    only the ``ms=`` clock heals it."""
    global ENABLED, _active
    _active = FaultInjector(spec, seed=seed, rank=rank)
    _active.persist = persist
    ENABLED = True
    get_logger().warning("fault injection ARMED (rank %d, seed %d): %s",
                         rank, seed, "; ".join(map(repr, _active.rules)))
    return _active


def disarm(engine_scoped_only: bool = False) -> None:
    """Drop the process-wide injector.  ``engine_scoped_only=True`` is
    the engine-lifecycle form (init/shutdown): it spares an injector
    armed with ``persist=True``."""
    global ENABLED, _active
    if engine_scoped_only and _active is not None \
            and getattr(_active, "persist", False):
        return
    ENABLED = False
    _active = None


def active() -> Optional[FaultInjector]:
    return _active


# Hot-path delegates: sites call these only behind `if injector.ENABLED:`
# so the disarmed cost is the guard alone.

def on_step() -> None:
    if _active is not None:
        _active.on_step()


def on_serve() -> None:
    """Serving-host twin of :func:`on_step` (``kill:site=serve_host``)."""
    if _active is not None:
        _active.on_serve()


def on_serve_start() -> None:
    """Serve-host startup twin (``kill:site=serve_host_start`` — die
    before HOST-UP)."""
    if _active is not None:
        _active.on_serve_start()


def fire(site: str) -> None:
    if _active is not None:
        _active.fire(site)


def should_drop(site: str) -> bool:
    return _active is not None and _active.should_drop(site)


def socket_fault(site: str, op: str) -> Optional[str]:
    """Socket-shim delegate (see :meth:`FaultInjector.socket_fault`);
    None when chaos is disarmed."""
    return None if _active is None else _active.socket_fault(site, op)


def edge_cut(peer: int) -> bool:
    """Ranks-partition delegate (see :meth:`FaultInjector.edge_cut`);
    False when chaos is disarmed."""
    return _active is not None and _active.edge_cut(peer)


def corrupt(site: str, arr):
    return arr if _active is None else _active.corrupt(site, arr)


def corrupt_bytes(site: str, data: bytes) -> bytes:
    """Byte-payload twin of :func:`corrupt` for wire frames (integrity
    envelopes, compressed codec payloads): one random bit of the frame
    is flipped when a bitflip rule fires at ``site``."""
    if _active is None or not data:
        return data
    import numpy as np
    view = np.frombuffer(data, dtype=np.uint8)
    out = _active.corrupt(site, view)
    return data if out is view else out.tobytes()
