"""Supervised recovery: detector → drain → suspend → resume → restore.

The seed state wired detection (`utils/failure_detector.py`) to a bare
``os._exit(17)``: recovery meant "die restartable and hope the launcher
notices".  :class:`RecoveryCoordinator` replaces that exit as the
``HeartbeatMonitor.on_failure`` action with an *in-process* elastic
recovery, the flow the reference only sketches as manual suspend/resume
(reference operations.cc:96-119):

1. drain + suspend — ``bps.suspend()`` waits out outstanding handles,
   stops the engine and heartbeat, and snapshots the declared-tensor
   order (so re-declaration reproduces identical key assignment);
2. resume on the survivor topology — ``bps.resume(num_workers=k-len
   (stale))`` re-initializes mesh + engine against the shrunk world;
3. restore — ``CheckpointManager.restore_latest`` + broadcast, so the
   survivors continue from the last durable step.

If any stage fails, the coordinator escalates to the configurable
restartable exit (``BYTEPS_FAILURE_EXIT_CODE``) — the launcher's
``--restart`` supervision is the outer loop; in-process recovery is the
inner, cheaper one.  Events land in telemetry counters
(``recovery.attempt/completed/failed``) and, when tracing is on, a
``recovery`` span in the chrome timeline.

This coordinator recovers ONE process against a checkpoint.  The
multi-survivor story — every survivor agreeing on the shrunk world and
continuing from *in-memory* state, plus in-place rejoin of a restarted
rank — is :mod:`byteps_tpu.fault.membership`, which composes the same
drain/suspend/resume primitives under an epoch-tagged rendezvous.

The wedged-collective caveat from the detector's docstring still holds:
a survivor stuck *inside* a DCN collective cannot run this path (the
thread is captive in XLA) — that case stays with the StepWatchdog's
process exit.  This coordinator covers the common case where the failure
is detected out-of-band while the host thread is schedulable.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Callable, Optional, Set

from ..common.logging import get_logger
from ..common.telemetry import counters

# monkeypatch point for tests (escalation must not kill the test runner)
_exit = os._exit


@dataclasses.dataclass
class RecoveryResult:
    """What a completed recovery handed back to the training loop."""

    failed_ranks: Set[int]
    num_workers: int            # surviving topology
    step: Optional[int]         # restored checkpoint step (None: no ckpt)
    state: Any                  # restored pytree (template when no ckpt)
    elapsed_s: float
    durable: Optional[dict] = None
    #                             durable-plane recovery stats
    #                             (server/wal.py) when BYTEPS_DURABLE_DIR
    #                             is set: snapshot lsn, records replayed,
    #                             torn tails truncated.  A surviving
    #                             process keeps its OPEN store (the
    #                             stats are from when it opened); only a
    #                             process with no open incarnation
    #                             rebuilds from disk.  None when the
    #                             durable plane is off or its restore
    #                             failed (the in-memory recovery stands
    #                             either way)


class RecoveryCoordinator:
    """Turns a detected failure into an automated elastic restart.

    Parameters
    ----------
    checkpoint_manager / template : optional
        ``utils.checkpoint.CheckpointManager`` and the pytree template to
        restore into.  Without them, recovery re-initializes the engine
        but restores nothing (``result.step`` is None).
    survivors : optional
        Override for the post-recovery worker count; default is the
        current ``DMLC_NUM_WORKER`` minus the stale set.
    devices : optional
        Devices for the resumed mesh.  Pass ``jax.local_devices()`` when
        the dead peer's devices must drop out of the topology (the cached
        JAX backend keeps advertising them in ``jax.devices()``).
    on_recovered : optional
        Callback run with the :class:`RecoveryResult` after a successful
        recovery (detector-thread context — keep it short).
    rearm_heartbeat : bool
        Re-arm liveness after resume.  Default False: the monitor was
        sized for the old topology and ``jax.process_count()`` still
        reports the pre-failure world, so re-arming would immediately
        re-detect the dead rank and exit a healthy survivor.
    """

    def __init__(self, checkpoint_manager=None, template: Any = None,
                 survivors: Optional[int] = None, devices=None,
                 on_recovered: Optional[Callable[[RecoveryResult],
                                                 None]] = None,
                 rearm_heartbeat: bool = False):
        self.checkpoint_manager = checkpoint_manager
        self.template = template
        self.survivors = survivors
        self.devices = devices
        self.on_recovered = on_recovered
        self.rearm_heartbeat = rearm_heartbeat
        self.result: Optional[RecoveryResult] = None
        self._done = threading.Event()
        self._started = threading.Event()
        self._lock = threading.Lock()

    # -- the HeartbeatMonitor.on_failure action ----------------------------

    def on_failure(self, stale: Set[int]) -> None:
        """Detector action: recover in place; escalate to the restartable
        exit code when recovery itself fails (launcher takes over)."""
        try:
            self.recover(stale)
        except Exception:  # noqa: BLE001 — end of the in-process line
            counters.inc("recovery.failed")
            code = _failure_exit_code()
            get_logger().error(
                "in-process recovery failed — exiting %d so the launcher "
                "can restart", code, exc_info=True)
            _exit(code)

    # -- the recovery flow -------------------------------------------------

    def recover(self, stale: Set[int]) -> RecoveryResult:
        """Drain → suspend → resume(survivors) → restore.  Idempotent:
        concurrent detections run it once; later callers get the first
        outcome — including a failed one, re-raised so their escalation
        path (on_failure → restartable exit) still runs instead of
        parking forever on a recovery that already died."""
        with self._lock:
            first = not self._started.is_set()
            self._started.set()
        if not first:
            self._done.wait()
            if self.result is None:
                raise RuntimeError(
                    "recovery already ran on another thread and failed")
            return self.result
        try:
            return self._recover(stale)
        except BaseException:
            # release waiters with the failure outcome (result stays
            # None); their recover() re-raises and escalates
            self._done.set()
            raise

    def _recover(self, stale: Set[int]) -> RecoveryResult:
        counters.inc("recovery.attempt")
        t0 = time.monotonic()
        from ..core import api
        old_n = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        k = self.survivors if self.survivors is not None \
            else max(1, old_n - len(stale))
        get_logger().error(
            "recovery: rank(s) %s lost — drain/suspend, resume on %d "
            "worker(s), restore from checkpoint", sorted(stale), k)
        # durable-plane survivor probe — BEFORE suspend/resume: resume's
        # init() opens the durable process store itself, so probing
        # afterwards would always look like a survivor
        from ..common.config import get_config
        dur_survivor = False
        if get_config().durable_dir:
            from ..server import wal as _wal
            dur_survivor = _wal.process_store() is not None
        if api.initialized():
            api.suspend()          # drains handles, stops engine+heartbeat
        if not self.rearm_heartbeat:
            # the resumed init must not re-arm liveness sized for the dead
            # topology (see class docstring)
            os.environ["BYTEPS_HEARTBEAT_ON"] = "0"
        api.resume(num_workers=k, devices=self.devices)
        step, state = None, self.template
        if self.checkpoint_manager is not None:
            if hasattr(self.checkpoint_manager, "reload"):
                # the trainer wrote the steps; this manager must re-scan
                # or it restores from its stale (possibly empty) view
                self.checkpoint_manager.reload()
            step, state = self.checkpoint_manager.restore_latest(
                self.template)
        # durable state plane (server/wal.py): when no survivor holds
        # the KV state in memory, the journal + snapshot cuts on local
        # disk DO — rebuild the trainer-side store from them.  When
        # THIS process survived with its durable store open, the store
        # in memory is the authority: under wal_fsync=interval/off the
        # journal tail exists only in memory, so closing and
        # re-replaying from disk would discard acknowledged pushes —
        # keep the live incarnation and harden its tail instead.
        # Failure is non-fatal either way: the in-memory recovery above
        # already stands, and the store simply starts empty (the
        # pre-ISSUE-19 world).
        dur_stats = None
        if get_config().durable_dir:
            from ..server import wal as _wal
            try:
                _store, dur = _wal.ensure_process_store()
                if dur_survivor:
                    dur.wal.sync()
                    counters.inc("recovery.durable_kept")
                else:
                    counters.inc("recovery.durable_restore")
                dur_stats = dict(dur.recover_stats)
            except Exception:  # noqa: BLE001 — degraded, not dead
                counters.inc("recovery.durable_restore_failed")
                get_logger().error(
                    "recovery: durable KV restore failed — continuing "
                    "with an empty store", exc_info=True)
        elapsed = time.monotonic() - t0
        result = RecoveryResult(failed_ranks=set(stale), num_workers=k,
                                step=step, state=state, elapsed_s=elapsed,
                                durable=dur_stats)
        self._record_span(result, t0)
        counters.inc("recovery.completed")
        get_logger().warning(
            "recovery complete in %.2fs: %d worker(s), restored step %s",
            elapsed, k, step)
        self.result = result
        self._done.set()
        if self.on_recovered is not None:
            try:
                self.on_recovered(result)
            except Exception:  # noqa: BLE001 — the recovery itself
                # succeeded; a broken user callback must not convert a
                # healthy survivor into a restartable exit
                get_logger().error("on_recovered callback raised after a "
                                   "successful recovery", exc_info=True)
        return result

    def _record_span(self, result: RecoveryResult, t0: float) -> None:
        """Recovery span into the *resumed* engine's tracer (the old
        tracer flushed when suspend tore the engine down)."""
        try:
            from ..core import api
            eng = api._require()
        except Exception:  # noqa: BLE001 — tracing is best-effort
            return
        eng.tracer.record_span(
            "recovery", t0, time.monotonic(),
            failed_ranks=sorted(result.failed_ranks),
            num_workers=result.num_workers, restored_step=result.step)

    # -- training-loop side ------------------------------------------------

    @property
    def triggered(self) -> bool:
        """True once a detection started recovery (training loops poll
        this to stop pushing into an engine being torn down)."""
        return self._started.is_set()

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None
             ) -> Optional[RecoveryResult]:
        """Block until recovery completes; None on timeout."""
        if not self._done.wait(timeout):
            return None
        return self.result


def _failure_exit_code() -> int:
    """The restartable exit code — one implementation, shared with the
    detector's default actions (utils/failure_detector.py).  Imported
    lazily: failure_detector imports the fault package for its
    heartbeat-drop site, so a module-level import here would cycle."""
    from ..utils.failure_detector import _failure_exit_code as _impl
    return _impl()
