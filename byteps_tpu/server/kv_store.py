"""Host-side KV store: async-PS semantics without a server process.

Reference behavior being reproduced (server.cc):
- init-push allocates the store and acks after all workers arrive — a
  barrier (server.cc:261-289); here ``init_key`` is idempotent and the
  mesh bootstrap is the barrier.
- async mode: pushes are summed into the store on arrival, no per-step
  barrier (server.cc:310-314); pulls return the current value immediately
  (server.cc:371-404).
- per-key engine-thread assignment and priority queues (server.cc:77-198)
  collapse away: summation here is numpy on the host (or the engine's
  collective when several local ranks contribute one delta each).

Single-process scope: this store backs the async training mode for all
ranks under one controller.  A cross-host replicated store (gossip over
DCN collectives) is the natural extension and rides the same interface.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from ..common.logging import get_logger
from ..common.telemetry import counters
from ..fault import membership as _membership
from ..native import inplace_add, load as _native_load


class KVStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._store: Dict[str, np.ndarray] = {}
        self._versions: Dict[str, int] = {}
        self._codecs: Dict[str, object] = {}
        self.wire_bytes = 0  # total compressed bytes pushed (accounting)
        # membership-epoch gate (fault/membership.py): deltas stamped
        # with another epoch are dropped, not summed
        self._membership_epoch = _membership.current_epoch()
        # force the one-time native build/load here, NOT under self._lock in
        # push_delta (the first load may g++-compile core.cc for seconds)
        _native_load()

    def set_membership_epoch(self, epoch: int) -> None:
        """Adopt a new membership epoch (monotonic); see ServerEngine."""
        with self._lock:
            if epoch > self._membership_epoch:
                self._membership_epoch = epoch

    def _stale(self, key: str, mepoch: Optional[int]) -> bool:
        """True when the delta crossed an elastic world change; stale
        deltas are dropped (the async accumulation they belonged to no
        longer exists) and the key's version is left untouched."""
        if mepoch is None or mepoch == self._membership_epoch:
            return False
        counters.inc("membership.stale_pushes_dropped")
        get_logger().warning(
            "kv store: dropped delta for %r from membership epoch %d "
            "(current %d)", key, mepoch, self._membership_epoch)
        return True

    def init_key(self, key: str, value) -> None:
        """Idempotent first-push initialization (reference init-push
        barrier, server.cc:261-289)."""
        with self._lock:
            if key not in self._store:
                self._store[key] = np.array(value, copy=True)
                self._versions[key] = 0

    def _push_delta_locked(self, key: str, delta: np.ndarray) -> int:
        if key not in self._store:
            raise KeyError(f"key {key!r} not initialized")
        # native multithreaded sum when available (reference server
        # engine threads sum with the C++ CpuReducer, server.cc:77-198)
        inplace_add(self._store[key], delta.reshape(
            self._store[key].shape))
        self._versions[key] += 1
        return self._versions[key]

    def push_delta(self, key: str, delta,
                   mepoch: Optional[int] = None) -> int:
        """Sum a delta into the store (async SUM_RECV path); returns the
        new version.  A stale ``mepoch`` (see :meth:`_stale`) is dropped
        — the current version is returned unchanged."""
        with self._lock:
            if self._stale(key, mepoch):
                return self._versions.get(key, -1)
            return self._push_delta_locked(key, np.asarray(delta))

    def register_compression(self, key: str, kwargs: dict, numel: int,
                             dtype=np.float32) -> None:
        """Declare a key's wire codec ON the store (one source of truth
        for the key's format, mirroring ServerEngine.register_compression
        — two workers with diverging kwargs must fail loudly, not sum
        mismatched decodes)."""
        from ..compression import registry as reg
        with self._lock:
            existing = self._codecs.get(key)
            if existing is not None:
                if existing[0] != dict(kwargs):
                    raise ValueError(
                        f"key {key!r} already registered with different "
                        f"compression kwargs {existing[0]}")
                return
            comp = reg.create(dict(kwargs), numel, dtype, for_server=True)
            self._codecs[key] = (dict(kwargs), comp)

    def push_delta_wire(self, key: str, data: bytes,
                        mepoch: Optional[int] = None) -> int:
        """Sum a wire-encoded compressed delta (the reference's async +
        compressed combination: compressed pushes, decompress-and-sum on
        the server, server.cc:87-113 + 310-314).  The key's codec must
        be registered via :meth:`register_compression`; the bytes are
        what a real worker->server network hop would carry, accumulated
        in :attr:`wire_bytes` only for pushes that land.  A stale
        ``mepoch`` is dropped before the decode runs."""
        with self._lock:
            if self._stale(key, mepoch):
                return self._versions.get(key, -1)
            codec = self._codecs.get(key)
            if codec is None:
                raise KeyError(f"key {key!r} has no registered compression")
            delta = np.asarray(codec[1].decompress(
                codec[1].wire_decode(data)))
            version = self._push_delta_locked(key, delta)
            self.wire_bytes += len(data)
            return version

    def pull(self, key: str) -> np.ndarray:
        """Return the current value (no barrier — async pull,
        server.cc:371-404)."""
        with self._lock:
            return self._store[key].copy()

    def version(self, key: str) -> int:
        with self._lock:
            return self._versions.get(key, -1)

    def keys(self):
        with self._lock:
            return list(self._store)

    def clear(self) -> None:
        with self._lock:
            self._store.clear()
            self._versions.clear()
            self._codecs.clear()
            self.wire_bytes = 0
