"""Host-side KV store: async-PS semantics without a server process.

Reference behavior being reproduced (server.cc):
- init-push allocates the store and acks after all workers arrive — a
  barrier (server.cc:261-289); here ``init_key`` is idempotent and the
  mesh bootstrap is the barrier.
- async mode: pushes are summed into the store on arrival, no per-step
  barrier (server.cc:310-314); pulls return the current value immediately
  (server.cc:371-404).
- per-key engine-thread assignment and priority queues (server.cc:77-198)
  collapse away: summation here is numpy on the host (or the engine's
  collective when several local ranks contribute one delta each).

Single-process scope: this store backs the async training mode for all
ranks under one controller.  A cross-host replicated store (gossip over
DCN collectives) is the natural extension and rides the same interface.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from ..native import inplace_add, load as _native_load


class KVStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._store: Dict[str, np.ndarray] = {}
        self._versions: Dict[str, int] = {}
        # force the one-time native build/load here, NOT under self._lock in
        # push_delta (the first load may g++-compile core.cc for seconds)
        _native_load()

    def init_key(self, key: str, value) -> None:
        """Idempotent first-push initialization (reference init-push
        barrier, server.cc:261-289)."""
        with self._lock:
            if key not in self._store:
                self._store[key] = np.array(value, copy=True)
                self._versions[key] = 0

    def push_delta(self, key: str, delta) -> int:
        """Sum a delta into the store (async SUM_RECV path); returns the
        new version."""
        with self._lock:
            if key not in self._store:
                raise KeyError(f"key {key!r} not initialized")
            # native multithreaded sum when available (reference server
            # engine threads sum with the C++ CpuReducer, server.cc:77-198)
            inplace_add(self._store[key], np.asarray(delta))
            self._versions[key] += 1
            return self._versions[key]

    def pull(self, key: str) -> np.ndarray:
        """Return the current value (no barrier — async pull,
        server.cc:371-404)."""
        with self._lock:
            return self._store[key].copy()

    def version(self, key: str) -> int:
        with self._lock:
            return self._versions.get(key, -1)

    def keys(self):
        with self._lock:
            return list(self._store)

    def clear(self) -> None:
        with self._lock:
            self._store.clear()
            self._versions.clear()
