"""Host-side KV store: async-PS semantics without a server process.

Reference behavior being reproduced (server.cc):
- init-push allocates the store and acks after all workers arrive — a
  barrier (server.cc:261-289); here ``init_key`` is idempotent and the
  mesh bootstrap is the barrier.
- async mode: pushes are summed into the store on arrival, no per-step
  barrier (server.cc:310-314); pulls return the current value immediately
  (server.cc:371-404).
- per-key engine-thread assignment and priority queues (server.cc:77-198)
  collapse away: summation here is numpy on the host (or the engine's
  collective when several local ranks contribute one delta each).

Data integrity (common/integrity.py, BYTEPS_INTEGRITY):
- every delta crosses a CRC32C-verified envelope hop (chaos site
  ``kv_push``); a corrupt frame is NACKed and retransmitted from the
  sealed source copy, never decoded or summed;
- pushes carrying a ``(worker_id, seq)`` token are **idempotent**: a
  retry after a lost ack (``drop:site=kv_push``, raised to the caller as
  :class:`integrity.AckLost` AFTER the sum applied) is dropped by the
  per-(key, worker) monotonic dedup — async mode can never double-sum;
- non-finite deltas and non-finite merge results go through the
  ``BYTEPS_NONFINITE_POLICY`` quarantine (``skip`` leaves the stored
  value at its previous version);
- :attr:`wire_bytes` counts only bytes that *landed*;
  :attr:`wire_bytes_wasted` counts retransmitted and duplicate-dropped
  frames, so compression-ratio accounting stays meaningful under chaos.
  Both are denominated in wire-ENCODED (compressed) bytes — raw
  ``push_delta`` traffic never touches either (its rejects show up in
  ``integrity.crc_reject``/``integrity.retransmit``).

Single-process scope: this store backs the async training mode for all
ranks under one controller.  A cross-host replicated store (gossip over
DCN collectives) is the natural extension and rides the same interface.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..common import integrity as _integrity
from ..common import tracing as _tracing
from ..common.lock_witness import named_lock
from ..common.logging import get_logger
from ..common.telemetry import counters
from ..fault import injector as _fault
from ..fault import membership as _membership
from ..native import inplace_add, load as _native_load

# /debug/state clamp: dedup_floors lists at most this many (key, worker)
# entries — the WORST (lowest-floor) ones, the laggards a postmortem
# cares about — plus a total count, so a many-key/many-worker run cannot
# turn one debug scrape into a megabyte JSON document.
DEBUG_FLOORS_MAX = 16


def _copy_outside_lock(arr: np.ndarray) -> np.ndarray:
    """The pull path's value copy, deliberately a module-level hook so
    tests can prove the copy runs OUTSIDE the store lock (a slow pull of
    a large key must not serialize concurrent pushes).  The reference
    held by the caller is copy-on-write-protected: a concurrent push to
    the same key replaces the stored array instead of mutating this one
    in place, so the copy is torn-free even without the lock."""
    return arr.copy()


class KVStore:
    def __init__(self):
        self._lock = named_lock("kvstore")
        self._store: Dict[str, np.ndarray] = {}
        self._versions: Dict[str, int] = {}
        self._codecs: Dict[str, object] = {}
        # copy-on-write marks: a key in this set has its stored array
        # referenced outside the lock (a pull mid-copy or a serving
        # snapshot); the NEXT push to it replaces the array with a fresh
        # copy before summing, so the outstanding reference stays frozen
        self._cow: set = set()
        # write-subscription hook (server/serving.py SnapshotStore):
        # callbacks fired OUTSIDE the lock after a version advances, at
        # consistent points only (deferred to batch exit inside
        # :meth:`write_batch`)
        self._subs: List[Callable[[str, int], None]] = []
        self._tls = threading.local()
        self.wire_bytes = 0         # compressed bytes that LANDED (summed)
        self.wire_bytes_wasted = 0  # retransmitted + duplicate-dropped bytes
        # per-(key, worker) highest sequence token seen — the dedup floor
        self._seen: Dict[Tuple[str, int], int] = {}
        self._wire_seq = itertools.count(1)
        # membership-epoch gate (fault/membership.py): deltas stamped
        # with another epoch are dropped, not summed
        self._membership_epoch = _membership.current_epoch()
        # store generation: bumped by clear().  Snapshots carry it so a
        # serving delta pull whose base predates a clear is answered
        # FULL — per-key versions restart at 0 after a clear, and a
        # version-vector comparison across the reset would skip every
        # re-initialized key and serve pre-clear values as fresh
        self._generation = 0
        # durable state plane (server/wal.py): when bound, every
        # mutation is journaled BEFORE it applies (classic WAL intent
        # ordering — a failed append leaves memory untouched and the
        # dedup floor unadvanced, so disk and memory can never disagree
        # about a landed delta); None = the in-memory-only default
        self._durable = None
        self._wal = None
        # force the one-time native build/load here, NOT under self._lock in
        # push_delta (the first load may g++-compile core.cc for seconds)
        _native_load()
        # /debug/state reachability (weakly held)
        from ..common import metrics as _metrics
        _metrics.register_component("kv_store", self)

    def _account_wire(self, nbytes: int, wasted: bool = False) -> None:
        """Caller holds the lock.  Wire accounting lands BOTH on the
        instance attributes (the established per-store figures) and the
        process-wide metrics registry (``wire_bytes`` /
        ``wire_bytes_wasted`` counters — the /metrics surface)."""
        if wasted:
            self.wire_bytes_wasted += nbytes
            counters.inc("wire_bytes_wasted", nbytes)
        else:
            self.wire_bytes += nbytes
            counters.inc("wire_bytes", nbytes)
            # per-leg series (ISSUE 20, PR-6 label convention): a
            # store-bound delta is a push; the labeled series sits
            # BESIDE the unlabeled total, which stays the established
            # async-PS figure
            counters.inc("wire_bytes", nbytes, leg="push")

    def debug_state(self) -> dict:
        """Postmortem internals for ``/debug/state``: dedup floors, wire
        accounting, key count.  ``dedup_floors`` is CLAMPED to the
        :data:`DEBUG_FLOORS_MAX` lowest floors (the laggards) —
        ``dedup_floor_count`` carries the true total, so a
        many-key/many-worker run cannot balloon a debug scrape."""
        with self._lock:
            worst = sorted(self._seen.items(), key=lambda kv: kv[1])
            return {"kind": "kv_store",
                    "membership_epoch": self._membership_epoch,
                    "keys": len(self._store),
                    "wire_bytes": self.wire_bytes,
                    "wire_bytes_wasted": self.wire_bytes_wasted,
                    "dedup_floor_count": len(self._seen),
                    "dedup_floors": {f"{k}:{w}": s for (k, w), s
                                     in worst[:DEBUG_FLOORS_MAX]}}

    # -- write subscription (serving-plane snapshot cutting) ----------------

    def subscribe(self, fn: Callable[[str, int], None]) -> None:
        """Register a write hook: ``fn(key, new_version)`` runs after a
        delta lands, OUTSIDE the store lock (the subscriber may pull,
        snapshot, or copy large arrays without stalling pushers).  Inside
        a :meth:`write_batch`, notifications are deferred to batch exit
        so a subscriber cutting snapshots never observes a half-applied
        multi-key update from this writer."""
        with self._lock:
            self._subs.append(fn)

    def unsubscribe(self, fn: Callable[[str, int], None]) -> None:
        """Detach a write hook.  Subscribers are STRONGLY referenced (a
        bound method pins its owner), so a dropped serving plane must
        detach or the store keeps it — and its snapshot cutting — alive
        forever.  Unknown hooks are ignored (idempotent)."""
        with self._lock:
            try:
                self._subs.remove(fn)
            except ValueError:
                pass

    @contextlib.contextmanager
    def write_batch(self):
        """Group several pushes into one consistent point: subscriber
        notifications for everything pushed inside the block fire only
        at exit.  Per-writer-thread (reentrant); concurrent writers'
        batches are independent — multi-key atomicity is a single
        writer's contract (async-PS sums commute per key across
        workers)."""
        depth = getattr(self._tls, "depth", 0)
        if depth == 0:
            self._tls.pending = []
        self._tls.depth = depth + 1
        try:
            yield
        finally:
            self._tls.depth = depth
            if depth == 0:
                pending, self._tls.pending = self._tls.pending, []
                for key, version in pending:
                    self._fire(key, version)

    def _notify(self, key: str, version: int) -> None:
        """Caller does NOT hold the lock."""
        if not self._subs:
            return
        if getattr(self._tls, "depth", 0) > 0:
            self._tls.pending.append((key, version))
            return
        self._fire(key, version)

    def _fire(self, key: str, version: int) -> None:
        with self._lock:
            subs = list(self._subs)
        for fn in subs:
            try:
                fn(key, version)
            except Exception:  # noqa: BLE001 — a subscriber must never
                # fail a push that already landed
                get_logger().error(
                    "kv store: write subscriber raised for %r", key,
                    exc_info=True)

    # -- durable state plane (server/wal.py) --------------------------------

    def bind_wal(self, durable) -> None:
        """Arm journaling (called by ``wal.attach`` AFTER recovery — a
        replay must not re-journal itself)."""
        with self._lock:
            self._durable = durable
            self._wal = durable.wal

    def durable_state(self) -> dict:
        """The full restorable state as one consistent cut, taken under
        ONE lock hold: arrays, versions, generation, membership epoch,
        and the dedup floors (restored floors mean a worker's duplicate
        retry arriving AFTER a cold restart is still absorbed).  The
        WAL position is captured under the same hold — appends run
        under this lock, so the state and the LSN cannot shear."""
        with self._lock:
            state = {
                "arrays": {k: np.array(a, copy=True)
                           for k, a in self._store.items()},
                "versions": dict(self._versions),
                "generation": self._generation,
                "epoch": self._membership_epoch,
                "seen": dict(self._seen),
            }
            if self._wal is not None:
                state["wal_lsn"] = self._wal.lsn
            return state

    def restore_durable_state(self, state: dict) -> None:
        """Adopt a snapshot cut wholesale (cold-start restore; no
        subscriber notifications — serving planes attach afterwards and
        cut from the restored state)."""
        with self._lock:
            self._store = {k: np.array(a, copy=True)
                           for k, a in state["arrays"].items()}
            self._versions = dict(state["versions"])
            self._seen = dict(state.get("seen") or {})
            self._generation = int(state.get("generation", 0))
            self._membership_epoch = int(
                state.get("epoch", self._membership_epoch))
            self._cow.clear()

    def apply_wal_record(self, kind: str, data) -> None:
        """Replay ONE journaled mutation (``wal.DurableKV._recover``).
        Deltas re-merge through the normal landing path — the stale
        gate already passed at journal time, so they apply
        unconditionally; the ``(worker_id, seq)`` token rebuilds the
        dedup floor exactly."""
        if kind == "delta":
            key, delta, worker_id, seq = data
            with self._lock:
                if key not in self._store:
                    counters.inc("wal.replay_skipped")
                    get_logger().error(
                        "wal replay: delta for unknown key %r skipped "
                        "(journal hole ahead of a lost init record)",
                        key)
                    return
                self._push_delta_locked(key, np.asarray(delta))
                self._mark_seen(key, worker_id, seq)
        elif kind == "init":
            key, value = data
            with self._lock:
                if key not in self._store:
                    self._store[key] = np.array(value, copy=True)
                    self._versions[key] = 0
        elif kind == "publish":
            key, value = data
            with self._lock:
                version = 0 if key not in self._store \
                    else self._versions[key] + 1
                self._cow.discard(key)
                self._store[key] = np.array(value, copy=True)
                self._versions[key] = version
        elif kind == "epoch":
            with self._lock:
                if data > self._membership_epoch:
                    self._membership_epoch = int(data)
                    self._seen.clear()
        elif kind == "clear":
            if isinstance(data, (tuple, list)):
                generation, epoch = data
            else:  # pre-epoch records journaled the bare generation
                generation, epoch = data, None
            with self._lock:
                self._store.clear()
                self._versions.clear()
                self._codecs.clear()
                self._seen.clear()
                self._cow.clear()
                self._generation = int(generation)
                if epoch is not None:
                    # the live clear() re-syncs the epoch to the world
                    # observed AT CLEAR TIME; replaying that observation
                    # keeps a cold-started store from holding the stale
                    # pre-clear epoch and dropping new-world deltas
                    self._membership_epoch = int(epoch)
        elif kind == "__advance__":
            pass  # WAL LSN-jump marker (wal.advance_to) — no mutation
        else:
            counters.inc("wal.replay_skipped")
            get_logger().error("wal replay: unknown record kind %r "
                               "skipped", kind)

    def set_membership_epoch(self, epoch: int) -> None:
        """Adopt a new membership epoch (monotonic); see ServerEngine.

        The dedup floors reset with the world: a rejoined incarnation of
        a dead rank restarts its sequence counter at 1, and holding it
        to the dead incarnation's floor would silently dup-drop every
        delta it ever pushes (mirrors ServerEngine clearing
        drop_once/known_workers on adoption).  The cross-boundary
        retry-dup window this reopens is closed by the mepoch gate: a
        retry of a pre-change push still carries the old epoch and is
        dropped as stale in :meth:`_stale`."""
        with self._lock:
            if epoch > self._membership_epoch:
                if self._wal is not None:
                    self._wal.append("epoch", int(epoch))
                self._membership_epoch = epoch
                self._seen.clear()

    def _stale(self, key: str, mepoch: Optional[int]) -> bool:
        """True when the delta crossed an elastic world change; stale
        deltas are dropped (the async accumulation they belonged to no
        longer exists) and the key's version is left untouched."""
        if mepoch is None or mepoch == self._membership_epoch:
            return False
        counters.inc("membership.stale_pushes_dropped")
        get_logger().warning(
            "kv store: dropped delta for %r from membership epoch %d "
            "(current %d)", key, mepoch, self._membership_epoch)
        return True

    def _dup(self, key: str, worker_id: int, seq: Optional[int]) -> bool:
        """Idempotence gate (caller holds the lock): a (key, worker)
        token at or below the recorded floor is a duplicate — the retry
        of a push whose ACK was lost — and is dropped, not re-summed.
        Check only; the floor advances via :meth:`_mark_seen`.  Legacy
        callers that pass no token are exempt (and unprotected)."""
        if seq is None:
            return False
        floor = self._seen.get((key, worker_id), 0)
        if seq <= floor:
            counters.inc("integrity.dup_dropped")
            get_logger().warning(
                "kv store: dropped duplicate delta for %r from worker %d "
                "(seq %d <= %d)", key, worker_id, seq, floor)
            return True
        return False

    def _mark_seen(self, key: str, worker_id: int,
                   seq: Optional[int]) -> None:
        """Advance the dedup floor — called only once the push's fate is
        FINAL (summed, or deliberately dropped by policy).  A push that
        died on the wire must not burn its token, or the caller's
        legitimate retry would be swallowed as a duplicate."""
        if seq is not None and seq > self._seen.get((key, worker_id), 0):
            self._seen[(key, worker_id)] = seq

    def init_key(self, key: str, value) -> None:
        """Idempotent first-push initialization (reference init-push
        barrier, server.cc:261-289)."""
        created = False
        with self._lock:
            if key not in self._store:
                arr = np.array(value, copy=True)
                if self._wal is not None:
                    self._wal.append("init", (key, arr))
                self._store[key] = arr
                self._versions[key] = 0
                created = True
        if created:
            self._notify(key, 0)

    def publish_key(self, key: str, value) -> int:
        """Serving-side overwrite: replace ``key``'s value wholesale and
        bump its version (creating it at version 0 if absent).

        Unlike the training-side delta paths this does NOT sum — the
        caller owns the key exclusively (the sharded-update serving cut
        publishes each owner's parameter slice here, serving_tier.py).
        An overwrite is the only bitwise-exact refresh: ``old + (new -
        old)`` re-rounds in float, so a delta-summed publish could
        serve values that differ from the training master in the last
        ulp.  COW references from outstanding snapshots stay frozen —
        the store slot is re-pointed, never mutated in place."""
        arr = np.array(value, copy=True)
        with self._lock:
            if self._wal is not None:
                self._wal.append("publish", (key, arr))
            if key not in self._store:
                version = 0
            else:
                version = self._versions[key] + 1
            self._cow.discard(key)
            self._store[key] = arr
            self._versions[key] = version
        self._notify(key, version)
        return version

    def _push_delta_locked(self, key: str, delta: np.ndarray) -> int:
        if key not in self._store:
            raise KeyError(f"key {key!r} not initialized")
        target = self._store[key]
        if key in self._cow:
            # copy-on-write: an outstanding reference (a pull copying
            # outside the lock, or a serving snapshot) holds the current
            # array — replace it instead of mutating it in place, so the
            # reference stays a frozen consistent value
            target = self._store[key] = target.copy()
            self._cow.discard(key)
        screened = _integrity.enabled()
        prev = None
        if screened and _integrity.nonfinite_policy() in ("skip", "raise"):
            # skip must UNDO a sum (inf + -inf can merge non-finite from
            # finite inputs); raise must leave the store untouched — the
            # error goes to the pushing worker only, so a mutated value
            # would be silently pullable by everyone else
            prev = target.copy()
        # native multithreaded sum when available (reference server
        # engine threads sum with the C++ CpuReducer, server.cc:77-198)
        inplace_add(target, delta.reshape(target.shape))
        if (screened and np.issubdtype(target.dtype, np.inexact)
                and not np.isfinite(target).all()):
            policy = _integrity.nonfinite_policy()
            if policy == "skip":
                target[...] = prev
                counters.inc("integrity.nonfinite_skipped")
                get_logger().error(
                    "kv store: merge for %r went non-finite — delta "
                    "dropped, value stays at version %d", key,
                    self._versions[key])
                return self._versions[key]
            if policy == "zero":
                counters.inc("integrity.nonfinite_zeroed")
                get_logger().warning(
                    "kv store: zeroed non-finite elements in merged "
                    "value for %r", key)
                np.nan_to_num(target, copy=False, nan=0.0, posinf=0.0,
                              neginf=0.0)
            else:
                counters.inc("integrity.nonfinite_rejected")
                target[...] = prev  # version not bumped: pulls stay sane
                raise RuntimeError(
                    f"kv store: merged value for {key!r} is non-finite "
                    "(BYTEPS_NONFINITE_POLICY=raise)")
        self._versions[key] += 1
        return self._versions[key]

    def _maybe_drop_ack(self, key: str, version: int,
                        seq: Optional[int]) -> None:
        """Chaos ``drop:site=kv_push``: the delta HAS been applied; the
        acknowledgement is what gets lost.  The caller retries with the
        same seq token and the dedup absorbs the duplicate.  A token-less
        legacy push (``seq is None``) never loses its ack — it has no
        token to retry with, so its retry would double-sum (the dedup
        exempts ``seq=None``) and a non-retry would crash the caller."""
        if (seq is not None and _fault.ENABLED
                and _fault.should_drop("kv_push")):
            raise _integrity.AckLost(
                f"push for {key!r} applied as version {version} but the "
                "ack was dropped; retry with the same seq token")

    def _land_delta_locked(self, key: str, delta: np.ndarray,
                           worker_id: int, seq: Optional[int],
                           wire_len: Optional[int] = None
                           ) -> Tuple[int, Optional[int]]:
        """The landing tail EVERY delta path shares (caller holds
        ``_lock``; ``delta`` already verified and screened): merge,
        advance the dedup floor (fate final), account wire bytes on the
        wire-denominated paths, maybe chaos-drop the ack — ONE copy of
        the ordering the loopback and transport entry points must agree
        on.  Returns ``(version, landed)``: ``landed`` is the new
        version when the merge changed the key (the caller notifies
        subscribers OUTSIDE the lock), None on a merged-screen skip
        (wire bytes wasted)."""
        if self._wal is not None:
            # journal the INTENT before the merge: if the append fails
            # (disk full, torn write) the push fails with the store
            # untouched and the floor unadvanced — the caller's retry
            # is legitimate, not a duplicate.  A crash after the append
            # but before the merge is equally safe: replay re-merges it.
            self._wal.append("delta",
                             (key, np.asarray(delta), worker_id, seq))
        before = self._versions.get(key, -1)
        version = self._push_delta_locked(key, delta)
        self._mark_seen(key, worker_id, seq)
        landed = version if version != before else None
        if wire_len is not None:
            self._account_wire(wire_len, wasted=landed is None)
        self._maybe_drop_ack(key, version, seq)
        return version, landed

    def _wire_recv(self, key: str, frame: bytes, worker_id: int, seq: int,
                   opener, wasted_nbytes: int):
        """Envelope hop for a sealed frame (caller holds the lock): the
        shared :func:`integrity.wire_transmit` NACK/retransmit machine at
        chaos site ``kv_push``, with every rejected transmission
        accounting ``wasted_nbytes`` into :attr:`wire_bytes_wasted`."""
        def wasted():
            self._account_wire(wasted_nbytes, wasted=True)

        return _integrity.wire_transmit(
            frame, key=key, worker=worker_id, seq=seq, site="kv_push",
            opener=opener, who="kv store", on_reject=wasted)

    def push_delta(self, key: str, delta, mepoch: Optional[int] = None,
                   worker_id: int = 0, seq: Optional[int] = None) -> int:
        """Sum a delta into the store (async SUM_RECV path); returns the
        new version.  A stale ``mepoch`` (see :meth:`_stale`) is dropped
        — the current version is returned unchanged.  With integrity
        armed the delta crosses the envelope hop (chaos-visible, CRC
        verified); a ``(worker_id, seq)`` token makes the push
        idempotent (see :meth:`_dup`).  A landed delta notifies write
        subscribers outside the lock — even when the ack is then
        chaos-dropped (the sum DID apply)."""
        landed: Optional[int] = None
        # causal tracing (ISSUE 12): join the caller's captured trace or
        # sample one; the sealed-envelope hop below stamps its span with
        # the same id (the async-PS push's wire leg)
        tctx, t_kv0 = _tracing.begin_sample("kv.push")
        try:
            with self._lock:
                if self._stale(key, mepoch):
                    return self._versions.get(key, -1)
                if self._dup(key, worker_id, seq):
                    version = self._versions.get(key, -1)
                    self._maybe_drop_ack(key, version, seq)
                    return version
                arr = np.asarray(delta)
                if _integrity.enabled():
                    seq_env = (seq if seq is not None
                               else next(self._wire_seq))
                    frame = _integrity.seal_array(arr, key=key, seq=seq_env,
                                                  worker=worker_id)
                    # wasted_nbytes=0: the wire counters are denominated
                    # in wire-ENCODED (compressed) bytes only — charging
                    # raw float32 nbytes here would let uncompressed
                    # deltas dwarf the compressed traffic and wreck the
                    # waste ratio; raw rejects stay visible in
                    # integrity.crc_reject/retransmit
                    with _tracing.use(tctx):
                        arr = self._wire_recv(key, frame, worker_id,
                                              seq_env,
                                              _integrity.open_array, 0)
                    arr = _integrity.screen_nonfinite(
                        arr, what="delta", key=key, worker=worker_id)
                    if arr is None:  # skip policy: drop this contribution
                        self._mark_seen(key, worker_id, seq)  # fate final
                        return self._versions.get(key, -1)
                elif _fault.ENABLED:
                    # integrity off: the bitflip lands silently in this
                    # delta — the unprotected baseline the envelope fixes
                    # (mirrors ServerEngine.push; a corrupt-site spec must
                    # never silently no-op)
                    arr = np.asarray(_fault.corrupt("kv_push", arr))
                    _fault.fire("kv_push")
                version, landed = self._land_delta_locked(
                    key, arr, worker_id, seq)
                return version
        finally:
            if tctx is not None:
                _tracing.tracer().record_traced(
                    tctx.trace_id, "kv.push", f"kv/{key}", t_kv0,
                    time.monotonic(), worker=worker_id)
            if landed is not None:
                self._notify(key, landed)

    def register_compression(self, key: str, kwargs: dict, numel: int,
                             dtype=np.float32) -> None:
        """Declare a key's wire codec ON the store (one source of truth
        for the key's format, mirroring ServerEngine.register_compression
        — two workers with diverging kwargs must fail loudly, not sum
        mismatched decodes)."""
        from ..compression import registry as reg
        with self._lock:
            existing = self._codecs.get(key)
            if existing is not None:
                if existing[0] != dict(kwargs):
                    raise ValueError(
                        f"key {key!r} already registered with different "
                        f"compression kwargs {existing[0]}")
                return
            comp = reg.create(dict(kwargs), numel, dtype, for_server=True)
            self._codecs[key] = (dict(kwargs), comp, numel, dtype)

    def codec_info(self, key: str):
        """(kwargs, comp, numel, dtype) of the key's registered wire
        codec, or ``None`` — the serving plane reuses the TRAINING
        plane's codec on the read path (delta pulls ship the same wire
        encoding the pushes arrive in), and a pull client rebuilds its
        decoder from the kwargs/numel/dtype triple."""
        with self._lock:
            return self._codecs.get(key)

    def codec_infos(self) -> Dict[str, tuple]:
        """Every registered codec in ONE lock acquisition — captured
        into each serving snapshot at cut time so the per-key read path
        never touches the store lock (the contention the COW design
        exists to keep off the pull path)."""
        with self._lock:
            return dict(self._codecs)

    def push_delta_wire(self, key: str, data: bytes,
                        mepoch: Optional[int] = None,
                        worker_id: int = 0,
                        seq: Optional[int] = None) -> int:
        """Sum a wire-encoded compressed delta (the reference's async +
        compressed combination: compressed pushes, decompress-and-sum on
        the server, server.cc:87-113 + 310-314).  The key's codec must
        be registered via :meth:`register_compression`; the bytes are
        what a real worker->server network hop would carry, accumulated
        in :attr:`wire_bytes` only for pushes that land (retransmits and
        duplicates land in :attr:`wire_bytes_wasted`).  A stale
        ``mepoch`` is dropped before the decode runs; a corrupt frame is
        NACKed and retransmitted before the decode runs — the codec
        never sees unverified bytes."""
        landed: Optional[int] = None
        tctx, t_kv0 = _tracing.begin_sample("kv.push")
        try:
            with self._lock:
                if self._stale(key, mepoch):
                    return self._versions.get(key, -1)
                codec = self._codecs.get(key)
                if codec is None:
                    raise KeyError(
                        f"key {key!r} has no registered compression")
                if self._dup(key, worker_id, seq):
                    self._account_wire(len(data), wasted=True)
                    version = self._versions.get(key, -1)
                    self._maybe_drop_ack(key, version, seq)
                    return version
                if _integrity.enabled():
                    env_seq = (seq if seq is not None
                               else next(self._wire_seq))
                    frame = _integrity.seal_bytes(data, key=key, seq=env_seq,
                                                  worker=worker_id)
                    with _tracing.use(tctx):
                        verified = bytes(self._wire_recv(
                            key, frame, worker_id, env_seq,
                            _integrity.open_bytes, len(data)))
                else:
                    verified = data
                    if _fault.ENABLED:
                        # integrity off: corruption reaches the codec and
                        # decodes into a many-element error — the baseline
                        # the envelope exists to fix
                        verified = _fault.corrupt_bytes("kv_push", verified)
                        _fault.fire("kv_push")
                delta = np.asarray(codec[1].decompress(
                    codec[1].wire_decode(verified)))
                if _integrity.enabled():
                    delta = _integrity.screen_nonfinite(
                        delta, what="delta", key=key, worker=worker_id)
                    if delta is None:  # skip policy: dropped, bytes wasted
                        self._account_wire(len(data), wasted=True)
                        self._mark_seen(key, worker_id, seq)  # fate final
                        return self._versions.get(key, -1)
                version, landed = self._land_delta_locked(
                    key, delta, worker_id, seq, wire_len=len(data))
                return version
        finally:
            if tctx is not None:
                _tracing.tracer().record_traced(
                    tctx.trace_id, "kv.push", f"kv/{key}", t_kv0,
                    time.monotonic(), worker=worker_id, compressed=True)
            if landed is not None:
                self._notify(key, landed)

    # -- transport receive side (comm/transport.py) -------------------------
    #
    # The TCP transport verifies the sealed envelope AT THE SOCKET and
    # NACKs corruption back to the sender, so these entry points skip
    # the store's own envelope hop (re-sealing a verified payload would
    # CRC bytes against themselves AND double-fire any armed chaos
    # site) while keeping every other semantic: stale-epoch drop,
    # seq-token dedup, non-finite screen, the chaos ack-drop, and the
    # write-subscriber notification.

    def apply_delta(self, key: str, delta, *,
                    mepoch: Optional[int] = None, worker_id: int = 0,
                    seq: Optional[int] = None) -> int:
        """Sum a transport-delivered (already-verified) raw delta.
        Raises :class:`integrity.AckLost` AFTER the sum applied when
        chaos drops the ack (``drop:site=kv_push``) — the transport
        server suppresses its reply and the sender's same-token retry
        is dedup-absorbed."""
        landed: Optional[int] = None
        try:
            with self._lock:
                if self._stale(key, mepoch):
                    return self._versions.get(key, -1)
                if self._dup(key, worker_id, seq):
                    version = self._versions.get(key, -1)
                    self._maybe_drop_ack(key, version, seq)
                    return version
                arr = np.asarray(delta)
                if _integrity.enabled():
                    arr = _integrity.screen_nonfinite(
                        arr, what="delta", key=key, worker=worker_id)
                    if arr is None:  # skip policy: fate final
                        self._mark_seen(key, worker_id, seq)
                        return self._versions.get(key, -1)
                version, landed = self._land_delta_locked(
                    key, arr, worker_id, seq)
                return version
        finally:
            if landed is not None:
                self._notify(key, landed)

    def apply_delta_wire(self, key: str, data: bytes, *,
                         mepoch: Optional[int] = None, worker_id: int = 0,
                         seq: Optional[int] = None) -> int:
        """Sum a transport-delivered (already-verified) wire-encoded
        delta; the key's registered codec decodes it.  Wire accounting
        matches :meth:`push_delta_wire`: landed bytes in
        :attr:`wire_bytes`, duplicates and screened-out deltas in
        :attr:`wire_bytes_wasted`."""
        landed: Optional[int] = None
        try:
            with self._lock:
                if self._stale(key, mepoch):
                    return self._versions.get(key, -1)
                codec = self._codecs.get(key)
                if codec is None:
                    raise KeyError(
                        f"key {key!r} has no registered compression")
                if self._dup(key, worker_id, seq):
                    self._account_wire(len(data), wasted=True)
                    version = self._versions.get(key, -1)
                    self._maybe_drop_ack(key, version, seq)
                    return version
                delta = np.asarray(codec[1].decompress(
                    codec[1].wire_decode(bytes(data))))
                if _integrity.enabled():
                    delta = _integrity.screen_nonfinite(
                        delta, what="delta", key=key, worker=worker_id)
                    if delta is None:  # skip policy: dropped, wasted
                        self._account_wire(len(data), wasted=True)
                        self._mark_seen(key, worker_id, seq)
                        return self._versions.get(key, -1)
                version, landed = self._land_delta_locked(
                    key, delta, worker_id, seq, wire_len=len(data))
                return version
        finally:
            if landed is not None:
                self._notify(key, landed)

    def pull(self, key: str) -> np.ndarray:
        """Return the current value (no barrier — async pull,
        server.cc:371-404).

        The lock is held only to take the reference and mark the key
        copy-on-write; the (possibly large) copy runs OUTSIDE it, so a
        slow pull never serializes concurrent pushes.  The COW mark
        makes the unlocked copy torn-free: a concurrent push replaces
        the stored array instead of mutating this reference."""
        with self._lock:
            ref = self._store[key]
            self._cow.add(key)
        return _copy_outside_lock(ref)

    def pull_versioned(self, key: str) -> Tuple[np.ndarray, int]:
        """``(value, version)`` with the same outside-the-lock copy as
        :meth:`pull` — the serving plane's cheap read primitive (a
        client compares the version against its cached one)."""
        with self._lock:
            ref = self._store[key]
            version = self._versions[key]
            self._cow.add(key)
        return _copy_outside_lock(ref), version

    def snapshot_refs(self) -> Tuple[Dict[str, Tuple[np.ndarray, int]],
                                     int]:
        """Consistent copy-on-write snapshot of every key:
        ``({key: (read-only view, version)}, generation)`` taken under
        ONE lock acquisition with no copying at all — every key is
        marked COW, so later pushes replace arrays rather than mutate
        them and the returned views stay a frozen, mutually-consistent
        cut of the store.  The generation rides the same lock hold so a
        racing :meth:`clear` cannot stamp pre-clear refs with a
        post-clear generation.  This is what ``server/serving.py`` cuts
        snapshots from; the cost is one lazy copy per (snapshot,
        subsequently-pushed key), paid on the push path."""
        with self._lock:
            self._cow.update(self._store.keys())
            out = {}
            for k, a in self._store.items():
                v = a.view()
                v.flags.writeable = False
                out[k] = (v, self._versions[k])
            return out, self._generation

    def version(self, key: str) -> int:
        with self._lock:
            return self._versions.get(key, -1)

    def keys(self):
        with self._lock:
            return list(self._store)

    def clear(self) -> None:
        """Reset the store to empty.  The membership epoch RE-SYNCS to
        the process-wide current epoch rather than surviving the clear:
        a cleared-and-reused store is a new logical store in whatever
        world exists NOW — keeping the old epoch would silently drop
        every delta from the new world as stale (the dedup floors and
        versions it guarded are gone anyway).  The store GENERATION is
        bumped so serving snapshots cut before the clear can never act
        as a delta base afterwards: per-key versions restart at 0, and
        a cross-clear version comparison would silently serve pre-clear
        values as fresh."""
        with self._lock:
            epoch = _membership.current_epoch()
            if self._wal is not None:
                # the epoch rides the record so replay restores the
                # re-sync below, not the stale pre-clear epoch
                self._wal.append("clear", (self._generation + 1, epoch))
            self._store.clear()
            self._versions.clear()
            self._codecs.clear()
            self._seen.clear()
            self._cow.clear()
            self.wire_bytes = 0
            self.wire_bytes_wasted = 0
            self._membership_epoch = epoch
            self._generation += 1
