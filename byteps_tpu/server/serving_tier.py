"""Distributed serving tier: out-of-process serving hosts behind the wire.

PRs 8-9 built a fast read plane, but every endpoint was a thread in the
trainer's process.  This module is the scale-out (ROADMAP item 4): real
**serving-host processes** (``server/serve_host.py``) behind the PR-14
supervised TCP transport, fed **snapshot cuts as per-key deltas** and
answering ``PullClient`` storms routed by a client-side
**consistent-hash ring** (``server/serve_ring.py``) — with **admission
control** so a storm degrades to bounded staleness instead of collapse,
and an autoscaler (``server/serve_autoscaler.py``) steering the host set
through the membership bus.

Four roles, one module:

**ServingHostCore** (runs inside each serving host): receives per-key
delta ships (sealed envelopes, hop ``serve_cut``) staged until a
``serve_commit`` atomically publishes a host-local
:class:`~.serving.Snapshot` — so a host holds ONLY its ring arcs' keys,
never the full model ("Automatic Cross-Replica Sharding of Weight
Update", PAPERS.md), and compressed keys travel wire-encoded with the
training codecs so DCN bytes scale with churn, not model size
("Compressed Communication for Distributed Training", PAPERS.md).
Pulls cross :class:`AdmissionControl` — a token bucket plus a
queue-depth watermark; an over-budget pull whose client is still inside
its own staleness bound is answered ``shed`` (keep serving your cache)
at near-zero cost, and a client that would exceed its bound is served
anyway (``serve.shed_bypass``): load-shedding degrades freshness, never
correctness.

**ServingTier** (runs beside the trainer): cuts COW snapshots of the
live :class:`~.kv_store.KVStore` (the PR-8 machinery, unchanged) and
ships each host exactly the keys the ring assigns it whose version
advanced since the host's last commit — the delta/version-vector
protocol of ``SnapshotServer.pull``, turned around into a push.  Hosts
that fail consecutive ships are retired from the directory so the ring
heals without operator action.

**TierRouter** (one per :class:`~.serve_client.PullClient`): resolves
each key to its owner host on the ring, fails over along the arc's
replica successors, re-resolves the directory on ``ServeUnavailable``
(a dead host's arc remaps in one pull, not at the next cut), and merges
per-host slices into one reply.

**TierDirectory**: the membership-bus client (verbs ``serve_register``
/ ``serve_unregister`` / ``serve_dir`` / ``serve_scale``,
``fault/membership.py``) — hosts register with a TTL, consumers poll
the generation, and the autoscaler's proposals ride the same channel:
the ring follows MEMBERSHIP, not static config.
"""

from __future__ import annotations

import itertools
import os
import pickle
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import integrity as _integrity
from ..common.lock_witness import named_lock
from ..common.logging import get_logger
from ..common.telemetry import counters, gauges
from ..fault import injector as _fault
from .serve_ring import ServeRing
from .serving import (ServeReply, ServeUnavailable, Snapshot, SnapshotRing,
                      SnapshotServer, SnapshotStore)

__all__ = ["AdmissionControl", "ServingHostCore", "TierDirectory",
           "ServingTier", "TierRouter", "inproc_host", "SERVE_RANK_BASE",
           "assemble_shard_keys"]


def assemble_shard_keys(pull, name: str) -> np.ndarray:
    """Rebuild one shard-published parameter (ISSUE 20) from its
    per-owner keys: ``pull`` is any ``key -> ndarray`` callable — a
    :class:`~.kv_store.KVStore`'s ``pull``, a tier client wrapper, or
    ``snapshot.refs.__getitem__``.  Reads the ``{name}@shards``
    manifest (shard count, logical length, column width, shape) and
    concatenates the ``{name}@shard{i}`` slices in offset order; the
    result is bitwise the training master cast to the declared dtype —
    identical to what an unsharded cut of the full parameter would
    serve."""
    meta = np.asarray(pull(f"{name}@shards"))
    nshards, n = int(meta[0]), int(meta[1])
    shape = tuple(int(d) for d in meta[3:])
    parts = [np.asarray(pull(f"{name}@shard{i}")).reshape(-1)
             for i in range(nshards)]
    flat = np.concatenate(parts) if parts else np.zeros(0, np.float32)
    return flat[:n].reshape(shape)

# serving hosts publish bus metrics at host_id + this base (one id space
# for bps_top rows, zero collision with trainer ranks)
from ..fault.membership import SERVE_RANK_BASE  # noqa: E402  (re-export)


# -- admission control -------------------------------------------------------


class AdmissionControl:
    """Per-host pull admission: a token bucket (``rate`` pulls/s refill,
    ``burst`` capacity) AND an in-flight queue-depth watermark.  Either
    tripping sheds.  ``rate=0`` disables the bucket (watermark only);
    the watermark cannot be disabled — unbounded queueing IS the
    collapse mode this exists to rule out.

    ``admit()`` is hot-path cheap: one lock, two float ops.  The
    decision is advisory — the caller chooses between a ``shed`` reply
    and a bypass (staleness floor), never an error."""

    def __init__(self, rate: Optional[float] = None,
                 burst: Optional[float] = None,
                 queue_high: Optional[int] = None):
        from ..common.config import get_config
        cfg = get_config()
        self.rate = cfg.serve_tier_rate if rate is None else float(rate)
        b = cfg.serve_tier_burst if burst is None else float(burst)
        self.burst = b if b > 0 else max(self.rate, 1.0)
        self.queue_high = (cfg.serve_tier_queue_high if queue_high is None
                           else int(queue_high))
        self._lock = threading.Lock()
        self._tokens = self.burst
        self._last = time.monotonic()
        self._inflight = 0

    def enter(self) -> int:
        with self._lock:
            self._inflight += 1
            return self._inflight

    def exit(self) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)

    @property
    def inflight(self) -> int:
        return self._inflight

    def admit(self) -> bool:
        with self._lock:
            if self._inflight > self.queue_high:
                return False
            if self.rate <= 0:
                return True
            now = time.monotonic()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def snapshot(self) -> dict:
        with self._lock:
            return {"rate": self.rate, "burst": self.burst,
                    "queue_high": self.queue_high,
                    "tokens": round(self._tokens, 2),
                    "inflight": self._inflight}


# -- the serving host (receiver side) ----------------------------------------


class _Staged:
    __slots__ = ("arr", "version", "codec", "enc")

    def __init__(self, arr, version, codec, enc):
        self.arr = arr
        self.version = version
        self.codec = codec       # (kwargs, numel, dtype_str) or None
        self.enc = enc           # wire-encoded bytes for codec keys


class ServingHostCore:
    """One serving host's state: staged delta ships, the committed
    snapshot ring, and the shed-aware pull path.

    Publication is two-phase: ``serve_cut`` frames stage (idempotent —
    a transport retransmit overwrites with identical bytes), then ONE
    ``serve_commit`` builds the snapshot — staged keys for advanced
    versions, carried-forward refs for unchanged ones — and publishes it
    atomically (the PR-8 ring swap).  A reader on this host sees the
    previous complete cut or the new one, never a torn mix; a commit
    naming a version the host holds in neither place drops that key
    (``serve.tier_missing_keys``) and the publisher's un-acked ship
    state re-ships it at the next cut."""

    supports_shed = True

    def __init__(self, host_id: int = 0, *,
                 retention: Optional[int] = None,
                 admission: Optional[AdmissionControl] = None,
                 durable_dir: Optional[str] = None):
        from ..common.config import get_config
        cfg = get_config()
        self.host_id = int(host_id)
        self.ring = SnapshotRing(cfg.serve_retention if retention is None
                                 else retention)
        # partial=True: this host holds its arcs, not the model — a key
        # it does not mirror must REFUSE so the router fails over
        self.server = SnapshotServer(self.ring, server_id=self.host_id,
                                     partial=True)
        self.admission = admission if admission is not None \
            else AdmissionControl()
        self._stage_lock = named_lock("serve_tier.stage")
        self._staged: Dict[str, _Staged] = {}
        self._last_commit = 0
        self._decoders: Dict[str, Tuple[tuple, object]] = {}
        self._pull_counts: Dict[str, int] = {}
        self.pulls = 0
        self.sheds = 0
        # graceful-drain latch (``serve_ctl drain``): the owning process
        # (serve_host.py main loop) watches it — marks the directory,
        # lets in-flight pulls finish, unregisters, exits clean
        self.draining = threading.Event()
        # durable arc (server/wal.py, ISSUE 19): when a durable dir is
        # configured, every committed snapshot is persisted atomically
        # and restored at construction — a restarted host rejoins with
        # its arc already published, so the publisher's next cut finds
        # every unchanged key carried forward and ships NOTHING
        # (restart-in-place without the full-arc DCN re-ship)
        dd = cfg.durable_dir if durable_dir is None else durable_dir
        self._arc_path = (os.path.join(dd, f"serve-{self.host_id}",
                                       "arc.bin") if dd else None)
        self.restored_commit = 0
        if self._arc_path is not None:
            self._restore_arc()
        from ..common import metrics as _metrics
        _metrics.register_component("serving_tier", self)

    # -- durable arc persistence (server/wal.py) ----------------------------

    def _persist_arc(self, snap: Snapshot) -> None:
        """Persist the committed snapshot atomically (sealed blob,
        write-to-temp + fsync + rename).  Best-effort AFTER the
        in-memory publish: a failing disk degrades restart-in-place to
        a full re-ship, never a failed commit."""
        from . import wal as _wal
        state = {"id": snap.id, "gen": snap.gen, "host_id": self.host_id,
                 "versions": dict(snap.versions),
                 "arrays": {k: np.array(a, copy=True)
                            for k, a in snap.refs.items()},
                 "codecs": {k: (dict(kw), numel, np.dtype(dt).str)
                            for k, (kw, _dec, numel, dt)
                            in snap.codecs.items()},
                 "enc": dict(snap.enc_cache)}
        blob = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
        frame = _integrity.seal_bytes(blob, key="serve-arc", seq=snap.id)
        try:
            os.makedirs(os.path.dirname(self._arc_path), exist_ok=True)
            _wal._atomic_write(self._arc_path, frame)
        except OSError:
            counters.inc("wal.arc_save_failures")
            get_logger().error(
                "serve host %d: durable arc persist failed for commit "
                "%d — a restart re-ships the arc", self.host_id,
                snap.id, exc_info=True)
            return
        counters.inc("wal.arc_saves")

    def _restore_arc(self) -> None:
        """Cold-start restore of the last committed snapshot — runs in
        ``__init__`` so the host's ring is populated BEFORE it
        registers with the directory.  A blob that fails verification
        is quarantined (removed, counted), never published."""
        path = self._arc_path
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path, "rb") as fh:
                frame = fh.read()
            blob, _meta = _integrity.open_bytes(frame)
            # restricted unpickler (server/wal.py): the seal is CRC, not
            # authentication — a writable durable dir must not name
            # arbitrary callables
            from . import wal as _wal_mod
            state = _wal_mod._loads(blob)
            refs: Dict[str, np.ndarray] = {}
            for k, a in state["arrays"].items():
                arr = np.array(a, copy=True)
                arr.flags.writeable = False
                refs[k] = arr
            codecs = {}
            for k, (kw, numel, dtype_s) in state["codecs"].items():
                codecs[k] = (dict(kw),
                             self._decoder(k, (dict(kw), numel, dtype_s)),
                             numel, np.dtype(dtype_s))
            snap = Snapshot(id=int(state["id"]), ts=time.monotonic(),
                            versions=dict(state["versions"]), refs=refs,
                            gen=int(state["gen"]), codecs=codecs,
                            enc_cache=dict(state.get("enc") or {}))
        except Exception as e:  # noqa: BLE001 — any failure here is a
            # corrupt or torn blob; restart-in-place degrades to the
            # full re-ship, never a half-restored arc
            counters.inc("wal.arc_corrupt")
            get_logger().error(
                "serve host %d: durable arc at %s failed verification "
                "(%s) — removed; the publisher re-ships the arc",
                self.host_id, path, e)
            from ..common import flight_recorder as _flight
            _flight.record("wal.arc_corrupt", host=self.host_id,
                           reason=str(e))
            try:
                os.remove(path)
            except OSError:
                pass
            return
        with self._stage_lock:
            self.ring.publish(snap)
            self._last_commit = snap.id
            self.restored_commit = snap.id
        counters.inc("wal.arc_restores")
        gauges.set("serve.snapshot_id", snap.id)
        from ..common import flight_recorder as _flight
        _flight.record("wal.arc_restored", host=self.host_id,
                       snapshot_id=snap.id, keys=len(refs))
        get_logger().warning(
            "serve host %d: restored committed arc from disk — "
            "snapshot %d, %d key(s) (restart-in-place, no re-ship)",
            self.host_id, snap.id, len(refs))

    # -- the publication path (transport hops land here) --------------------

    def _decoder(self, key: str, codec: tuple):
        kwargs, numel, dtype_s = codec
        sig = (tuple(sorted(kwargs.items())), numel, dtype_s)
        ent = self._decoders.get(key)
        if ent is None or ent[0] != sig:
            from ..compression import registry as reg
            ent = (sig, reg.create(dict(kwargs), numel, np.dtype(dtype_s),
                                   for_server=True))
            self._decoders[key] = ent
        return ent[1]

    def receive_key(self, key: str, payload, meta: dict) -> None:
        """Stage one shipped key (hop ``serve_cut``): an ndarray for raw
        keys, wire-encoded codec bytes otherwise — decoded HERE so the
        pull path serves materialized values, with the encoded bytes
        kept as the snapshot's encode cache (a client pulling the codec
        key gets the exact bytes the trainer shipped, zero
        re-compression)."""
        codec = meta.get("codec")
        if codec is not None:
            enc = bytes(payload)
            comp = self._decoder(key, tuple(codec))
            arr = np.array(comp.decompress(comp.wire_decode(enc)),
                           copy=True)
            nbytes = len(enc)
        else:
            enc = None
            arr = np.array(payload, copy=True)
            nbytes = arr.nbytes
        arr.flags.writeable = False
        with self._stage_lock:
            self._staged[key] = _Staged(arr, int(meta["version"]),
                                        tuple(codec) if codec else None,
                                        enc)
        counters.inc("serve.tier_recv_keys")
        counters.inc("serve.tier_recv_bytes", nbytes)

    def commit(self, meta: dict) -> dict:
        """Publish one cut (hop ``serve_commit``): ``meta['versions']``
        is this host's FULL owned key->version map for the cut; staged
        entries satisfy advanced versions, the previous snapshot carries
        unchanged ones forward."""
        sid = int(meta["snapshot_id"])
        gen = int(meta.get("gen", 0))
        versions: Dict[str, int] = {k: int(v)
                                    for k, v in meta["versions"].items()}
        with self._stage_lock:
            if sid <= self._last_commit:
                # transport retransmit of an applied commit: idempotent
                return {"snapshot_id": self._last_commit, "dup": True}
            prev = self.ring.latest()
            refs: Dict[str, np.ndarray] = {}
            codecs: Dict[str, tuple] = {}
            enc: Dict[str, bytes] = {}
            kept: Dict[str, int] = {}
            dropped: List[str] = []
            for k, ver in versions.items():
                st = self._staged.get(k)
                if st is not None and st.version == ver:
                    refs[k] = st.arr
                    kept[k] = ver
                    if st.codec is not None:
                        kwargs, numel, dtype_s = st.codec
                        codecs[k] = (dict(kwargs),
                                     self._decoder(k, st.codec), numel,
                                     np.dtype(dtype_s))
                        enc[k] = st.enc
                elif (prev is not None and prev.gen == gen
                        and prev.versions.get(k) == ver):
                    refs[k] = prev.refs[k]
                    kept[k] = ver
                    if k in prev.codecs:
                        codecs[k] = prev.codecs[k]
                        cached = prev.enc_cache.get(k)
                        if cached is not None:
                            enc[k] = cached
                else:
                    dropped.append(k)
            missing = len(dropped)
            # staged entries at or below the committed version are
            # consumed; newer ones (a racing next cut's early frames)
            # stay for THEIR commit
            for k in list(self._staged):
                st = self._staged[k]
                if st.version <= versions.get(k, st.version):
                    del self._staged[k]
            self._last_commit = sid
            snap = Snapshot(id=sid, ts=time.monotonic(), versions=kept,
                            refs=refs, gen=gen, codecs=codecs,
                            enc_cache=enc)
            self.ring.publish(snap)
        if self._arc_path is not None:
            self._persist_arc(snap)
        if missing:
            counters.inc("serve.tier_missing_keys", missing)
            get_logger().warning(
                "serve host %d: commit %d missing %d key(s) (neither "
                "staged nor carried) — re-shipped at the next cut",
                self.host_id, sid, missing)
        counters.inc("serve.tier_commits")
        gauges.set("serve.snapshot_id", sid)
        # the DROPPED key list travels back so the publisher acks only
        # what the host actually published — acking the full owned map
        # would mean a restarted host's holes (nothing staged, nothing
        # to carry forward) were never re-shipped until the key next
        # changed
        return {"snapshot_id": sid, "keys": len(kept), "missing": missing,
                "dropped": dropped}

    def control(self, meta: dict) -> dict:
        """Ring-aware chaos / management channel (hop ``serve_ctl``):
        ``chaos_arm`` installs a fault spec in THIS host mid-run — the
        harness partitions or throttles one serving host by ring
        identity while a storm is in flight, no restart, no cooperating
        schedule."""
        cmd = meta.get("cmd")
        if cmd == "chaos_arm":
            _fault.arm(meta["spec"], seed=int(meta.get("seed", 0)),
                       rank=self.host_id)
            return {"armed": meta["spec"]}
        if cmd == "chaos_disarm":
            _fault.disarm()
            return {"disarmed": True}
        if cmd == "drain":
            # graceful retirement (the reconciler's scale-down path):
            # flip the latch and ACK with the current in-flight depth —
            # the process-level state machine (serve_host.py) marks the
            # directory, finishes in-flight pulls, unregisters, exits.
            # Idempotent: a retransmitted drain finds the latch set.
            self.draining.set()
            counters.inc("serve.drain_requested")
            return {"draining": True,
                    "inflight": self.admission.inflight}
        if cmd == "arc_info":
            # durable restart-in-place (server/wal.py): the publisher
            # probes a fresh incarnation for what it already publishes
            # — a host restored from its on-disk arc answers with its
            # committed versions, and the publisher ships only the
            # drift instead of the full owned slice
            with self._stage_lock:
                snap = self.ring.latest()
                if snap is None:
                    return {"snapshot_id": 0, "gen": 0, "versions": {}}
                return {"snapshot_id": snap.id, "gen": snap.gen,
                        "versions": dict(snap.versions),
                        "restored": self.restored_commit}
        raise ValueError(f"unknown serve_ctl command {cmd!r}")

    # -- the read path -------------------------------------------------------

    def _can_shed(self, since_id: Optional[int],
                  max_stale_s: Optional[float]) -> bool:
        """Shedding is allowed only when the client keeps its OWN
        guarantee: its delta base is still retained, same generation,
        and young enough that "keep your cache" leaves it inside its
        staleness bound.  Anyone else is served despite the pressure."""
        if since_id is None:
            return False
        latest = self.ring.latest()
        base = self.ring.get(since_id)
        if latest is None or base is None or base.gen != latest.gen:
            return False
        from ..common.config import get_config
        bound = (get_config().serve_max_staleness_s if max_stale_s is None
                 else float(max_stale_s))
        return (latest.ts - base.ts) <= bound

    def pull(self, since_id: Optional[int] = None,
             keys: Optional[List[str]] = None,
             max_stale_s: Optional[float] = None) -> ServeReply:
        self.admission.enter()
        try:
            gauges.set("serve.tier_queue_depth", self.admission.inflight)
            if not self.admission.admit():
                if self._can_shed(since_id, max_stale_s):
                    self.sheds += 1
                    counters.inc("serve.shed")
                    return ServeReply(snapshot_id=since_id, full=False,
                                      items={}, wire_bytes=0,
                                      server_id=self.host_id, shed=True)
                counters.inc("serve.shed_bypass")
            if _fault.ENABLED:
                _fault.fire("serve_host")
                _fault.on_serve()
            reply = self.server.pull(since_id=since_id, keys=keys)
            self.pulls += 1
            # the established serving counter, emitted HERE too: the
            # bps_top PULLS and SHED% cells for a tier row are computed
            # from the host's published registry snapshot, not from the
            # in-process plane this host never runs
            counters.inc("serve.pulls")
            hot = keys if keys else list(reply.items)
            if hot:
                with self._stage_lock:
                    for k in hot:
                        self._pull_counts[k] = \
                            self._pull_counts.get(k, 0) + 1
            return reply
        finally:
            self.admission.exit()
            gauges.set("serve.tier_tokens", self.admission.snapshot()["tokens"])

    def hot_keys(self, top_n: int = 8) -> List[str]:
        with self._stage_lock:
            ranked = sorted(self._pull_counts.items(),
                            key=lambda kv: (-kv[1], kv[0]))
            return [k for k, c in ranked[:top_n] if c > 0]

    def debug_state(self) -> dict:
        snap = self.ring.latest()
        with self._stage_lock:
            staged = len(self._staged)
        return {"kind": "serving_host",
                "host_id": self.host_id,
                "snapshot_id": snap.id if snap is not None else None,
                "keys": len(snap.versions) if snap is not None else 0,
                "durable": self._arc_path is not None,
                "restored_commit": self.restored_commit,
                "staged": staged,
                "pulls": self.pulls,
                "sheds": self.sheds,
                "draining": self.draining.is_set(),
                "hot_keys": self.hot_keys(4),
                "admission": self.admission.snapshot()}


# -- in-process host registry (tests / single-process tiers) ----------------

_inproc: Dict[int, ServingHostCore] = {}
_inproc_lock = threading.Lock()
# every ServingTier/TierRouter that may own TcpEndpoints (they dial
# serving hosts DIRECTLY, outside transport.endpoint_to's cache, so the
# transport module's test reset cannot see them): weakly tracked so the
# test harness can close leaked supervisors between tests
_closables: "weakref.WeakSet" = weakref.WeakSet()


def inproc_host(core: Optional[ServingHostCore] = None,
                host_id: Optional[int] = None):
    """Register (or look up) an in-process serving host.  The publisher
    and router short-circuit transport for registered ids — the
    same-process fast path the loopback endpoint gives the training
    plane, so unit tests exercise the full stage/commit/shed protocol
    without sockets."""
    with _inproc_lock:
        if core is not None:
            _inproc[core.host_id] = core
            return core
        return _inproc.get(host_id)


def _close_endpoint(ep) -> None:
    """Best-effort endpoint teardown (shared by every drop site)."""
    try:
        ep.close(drain=False)
    except Exception:  # noqa: BLE001 — teardown best-effort
        pass


def _resolve_endpoint(host: int, addr, conn_kw: dict):
    """ONE endpoint-resolution policy for publisher and router alike:
    the in-process fast path when the host core lives here, else a
    supervised TCP endpoint at ``SERVE_RANK_BASE + host`` (the tier's
    peer-id namespace)."""
    core = inproc_host(host_id=host)
    if core is not None:
        return _InprocEndpoint(core)
    if addr is None:
        raise ServeUnavailable(f"serve host {host} has no address")
    from ..comm.transport import TcpEndpoint
    return TcpEndpoint(addr, peer=SERVE_RANK_BASE + host, **conn_kw)


class _InprocEndpoint:
    """Direct-call endpoint for a registered in-process host (protocol
    identical to the TCP hops, minus serialization)."""

    def __init__(self, core: ServingHostCore):
        self._core = core

    def serve_cut(self, key, payload, *, snapshot_id, version, codec=None,
                  deadline_s=None, gen=0):
        del snapshot_id, deadline_s, gen
        self._core.receive_key(key, payload,
                               {"version": version, "codec": codec})

    def serve_commit(self, *, snapshot_id, gen, versions, deadline_s=None):
        del deadline_s
        return self._core.commit({"snapshot_id": snapshot_id, "gen": gen,
                                  "versions": versions})

    def serve_ctl(self, **meta):
        return self._core.control(meta)

    def serve_pull(self, since_id=None, keys=None, max_stale_s=None,
                   deadline_s=None):
        del deadline_s
        return self._core.pull(since_id=since_id, keys=keys,
                               max_stale_s=max_stale_s)

    def close(self, drain=True):
        pass


# -- the directory (membership-bus client) -----------------------------------


class TierDirectory:
    """The serving-host directory: who is in the tier, at which address,
    as of which generation.

    Backed by the membership bus when ``bus`` (or
    ``BYTEPS_SERVE_TIER_BUS``) names one — registrations TTL out, the
    autoscaler's target proposal rides the same replies, and a
    coordinator failover carries the directory to the successor
    (``_replica_snapshot``).  With no bus it is a local in-process
    directory — single-process tiers and unit tests."""

    def __init__(self, bus=None, static_hosts=None,
                 ttl_s: Optional[float] = None,
                 poll_interval_s: float = 0.25):
        from ..common.config import get_config
        cfg = get_config()
        if bus is None and cfg.serve_tier_bus:
            bus = cfg.serve_tier_bus
        if isinstance(bus, str):
            host, port = bus.rsplit(":", 1)
            bus = (host, int(port))
        self.bus: Optional[Tuple[str, int]] = bus
        self.ttl_s = cfg.serve_tier_ttl_s if ttl_s is None else float(ttl_s)
        self._poll = poll_interval_s
        self._lock = threading.Lock()
        self._gen = 0
        self._hosts: Dict[int, Tuple[str, int]] = {}
        self._meta: Dict[int, dict] = {}
        self._probation: List[int] = []
        self._draining: List[int] = []
        self._victims: List[int] = []
        self._target: Optional[int] = None
        self._fetched = 0.0
        self._next_id = itertools.count(0)
        if static_hosts:
            for hid, addr in dict(static_hosts).items():
                self._hosts[int(hid)] = (str(addr[0]), int(addr[1]))
            self._gen = 1

    def _request(self, msg: dict) -> dict:
        from ..fault.membership import bus_request
        return bus_request(self.bus, msg, timeout=5.0)

    # -- registration (host side) -------------------------------------------

    def register(self, addr, host_id: Optional[int] = None,
                 meta: Optional[dict] = None,
                 draining: bool = False) -> int:
        """``draining=True`` marks the registration as mid graceful
        drain: the directory keeps the host visible (its in-flight pulls
        still need the address) but every consumer's :meth:`hosts` view
        excludes it, so no NEW pulls route there — the routing half of
        the ``serve_ctl drain`` protocol (docs/serving.md)."""
        addr = (str(addr[0]), int(addr[1]))
        if self.bus is None:
            with self._lock:
                if host_id is None:
                    host_id = (max(self._hosts) + 1 if self._hosts else 0)
                hid = int(host_id)
                changed = self._hosts.get(hid) != addr
                self._hosts[hid] = addr
                self._meta[hid] = dict(meta or {})
                if draining != (hid in self._draining):
                    if draining:
                        self._draining.append(hid)
                    else:
                        self._draining.remove(hid)
                    changed = True
                if changed:
                    self._gen += 1
                return hid
        reply = self._request({"op": "serve_register", "host_id": host_id,
                              "addr": list(addr), "ttl_s": self.ttl_s,
                              "draining": bool(draining),
                              "meta": meta or {}})
        if not reply.get("ok"):
            if reply.get("banned"):
                raise ConnectionError(
                    f"serve host {host_id} is banned for "
                    f"{reply.get('retry_after_s')}s (recently retired — "
                    "the publisher evicted it after ship failures)")
            raise ConnectionError(f"serve_register refused: {reply!r}")
        return int(reply["host_id"])

    def unregister(self, host_id: int,
                   ban_s: Optional[float] = None) -> None:
        if self.bus is None:
            with self._lock:
                hid = int(host_id)
                if self._hosts.pop(hid, None) is not None:
                    self._meta.pop(hid, None)
                    self._gen += 1
                if hid in self._draining:
                    self._draining.remove(hid)
                if hid in self._victims:
                    self._victims.remove(hid)
            return
        try:
            self._request({"op": "serve_unregister",
                           "host_id": int(host_id),
                           "ban_s": ban_s})
        except (ConnectionError, TimeoutError):
            # unreachable OR stalled (bus_request raises
            # MembershipTimeout, a TimeoutError, on a slow established
            # connection): TTL expiry finishes the job either way
            get_logger().warning("serve_unregister(%d) bus unreachable "
                                 "or stalled", host_id)

    # -- consumption (router / publisher / autoscaler side) -----------------

    def refresh(self, force: bool = False) -> None:
        if self.bus is None:
            return
        now = time.monotonic()
        with self._lock:
            if not force and now - self._fetched < self._poll:
                return
            self._fetched = now   # claim the poll slot before the wire
        try:
            reply = self._request({"op": "serve_dir"})
        except (ConnectionError, TimeoutError):
            # unreachable or stalled bus (MembershipTimeout is a
            # TimeoutError): keep the cached view — a bus hiccup must
            # degrade to stale routing, not fail a training push whose
            # write-driven cut landed here or a client read mid-_sync
            return
        if not reply.get("ok"):
            return
        with self._lock:
            self._gen = int(reply["gen"])
            self._hosts = {int(h): (v["addr"][0], int(v["addr"][1]))
                           for h, v in reply["hosts"].items()}
            self._meta = {int(h): dict(v.get("meta") or {})
                          for h, v in reply["hosts"].items()}
            self._probation = [int(r) for r in reply.get("probation") or ()]
            self._draining = [int(h) for h in reply.get("draining") or ()]
            self._victims = [int(h) for h in reply.get("victims") or ()]
            self._target = reply.get("target")

    def hosts(self, force: bool = False) -> Tuple[int, Dict[int,
                                                            Tuple[str, int]]]:
        """``(generation, PLACED hosts)`` — probationed hosts are
        excluded here, for PUBLISHER and ROUTER alike: a host the
        autoscaler demoted stops receiving cuts, so clients must stop
        reading its frozen snapshot too (the asymmetry would serve
        unboundedly stale data as fresh).  Probation changes bump the
        generation, so consumers re-sync exactly when it changes.  The
        raw registration list (probation included) is in
        :meth:`info`.  DRAINING hosts are excluded the same way: a
        draining host finishes its in-flight pulls but must receive no
        new ones — the gen bump at the drain mark re-syncs every
        consumer off its arc."""
        self.refresh(force=force)
        with self._lock:
            return self._gen, {h: a for h, a in self._hosts.items()
                               if h not in self._probation
                               and h not in self._draining}

    def info(self) -> dict:
        self.refresh()
        with self._lock:
            return {"gen": self._gen, "hosts": dict(self._hosts),
                    "meta": {h: dict(m) for h, m in self._meta.items()},
                    "probation": list(self._probation),
                    "draining": list(self._draining),
                    "victims": list(self._victims),
                    "target": self._target}

    def set_target(self, target: Optional[int]) -> None:
        if self.bus is None:
            with self._lock:
                self._target = target
            return
        self._request({"op": "serve_scale", "target": target})

    def set_probation(self, hosts) -> None:
        """Publish the serving-host probation set (autoscaler): rides
        the same ``serve_scale`` verb; the bus bumps the generation on
        change so every ring consumer re-routes the demoted arcs."""
        probation = sorted(int(h) for h in hosts)
        if self.bus is None:
            with self._lock:
                if set(probation) != set(self._probation):
                    self._probation = probation
                    self._gen += 1
            return
        self._request({"op": "serve_scale", "probation": probation})

    def propose_victims(self, hosts) -> None:
        """Publish the autoscaler's scale-down victim PROPOSALS (rides
        ``serve_scale`` like the target): the reconciler reads them from
        ``serve_dir`` and retires each through the graceful drain
        protocol instead of an immediate unregister.  No gen bump —
        routing only changes when a victim actually flips to
        DRAINING."""
        victims = sorted(int(h) for h in hosts)
        if self.bus is None:
            with self._lock:
                self._victims = [h for h in victims if h in self._hosts]
            return
        self._request({"op": "serve_scale", "victims": victims})

    def target(self) -> Optional[int]:
        self.refresh()
        with self._lock:
            return self._target


# -- the publisher (trainer side) --------------------------------------------


class ServingTier:
    """Ships the live store's cuts to the serving hosts and hands out
    ring-routed clients.  ``cut()`` is the publication point (manual, or
    write-driven via ``cut_interval_s`` exactly like the in-process
    plane); each host receives only the keys the ring assigns it whose
    versions advanced since its last acknowledged commit."""

    def __init__(self, store, *, bus=None, directory=None,
                 static_hosts=None, replicas: Optional[int] = None,
                 vnodes: Optional[int] = None,
                 retention: Optional[int] = None,
                 cut_interval_s: Optional[float] = None,
                 ship_deadline_s: float = 2.0,
                 fail_streak: int = 2,
                 conn_kw: Optional[dict] = None,
                 update_slots=None):
        from ..common.config import get_config
        cfg = get_config()
        self.store = store
        # shard-published cuts (ISSUE 20): a mapping name -> slot, or a
        # zero-arg callable returning one; None auto-discovers the live
        # engine's sharded-update slots at each cut
        self._update_slots = update_slots
        self._pub_applied: Dict[str, int] = {}
        self.replicas = (cfg.serve_tier_replicas if replicas is None
                         else int(replicas))
        self.directory = directory if directory is not None else \
            TierDirectory(bus=bus, static_hosts=static_hosts)
        self.ring = ServeRing(vnodes=vnodes)
        self._gen = -1
        self._ship_deadline = float(ship_deadline_s)
        self._fail_streak = int(fail_streak)
        self._conn_kw = dict(conn_kw or {})
        self._lock = named_lock("serve_tier.pub")
        self._cut_serial = named_lock("serve_tier.cut")
        self._addrs: Dict[int, Tuple[str, int]] = {}
        self._shipped: Dict[int, Dict[str, int]] = {}
        self._fails: Dict[int, int] = {}
        self._eps: Dict[int, object] = {}
        self._owner_memo: Dict[object, List[int]] = {}
        self._probation: set = set()
        # write-driven cutting runs on a DEDICATED publisher thread: a
        # tier cut SHIPS over the network (per-host threads joined up
        # to ship_deadline_s, plus a directory round trip) — inline in
        # the pusher's thread that would stall training pushes seconds
        # per cut whenever a host is dead or the bus is slow.  The
        # in-process plane's inline write-driven cut is fine because it
        # copies nothing; this one talks to real sockets.  The write
        # hook therefore only SIGNALS; bursts coalesce into one cut.
        self._cut_wake = threading.Event()
        self._cut_stop = threading.Event()
        self._cut_thread: Optional[threading.Thread] = None
        self.snapstore = SnapshotStore(store, retention=retention,
                                       cut_interval_s=cut_interval_s,
                                       cut_fn=self._request_cut,
                                       defer_subscribe=True)
        from ..common import metrics as _metrics
        _metrics.register_component("serving_tier", self)
        _closables.add(self)
        if cut_interval_s is not None:
            self._cut_thread = threading.Thread(
                target=self._cut_loop, daemon=True, name="bps-tier-pub")
            self._cut_thread.start()
        self.snapstore.attach()

    def _request_cut(self) -> None:
        self._cut_wake.set()

    def _cut_loop(self) -> None:
        while True:
            self._cut_wake.wait()
            if self._cut_stop.is_set():
                return
            self._cut_wake.clear()
            try:
                self.cut()
            except Exception:  # noqa: BLE001 — a failed publish must
                # not kill the publisher thread; the next write retries
                get_logger().error("serving tier: write-driven cut "
                                   "failed", exc_info=True)

    # -- membership ----------------------------------------------------------

    def refresh_directory(self, force: bool = False) -> None:
        gen, hosts = self.directory.hosts(force=force)
        with self._lock:
            if gen == self._gen:
                return
            self._gen = gen
            placed = set(hosts) - self._probation
            # a host re-registered under the SAME id at a NEW address is
            # a new incarnation (the reconciler's restart-in-place): its
            # staged state is gone, so the cached connection, the acked
            # map, and the fail streak all describe a dead process —
            # drop them, and the next cut re-ships the full owned slice
            moved = {h for h, a in hosts.items()
                     if h in self._addrs and self._addrs[h] != a}
            stale_eps = [self._eps.pop(h) for h in list(self._eps)
                         if h not in hosts or h in moved]
            for h in list(self._shipped):
                if h not in hosts or h in moved:
                    del self._shipped[h]
                    self._fails.pop(h, None)
            self._addrs = dict(hosts)
            self._owner_memo.clear()
        self.ring.set_hosts(placed)
        for ep in stale_eps:
            _close_endpoint(ep)
        gauges.set("serve.tier_hosts", len(self.ring))
        gauges.set("serve.tier_gen", gen)
        for h, share in self.ring.arc_share().items():
            gauges.set("serve.tier_arc_share", round(share, 4), host=h)

    def set_probation(self, hosts) -> None:
        """Exclude ``hosts`` from placement (the autoscaler's gray-
        failure signal): published THROUGH the directory (bus verb
        ``serve_scale``), so the publisher stops shipping AND every
        client router stops reading the demoted arcs — the one-sided
        version would leave clients pinned to a host whose snapshot no
        longer advances, serving unboundedly stale data as fresh.
        Demoted, not unregistered: a recovered host returns on the next
        probation clear without re-registering."""
        with self._lock:
            self._probation = {int(h) for h in hosts}
            self._gen = -1      # force a re-derive at the next cut
        try:
            self.directory.set_probation(hosts)
        except (ConnectionError, TimeoutError):
            get_logger().warning("serving tier: probation update could "
                                 "not reach the bus (will retry at the "
                                 "next autoscaler step)")
        self.refresh_directory(force=True)

    def _endpoint(self, host: int):
        with self._lock:
            ep = self._eps.get(host)
            addr = self._addrs.get(host)
        if ep is not None:
            return ep
        ep = _resolve_endpoint(host, addr, self._conn_kw)
        with self._lock:
            self._eps.setdefault(host, ep)
        return ep

    def retire_host(self, host: int, reason: str = "") -> None:
        """Drop a host NOW: unregister from the directory (gen bumps for
        every consumer) and heal the local ring without waiting for the
        TTL."""
        get_logger().warning("serving tier: retiring host %d (%s)", host,
                             reason)
        counters.inc("serve.tier_retired")
        # the ban outlives a few heartbeat periods: a retired host whose
        # control plane still beats must not flap back into the ring
        self.directory.unregister(host,
                                  ban_s=max(10.0,
                                            3 * self.directory.ttl_s))
        with self._lock:
            ep = self._eps.pop(host, None)
            self._shipped.pop(host, None)
            self._fails.pop(host, None)
            self._owner_memo.clear()
        self.ring.remove(host)
        if ep is not None:
            _close_endpoint(ep)

    # -- publication ---------------------------------------------------------

    def _update_slot_map(self) -> Dict[str, object]:
        src = self._update_slots
        if src is None:
            from ..core import api as _api
            eng = _api._engine
            return dict(getattr(eng, "update_slots", None) or {})
        return dict(src() if callable(src) else src)

    def _publish_update_slots(self) -> None:
        """Shard-published serving cut (ISSUE 20): under sharded update
        the parameters live as owner-resident flat shards inside the
        engine — there is no replicated copy to snapshot.  Each owner's
        slice is published into the store as its own key
        (``name@shard{i}``, plus a ``name@shards`` manifest for
        read-side reassembly, :func:`assemble_shard_keys`), so the ring
        routes every slice to its arc directly and NO step of this path
        allocates a full-parameter buffer (``ShardedUpdateSlot.
        export_shards`` reads per-device shards; ``publish_key``
        overwrites exactly — a delta-summed refresh would re-round).
        Slots whose ``applied`` counter has not advanced since the last
        cut publish nothing, so steady-state cuts are write-free."""
        try:
            slots = self._update_slot_map()
        except Exception:  # noqa: BLE001 — a torn-down engine mid-cut
            # must not fail the cut of everything else in the store
            get_logger().warning("serving tier: sharded-update slot "
                                 "discovery failed", exc_info=True)
            return
        for name, slot in sorted(slots.items()):
            applied = int(getattr(slot, "applied", 0))
            if self._pub_applied.get(name) == applied:
                continue
            shards = slot.export_shards()
            nbytes = 0
            for i, (_, _, arr) in enumerate(shards):
                self.store.publish_key(f"{name}@shard{i}", arr)
                nbytes += arr.nbytes
            meta = np.array([len(shards), slot.n, slot.C]
                            + list(slot.out_shape), np.int64)
            self.store.publish_key(f"{name}@shards", meta)
            self._pub_applied[name] = applied
            counters.inc("serve.shard_publishes")
            counters.inc("serve.shard_publish_bytes", nbytes)

    def _replica_hosts(self, key) -> List[int]:
        memo = self._owner_memo.get(key)
        if memo is None:
            memo = self.ring.replica_hosts(key, self.replicas)
            self._owner_memo[key] = memo
        return memo

    def cut(self) -> Optional[Snapshot]:
        """Snapshot the store and ship every host its changed slice
        (concurrently — one slow host must not serialize the others
        behind its deadline).  Returns the snapshot, or None when the
        tier has no hosts yet."""
        with self._cut_serial:
            self.refresh_directory()
            self._publish_update_slots()
            snap = self.snapstore.cut()
            hosts = sorted(self.ring.hosts())
            if not hosts:
                return snap
            results: Dict[int, bool] = {}
            threads = []
            for h in hosts:
                t = threading.Thread(target=self._ship_host,
                                     args=(h, snap, results),
                                     daemon=True,
                                     name=f"bps-tier-ship-{h}")
                threads.append(t)
                t.start()
            for t in threads:
                t.join()
            for h, ok in results.items():
                if ok:
                    self._fails[h] = 0
                    continue
                self._fails[h] = self._fails.get(h, 0) + 1
                if self._fails[h] >= self._fail_streak:
                    self.retire_host(h, reason="consecutive ship failures")
            return snap

    def _ship_host(self, host: int, snap: Snapshot,
                   results: Dict[int, bool]) -> None:
        owned = [k for k in snap.versions
                 if host in self._replica_hosts(k)]
        with self._lock:
            acked = dict(self._shipped.get(host, {}))
        shipped_bytes = 0
        try:
            ep = self._endpoint(host)
            if not acked:
                # no ship history for this incarnation — before blindly
                # re-shipping the full owned slice, ask the host what it
                # already publishes: one restored from its durable arc
                # (server/wal.py restart-in-place) answers with its
                # committed versions, and only the drift ships over DCN.
                # A probe failure just means the conservative full ship.
                try:
                    info = ep.serve_ctl(cmd="arc_info")
                    if int(info.get("gen", -1)) == snap.gen:
                        acked = {k: int(v) for k, v in
                                 (info.get("versions") or {}).items()}
                        if acked:
                            counters.inc("wal.arc_probe_hits")
                except Exception:  # noqa: BLE001 — probe is best-effort
                    pass
            changed = [k for k in owned if acked.get(k) != snap.versions[k]]
            for k in changed:
                info = snap.codecs.get(k)
                if info is not None:
                    kwargs, comp, numel, dtype = info
                    wire = snap.enc_cache.get(k)
                    if wire is None:
                        wire = comp.wire_encode(
                            comp.compress(snap.refs[k],
                                          comp.init_state())[0])
                        snap.enc_cache[k] = wire
                    ep.serve_cut(k, wire, snapshot_id=snap.id,
                                 version=snap.versions[k],
                                 codec=(dict(kwargs), numel,
                                        np.dtype(dtype).str),
                                 deadline_s=self._ship_deadline)
                    shipped_bytes += len(wire)
                else:
                    ep.serve_cut(k, snap.refs[k], snapshot_id=snap.id,
                                 version=snap.versions[k],
                                 deadline_s=self._ship_deadline)
                    shipped_bytes += snap.refs[k].nbytes
            reply = ep.serve_commit(
                snapshot_id=snap.id, gen=snap.gen,
                versions={k: snap.versions[k] for k in owned},
                deadline_s=self._ship_deadline)
        except Exception as e:  # noqa: BLE001 — a dead host fails ITS
            # ship; the commit was never sent, so the host's previous
            # snapshot stays live and nothing is half-published
            counters.inc("serve.tier_ship_failures")
            get_logger().warning("serving tier: ship to host %d failed: %s",
                                 host, e)
            results[host] = False
            return
        # ack only what the host actually PUBLISHED: keys it reported
        # dropped (e.g. a restarted host with nothing to carry forward)
        # stay un-acked and re-ship at the next cut.  A dup reply (this
        # commit was a retransmit) carries no drop list — keep the
        # previous acks and let the next cut reconcile.
        if not reply.get("dup"):
            dropped = set(reply.get("dropped") or ())
            with self._lock:
                self._shipped[host] = {k: snap.versions[k] for k in owned
                                       if k not in dropped}
        counters.inc("serve.tier_ships")
        counters.inc("serve.tier_ship_bytes", shipped_bytes)
        results[host] = True

    # -- clients -------------------------------------------------------------

    def client(self, keys: Optional[List[str]] = None, **kw):
        """A staleness-bounded :class:`~.serve_client.PullClient` routed
        by the tier's ring (fresh router per client — the router keeps
        per-host delta bases)."""
        from .serve_client import PullClient
        router = TierRouter(self.directory, replicas=self.replicas,
                            conn_kw=self._conn_kw,
                            pull_deadline_s=kw.pop("pull_deadline_s",
                                                   self._ship_deadline))
        kw.setdefault("stale_on_error", True)
        return PullClient(router, keys=keys, **kw)

    # -- lifecycle / observability -------------------------------------------

    def close(self) -> None:
        self.snapstore.detach()
        self._cut_stop.set()
        self._cut_wake.set()
        if self._cut_thread is not None:
            self._cut_thread.join(timeout=10)
        with self._lock:
            eps = list(self._eps.values())
            self._eps.clear()
        for ep in eps:
            _close_endpoint(ep)

    def debug_state(self) -> dict:
        snap = self.snapstore.ring.latest()
        with self._lock:
            fails = dict(self._fails)
            shipped = {h: len(v) for h, v in self._shipped.items()}
            probation = sorted(self._probation)
        return {"kind": "serving_tier",
                "gen": self._gen,
                "hosts": sorted(self.ring.hosts()),
                "replicas": self.replicas,
                "snapshot_id": snap.id if snap is not None else None,
                "arc_share": {h: round(s, 4)
                              for h, s in self.ring.arc_share().items()},
                "shipped_keys": shipped,
                "fail_streaks": fails,
                "probation": probation}


# -- the router (client side) ------------------------------------------------


class TierRouter:
    """Plane-shaped router for ONE :class:`~.serve_client.PullClient`:
    resolves keys to hosts on the ring, keeps a per-host delta base
    (``since_id`` is per HOST — each host numbers its own snapshots),
    fails over along each key's replica arc, and merges the per-host
    slices into one reply with a synthetic monotonic snapshot id.

    On ``ServeUnavailable`` from every candidate the client's refresh
    calls :meth:`reroute` — a FORCED directory re-sync — and retries, so
    a dead host's arc remaps within one pull instead of parking on the
    corpse until the next cut (the single-flight background refresh used
    to do exactly that)."""

    accepts_max_stale = True
    client_owned = True     # one router per PullClient; client.close()
    #                         closes it (supervised connections inside)

    def __init__(self, directory: TierDirectory, *,
                 replicas: Optional[int] = None,
                 vnodes: Optional[int] = None,
                 conn_kw: Optional[dict] = None,
                 pull_deadline_s: float = 2.0,
                 sync_interval_s: float = 0.25):
        from ..common.config import get_config
        cfg = get_config()
        self.directory = directory
        self.replicas = (cfg.serve_tier_replicas if replicas is None
                         else int(replicas))
        self.ring = ServeRing(vnodes=vnodes)
        self._conn_kw = dict(conn_kw or {})
        self._deadline = float(pull_deadline_s)
        self._sync_every = float(sync_interval_s)
        self._lock = threading.Lock()
        self._gen = -1
        self._addrs: Dict[int, Tuple[str, int]] = {}
        self._eps: Dict[int, object] = {}
        self._owner_memo: Dict[object, List[int]] = {}
        self._since: Dict[int, Optional[int]] = {}
        self._synced = 0.0
        self._ids = itertools.count(1)
        self.host_pulls: Dict[int, int] = {}
        _closables.add(self)
        # whole-model routing state: the key universe learned from
        # replies.  Once known, a keys=None pull asks each key's OWNER
        # only (a key lives on R hosts; fanning keys=None everywhere
        # would ship every changed key R times), with one ROTATING host
        # per pull still serving its whole slice so keys that appear
        # later are discovered within ~N pulls.
        self._known: set = set()
        self._disc = 0

    def _sync(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if not force and now - self._synced < self._sync_every \
                    and self._gen >= 0:
                return
            self._synced = now
        gen, hosts = self.directory.hosts(force=force)
        with self._lock:
            if gen == self._gen:
                return
            self._gen = gen
            # same-id re-registration at a new address = a RESTARTED
            # host (new incarnation): the cached connection dials a dead
            # port and the delta base refers to the old process's
            # snapshot numbering — both must go, or every pull to the
            # arc parks on the corpse / reads a bogus delta
            moved = {h for h, a in hosts.items()
                     if h in self._addrs and self._addrs[h] != a}
            self._addrs = dict(hosts)
            self._owner_memo.clear()
            dead_eps = [self._eps.pop(h) for h in list(self._eps)
                        if h not in hosts or h in moved]
            for h in list(self._since):
                if h not in hosts or h in moved:
                    del self._since[h]
        self.ring.set_hosts(hosts)
        for ep in dead_eps:
            _close_endpoint(ep)

    def reroute(self) -> None:
        """Forced re-resolution (the ``ServeUnavailable`` path)."""
        self._sync(force=True)

    def _endpoint(self, host: int):
        with self._lock:
            ep = self._eps.get(host)
            addr = self._addrs.get(host)
        if ep is not None:
            return ep
        ep = _resolve_endpoint(host, addr, self._conn_kw)
        with self._lock:
            self._eps.setdefault(host, ep)
        return ep

    def _replica_hosts(self, key) -> List[int]:
        """Gen-memoized replica set (the publisher keeps the identical
        memo): the hot read path must not re-hash and re-walk the ring
        for every known key on every pull when routing only changes
        with the directory generation."""
        memo = self._owner_memo.get(key)
        if memo is None:
            memo = self.ring.replica_hosts(key, self.replicas)
            self._owner_memo[key] = memo
        return memo

    def _pull_group_salvaged(self, cands: Sequence[int], klist,
                             max_stale_s) -> List[ServeReply]:
        """One owner group, with the per-key fallback: grouped keys can
        have DIFFERENT replica successors (A's set [0,1], B's [0,2]),
        so when the shared candidate chain is exhausted — e.g. the
        owner died and the first successor mirrors only some of the
        group — each key retries along its OWN arc before the group is
        declared unreadable.  The salvage first FORCES a directory
        re-sync and skips the candidates that already failed: paying a
        dead owner's full deadline again for every key would turn one
        host failure into a many-second pull that quietly outlives the
        staleness bound."""
        try:
            return [self._pull_group(cands, klist, max_stale_s)]
        except ServeUnavailable:
            if not klist or len(klist) == 1:
                raise
        failed = set(cands)
        self.reroute()
        out = []
        for k in klist:
            chain = [h for h in self._replica_hosts(k)
                     if h not in failed]
            if not chain:
                raise ServeUnavailable(
                    f"no live replica for key {k!r} after owner "
                    "failure")
            out.append(self._pull_group(chain, [k], max_stale_s))
        return out

    def _pull_group(self, cands: Sequence[int], klist,
                    max_stale_s) -> ServeReply:
        last_exc: Optional[BaseException] = None
        for i, h in enumerate(cands):
            if i > 0:
                counters.inc("serve.tier_failover")
            try:
                ep = self._endpoint(h)
                t0 = time.perf_counter()
                r = ep.serve_pull(since_id=self._since.get(h), keys=klist,
                                  max_stale_s=max_stale_s,
                                  deadline_s=self._deadline)
            except ServeUnavailable as e:
                last_exc = e
                continue
            dt = time.perf_counter() - t0
            from ..utils import slowness as _slowness
            _slowness.tracker().observe(h, dt, site="serve_pull")
            with self._lock:
                self._since[h] = r.snapshot_id
                self.host_pulls[h] = self.host_pulls.get(h, 0) + 1
            return r
        raise last_exc if last_exc is not None else ServeUnavailable(
            "serve ring has no candidates")

    def pull(self, since_id: Optional[int] = None,
             keys: Optional[List[str]] = None, record: bool = True,
             hedge: Optional[bool] = None,
             max_stale_s: Optional[float] = None) -> ServeReply:
        # the caller's since_id is its COMPOSITE id — per-host bases are
        # this router's own bookkeeping; record/hedge are plane-router
        # concerns (hotness lives host-side, failover replaces hedging)
        del since_id, record, hedge
        self._sync()
        if not len(self.ring):
            raise ServeUnavailable("serving tier has no hosts")
        groups: Dict[int, Optional[List[str]]] = {}
        cands: Dict[int, List[int]] = {}
        if keys is None:
            with self._lock:
                known = sorted(self._known)
            hosts = sorted(self.ring.hosts())
            if not known:
                # hydration: every host serves its whole slice once
                for h in hosts:
                    groups[h] = None
                    cands[h] = [h]
            else:
                self._disc = (self._disc + 1) % len(hosts)
                disc = hosts[self._disc]
                groups[disc] = None          # the discovery slice
                cands[disc] = [disc]
                for k in known:
                    rh = self._replica_hosts(k)
                    if rh[0] == disc:
                        continue             # covered by the slice
                    g = groups.setdefault(rh[0], [])
                    if g is not None:
                        g.append(k)
                    cands.setdefault(rh[0], rh)
        else:
            for k in keys:
                rh = self._replica_hosts(k)
                g = groups.setdefault(rh[0], [])
                if g is not None:
                    g.append(k)
                cands[rh[0]] = rh
        replies = self._fan_out(groups, cands, max_stale_s)
        if keys is None and replies:
            with self._lock:
                for r in replies:
                    self._known.update(r.items)
        return self._merge(replies)

    def _fan_out(self, groups: Dict[int, Optional[List[str]]],
                 cands: Dict[int, List[int]],
                 max_stale_s) -> List[ServeReply]:
        """Pull every host group CONCURRENTLY: one slow or partitioned
        owner must not serialize the other slices behind its full pull
        deadline (the publisher ships per-host concurrently for the
        same reason).  A single-group pull skips the thread."""
        order = list(groups)
        if len(order) == 1:
            h = order[0]
            return self._pull_group_salvaged(cands[h], groups[h],
                                             max_stale_s)
        results: Dict[int, List[ServeReply]] = {}
        errors: Dict[int, BaseException] = {}

        def run(h: int) -> None:
            try:
                results[h] = self._pull_group_salvaged(cands[h],
                                                       groups[h],
                                                       max_stale_s)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors[h] = e

        threads = [threading.Thread(target=run, args=(h,), daemon=True,
                                    name="bps-tier-pull")
                   for h in order]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[min(errors)]
        return [r for h in order for r in results[h]]

    def _merge(self, replies: List[ServeReply]) -> ServeReply:
        items: Dict[str, object] = {}
        wire = 0
        for r in replies:
            wire += r.wire_bytes
            for k, it in r.items.items():
                prev = items.get(k)
                if prev is None or it.version > prev.version:
                    items[k] = it
        # full only when EVERY host answered full and none shed: a
        # whole-model client prunes cache keys absent from a full reply,
        # and a shed host's keys are absent by design, not deletion
        any_shed = any(r.shed for r in replies)
        all_shed = bool(replies) and all(r.shed for r in replies)
        return ServeReply(
            snapshot_id=next(self._ids),
            full=bool(replies) and all(r.full and not r.shed
                                       for r in replies),
            items=items, wire_bytes=wire, server_id=-1,
            shed=all_shed,
            shed_partial=any_shed and not all_shed)

    def close(self) -> None:
        with self._lock:
            eps = list(self._eps.values())
            self._eps.clear()
        for ep in eps:
            _close_endpoint(ep)


def _reset_for_tests() -> None:
    with _inproc_lock:
        _inproc.clear()
    for obj in list(_closables):
        try:
            obj.close()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
