"""Server sum-engine semantics: multi-threaded, priority-scheduled merge.

Reference behavior being re-created (SURVEY.md §2.3, server.cc / queue.h):

- N engine threads (``BYTEPS_SERVER_ENGINE_THREAD``, default 4), each
  draining its own queue; keys are sticky-assigned to the least-loaded
  thread by accumulated bytes (server.h:149-173 GetThreadID).
- Sync flow per key and round: the first worker's push is COPY_FIRST
  (replaces the store), later workers are SUM_RECV (in-place sum via the
  native reducer), and when all ``num_workers`` arrived (ALL_RECV) the
  merged version is published and parked pulls are answered
  (server.cc:290-404).
- Optional scheduling (``BYTEPS_SERVER_ENABLE_SCHEDULE``): queues pop the
  message whose key has the *fewest* outstanding pushes first — keys
  closest to completing a merge go first, unblocking pulls sooner
  (queue.h:31-104; counters cleared on ALL_RECV).
- Debug value printing for a key (``BYTEPS_SERVER_DEBUG[_KEY]``,
  server.cc:115-139).

On TPU the synchronous reduction itself lives in XLA collectives — this
engine exists for the *stateful* paths that genuinely need a host: the
async-PS mode (KVStore uses it to merge deltas off the caller's thread)
and tests that pin the reference's server semantics.
"""

from __future__ import annotations

from collections import deque
import itertools
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..common import integrity as _integrity
from ..common import tracing as _tracing
from ..common.logging import get_logger
from ..common.retry import RetryPolicy
from ..common.telemetry import attribution as _attribution
from ..common.telemetry import counters
from ..fault import injector as _fault
from ..fault import membership as _membership
from ..native import inplace_add


@dataclass
class _Msg:
    key: str
    value: Optional[np.ndarray] = None
    worker_id: int = 0
    num_workers: int = 1
    kind: str = "push"  # push | stop
    seq: int = 0        # arrival order, stamped by PriorityQueue.push
    epoch: int = 0      # key epoch at push time; bumped by reset_key so
    #                     pre-reset residue in the queues is dropped
    round_no: int = 0   # push-side merge round this message belongs to —
    #                     lets a quarantine drop exactly the blamed
    #                     round's queued messages, not earlier complete
    #                     rounds still waiting in the queue
    trace_id: int = 0   # causal-tracing id of a CAPTURED push (ISSUE 12):
    #                     the merge thread closes the push's flow arc
    #                     with it; 0 = uncaptured


class PriorityQueue:
    """queue.h parity: FIFO by default; with scheduling enabled, pops the
    entry whose key has the fewest outstanding pushes (ties by arrival).

    Priority is evaluated at *pop* time from the live per-key counter, as
    the reference does (queue.h ComparePriority reads push_cnt_[key] when
    ordering): all queued messages of a key share the key's current total
    count, and clear_counter re-prioritizes messages that are already
    queued.  The stop sentinel sorts after every data message so pending
    merges drain before an engine thread exits.
    """

    def __init__(self, enable_schedule: bool):
        self._sched = enable_schedule
        self._cv = threading.Condition()
        # scheduling mode: per-key FIFO lanes; pop picks the lane with the
        # smallest live (push_cnt, head-arrival) — O(queued keys) per pop,
        # matching the reference's O(n) heap re-sort per operation.
        # FIFO mode (default): one global O(1) deque.
        self._fifos: Dict[str, "deque[_Msg]"] = {}
        self._fifo: "deque[_Msg]" = deque()
        self._stops: "deque[_Msg]" = deque()
        self._push_cnt: Dict[str, int] = {}
        self._seq = itertools.count()
        self._size = 0

    def push(self, msg: _Msg) -> None:
        with self._cv:
            msg.seq = next(self._seq)
            if msg.kind == "stop":
                self._stops.append(msg)
            elif self._sched:
                self._push_cnt[msg.key] = self._push_cnt.get(msg.key, 0) + 1
                self._fifos.setdefault(msg.key, deque()).append(msg)
            else:
                self._fifo.append(msg)
            self._size += 1
            self._cv.notify()

    def wait_and_pop(self) -> _Msg:
        with self._cv:
            self._cv.wait_for(lambda: self._size > 0)
            self._size -= 1
            if not self._sched:
                if self._fifo:
                    return self._fifo.popleft()
                # only the lowest-priority sentinel remains
                return self._stops.popleft()
            if not self._fifos:
                return self._stops.popleft()
            key = min(self._fifos,
                      key=lambda k: (self._push_cnt.get(k, 0),
                                     self._fifos[k][0].seq))
            dq = self._fifos[key]
            msg = dq.popleft()
            if not dq:  # prune empty lanes: pop cost stays O(queued keys)
                del self._fifos[key]
            return msg

    def clear_counter(self, key: str) -> None:
        if not self._sched:
            return
        with self._cv:
            self._push_cnt[key] = 0


class _Codec:
    """Per-key compression codec: the server-side compressor chain with
    its functional state, plus a per-merge-version wire cache (the
    reference likewise caches compressed pull responses per key,
    server.cc:34-75)."""

    __slots__ = ("comp", "state", "lock", "cached_version", "cached_wire")

    def __init__(self, comp):
        self.comp = comp
        self.state = comp.init_state()
        self.lock = threading.Lock()
        self.cached_version = -1
        self.cached_wire: Optional[bytes] = None


class _KeyState:
    __slots__ = ("merged", "count", "version", "parked", "lock",
                 "submitted", "shape", "dtype", "poisoned", "epoch",
                 "published", "round_pushed", "drop_once", "known_workers",
                 "round_no", "merge_round", "quarantined_rounds")

    def __init__(self):
        self.merged: Optional[np.ndarray] = None
        self.count = 0          # pushes processed this round
        self.version = 0        # completed merge rounds
        self.submitted = 0      # pushes enqueued (caller side)
        self.shape = None       # established by the first push (caller side)
        self.dtype = None
        self.poisoned = False   # poisoned until reset_key(): merge failed
        self.epoch = 0          # bumped by reset_key()
        self.published: Optional[np.ndarray] = None
        #                         last COMPLETED merge (aliases merged at
        #                         publish time; COPY_FIRST rebinds merged to
        #                         a fresh buffer, leaving this intact) — what
        #                         a non-finite quarantine republishes
        self.round_pushed: set = set()
        #                         worker ids that entered the current round
        #                         (push side; cleared when all num_workers
        #                         have) — lets a quarantine know which
        #                         workers' round-k pushes are still inbound
        self.drop_once: set = set()
        #                         workers whose NEXT push belongs to a
        #                         quarantined round and must be dropped,
        #                         not counted into the restarted round
        self.known_workers: set = set()
        #                         every worker id that has ever pushed this
        #                         key — after an elastic shrink the survivor
        #                         world keeps ORIGINAL ranks (e.g. {0, 2}
        #                         with num_workers=2), so a quarantine must
        #                         not derive the inbound-push set from
        #                         range(num_workers) alone
        self.round_no = 0       # push-side round id (incremented when a
        #                         round is fully entered); stamped onto
        #                         every queued message
        self.merge_round = -1   # round id currently being merged (set at
        #                         COPY_FIRST) — tells a quarantine whether
        #                         the partial sum in ``merged`` belongs to
        #                         the blamed round or an earlier one
        self.quarantined_rounds: set = set()
        #                         round ids whose queued messages must be
        #                         dropped at _process; pruned as later
        #                         rounds stream past (per-key FIFO)
        self.parked: List[Callable[[Optional[np.ndarray]], None]] = []
        self.lock = threading.Lock()


class ServerEngine:
    """The merge engine: push/pull with the reference's barrier flow."""

    def __init__(self, num_threads: Optional[int] = None,
                 enable_schedule: Optional[bool] = None,
                 debug_key: Optional[str] = None):
        from ..common.config import get_config
        cfg = get_config()
        self.num_threads = (num_threads if num_threads is not None
                            else cfg.server_engine_threads)
        if self.num_threads < 1:
            raise ValueError("need at least one engine thread")
        sched = (enable_schedule if enable_schedule is not None
                 else cfg.server_enable_schedule)
        self._debug_key = (debug_key if debug_key is not None
                           else cfg.server_debug_key)
        self.queues = [PriorityQueue(sched) for _ in range(self.num_threads)]
        # membership-epoch gate (fault/membership.py): pushes stamped
        # with another epoch arrive from a world that no longer exists
        # and are dropped, not summed
        self._membership_epoch = _membership.current_epoch()
        # integrity envelope sequence numbers (one counter per engine; the
        # (key, worker) identity rides the frame header)
        self._wire_seq = itertools.count(1)
        self._states: Dict[str, _KeyState] = {}
        self._codecs: Dict[str, "_Codec"] = {}
        self._states_lock = threading.Lock()
        # sticky least-loaded-by-bytes assignment (server.h GetThreadID)
        self._tid_of: Dict[str, int] = {}
        self._acc_load = [0] * self.num_threads
        self._assign_lock = threading.Lock()
        self._threads = [
            threading.Thread(target=self._run, args=(q,), daemon=True,
                             name=f"bps-server-engine-{i}")
            for i, q in enumerate(self.queues)]
        for t in self._threads:
            t.start()
        # /debug/state reachability (weakly held — registration must not
        # keep a shut-down engine alive)
        from ..common import metrics as _metrics
        _metrics.register_component("server_engine", self)

    # -- assignment --------------------------------------------------------

    def thread_id(self, key: str, nbytes: int) -> int:
        with self._assign_lock:
            tid = self._tid_of.get(key)
            if tid is None:
                tid = min(range(self.num_threads),
                          key=lambda i: self._acc_load[i])
                self._tid_of[key] = tid
                self._acc_load[tid] += nbytes
            return tid

    def _state(self, key: str) -> _KeyState:
        with self._states_lock:
            st = self._states.get(key)
            if st is None:
                st = self._states[key] = _KeyState()
            return st

    # -- public API --------------------------------------------------------

    def set_membership_epoch(self, epoch: int) -> None:
        """Adopt a new membership epoch (monotonic).  From now on any
        push stamped with a different epoch — residue from before an
        elastic shrink, or a worker that missed the world change — is
        dropped at the door instead of poisoning a merge round."""
        if epoch > self._membership_epoch:
            self._membership_epoch = epoch
            # a world change invalidates the quarantine bookkeeping: a
            # one-shot drop armed against a departed rank must not fire
            # on its REJOINED incarnation's first push, and known ids
            # repopulate from the new world's actual pushes
            with self._states_lock:
                states = list(self._states.values())
            for st in states:
                with st.lock:
                    st.drop_once.clear()
                    st.known_workers.clear()
            get_logger().warning(
                "server engine: membership epoch now %d; differently "
                "stamped pushes will be dropped", epoch)

    @property
    def membership_epoch(self) -> int:
        return self._membership_epoch

    def debug_state(self) -> dict:
        """Postmortem internals for ``/debug/state``
        (common/obs_server.py): per-key merge round, version, poison
        flag, and the quarantined-round set."""
        with self._states_lock:
            items = list(self._states.items())
        keys = {}
        for key, st in items:
            with st.lock:
                keys[key] = {
                    "version": st.version,
                    "round_no": st.round_no,
                    "count": st.count,
                    "poisoned": st.poisoned,
                    "quarantined_rounds": sorted(st.quarantined_rounds),
                    "drop_once": sorted(st.drop_once),
                }
        return {"kind": "server_engine",
                "membership_epoch": self._membership_epoch,
                "threads": self.num_threads,
                "keys": keys}

    def push(self, key: str, value, worker_id: int,
             num_workers: int, mepoch: Optional[int] = None) -> None:
        """One worker's contribution for this round (non-blocking).
        The key's shape/dtype are established by its first push and every
        later push is validated here, in the caller's thread — a
        mismatched push must never reach COPY_FIRST/SUM_RECV on the
        engine thread (where it would poison the round).

        ``mepoch``: the caller's membership epoch.  A mismatch against
        the engine's current epoch means the push crossed an elastic
        world change — it is dropped, not summed (the merge round it was
        meant for no longer exists).  ``None`` (non-elastic callers)
        skips the check."""
        if mepoch is not None and mepoch != self._membership_epoch:
            counters.inc("membership.stale_pushes_dropped")
            get_logger().warning(
                "server engine: dropped push(%r) from membership epoch "
                "%d (current %d)", key, mepoch, self._membership_epoch)
            return
        arr = np.asarray(value)
        # Causal tracing (ISSUE 12): join the caller's captured trace
        # (engine/async-opt pushes under a context) or make a sampling
        # decision here; the wire hop below and the merge thread both
        # stamp their spans with the same id — the push's journey is one
        # flow arc: caller push(s) → wire(t) → merge(f).
        tctx = _tracing.current()
        if tctx is None:
            tctx = _tracing.tracer().maybe_sample("server_push")
        t_push0 = time.monotonic() if tctx is not None else 0.0
        if _integrity.enabled():
            if _integrity.loopback_fast() and not _fault.ENABLED:
                # In-process hop with no chaos armed: the "wire" is the
                # caller's own memory, so seal -> CRC -> open would verify
                # bytes against themselves — provably redundant.  The
                # receiver still SNAPSHOTS the contribution (one plain
                # copy — push() is async and the caller may reuse its
                # gradient buffer before the engine thread merges; the
                # envelope path always copied via seal->open too): what
                # the fast path skips is the two CRC passes and the frame
                # build, while every BYTEPS_INTEGRITY=1 semantic
                # downstream — non-finite screen, quarantine, dedup —
                # still runs.
                counters.inc("integrity.loopback_fast")
                arr = np.array(arr)
                arr.flags.writeable = False
            else:
                # the loopback wire: seal → (chaos corrupts the frame) →
                # verify-on-receive, with bounded NACK-driven retransmit
                # from the sealed source copy.  A frame still corrupt past
                # the budget raises IntegrityError to the caller.
                with _tracing.use(tctx):
                    arr = self._wire_recv_array(key, arr, worker_id)
        elif _fault.ENABLED:
            # integrity off: the bitflip lands silently in this worker's
            # contribution — the unprotected baseline the envelope fixes
            arr = np.asarray(_fault.corrupt("server_push", arr))
            _fault.fire("server_push")
        enqueued = self._push_checked(key, arr, worker_id, num_workers,
                                      trace_id=tctx.trace_id if tctx else 0)
        if tctx is not None:
            tr = _tracing.tracer()
            now = time.monotonic()
            tr.record_traced(tctx.trace_id, "server.push", f"server/{key}",
                             t_push0, now, worker=worker_id)
            if enqueued:
                # flow start only for pushes that actually reached the
                # merge queue: the merge thread closes the arc, and a
                # quarantine-dropped push must not leave an orphan ``s``
                tr.flow(tctx.trace_id, "s", f"server/{key}", t_push0)

    def _push_checked(self, key: str, arr: np.ndarray, worker_id: int,
                      num_workers: int, trace_id: int = 0) -> bool:
        """Post-wire half of push(): non-finite screen, shape/dtype
        validation, round accounting, enqueue.  Returns True when the
        message reached a merge queue (False = dropped/quarantined —
        the caller must not open a flow arc nothing will close)."""
        st = self._state(key)
        if _integrity.enabled():
            with st.lock:
                st.known_workers.add(worker_id)
                if self._drop_if_quarantined(st, key, worker_id):
                    return False
            arr = _integrity.screen_nonfinite(arr, what="push", key=key,
                                              worker=worker_id)
            if arr is None:  # skip policy: quarantine the whole round
                # atomic with the drop_once re-check: a quarantine that
                # fired while this push was being screened already dropped
                # it, and its non-finite values must not quarantine the
                # restarted round too
                with st.lock:
                    if self._drop_if_quarantined(st, key, worker_id):
                        return False
                    quarantined = self._quarantine_round_locked(
                        st, key, worker_id, num_workers)
                self._fulfill_quarantined(key, quarantined)
                return False
        with st.lock:
            # re-checked atomically with round entry: a quarantine firing
            # between the pre-screen check and here would otherwise count
            # this stale round-k push into the restarted round AND leave
            # the one-shot drop armed against the next legitimate push
            if _integrity.enabled() and self._drop_if_quarantined(
                    st, key, worker_id):
                return False
            if st.poisoned:
                raise RuntimeError(f"key {key!r} is poisoned by an "
                                   "earlier merge failure")
            if st.shape is None:
                st.shape, st.dtype = arr.shape, arr.dtype
            elif arr.shape != st.shape or arr.dtype != st.dtype:
                raise ValueError(
                    f"push({key!r}): {arr.shape}/{arr.dtype} != "
                    f"established {st.shape}/{st.dtype}")
            st.round_pushed.add(worker_id)
            round_no = st.round_no
            if len(st.round_pushed) >= num_workers:
                st.round_pushed.clear()  # the round is fully entered
                st.round_no += 1
            st.submitted += 1
            epoch = st.epoch
        q = self.queues[self.thread_id(key, arr.nbytes)]
        q.push(_Msg(key=key, value=arr, worker_id=worker_id,
                    num_workers=num_workers, epoch=epoch,
                    round_no=round_no, trace_id=trace_id))
        return True

    # -- the loopback wire (integrity envelopes) ---------------------------

    def _wire_recv_array(self, key: str, arr: np.ndarray,
                         worker_id: int) -> np.ndarray:
        seq = next(self._wire_seq)
        frame = _integrity.seal_array(arr, key=key, seq=seq,
                                      worker=worker_id)
        return _integrity.wire_transmit(
            frame, key=key, worker=worker_id, seq=seq, site="server_push",
            opener=_integrity.open_array, who="server engine")

    def _drop_if_quarantined(self, st: "_KeyState", key: str,
                             worker_id: int) -> bool:
        """Caller holds ``st.lock``.  True when this worker's in-flight
        push belongs to a round that was quarantined before it landed:
        counting it into the restarted round would phase-shift every
        later merge by one contribution."""
        if worker_id not in st.drop_once:
            return False
        st.drop_once.discard(worker_id)
        counters.inc("integrity.quarantine_dropped")
        get_logger().warning(
            "server engine: dropped push(%r) from worker %d — "
            "its round was quarantined", key, worker_id)
        return True

    def _quarantine_round_locked(self, st: "_KeyState", key: str,
                                 blamed: int, num_workers: int) -> tuple:
        """Abandon the round the blamed push was entering after a skipped
        non-finite contribution, *without* wedging it: that round's
        already-queued messages are marked droppable (``round_no``-scoped
        — earlier fully-entered rounds still waiting in the queue merge
        and publish normally), workers whose same-round push is still
        inbound are marked for a one-shot drop (their late arrival must
        not be counted into the restarted round), the round accounting
        restarts, and parked pulls are answered with the previous
        completed merge — the round's result is REPUBLISHED rather than
        advanced.  Shape/dtype survive (same world, same geometry); a
        first-round quarantine has nothing to republish, so its parked
        pulls stay parked for the next round.

        Caller holds ``st.lock`` so the decision to quarantine and the
        round restart are one atomic step (two concurrent non-finite
        pushers must produce ONE quarantine — the second pusher lands in
        ``drop_once`` and is dropped, not a second round restart).
        Returns ``(parked, out, version, t0)`` for
        :meth:`_fulfill_quarantined` to answer outside the lock."""
        t0 = time.monotonic()
        q_round = st.round_no   # the round the blamed push was entering
        st.quarantined_rounds.add(q_round)
        # round-q messages already queued: every worker in round_pushed
        # enqueued exactly one (the blamed push was screened before it
        # could), minus any _process already merged
        inflight_q = len(st.round_pushed)
        if st.count and st.merge_round == q_round:
            # part of the quarantined round is already in the partial
            # sum — discard it; COPY_FIRST of the next surviving round
            # rebinds ``merged``
            inflight_q -= st.count
            st.count = 0
            st.merged = st.published
        # pre-deduct the to-be-dropped messages so pull's in-flight
        # check (submitted == 0) never waits on a round that will not
        # publish; _process skips the decrement for quarantined drops
        st.submitted -= inflight_q
        # workers that have neither entered this round nor are the blamed
        # one will still send their round-k contribution — drop exactly
        # one push each.  range(num_workers) covers the contiguous-rank
        # convention (incl. a first-round quarantine before anyone else
        # pushed); known_workers covers post-shrink worlds that keep
        # ORIGINAL ranks (e.g. {0, 2} with num_workers=2).  Ghost ids the
        # union may arm for a world that shrank are cleared on the next
        # membership-epoch adoption.
        st.drop_once |= ((st.known_workers | set(range(num_workers)))
                         - st.round_pushed - {blamed})
        st.round_pushed.clear()
        st.round_no = q_round + 1
        version = st.version
        # flush parked pulls with the previous merge ONLY when no earlier
        # fully-entered round is still in flight — otherwise that round's
        # own publish (which this quarantine must not discard) answers
        # them with the value they were actually waiting for
        if st.published is not None and st.submitted <= 0:
            parked, st.parked = st.parked, []
            out = st.published
        else:
            parked, out = [], None
        return parked, out, version, t0

    def _fulfill_quarantined(self, key: str, quarantined: tuple) -> None:
        parked, out, version, t0 = quarantined
        for fulfill in parked:
            fulfill(np.array(out, copy=True), version)
        _integrity.record_span("quarantine", t0, key=key,
                               republished_version=version)
        # quarantines are exactly the "what was it doing when it broke"
        # moment the flight recorder exists for: dump the black box
        from ..common import flight_recorder as _flight
        _flight.record("quarantine", key=key, republished_version=version)
        _flight.dump("quarantine")
        get_logger().error(
            "server engine: round for key %r quarantined — previous merge "
            "version %d republished", key, version)

    # -- transport receive side (comm/transport.py) ------------------------
    #
    # The TCP transport verifies the sealed envelope AT THE SOCKET (a
    # corrupt frame was already NACKed back to the sender), so these
    # entry points must not run a second wire hop: they gate on the
    # membership epoch and hand the verified payload straight to the
    # post-wire half of push() — non-finite screen, shape validation,
    # round accounting, enqueue — exactly what the loopback path runs
    # after ITS envelope verification.

    def receive_push(self, key: str, value: np.ndarray, worker_id: int,
                     num_workers: int,
                     mepoch: Optional[int] = None) -> bool:
        """A transport-delivered (already-verified) dense push.  Returns
        True when the contribution reached a merge queue (False =
        stale-epoch or quarantine drop — fate final either way, so the
        transport's dedup floor advances regardless)."""
        if mepoch is not None and mepoch != self._membership_epoch:
            counters.inc("membership.stale_pushes_dropped")
            get_logger().warning(
                "server engine: dropped transport push(%r) from "
                "membership epoch %d (current %d)", key, mepoch,
                self._membership_epoch)
            return False
        return self._push_checked(key, np.asarray(value), worker_id,
                                  num_workers)

    def receive_push_wire(self, key: str, data: bytes, worker_id: int,
                          num_workers: int,
                          mepoch: Optional[int] = None) -> bool:
        """A transport-delivered (already-verified) compressed push:
        the wire bytes are decoded with the key's registered server
        codec and merged like any dense contribution.  A stale
        ``mepoch`` is dropped before the decode runs."""
        if mepoch is not None and mepoch != self._membership_epoch:
            counters.inc("membership.stale_pushes_dropped")
            get_logger().warning(
                "server engine: dropped transport compressed push(%r) "
                "from membership epoch %d (current %d)", key, mepoch,
                self._membership_epoch)
            return False
        comp = self._codec(key).comp
        value = np.asarray(comp.decompress(comp.wire_decode(bytes(data))))
        return self._push_checked(key, value, worker_id, num_workers)

    def pull_versioned(self, key: str,
                       timeout: Optional[float] = None) -> tuple:
        """Public form of the versioned pull — ``(merged array, merge
        version)`` read atomically — for callers that cache or ship the
        result keyed by the version that produced it (the transport's
        ``server_pull`` reply stamps the envelope seq with it)."""
        return self._pull_versioned(key, timeout)

    def pull(self, key: str, timeout: Optional[float] = None,
             retry: Optional[RetryPolicy] = None) -> np.ndarray:
        """Blocks until the current round's merge completes (parked-pull
        semantics, server.cc:371-404).  ``retry`` re-parks a timed-out
        pull with the policy's backoff/deadline — under chaos-injected
        delay a merge can land just after a too-tight timeout, and
        re-parking is cheap while raising tears down the caller."""
        if _fault.ENABLED:
            _fault.fire("server_pull")
        if retry is None:
            return self._pull_versioned(key, timeout)[0]
        import dataclasses
        # only the timeout is transient: a poisoned key raises
        # RuntimeError and re-parking it would just burn the backoff
        retry = dataclasses.replace(retry, retry_on=(TimeoutError,))
        return retry.call(
            lambda: self._pull_versioned(key, timeout)[0],
            describe=f"pull({key!r})")

    def _pull_versioned(self, key: str, timeout: Optional[float] = None
                        ) -> tuple:
        """(merged array, merge version) — read atomically under the key
        lock / at publish time, so a caller can key caches by the version
        that actually produced the array (pull_compressed's wire cache
        would otherwise tag round k's data with k+1 under overlap)."""
        st = self._state(key)
        ev = threading.Event()
        box: Dict[str, Any] = {}

        def fulfill(arr: Optional[np.ndarray], version: int = -1) -> None:
            box["v"] = arr
            box["ver"] = version
            ev.set()

        with st.lock:
            if st.poisoned:
                raise RuntimeError(f"key {key!r} is poisoned by an "
                                   "earlier merge failure")
            # answer immediately only when no round is in flight: nothing
            # queued (submitted == 0) AND nothing partially merged
            # (count == 0) — a popped-but-unfinished round would otherwise
            # leak one worker's raw contribution (arrival-order semantics
            # of the reference handler: a pull enqueued after a round's
            # pushes waits for that round).  ``merged`` can be None with
            # version > 0 after reset_key (version survives a reset so
            # pull caches never regress) — park until the next round
            # completes instead of answering with nothing
            if (st.version > 0 and st.submitted == 0 and st.count == 0
                    and st.merged is not None):
                return np.array(st.merged, copy=True), st.version
            st.parked.append(fulfill)
        if not ev.wait(timeout):
            raise TimeoutError(f"pull({key!r}) timed out")
        if box["v"] is None:
            raise RuntimeError(f"key {key!r} was poisoned while this "
                               "pull was parked")
        return box["v"], box["ver"]

    # -- compressed push/pull (reference server.cc:87-113) -----------------

    def register_compression(self, key: str, kwargs: Dict[str, str],
                             numel: int, dtype=np.float32) -> None:
        """Declare a key as compressed: pushes arrive as wire bytes and
        are decompressed before merging; pulls return the merged result
        re-compressed (the reference server's compressed mode — it
        decompresses each push, sums, and re-compresses the merged data,
        server.cc:87-113).  The codec is the server-side compressor chain
        (momentum skipped, compressor_registry.cc:39-56)."""
        from ..compression import registry as compression_registry
        comp = compression_registry.create(dict(kwargs), numel, dtype,
                                           for_server=True)
        with self._states_lock:
            self._codecs[key] = _Codec(comp)

    def _codec(self, key: str) -> "_Codec":
        with self._states_lock:
            codec = self._codecs.get(key)
        if codec is None:
            # actionable, not a bare KeyError three frames deep: the
            # caller skipped (or failed) the declare-time registration
            raise ValueError(
                f"key {key!r} has no registered compression codec: call "
                f"ServerEngine.register_compression(key, kwargs, numel) "
                f"before push_compressed/pull_compressed")
        return codec

    def push_compressed(self, key: str, data: bytes, worker_id: int,
                        num_workers: int,
                        mepoch: Optional[int] = None) -> None:
        """Push one worker's wire-encoded payload; decompressed here (the
        caller's thread — same placement as shape validation) and merged
        by the engine threads like any dense push.  A stale ``mepoch``
        is dropped before the decode even runs.

        With integrity armed, the envelope wraps the *compressed wire
        bytes* — exactly what a real network hop would carry.  A corrupt
        frame is NACKed and retransmitted BEFORE ``wire_decode`` ever
        runs: one flipped bit in an entropy-coded payload would otherwise
        decode into an undetectable many-element error."""
        if mepoch is not None and mepoch != self._membership_epoch:
            counters.inc("membership.stale_pushes_dropped")
            get_logger().warning(
                "server engine: dropped compressed push(%r) from "
                "membership epoch %d (current %d)", key, mepoch,
                self._membership_epoch)
            return
        comp = self._codec(key).comp
        if _integrity.enabled():
            tctx = _tracing.current()
            if tctx is None:
                tctx = _tracing.tracer().maybe_sample("server_push")
            t_c0 = time.monotonic() if tctx is not None else 0.0
            if _integrity.loopback_fast() and not _fault.ENABLED:
                # same in-process fast path as push(): the wire bytes are
                # already the caller's buffer, nothing to re-CRC
                counters.inc("integrity.loopback_fast")
            else:
                seq = next(self._wire_seq)
                frame = _integrity.seal_bytes(data, key=key, seq=seq,
                                              worker=worker_id)
                with _tracing.use(tctx):
                    data = _integrity.wire_transmit(
                        frame, key=key, worker=worker_id, seq=seq,
                        site="server_push", opener=_integrity.open_bytes,
                        who="server engine")
            value = np.asarray(comp.decompress(comp.wire_decode(
                bytes(data))))
            enq = self._push_checked(key, value, worker_id, num_workers,
                                     trace_id=tctx.trace_id if tctx else 0)
            if tctx is not None:
                tr = _tracing.tracer()
                tr.record_traced(tctx.trace_id, "server.push",
                                 f"server/{key}", t_c0, time.monotonic(),
                                 worker=worker_id, compressed=True)
                if enq:
                    tr.flow(tctx.trace_id, "s", f"server/{key}", t_c0)
            return
        value = np.asarray(comp.decompress(comp.wire_decode(data)))
        self.push(key, value, worker_id, num_workers)

    def pull_compressed(self, key: str,
                        timeout: Optional[float] = None) -> bytes:
        """Pull the merged result re-compressed to wire bytes.  Stateful
        codecs (server-side error feedback) advance once per completed
        round: the compression is cached under the merge version, so
        concurrent pullers of one round share a single compression."""
        import jax.numpy as jnp
        codec = self._codec(key)
        merged, version = self._pull_versioned(key, timeout=timeout)
        with codec.lock:
            if codec.cached_version == version:
                return codec.cached_wire
            if version > codec.cached_version:
                # newest round: advance the codec state exactly once
                payload, codec.state = codec.comp.compress(
                    jnp.asarray(merged.reshape(-1)), codec.state)
                codec.cached_wire = codec.comp.wire_encode(payload)
                codec.cached_version = version
                return codec.cached_wire
            # A puller that slept through newer rounds: compress its
            # round's data WITHOUT touching state or cache — advancing a
            # stateful codec (EF) out of order would corrupt the error
            # accumulator, and regressing cached_version would hand later
            # pullers stale bytes.
            payload, _ = codec.comp.compress(
                jnp.asarray(merged.reshape(-1)), codec.state)
            return codec.comp.wire_encode(payload)

    def version(self, key: str) -> int:
        return self._state(key).version

    def reset_key(self, key: str) -> None:
        """Clear a key poisoned by a merge failure so a recovery pass can
        reuse it (poisoning was terminal by design — a partial round is
        unrepairable *within* the round; a supervised recovery that
        re-pushes everything from scratch IS the cross-round accounting).

        Drops the merged buffer, the round counters, and the established
        shape/dtype (the recovering workers may legitimately re-declare a
        different geometry); completed-round ``version`` survives so pull
        caches keyed on it never see a version regress.  Parked pulls
        from the poisoned era are flushed with the poison error — their
        callers predate the reset and must re-pull."""
        st = self._state(key)
        with st.lock:
            st.poisoned = False
            st.merged = None
            st.published = None
            st.count = 0
            st.submitted = 0
            st.shape = None
            st.dtype = None
            st.round_pushed.clear()
            st.drop_once.clear()
            st.known_workers.clear()
            st.quarantined_rounds.clear()
            st.merge_round = -1
            st.epoch += 1   # queued pre-reset messages become droppable
            parked, st.parked = st.parked, []
        for fulfill in parked:
            fulfill(None)
        get_logger().warning("server engine: key %r reset for recovery",
                             key)

    def shutdown(self) -> None:
        for q in self.queues:
            q.push(_Msg(key="", kind="stop"))
        for t in self._threads:
            t.join(timeout=5)

    # -- engine thread -----------------------------------------------------

    def _run(self, q: PriorityQueue) -> None:
        while True:
            msg = q.wait_and_pop()
            if msg.kind == "stop":
                return
            t_m0 = time.monotonic()
            try:
                self._process(msg, q)
            except Exception:  # noqa: BLE001 — push() pre-validates
                # shape/dtype, so this is exceptional (OOM etc.); the key
                # is poisoned terminally rather than half-reset, because a
                # partial round cannot be repaired without cross-round
                # message accounting — but the engine thread (and every
                # other key assigned to it) must survive
                get_logger().error(
                    "server engine: merge failed for key=%r — key "
                    "poisoned; pending and future push/pull raise",
                    msg.key, exc_info=True)
                st = self._state(msg.key)
                with st.lock:
                    st.poisoned = True
                    st.count = 0
                    st.merged = None
                    st.published = None
                    parked, st.parked = st.parked, []
                q.clear_counter(msg.key)
                for fulfill in parked:
                    fulfill(None)
            # merge attribution + the arc's closing hop, on success AND
            # on the poison path (the push's journey ended either way)
            _attribution.add("merge", (time.monotonic() - t_m0) * 1e3)
            if msg.trace_id:
                tr = _tracing.tracer()
                if tr.active:
                    now = time.monotonic()
                    tr.record_traced(msg.trace_id, "server.merge",
                                     f"server/{msg.key}", t_m0, now,
                                     worker=msg.worker_id)
                    tr.flow(msg.trace_id, "f", f"server/{msg.key}", now)

    def _process(self, msg: _Msg, q: PriorityQueue) -> None:
        st = self._state(msg.key)
        with st.lock:
            if msg.epoch != st.epoch:
                # pre-reset residue: reset_key zeroed the round accounting
                # this message was counted under — merging it would seed
                # the fresh round with a dead worker's contribution
                return
            if msg.round_no in st.quarantined_rounds:
                # the round was quarantined after this push was queued;
                # its submitted share was already deducted at quarantine
                return
            st.submitted -= 1
            if st.quarantined_rounds:
                # per-key FIFO: once a later round's message arrives, no
                # more messages of an earlier quarantined round can follow
                st.quarantined_rounds = {
                    r for r in st.quarantined_rounds if r > msg.round_no}
            if st.poisoned:
                return  # drop: messages queued before the poison landed
            if st.count == 0:
                # COPY_FIRST: first worker replaces last round's merge
                st.merge_round = msg.round_no
                st.merged = np.array(msg.value, copy=True)
            else:
                # SUM_RECV: native multithreaded in-place sum
                inplace_add(st.merged, msg.value)
            st.count += 1
            if msg.key == self._debug_key:
                get_logger().warning(
                    "server debug key=%s recv %d/%d sum=%.6f",
                    msg.key, st.count, msg.num_workers,
                    float(np.sum(st.merged)))
            if st.count >= msg.num_workers:
                # ALL_RECV: screen, publish + flush parked pulls
                st.count = 0
                q.clear_counter(msg.key)
                if (_integrity.enabled()
                        and np.issubdtype(st.merged.dtype, np.inexact)
                        and not np.isfinite(st.merged).all()):
                    # contributions screened finite can still merge
                    # non-finite (overflow, inf + -inf); the policy
                    # decides before anything is published
                    if not self._screen_merged(st, msg.key):
                        return
                st.version += 1
                st.published = st.merged
                parked, st.parked = st.parked, []
                out = st.merged
                version = st.version
                for fulfill in parked:
                    fulfill(np.array(out, copy=True), version)

    def _screen_merged(self, st: _KeyState, key: str) -> bool:
        """Policy gate for a non-finite MERGED result (caller holds
        ``st.lock`` and has already zeroed the round count).  True →
        publish (possibly zero-patched); False → the previous completed
        merge was republished in place.  ``raise`` raises — _run's
        handler poisons the key, composing with reset_key exactly like
        any other merge failure."""
        policy = _integrity.nonfinite_policy()
        if policy == "zero":
            counters.inc("integrity.nonfinite_zeroed")
            get_logger().warning(
                "server engine: zeroed non-finite elements in merged "
                "result for key %r", key)
            np.nan_to_num(st.merged, copy=False, nan=0.0, posinf=0.0,
                          neginf=0.0)
            return True
        if policy == "skip":
            counters.inc("integrity.nonfinite_skipped")
            get_logger().error(
                "server engine: merged result for key %r is non-finite — "
                "republishing previous merge version %d", key, st.version)
            st.merged = st.published
            if st.published is not None:
                parked, st.parked = st.parked, []
                for fulfill in parked:
                    fulfill(np.array(st.published, copy=True), st.version)
            return False
        counters.inc("integrity.nonfinite_rejected")
        raise RuntimeError(
            f"merged result for key {key!r} is non-finite "
            "(BYTEPS_NONFINITE_POLICY=raise); key poisoned")
