"""Server sum-engine semantics: multi-threaded, priority-scheduled merge.

Reference behavior being re-created (SURVEY.md §2.3, server.cc / queue.h):

- N engine threads (``BYTEPS_SERVER_ENGINE_THREAD``, default 4), each
  draining its own queue; keys are sticky-assigned to the least-loaded
  thread by accumulated bytes (server.h:149-173 GetThreadID).
- Sync flow per key and round: the first worker's push is COPY_FIRST
  (replaces the store), later workers are SUM_RECV (in-place sum via the
  native reducer), and when all ``num_workers`` arrived (ALL_RECV) the
  merged version is published and parked pulls are answered
  (server.cc:290-404).
- Optional scheduling (``BYTEPS_SERVER_ENABLE_SCHEDULE``): queues pop the
  message whose key has the *fewest* outstanding pushes first — keys
  closest to completing a merge go first, unblocking pulls sooner
  (queue.h:31-104; counters cleared on ALL_RECV).
- Debug value printing for a key (``BYTEPS_SERVER_DEBUG[_KEY]``,
  server.cc:115-139).

On TPU the synchronous reduction itself lives in XLA collectives — this
engine exists for the *stateful* paths that genuinely need a host: the
async-PS mode (KVStore uses it to merge deltas off the caller's thread)
and tests that pin the reference's server semantics.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..common.logging import get_logger
from ..native import inplace_add


@dataclass(order=True)
class _Msg:
    sort_key: tuple
    seq: int = field(compare=False)
    key: str = field(compare=False)
    value: Optional[np.ndarray] = field(compare=False, default=None)
    worker_id: int = field(compare=False, default=0)
    num_workers: int = field(compare=False, default=1)
    kind: str = field(compare=False, default="push")  # push | stop


class PriorityQueue:
    """queue.h parity: FIFO by default; with scheduling enabled, pops the
    entry whose key has the fewest outstanding pushes (ties by arrival)."""

    def __init__(self, enable_schedule: bool):
        self._sched = enable_schedule
        self._heap: List[_Msg] = []
        self._cv = threading.Condition()
        self._push_cnt: Dict[str, int] = {}
        self._seq = itertools.count()

    def push(self, msg: _Msg) -> None:
        with self._cv:
            seq = next(self._seq)
            msg.seq = seq
            if self._sched:
                cnt = self._push_cnt.get(msg.key, 0) + 1
                self._push_cnt[msg.key] = cnt
            # re-keying on pop keeps it simple: priority is evaluated at
            # push time like the reference (heap re-sorted per operation)
            msg.sort_key = (self._push_cnt.get(msg.key, 0) if self._sched
                            else 0, seq)
            heapq.heappush(self._heap, msg)
            self._cv.notify()

    def wait_and_pop(self) -> _Msg:
        with self._cv:
            self._cv.wait_for(lambda: self._heap)
            return heapq.heappop(self._heap)

    def clear_counter(self, key: str) -> None:
        if not self._sched:
            return
        with self._cv:
            self._push_cnt[key] = 0


class _KeyState:
    __slots__ = ("merged", "count", "version", "parked", "lock",
                 "submitted", "shape", "dtype", "poisoned")

    def __init__(self):
        self.merged: Optional[np.ndarray] = None
        self.count = 0          # pushes processed this round
        self.version = 0        # completed merge rounds
        self.submitted = 0      # pushes enqueued (caller side)
        self.shape = None       # established by the first push (caller side)
        self.dtype = None
        self.poisoned = False   # terminal: an engine-side merge failed
        self.parked: List[Callable[[Optional[np.ndarray]], None]] = []
        self.lock = threading.Lock()


class ServerEngine:
    """The merge engine: push/pull with the reference's barrier flow."""

    def __init__(self, num_threads: Optional[int] = None,
                 enable_schedule: Optional[bool] = None,
                 debug_key: Optional[str] = None):
        from ..common.config import get_config
        cfg = get_config()
        self.num_threads = (num_threads if num_threads is not None
                            else cfg.server_engine_threads)
        if self.num_threads < 1:
            raise ValueError("need at least one engine thread")
        sched = (enable_schedule if enable_schedule is not None
                 else cfg.server_enable_schedule)
        self._debug_key = (debug_key if debug_key is not None
                           else cfg.server_debug_key)
        self.queues = [PriorityQueue(sched) for _ in range(self.num_threads)]
        self._states: Dict[str, _KeyState] = {}
        self._states_lock = threading.Lock()
        # sticky least-loaded-by-bytes assignment (server.h GetThreadID)
        self._tid_of: Dict[str, int] = {}
        self._acc_load = [0] * self.num_threads
        self._assign_lock = threading.Lock()
        self._threads = [
            threading.Thread(target=self._run, args=(q,), daemon=True,
                             name=f"bps-server-engine-{i}")
            for i, q in enumerate(self.queues)]
        for t in self._threads:
            t.start()

    # -- assignment --------------------------------------------------------

    def thread_id(self, key: str, nbytes: int) -> int:
        with self._assign_lock:
            tid = self._tid_of.get(key)
            if tid is None:
                tid = min(range(self.num_threads),
                          key=lambda i: self._acc_load[i])
                self._tid_of[key] = tid
                self._acc_load[tid] += nbytes
            return tid

    def _state(self, key: str) -> _KeyState:
        with self._states_lock:
            st = self._states.get(key)
            if st is None:
                st = self._states[key] = _KeyState()
            return st

    # -- public API --------------------------------------------------------

    def push(self, key: str, value, worker_id: int,
             num_workers: int) -> None:
        """One worker's contribution for this round (non-blocking).
        The key's shape/dtype are established by its first push and every
        later push is validated here, in the caller's thread — a
        mismatched push must never reach COPY_FIRST/SUM_RECV on the
        engine thread (where it would poison the round)."""
        arr = np.asarray(value)
        st = self._state(key)
        with st.lock:
            if st.poisoned:
                raise RuntimeError(f"key {key!r} is poisoned by an "
                                   "earlier merge failure")
            if st.shape is None:
                st.shape, st.dtype = arr.shape, arr.dtype
            elif arr.shape != st.shape or arr.dtype != st.dtype:
                raise ValueError(
                    f"push({key!r}): {arr.shape}/{arr.dtype} != "
                    f"established {st.shape}/{st.dtype}")
            st.submitted += 1
        q = self.queues[self.thread_id(key, arr.nbytes)]
        q.push(_Msg(sort_key=(0, 0), seq=0, key=key, value=arr,
                    worker_id=worker_id, num_workers=num_workers))

    def pull(self, key: str, timeout: Optional[float] = None) -> np.ndarray:
        """Blocks until the current round's merge completes (parked-pull
        semantics, server.cc:371-404)."""
        st = self._state(key)
        ev = threading.Event()
        box: Dict[str, np.ndarray] = {}

        def fulfill(arr: Optional[np.ndarray]) -> None:
            box["v"] = arr
            ev.set()

        with st.lock:
            if st.poisoned:
                raise RuntimeError(f"key {key!r} is poisoned by an "
                                   "earlier merge failure")
            # answer immediately only when no round is in flight: nothing
            # queued (submitted == 0) AND nothing partially merged
            # (count == 0) — a popped-but-unfinished round would otherwise
            # leak one worker's raw contribution (arrival-order semantics
            # of the reference handler: a pull enqueued after a round's
            # pushes waits for that round)
            if st.version > 0 and st.submitted == 0 and st.count == 0:
                return np.array(st.merged, copy=True)
            st.parked.append(fulfill)
        if not ev.wait(timeout):
            raise TimeoutError(f"pull({key!r}) timed out")
        if box["v"] is None:
            raise RuntimeError(f"key {key!r} was poisoned while this "
                               "pull was parked")
        return box["v"]

    def version(self, key: str) -> int:
        return self._state(key).version

    def shutdown(self) -> None:
        for q in self.queues:
            q.push(_Msg(sort_key=(0, 0), seq=0, key="", kind="stop"))
        for t in self._threads:
            t.join(timeout=5)

    # -- engine thread -----------------------------------------------------

    def _run(self, q: PriorityQueue) -> None:
        while True:
            msg = q.wait_and_pop()
            if msg.kind == "stop":
                return
            try:
                self._process(msg, q)
            except Exception:  # noqa: BLE001 — push() pre-validates
                # shape/dtype, so this is exceptional (OOM etc.); the key
                # is poisoned terminally rather than half-reset, because a
                # partial round cannot be repaired without cross-round
                # message accounting — but the engine thread (and every
                # other key assigned to it) must survive
                get_logger().error(
                    "server engine: merge failed for key=%r — key "
                    "poisoned; pending and future push/pull raise",
                    msg.key, exc_info=True)
                st = self._state(msg.key)
                with st.lock:
                    st.poisoned = True
                    st.count = 0
                    st.merged = None
                    parked, st.parked = st.parked, []
                q.clear_counter(msg.key)
                for fulfill in parked:
                    fulfill(None)

    def _process(self, msg: _Msg, q: PriorityQueue) -> None:
        st = self._state(msg.key)
        with st.lock:
            st.submitted -= 1
            if st.poisoned:
                return  # drop: messages queued before the poison landed
            if st.count == 0:
                # COPY_FIRST: first worker replaces last round's merge
                st.merged = np.array(msg.value, copy=True)
            else:
                # SUM_RECV: native multithreaded in-place sum
                inplace_add(st.merged, msg.value)
            st.count += 1
            if msg.key == self._debug_key:
                get_logger().warning(
                    "server debug key=%s recv %d/%d sum=%.6f",
                    msg.key, st.count, msg.num_workers,
                    float(np.sum(st.merged)))
            if st.count >= msg.num_workers:
                # ALL_RECV: publish + flush parked pulls
                st.count = 0
                st.version += 1
                q.clear_counter(msg.key)
                parked, st.parked = st.parked, []
                out = st.merged
                for fulfill in parked:
                    fulfill(np.array(out, copy=True))
