"""Client-side consistent-hash ring for the distributed serving tier.

The in-process serving plane (``server/serving.py``) routes reads by a
modulo hash (``ServerAssigner``): any change in the endpoint count
re-routes EVERY key.  That is fine for thread-replicas sharing one
snapshot store, and fatal for a tier of real serving hosts — a host
joining or leaving would invalidate every client's cache affinity and
every host's shipped key set at once, a full-model reshuffle over DCN.

This module is the standard fix: a consistent-hash ring with virtual
nodes.  Each host owns ``BYTEPS_SERVE_TIER_VNODES`` points on a 64-bit
circle (blake2b — deterministic across processes, like
``sharding.key_to_int``; Python's salted ``hash()`` would route the same
key to different hosts on different machines).  A key is owned by the
first point clockwise from its own hash; replicas are the next DISTINCT
hosts clockwise.  Adding or removing a host remaps only the arcs that
host's points bound — ~1/N of the key space — so:

- clients keep their delta bases for every unaffected key,
- the publisher re-ships only the moved arcs' keys,
- and the tier scales host-by-host without a global reshuffle
  (the property the autoscaler's whole economics rest on).

Every process that builds the ring from the same (host set, vnodes)
derives the IDENTICAL routing — the ring is pure data, synchronized via
the membership bus's serving-host directory generation
(``serving_tier.TierDirectory``), never via pickled ring state.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["ServeRing"]

_SPACE = 1 << 64


def _point(data: str) -> int:
    """Deterministic 64-bit circle position (no process hash salt)."""
    digest = hashlib.blake2b(data.encode(), digest_size=8).digest()
    return int.from_bytes(digest, "little")


def _key_point(key) -> int:
    # namespaced apart from vnode points so a key can never collide
    # with a host's own point by construction of the same string
    return _point(f"k/{key}")


class ServeRing:
    """The hash ring: ``{host_id}`` -> ``vnodes`` points on a 64-bit
    circle; ``owner(key)`` walks clockwise.  Thread-safe — the router
    reads it per pull while the directory thread applies membership.

    Mutation is cheap (sorted-list insert/remove of one host's points),
    lookup is a bisect.  Host ids are opaque ints (the serving-host
    directory's ids)."""

    def __init__(self, hosts: Iterable[int] = (),
                 vnodes: Optional[int] = None):
        if vnodes is None:
            from ..common.config import get_config
            vnodes = get_config().serve_tier_vnodes
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = vnodes
        self._lock = threading.Lock()
        self._points: List[int] = []          # sorted circle positions
        self._owners: Dict[int, int] = {}     # position -> host id
        self._hosts: set = set()
        for h in hosts:
            self.add(h)

    # -- membership ---------------------------------------------------------

    def _host_points(self, host_id: int) -> List[int]:
        return [_point(f"h/{host_id}/{v}") for v in range(self.vnodes)]

    def add(self, host_id: int) -> None:
        host_id = int(host_id)
        with self._lock:
            if host_id in self._hosts:
                return
            self._hosts.add(host_id)
            for p in self._host_points(host_id):
                if p in self._owners:
                    # astronomically unlikely 64-bit collision: lowest
                    # host id wins deterministically on every process
                    if self._owners[p] <= host_id:
                        continue
                    self._owners[p] = host_id
                    continue
                self._owners[p] = host_id
                bisect.insort(self._points, p)

    def remove(self, host_id: int) -> None:
        host_id = int(host_id)
        with self._lock:
            if host_id not in self._hosts:
                return
            self._hosts.discard(host_id)
            for p in self._host_points(host_id):
                if self._owners.get(p) == host_id:
                    del self._owners[p]
                    i = bisect.bisect_left(self._points, p)
                    if i < len(self._points) and self._points[i] == p:
                        del self._points[i]

    def set_hosts(self, hosts: Iterable[int]) -> None:
        """Converge to exactly ``hosts`` (the directory's current view):
        only the difference is touched, so unaffected arcs keep their
        positions."""
        target = {int(h) for h in hosts}
        for h in sorted(self.hosts() - target):
            self.remove(h)
        for h in sorted(target - self.hosts()):
            self.add(h)

    def hosts(self) -> set:
        with self._lock:
            return set(self._hosts)

    def __len__(self) -> int:
        with self._lock:
            return len(self._hosts)

    # -- routing ------------------------------------------------------------

    def owner(self, key) -> int:
        """The host owning ``key`` (its primary).  Raises when the ring
        is empty — routing against no hosts is a caller bug, not a
        silent default."""
        return self.replica_hosts(key, 1)[0]

    def replica_hosts(self, key, n: int) -> List[int]:
        """The first ``min(n, len(hosts))`` DISTINCT hosts clockwise
        from the key's point: entry 0 is the owner, the rest are its
        failover/replica set.  Deterministic on every process."""
        kp = _key_point(key)
        with self._lock:
            if not self._points:
                raise LookupError("serve ring has no hosts")
            want = min(max(1, n), len(self._hosts))
            out: List[int] = []
            start = bisect.bisect_right(self._points, kp)
            for i in range(len(self._points)):
                p = self._points[(start + i) % len(self._points)]
                h = self._owners[p]
                if h not in out:
                    out.append(h)
                    if len(out) == want:
                        break
            return out

    # -- observability ------------------------------------------------------

    def arc_share(self) -> Dict[int, float]:
        """Fraction of the 64-bit circle each host owns (sums to 1.0) —
        the load-balance figure ``bps_top``'s ARC column renders and the
        autoscaler's scale-down victim choice reads.  With enough
        vnodes every share approaches 1/N."""
        with self._lock:
            if not self._points:
                return {}
            shares: Dict[int, float] = {h: 0.0 for h in self._hosts}
            pts = self._points
            for i, p in enumerate(pts):
                prev = pts[i - 1]           # wraps: pts[-1] for i == 0
                arc = (p - prev) % _SPACE
                if len(pts) == 1:
                    arc = _SPACE
                shares[self._owners[p]] += arc / _SPACE
            return shares

    def moved_keys(self, keys, other: "ServeRing", n: int = 1
                   ) -> List:
        """Keys whose replica set differs between this ring and
        ``other`` — the re-ship set after a membership change (test and
        publisher helper)."""
        return [k for k in keys
                if self.replica_hosts(k, n) != other.replica_hosts(k, n)]
