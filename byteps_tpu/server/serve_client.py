"""Pull client: staleness-bounded, delta-pulling parameter consumer.

The consumer half of the serving plane (:mod:`~byteps_tpu.server.serving`):
a :class:`PullClient` holds a local cache of parameter values plus the
``snapshot_id`` it was hydrated at, and on every :meth:`pull` chooses its
own consistency point:

- cache younger than ``max_staleness_s`` → served locally
  (``serve.cache_hits``), zero wire traffic;
- stale, ``block=True`` (default) → a DELTA pull against the plane
  (only keys whose version advanced since the cached snapshot travel,
  codec-encoded where the training plane registered one), then serve;
- stale, ``block=False`` or ``prefetch=True`` → the stale cache is
  served immediately (``serve.stale_served``) while a single-flight
  background refresh brings it forward (``serve.async_refresh``) — the
  online-learning consumer's mode: bounded staleness, never a stall.

Byte accounting: :attr:`bytes_received` sums the wire-encoded payload
bytes of every refresh — the figure the delta-pull acceptance test and
``tools/serve_bench.py`` assert O(churn), not O(model), traffic with.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..common.logging import get_logger
from ..common.telemetry import counters

__all__ = ["PullClient"]


class PullClient:
    """One read-side consumer of a :class:`~.serving.ServingPlane`.

    ``keys=None`` tracks the whole model; a list restricts the working
    set (and the delta traffic) to those keys.  Thread-safe: one
    refresh at a time (single-flight), concurrent ``pull`` calls share
    its result."""

    def __init__(self, plane, keys: Optional[List[str]] = None,
                 max_staleness_s: Optional[float] = None,
                 prefetch: bool = False,
                 hedge: Optional[bool] = None,
                 stale_on_error: bool = False):
        from ..common.config import get_config
        self._plane = plane
        self._keys = list(keys) if keys is not None else None
        self.max_staleness_s = (get_config().serve_max_staleness_s
                                if max_staleness_s is None
                                else max_staleness_s)
        self.prefetch = prefetch
        # per-client hedging override (None = the plane's policy): a
        # tail-sensitive consumer opts in even when the plane default
        # is sequential, and vice versa (docs/gray_failures.md)
        self.hedge = hedge
        # distributed-tier degradation (server/serving_tier.py): when a
        # refresh fails even after the router re-resolved the ring, a
        # client with a hydrated cache serves it stale
        # (serve.stale_on_error) instead of failing the read — staleness
        # bounded by the tier's heal time (TTL/retire), correctness
        # never at stake.  Off by default: the in-process plane's
        # callers expect errors.
        self.stale_on_error = stale_on_error
        self._cache: Dict[str, np.ndarray] = {}
        self._versions: Dict[str, int] = {}
        self._codecs: Dict[str, object] = {}
        self._snapshot_id: Optional[int] = None
        self._fetched_at: float = 0.0
        self._refresh_lock = threading.Lock()
        # single-flight guard for background refreshes: acquired
        # non-blocking by the thread that wins the race, released when
        # its refresh finishes (an Event's check-then-set would let two
        # concurrent stale pulls both spawn refresh threads)
        self._inflight = threading.Lock()
        self.bytes_received = 0
        self.refreshes = 0

    # -- freshness -----------------------------------------------------------

    @property
    def snapshot_id(self) -> Optional[int]:
        return self._snapshot_id

    def staleness_s(self) -> float:
        """Seconds since the cache was last brought forward (``inf``
        before the first refresh)."""
        if self._snapshot_id is None:
            return float("inf")
        return time.monotonic() - self._fetched_at

    def version(self, key: str) -> int:
        return self._versions.get(key, -1)

    # -- the pull ------------------------------------------------------------

    def pull(self, keys: Optional[List[str]] = None,
             max_staleness_s: Optional[float] = None,
             block: bool = True) -> Dict[str, np.ndarray]:
        """Return ``{key: value}`` no staler than the bound.

        ``block=False`` (or a client built with ``prefetch=True``)
        serves the current cache immediately when stale and refreshes in
        the background; the very first pull always blocks — there is
        nothing to serve yet."""
        bound = (self.max_staleness_s if max_staleness_s is None
                 else max_staleness_s)
        wanted = keys if keys is not None else self._keys
        # the _snapshot_id check keeps the first-pull-always-blocks
        # contract even for an unbounded staleness (inf <= inf would
        # otherwise "hit" an empty cache forever)
        if self._snapshot_id is not None and self.staleness_s() <= bound:
            counters.inc("serve.cache_hits")
            return self._slice(wanted)
        if self._snapshot_id is not None and (self.prefetch or not block):
            counters.inc("serve.stale_served")
            self._refresh_async()
            return self._slice(wanted)
        try:
            self.refresh()
        except Exception:  # noqa: BLE001 — opt-in stale degradation:
            # with a hydrated cache the read succeeds stale rather than
            # failing; an unhydrated client has nothing to degrade to
            if not (self.stale_on_error and self._snapshot_id is not None):
                raise
            counters.inc("serve.stale_on_error")
            get_logger().warning("serve: refresh failed, serving stale "
                                 "cache", exc_info=True)
        return self._slice(wanted)

    def _slice(self, keys: Optional[List[str]]) -> Dict[str, np.ndarray]:
        cache = self._cache     # bind ONCE: a concurrent refresh swaps
        #                         the reference; re-reading it per key
        #                         could mix two snapshots
        if keys is None:
            return dict(cache)
        return {k: cache[k] for k in keys if k in cache}

    # -- refresh machinery ---------------------------------------------------

    def _routed_pull(self):
        """One plane pull, with the distributed-tier re-resolution fix:
        on ``ServeUnavailable`` a router exposing ``reroute()`` gets ONE
        forced ring/directory re-resolution and the pull retries against
        the healed routing — the background single-flight refresh used
        to park on the dead host until the next cut republished the
        mirror sets.  Tier routers also receive the client's staleness
        bound (``accepts_max_stale``): the host may shed the pull only
        while that bound holds."""
        kw = {"since_id": self._snapshot_id, "keys": self._keys,
              "hedge": self.hedge}
        if getattr(self._plane, "accepts_max_stale", False):
            kw["max_stale_s"] = self.max_staleness_s
        try:
            return self._plane.pull(**kw)
        except Exception as e:
            from .serving import ServeUnavailable
            reroute = getattr(self._plane, "reroute", None)
            if not isinstance(e, ServeUnavailable) or reroute is None:
                raise
            reroute()
            return self._plane.pull(**kw)

    def refresh(self) -> None:
        """Bring the cache forward to the plane's latest snapshot with
        one delta pull (full on first contact or after the cached id
        aged out of retention server-side)."""
        with self._refresh_lock:
            reply = self._routed_pull()
            if getattr(reply, "shed", False):
                # admission control answered "keep your cache": the data
                # did not move, so neither does the freshness clock —
                # the next stale pull retries (cheaply) until the host
                # has budget again
                counters.inc("serve.shed_served")
                return
            # build the updated view ASIDE and publish it with one
            # reference swap: a concurrent non-blocking pull slicing
            # the cache mid-refresh must see snapshot N or N+1 whole,
            # never a torn mix of the two
            cache = dict(self._cache)
            versions = dict(self._versions)
            for k, item in reply.items.items():
                cache[k] = self._decode(k, item)
                versions[k] = item.version
            if reply.full and self._keys is None:
                # a whole-model client's keys absent from a FULL reply
                # no longer exist server-side (store cleared/re-keyed);
                # a restricted client keeps its slice regardless
                for k in list(cache):
                    if k not in reply.items:
                        del cache[k]
                        versions.pop(k, None)
            self._cache = cache
            self._versions = versions
            self._snapshot_id = reply.snapshot_id
            if getattr(reply, "shed_partial", False):
                # SOME hosts shed this merged pull: their keys are only
                # inside the bound as of NOW — advancing the clock would
                # let the whole cache (shed slices included) ride as
                # "fresh" for another full bound.  Apply the fresh
                # slices, keep the clock, retry (cheaply) next pull.
                counters.inc("serve.shed_served")
            else:
                self._fetched_at = time.monotonic()
            self.bytes_received += reply.wire_bytes
            self.refreshes += 1
            counters.inc("serve.cache_misses")

    def _refresh_async(self) -> None:
        """Single-flight background refresh: while one is in flight,
        further stale pulls keep serving the cache instead of piling up
        refresh threads (atomic test-and-set — losers return
        immediately)."""
        if not self._inflight.acquire(blocking=False):
            return
        counters.inc("serve.async_refresh")

        def run():
            try:
                self.refresh()
            except Exception:  # noqa: BLE001 — the cache stays stale;
                # the next blocking pull surfaces the error
                get_logger().error("serve: async refresh failed",
                                   exc_info=True)
            finally:
                self._inflight.release()

        threading.Thread(target=run, daemon=True,
                         name="bps-serve-prefetch").start()

    def close(self) -> None:
        """Release the routing plane's resources when the client OWNS
        it — a per-client tier router (``client_owned = True``) holds
        supervised TCP connections, and dropping the client without
        closing would leak their supervisor threads.  A SHARED plane
        (``ServingPlane``) is never closed from here: clients do not
        own it."""
        if getattr(self._plane, "client_owned", False):
            self._plane.close()

    def _decode(self, key: str, item) -> np.ndarray:
        """Materialize one reply item into cache memory the client owns
        (reply payloads may be COW views of server memory on the
        loopback fast path)."""
        if item.codec is None:
            return np.array(item.payload, copy=True)
        kwargs, numel, dtype_s = item.codec
        comp = self._codecs.get(key)
        if comp is None or comp[0] != (kwargs, numel, dtype_s):
            from ..compression import registry as reg
            comp = ((kwargs, numel, dtype_s),
                    reg.create(dict(kwargs), numel, np.dtype(dtype_s),
                               for_server=True))
            self._codecs[key] = comp
        decoder = comp[1]
        return np.array(
            decoder.decompress(decoder.wire_decode(item.payload)),
            copy=True)
