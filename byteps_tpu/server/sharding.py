"""Key -> server/shard assignment: the reference's server-choice hashing.

Reference (global.cc:566-677): each 64-bit chunk key is routed to one of
``num_servers`` by a configurable hash (``BYTEPS_KEY_HASH_FN`` =
naive | built_in | djb2 | sdbm | mixed), with per-server byte-load
accounting logged at shutdown.  Mixed mode splits traffic between
non-colocated and colocated servers by a ratio derived from the cluster
shape (``BYTEPS_ENABLE_MIXED_MODE`` / ``BYTEPS_MIXED_MODE_BOUND``).

TPU mapping: there are no server processes, but the same assignment
problem appears when the hierarchical reduction shards chunks across DCN
slices or when the async KV store is partitioned across hosts — this
module is that router, hash-compatible with the reference so documented
tuning advice carries over.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Optional

__all__ = ["hash_naive", "hash_built_in", "hash_djb2", "hash_sdbm",
           "key_to_int", "ServerAssigner"]

_MASK = (1 << 64) - 1


def key_to_int(key) -> int:
    """Stable 64-bit identity for a non-integer key (the serving plane
    routes by STRING parameter names, the training plane by declared
    integer keys — both must land in the same hash space
    deterministically across processes)."""
    if isinstance(key, int):
        return key
    digest = hashlib.blake2b(str(key).encode(), digest_size=8).digest()
    return int.from_bytes(digest, "little")


def hash_naive(key: int) -> int:
    # global.cc:598 — ((key>>16) + (key%65536)) * 9973
    return (((key >> 16) + (key % 65536)) * 9973) & _MASK


def hash_built_in(key: int) -> int:
    # std::hash<string> is implementation-defined but stable within a
    # build; Python's hash() is salted per process (PYTHONHASHSEED), which
    # would route the same key to different shards on different hosts —
    # use a deterministic digest instead
    digest = hashlib.blake2b(str(key).encode(), digest_size=8).digest()
    return (int.from_bytes(digest, "little") * 9973) & _MASK


def hash_djb2(key: int) -> int:
    h = 5381
    for c in str(key).encode():
        h = ((h << 5) + h + c) & _MASK      # h*33 + c
    return h


def hash_sdbm(key: int) -> int:
    h = 0
    for c in str(key).encode():
        h = (c + (h << 6) + (h << 16) - h) & _MASK  # h*65599 + c
    return h


_FNS = {"naive": hash_naive, "built_in": hash_built_in,
        "djb2": hash_djb2, "sdbm": hash_sdbm}


class ServerAssigner:
    """Stable key->server routing with byte-load accounting.

    ``mixed`` mode (global.cc:566-596): with W workers colocated with
    servers and S total servers, the first ``ratio`` share of hash space
    goes to the S-W non-colocated servers, the rest to colocated ones —
    keeping the colocated machines' NICs from double-duty."""

    def __init__(self, num_servers: int, fn: Optional[str] = None,
                 mixed_mode: Optional[bool] = None, num_workers: int = 0,
                 bound: Optional[int] = None,
                 replicas: Optional[int] = None,
                 hot_keys: Optional[int] = None):
        if num_servers < 1:
            raise ValueError("num_servers must be >= 1")
        if replicas is None or hot_keys is None:
            from ..common.config import get_config
            scfg = get_config()
            replicas = scfg.serve_replicas if replicas is None else replicas
            hot_keys = scfg.serve_hot_keys if hot_keys is None else hot_keys
        if replicas < 1:
            raise ValueError("replicas must be >= 1 (1 = primary only)")
        self.replicas = replicas
        self.hot_key_budget = hot_keys
        if fn is None or mixed_mode is None or bound is None:
            # env-reachable knobs (reference global.cc:159-176, 566-596):
            # BYTEPS_KEY_HASH_FN, BYTEPS_ENABLE_MIXED_MODE,
            # BYTEPS_MIXED_MODE_BOUND — explicit arguments win
            from ..common.config import get_config
            cfg = get_config()
            fn = cfg.key_hash_fn if fn is None else fn
            if mixed_mode is None:
                mixed_mode = cfg.enable_mixed_mode
                if mixed_mode and num_workers == 0:
                    num_workers = cfg.num_hosts
            bound = cfg.mixed_mode_bound if bound is None else bound
        if fn not in _FNS:
            raise ValueError(f"unknown hash fn {fn!r}; one of {list(_FNS)}")
        self.num_servers = num_servers
        self.fn_name = fn
        self._fn = _FNS[fn]
        self._mixed = mixed_mode
        self._bound = bound
        self._num_workers = num_workers
        self._init_mixed()
        self._cache: Dict[int, int] = {}
        self.load_bytes: List[int] = [0] * num_servers
        self._lock = threading.Lock()
        # read-side state (server/serving.py): per-key pull-count
        # histogram feeding hot-key replica sets.  Writes stay
        # primary-routed (assign); reads fan across replica_set(key).
        self._pull_counts: Dict[object, int] = {}
        self._replica_sets: Dict[object, List[int]] = {}

    def _init_mixed(self) -> None:
        """(Re)derive the mixed-mode split from the current shape."""
        if not self._mixed:
            return
        nonco = self.num_servers - self._num_workers
        if not 0 < nonco <= self._num_workers:
            raise ValueError(
                "mixed mode needs 0 < num_servers - num_workers <= "
                "num_workers (global.cc ratio constraint)")
        if self._bound < self.num_servers:
            raise ValueError("BYTEPS_MIXED_MODE_BOUND must be >= "
                             "num_servers")
        w = self._num_workers
        self._ratio = (2.0 * nonco * (w - 1)) / (
            w * (w + nonco) - 2 * nonco)
        self._threshold = self._ratio * self._bound
        self._nonco = nonco

    def reshard(self, num_servers: int,
                num_workers: Optional[int] = None) -> None:
        """Re-hash the key space for a changed world (elastic shrink or
        rejoin, fault/membership.py).  Drops the assignment cache —
        every key re-routes under the new server count — and restarts
        the byte-load accounting.  A mixed-mode assigner REQUIRES an
        explicit ``num_workers``: the colocated/non-colocated split is
        deployment-specific and inferring it would silently misroute
        the key space; a shape the new world cannot satisfy raises and
        the previous shape is kept (the caller decides whether to
        degrade)."""
        if num_servers < 1:
            raise ValueError("num_servers must be >= 1")
        if self._mixed and num_workers is None:
            raise ValueError(
                "mixed-mode reshard needs an explicit num_workers (the "
                "colocated/non-colocated split cannot be inferred from "
                "the server count alone)")
        with self._lock:
            old = (self.num_servers, self._num_workers)
            self.num_servers = num_servers
            if num_workers is not None:
                self._num_workers = num_workers
            try:
                self._init_mixed()
            except ValueError:
                self.num_servers, self._num_workers = old
                raise
            self._cache.clear()
            self.load_bytes = [0] * num_servers
            # replica sets are rebuilt for the new shard count from the
            # RETAINED pull histogram (hotness does not change with the
            # world): a set that named a now-dead shard is replaced, so
            # reads degrade to live shards instead of erroring
            self._rebuild_replicas_locked()

    def _assign_locked(self, key: int, nbytes: int) -> int:
        sid = self._cache.get(key)
        if sid is None:
            if self._mixed:
                r = hash_djb2(key) % self._bound
                if r < self._threshold:
                    sid = hash_djb2(r) % self._nonco
                else:
                    sid = self._nonco + hash_djb2(r) % self._num_workers
            else:
                sid = self._fn(key) % self.num_servers
            self._cache[key] = sid
        self.load_bytes[sid] += nbytes
        return sid

    def assign(self, key: int, nbytes: int = 0) -> int:
        with self._lock:
            return self._assign_locked(key, nbytes)

    # -- read-side replication (server/serving.py) --------------------------

    def record_pull(self, key, nbytes: int = 0) -> None:
        """Count one pull of ``key`` into the hotness histogram (and its
        bytes into the PRIMARY shard's load accounting — routing load
        follows writes; replica reads are deliberately not charged, they
        exist to take load OFF the primary's figure)."""
        with self._lock:
            self._pull_counts[key] = self._pull_counts.get(key, 0) + 1
        if nbytes:
            self.assign(key_to_int(key), nbytes)

    def record_pulls(self, keys) -> None:
        """Bulk form of :meth:`record_pull` for the serving hot path:
        ONE lock acquisition for a whole-model pull's key list instead
        of K acquire/release cycles serializing concurrent clients."""
        with self._lock:
            counts = self._pull_counts
            for key in keys:
                counts[key] = counts.get(key, 0) + 1

    def pull_count(self, key) -> int:
        with self._lock:
            return self._pull_counts.get(key, 0)

    def hot_keys(self, top_n: Optional[int] = None) -> List:
        """The ``top_n`` most-pulled keys (default: the configured
        hot-key budget), hottest first."""
        n = self.hot_key_budget if top_n is None else top_n
        with self._lock:
            ranked = sorted(self._pull_counts.items(),
                            key=lambda kv: (-kv[1], str(kv[0])))
            return [k for k, c in ranked[:n] if c > 0]

    def _replica_set_for(self, key) -> List[int]:
        """Caller holds the lock: ``min(replicas, num_servers)`` DISTINCT
        shards starting at the key's primary — deterministic, so every
        process derives the identical set."""
        primary = self._assign_locked(key_to_int(key), 0)
        n = min(self.replicas, self.num_servers)
        return [(primary + j) % self.num_servers for j in range(n)]

    def _rebuild_replicas_locked(self) -> None:
        self._replica_sets.clear()
        if self.replicas <= 1 or self.hot_key_budget <= 0:
            return
        ranked = sorted(self._pull_counts.items(),
                        key=lambda kv: (-kv[1], str(kv[0])))
        for key, count in ranked[:self.hot_key_budget]:
            if count > 0:
                self._replica_sets[key] = self._replica_set_for(key)

    def rebuild_replicas(self) -> Dict[object, List[int]]:
        """(Re)derive the hot-key replica sets from the current pull
        histogram; returns a copy of ``{key: [shard, ...]}`` (first
        entry is the primary — writes route there, reads fan across the
        whole set)."""
        with self._lock:
            self._rebuild_replicas_locked()
            return {k: list(v) for k, v in self._replica_sets.items()}

    def replica_set(self, key) -> List[int]:
        """Shards ``key`` is readable from: its hot-key replica set, or
        ``[primary]`` for a cold key.  Writes must use
        :meth:`write_target` (always the primary) regardless."""
        with self._lock:
            s = self._replica_sets.get(key)
            if s:
                return list(s)
            return [self._assign_locked(key_to_int(key), 0)]

    def write_target(self, key) -> int:
        """Writes stay primary-routed — replication is a READ fan-out;
        a write landing on a replica would fork the value history."""
        return self.assign(key_to_int(key), 0)

    def load_summary(self) -> str:
        """Per-server accumulated bytes (the reference logs this at
        shutdown for balance debugging)."""
        total = sum(self.load_bytes) or 1
        return ", ".join(
            f"s{i}: {b} ({100.0 * b / total:.1f}%)"
            for i, b in enumerate(self.load_bytes))
