"""Key -> server/shard assignment: the reference's server-choice hashing.

Reference (global.cc:566-677): each 64-bit chunk key is routed to one of
``num_servers`` by a configurable hash (``BYTEPS_KEY_HASH_FN`` =
naive | built_in | djb2 | sdbm | mixed), with per-server byte-load
accounting logged at shutdown.  Mixed mode splits traffic between
non-colocated and colocated servers by a ratio derived from the cluster
shape (``BYTEPS_ENABLE_MIXED_MODE`` / ``BYTEPS_MIXED_MODE_BOUND``).

TPU mapping: there are no server processes, but the same assignment
problem appears when the hierarchical reduction shards chunks across DCN
slices or when the async KV store is partitioned across hosts — this
module is that router, hash-compatible with the reference so documented
tuning advice carries over.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Optional

__all__ = ["hash_naive", "hash_built_in", "hash_djb2", "hash_sdbm",
           "ServerAssigner"]

_MASK = (1 << 64) - 1


def hash_naive(key: int) -> int:
    # global.cc:598 — ((key>>16) + (key%65536)) * 9973
    return (((key >> 16) + (key % 65536)) * 9973) & _MASK


def hash_built_in(key: int) -> int:
    # std::hash<string> is implementation-defined but stable within a
    # build; Python's hash() is salted per process (PYTHONHASHSEED), which
    # would route the same key to different shards on different hosts —
    # use a deterministic digest instead
    digest = hashlib.blake2b(str(key).encode(), digest_size=8).digest()
    return (int.from_bytes(digest, "little") * 9973) & _MASK


def hash_djb2(key: int) -> int:
    h = 5381
    for c in str(key).encode():
        h = ((h << 5) + h + c) & _MASK      # h*33 + c
    return h


def hash_sdbm(key: int) -> int:
    h = 0
    for c in str(key).encode():
        h = (c + (h << 6) + (h << 16) - h) & _MASK  # h*65599 + c
    return h


_FNS = {"naive": hash_naive, "built_in": hash_built_in,
        "djb2": hash_djb2, "sdbm": hash_sdbm}


class ServerAssigner:
    """Stable key->server routing with byte-load accounting.

    ``mixed`` mode (global.cc:566-596): with W workers colocated with
    servers and S total servers, the first ``ratio`` share of hash space
    goes to the S-W non-colocated servers, the rest to colocated ones —
    keeping the colocated machines' NICs from double-duty."""

    def __init__(self, num_servers: int, fn: Optional[str] = None,
                 mixed_mode: Optional[bool] = None, num_workers: int = 0,
                 bound: Optional[int] = None):
        if num_servers < 1:
            raise ValueError("num_servers must be >= 1")
        if fn is None or mixed_mode is None or bound is None:
            # env-reachable knobs (reference global.cc:159-176, 566-596):
            # BYTEPS_KEY_HASH_FN, BYTEPS_ENABLE_MIXED_MODE,
            # BYTEPS_MIXED_MODE_BOUND — explicit arguments win
            from ..common.config import get_config
            cfg = get_config()
            fn = cfg.key_hash_fn if fn is None else fn
            if mixed_mode is None:
                mixed_mode = cfg.enable_mixed_mode
                if mixed_mode and num_workers == 0:
                    num_workers = cfg.num_hosts
            bound = cfg.mixed_mode_bound if bound is None else bound
        if fn not in _FNS:
            raise ValueError(f"unknown hash fn {fn!r}; one of {list(_FNS)}")
        self.num_servers = num_servers
        self.fn_name = fn
        self._fn = _FNS[fn]
        self._mixed = mixed_mode
        self._bound = bound
        self._num_workers = num_workers
        self._init_mixed()
        self._cache: Dict[int, int] = {}
        self.load_bytes: List[int] = [0] * num_servers
        self._lock = threading.Lock()

    def _init_mixed(self) -> None:
        """(Re)derive the mixed-mode split from the current shape."""
        if not self._mixed:
            return
        nonco = self.num_servers - self._num_workers
        if not 0 < nonco <= self._num_workers:
            raise ValueError(
                "mixed mode needs 0 < num_servers - num_workers <= "
                "num_workers (global.cc ratio constraint)")
        if self._bound < self.num_servers:
            raise ValueError("BYTEPS_MIXED_MODE_BOUND must be >= "
                             "num_servers")
        w = self._num_workers
        self._ratio = (2.0 * nonco * (w - 1)) / (
            w * (w + nonco) - 2 * nonco)
        self._threshold = self._ratio * self._bound
        self._nonco = nonco

    def reshard(self, num_servers: int,
                num_workers: Optional[int] = None) -> None:
        """Re-hash the key space for a changed world (elastic shrink or
        rejoin, fault/membership.py).  Drops the assignment cache —
        every key re-routes under the new server count — and restarts
        the byte-load accounting.  A mixed-mode assigner REQUIRES an
        explicit ``num_workers``: the colocated/non-colocated split is
        deployment-specific and inferring it would silently misroute
        the key space; a shape the new world cannot satisfy raises and
        the previous shape is kept (the caller decides whether to
        degrade)."""
        if num_servers < 1:
            raise ValueError("num_servers must be >= 1")
        if self._mixed and num_workers is None:
            raise ValueError(
                "mixed-mode reshard needs an explicit num_workers (the "
                "colocated/non-colocated split cannot be inferred from "
                "the server count alone)")
        with self._lock:
            old = (self.num_servers, self._num_workers)
            self.num_servers = num_servers
            if num_workers is not None:
                self._num_workers = num_workers
            try:
                self._init_mixed()
            except ValueError:
                self.num_servers, self._num_workers = old
                raise
            self._cache.clear()
            self.load_bytes = [0] * num_servers

    def assign(self, key: int, nbytes: int = 0) -> int:
        with self._lock:
            sid = self._cache.get(key)
            if sid is None:
                if self._mixed:
                    r = hash_djb2(key) % self._bound
                    if r < self._threshold:
                        sid = hash_djb2(r) % self._nonco
                    else:
                        sid = self._nonco + hash_djb2(r) % self._num_workers
                else:
                    sid = self._fn(key) % self.num_servers
                self._cache[key] = sid
            self.load_bytes[sid] += nbytes
            return sid

    def load_summary(self) -> str:
        """Per-server accumulated bytes (the reference logs this at
        shutdown for balance debugging)."""
        total = sum(self.load_bytes) or 1
        return ", ".join(
            f"s{i}: {b} ({100.0 * b / total:.1f}%)"
            for i, b in enumerate(self.load_bytes))
