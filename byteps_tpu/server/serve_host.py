"""Standalone serving-host process: ``python -m byteps_tpu.server.serve_host``.

The first runtime role beside trainer and coordinator: a process whose
whole job is answering serving pulls.  It binds a
:class:`~byteps_tpu.comm.transport.TransportServer`, attaches a
:class:`~byteps_tpu.server.serving_tier.ServingHostCore` (stage/commit
publication, shed-aware pulls), registers with the membership bus's
serving-host directory, and keeps re-registering inside the TTL — the
directory heartbeat doubling as the tier's liveness signal.  Each
re-registration carries the host's cumulative pull/shed counts and hot
keys, the signals the autoscaler reads; a metrics snapshot is also
pushed to the bus (``metrics_put`` at ``SERVE_RANK_BASE + host_id``) so
``bps_top`` renders the host as a first-class row.

Environment:

- ``BYTEPS_SERVE_TIER_BUS``    — membership-bus ``host:port`` (optional;
  without it the host only prints its address for a static directory)
- ``BYTEPS_SERVE_HOST_ID``     — fixed host id (default: bus-allocated)
- ``BYTEPS_SERVE_HOST_BIND``   — ``host:port`` to listen on
  (default ``127.0.0.1:0`` = ephemeral)
- ``BYTEPS_FAULT_SPEC``        — chaos schedule, validated at start
  (``kill:site=serve_host:step=N`` dies at the Nth answered pull)
- ``BYTEPS_DURABLE_DIR``       — durable state plane root (server/wal.py);
  when set, the committed arc persists to
  ``<dir>/serve-<host_id>/arc.bin`` and a restart restores it from
  local disk BEFORE registering (``HOST-RESTORED <host_id> <commit>``)
  so the publisher re-ships nothing on the happy path

Prints ``HOST-UP <host_id> <host> <port>`` once serving, then runs until
SIGTERM/SIGINT (clean: unregister, close), a graceful drain
(``serve_ctl drain``: mark the directory DRAINING, finish in-flight
pulls, final unregister handshake, print ``HOST-DRAINED <host_id>``,
exit 0), or the chaos injector kills it
(``kill:site=serve_host_start:step=1`` dies before HOST-UP — the
deterministic crash-looper the reconciler's flap ban is tested with).
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time

__all__ = ["main"]


def main(argv=None) -> int:
    del argv
    from ..common.config import get_config
    from ..common.logging import get_logger
    from ..comm import transport as tp
    from ..core.api import metrics_snapshot
    from ..fault import injector as inj
    from ..fault.membership import SERVE_RANK_BASE, bus_request
    from .serving_tier import ServingHostCore, TierDirectory

    cfg = get_config()
    # bpslint: ignore[env-knob] reason=per-process launch identity (like DMLC_WORKER_ID) consumed once at entrypoint start, before any Config is constructed or shared; documented in env.md
    bind = os.environ.get("BYTEPS_SERVE_HOST_BIND", "127.0.0.1:0")
    bind_host, bind_port = bind.rsplit(":", 1)
    # bpslint: ignore[env-knob] reason=per-process launch identity (like DMLC_WORKER_ID) consumed once at entrypoint start, before any Config is constructed or shared; documented in env.md
    want_id = os.environ.get("BYTEPS_SERVE_HOST_ID")
    want_id = int(want_id) if want_id not in (None, "") else None

    spec = cfg.fault_spec
    if spec:
        inj.arm(spec, seed=cfg.fault_seed,
                rank=want_id if want_id is not None else 0)

    core = ServingHostCore(host_id=want_id if want_id is not None else 0)
    if core.restored_commit:
        # durable restart-in-place (server/wal.py): the committed arc
        # came back from local disk BEFORE registration, so the
        # publisher's next cut carries every unchanged key forward
        # instead of re-shipping the full arc over DCN
        print(f"HOST-RESTORED {core.host_id} {core.restored_commit}",
              flush=True)
    srv = tp.TransportServer(host=bind_host, port=int(bind_port),
                             rank=SERVE_RANK_BASE + core.host_id,
                             serving=core, tier=core)
    directory = TierDirectory()
    hid = core.host_id
    if directory.bus is not None:
        hid = directory.register(srv.addr, host_id=want_id,
                                 meta={"pulls": 0, "sheds": 0, "hot": []})
        if hid != core.host_id:
            # bus-allocated id: adopt it everywhere the identity matters
            core.host_id = hid
            core.server.server_id = hid
            if spec:
                inj.arm(spec, seed=cfg.fault_seed, rank=hid)
    if inj.ENABLED:
        # the startup kill site: a ``kill:site=serve_host_start`` rule
        # dies HERE — registered (the directory will see the flap) but
        # before HOST-UP, the launch-crash the reconciler's crash-loop
        # backoff and flap ban must absorb
        inj.on_serve_start()
    print(f"HOST-UP {hid} {srv.host} {srv.port}", flush=True)

    stop = threading.Event()

    def _sig(_s, _f):
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)

    def heartbeat():
        """Directory TTL refresh + autoscaler signals + bps_top row.
        Drain-aware: once the drain latch is set, every beat re-asserts
        the DRAINING mark (a plain re-registration would clear it and
        flap the host back into the ring mid-drain)."""
        while not stop.wait(max(directory.ttl_s / 3.0, 0.5)):
            if directory.bus is None:
                continue
            try:
                directory.register(
                    srv.addr, host_id=hid,
                    draining=core.draining.is_set(),
                    meta={"pulls": core.pulls, "sheds": core.sheds,
                          "hot": core.hot_keys(8), "role": "serve"})
                snap = metrics_snapshot(light=True)
                snap["role"] = "serve"
                snap["host_id"] = hid
                bus_request(directory.bus,
                            {"op": "metrics_put",
                             "rank": SERVE_RANK_BASE + hid,
                             "metrics": snap}, timeout=5.0)
            except (ConnectionError, TimeoutError):
                # bus unreachable OR stalled (bus_request raises
                # MembershipTimeout, a TimeoutError, when an
                # established connection hangs — e.g. mid-coordinator-
                # failover): the TTL gives us a grace window; keep
                # serving and retry on the next beat.  The heartbeat
                # thread must never die — a healthy serving host
                # silently TTL-ing out of every client's ring is a
                # capacity loss nothing would ever report.
                get_logger().warning(
                    "serve host %d: bus unreachable or stalled", hid)

    threading.Thread(target=heartbeat, daemon=True,
                     name=f"bps-serve-host-hb-{hid}").start()
    drained = False
    try:
        while not stop.wait(0.25):
            if core.draining.is_set():
                break
        if core.draining.is_set() and not stop.is_set():
            # -- the graceful drain state machine --------------------
            # 1) mark the directory: the gen bump re-routes every
            #    consumer off this arc at its next sync
            if directory.bus is not None:
                try:
                    directory.register(
                        srv.addr, host_id=hid, draining=True,
                        meta={"pulls": core.pulls, "sheds": core.sheds,
                              "hot": core.hot_keys(8), "role": "serve"})
                except (ConnectionError, TimeoutError):
                    get_logger().warning(
                        "serve host %d: drain mark could not reach the "
                        "bus (heartbeat retries)", hid)
            # 2) in-flight pulls finish.  Quiet for a short settle
            #    window, not just a zero sample: stale routers (one
            #    sync interval behind the gen bump) may still land a
            #    last pull — answered normally, never refused.  The
            #    deadline bounds a wedged drain; the reconciler's own
            #    deadline escalates to kill beyond it.
            deadline = (time.monotonic()
                        + cfg.reconcile_drain_deadline_s)
            quiet_t = None
            while time.monotonic() < deadline and not stop.is_set():
                if core.admission.inflight > 0:
                    quiet_t = None
                elif quiet_t is None:
                    quiet_t = time.monotonic()
                elif time.monotonic() - quiet_t >= 0.3:
                    break
                time.sleep(0.05)
            drained = True
    finally:
        # 3) the final unregister handshake (clears the DRAINING mark
        #    on the bus), then clean exit
        if directory.bus is not None:
            try:
                directory.unregister(hid)
            except Exception:  # noqa: BLE001 — TTL finishes the job
                pass
        srv.close()
    if drained:
        print(f"HOST-DRAINED {hid} {core.pulls}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
