"""Durable state plane: crash-consistent WAL + atomic snapshot cuts.

Every robustness layer before this one — coordinator failover, gossip
membership, the fleet reconciler — assumes at least one *survivor*
holds authoritative state in memory.  This module is the layer for the
correlated case (power loss, OOM storm, a bad deploy rolled out to
every host at once): the system's memory outlives its processes.

Three pieces:

**Write-ahead journal** (:class:`WriteAheadLog`).  Segment files of
length-prefixed records; each record is a pickled ``(lsn, kind, data)``
tuple sealed with the PR-4 integrity envelope
(:func:`~byteps_tpu.common.integrity.seal_bytes`, CRC32C verified at
replay), so a torn tail, a bit flip, or a short write is *detected*,
truncated to the last whole record, and never trusted.  The journal is
written **before** the in-memory merge (classic WAL intent ordering): a
failed append raises with the store untouched and the dedup floor not
advanced, so memory and disk can never disagree about a landed delta.
Fsync policy is the operator's durability/latency dial
(``BYTEPS_WAL_FSYNC=always|interval|off``).

**Atomic snapshot cuts** (:func:`save_snapshot`).  The full store state
(arrays + versions + generation + membership epoch + dedup floors) as
one sealed blob, written to a temp file, fsynced, then *renamed* into
place — readers see the previous complete cut or the new one, never a
torn mix.  A manifest records the version vector and the WAL position
the cut covers; the journal is truncated up to it (whole segments
only), so cold-start replay cost is bounded by one cut interval, not
the life of the run.

**Cold-start recovery** (:func:`attach` / :func:`recover`).  Load the
newest snapshot that verifies (a corrupt one falls back to the next,
counted, never silently used), then replay the journal suffix through
the store's normal merge path — dedup floors and the membership-epoch
gate are rebuilt exactly, so a worker's duplicate retry arriving
*after* a cold restart is still absorbed.  Replay stops at the first
record that fails verification: a torn tail is truncated in place
(appends resume right after the valid prefix); a corrupt mid-log
record truncates there and discards the later segments — recovering to
the last *durable* point with zero silent corruption.  When corruption
leaves the journal's tail **below** the restored snapshot's cut,
recovery advances the LSN past the cut with a sealed ``__advance__``
marker (:meth:`WriteAheadLog.advance_to`): new appends are never
assigned LSNs an existing snapshot already covers, so a later restart
cannot skip them as "already folded in".

Durable blobs (journal records, snapshot cuts) are deserialized through
a **restricted unpickler** limited to numpy's array machinery and plain
builtins: the CRC seal detects corruption but does not *authenticate*,
so the durable dir must be as trusted as the binary — the allowlist
keeps a writable dir from naming arbitrary callables
(docs/fault_tolerance.md, "Trust boundary").

Chaos sites woven here (``fault/injector.py``): ``wal_write``
(``bitflip`` corrupts the on-disk frame, ``drop`` tears the write
short), ``fsync`` (``drop`` skips the fsync the policy promised), and
``disk_full`` (``drop`` fails the append with ``ENOSPC``).
"""

from __future__ import annotations

import errno
import json
import os
import pickle
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..common import integrity as _integrity
from ..common.lock_witness import named_lock
from ..common.logging import get_logger
from ..common.telemetry import counters, gauges
from ..fault import injector as _fault

__all__ = ["WriteAheadLog", "DurableKV", "attach", "recover",
           "save_snapshot", "load_snapshot", "ensure_process_store",
           "recover_process_store", "process_store"]

# record framing: [u32 big-endian frame length][sealed frame]
_LEN = struct.Struct("!I")
# sanity clamp on a length prefix: anything past this is garbage bytes
# read as a length, not a record something in this codebase wrote
_MAX_RECORD = 1 << 30
# marker record kind written by advance_to(): carries no mutation, only
# a verified forward LSN jump (data = {"prev": last LSN before the jump})
_ADVANCE = "__advance__"


class _RestrictedUnpickler(pickle.Unpickler):
    """Unpickler for durable blobs, limited to what the durable plane
    actually serializes: numpy's array reconstruction machinery plus a
    handful of containers pickle names as globals.  The integrity seal
    is corruption DETECTION (CRC), not authentication — without this
    allowlist, write access to BYTEPS_DURABLE_DIR would be arbitrary
    code execution in every process that recovers from it."""

    _SAFE_BUILTINS = {"complex", "set", "frozenset", "bytearray", "slice"}
    _SAFE_NUMPY = {("numpy", "ndarray"), ("numpy", "dtype"),
                   ("numpy.core.multiarray", "_reconstruct"),
                   ("numpy.core.multiarray", "scalar"),
                   ("numpy.core.numeric", "_frombuffer"),
                   ("numpy._core.multiarray", "_reconstruct"),
                   ("numpy._core.multiarray", "scalar"),
                   ("numpy._core.numeric", "_frombuffer")}

    def find_class(self, module, name):
        if module == "builtins" and name in self._SAFE_BUILTINS:
            import builtins
            return getattr(builtins, name)
        if (module, name) in self._SAFE_NUMPY:
            import importlib
            return getattr(importlib.import_module(module), name)
        raise pickle.UnpicklingError(
            f"durable blob names global {module}.{name}, which is not "
            "on the durable-plane allowlist (the durable dir is "
            "CRC-checked, not authenticated — see "
            "docs/fault_tolerance.md 'Trust boundary')")


def _loads(payload: bytes) -> Any:
    """Deserialize a verified durable blob through the allowlist."""
    import io
    return _RestrictedUnpickler(io.BytesIO(payload)).load()


def _fsync_dir(path: str) -> None:
    """Fsync a directory so a rename/create inside it is durable (the
    file's own fsync does not cover its directory entry)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without directory fsync — best effort
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _maybe_fsync(fh) -> bool:
    """The one fsync choke point, chaos-instrumented: a ``drop:site=fsync``
    rule models a kernel/disk that lied about durability.  Returns True
    when the fsync actually ran."""
    if _fault.ENABLED and _fault.should_drop("fsync"):
        counters.inc("wal.fsync_dropped")
        return False
    fh.flush()
    os.fsync(fh.fileno())
    counters.inc("wal.fsyncs")
    return True


class WriteAheadLog:
    """Append-only segmented journal of sealed records.

    ``replay()`` must run before the first ``append()`` — it scans the
    existing segments (truncating any invalid suffix) and positions the
    log so new appends continue the LSN sequence right after the last
    valid record.
    """

    def __init__(self, dirpath: str, *, fsync: str = "always",
                 fsync_interval_s: float = 0.05,
                 segment_bytes: int = 4 << 20, name: str = "kv"):
        self.dir = dirpath
        self.name = name
        self._fsync = fsync
        self._fsync_interval_s = float(fsync_interval_s)
        self._segment_bytes = int(segment_bytes)
        self._lock = named_lock("wal")
        self._fh = None
        self._seg_path: Optional[str] = None
        self._seg_size = 0
        self._lsn = 0              # last LSN written (0 = empty log)
        self._last_sync = 0.0
        self._replayed = False
        os.makedirs(dirpath, exist_ok=True)

    # -- paths ---------------------------------------------------------------

    def _seg_name(self, first_lsn: int) -> str:
        return os.path.join(self.dir,
                            f"{self.name}-{first_lsn:016d}.wal")

    def segments(self) -> List[Tuple[int, str]]:
        """``[(first_lsn, path)]`` sorted by first LSN."""
        out = []
        prefix, suffix = f"{self.name}-", ".wal"
        for fn in os.listdir(self.dir):
            if fn.startswith(prefix) and fn.endswith(suffix):
                mid = fn[len(prefix):-len(suffix)]
                if mid.isdigit():
                    out.append((int(mid), os.path.join(self.dir, fn)))
        out.sort()
        return out

    # -- append path ---------------------------------------------------------

    @property
    def lsn(self) -> int:
        with self._lock:
            return self._lsn

    def append(self, kind: str, data: Any) -> int:
        """Journal one mutation; returns its LSN.  Raises ``OSError`` on
        a failed or torn write — the caller must NOT apply the mutation
        to memory (journal-before-merge is the crash-consistency
        contract)."""
        with self._lock:
            return self._append_locked(kind, data)

    def _append_locked(self, kind: str, data: Any) -> int:
        if not self._replayed:
            raise RuntimeError("WriteAheadLog.append before replay() "
                               "— the log position is unknown")
        if _fault.ENABLED and _fault.should_drop("disk_full"):
            counters.inc("wal.disk_full_errors")
            raise OSError(errno.ENOSPC,
                          "wal: no space left on device (injected)")
        lsn = self._lsn + 1
        payload = pickle.dumps((lsn, kind, data),
                               protocol=pickle.HIGHEST_PROTOCOL)
        frame = _integrity.seal_bytes(payload, key="wal", seq=lsn)
        buf = _LEN.pack(len(frame)) + frame
        if _fault.ENABLED:
            buf = _fault.corrupt_bytes("wal_write", buf)
        if self._fh is None or self._seg_size >= self._segment_bytes:
            self._roll(lsn)
        if _fault.ENABLED and _fault.should_drop("wal_write"):
            # a torn write: half the record reaches the disk, then
            # the "crash" — the caller sees the failure (mutation
            # not applied) and replay truncates the torn tail
            self._fh.write(buf[:max(1, len(buf) // 2)])
            self._fh.flush()
            counters.inc("wal.torn_writes")
            raise OSError(errno.EIO,
                          "wal: torn write (injected crash)")
        self._fh.write(buf)
        self._seg_size += len(buf)
        self._lsn = lsn
        counters.inc("wal.appends")
        counters.inc("wal.append_bytes", len(buf))
        if self._fsync == "always":
            _maybe_fsync(self._fh)
        elif self._fsync == "interval":
            now = time.monotonic()
            if now - self._last_sync >= self._fsync_interval_s:
                if _maybe_fsync(self._fh):
                    self._last_sync = now
        else:  # "off": the OS page cache decides
            self._fh.flush()
        gauges.set("wal.lsn", lsn)
        return lsn

    def advance_to(self, lsn: int) -> int:
        """Force future appends onto LSNs strictly above ``lsn``.

        Recovery calls this when corruption truncated the journal BELOW
        a restored snapshot's cut: without the jump, new appends would
        reuse LSNs the snapshot already covers and the NEXT recovery's
        ``lsn <= snapshot`` skip would silently discard them.  Rolls a
        fresh segment and seals an explicit :data:`_ADVANCE` marker
        record into it, so the next replay can verify the LSN gap was an
        intentional, snapshot-covered advance — not a missing segment.
        No-op when the log is already at or past ``lsn``."""
        with self._lock:
            if not self._replayed:
                raise RuntimeError("WriteAheadLog.advance_to before "
                                   "replay() — the log position is "
                                   "unknown")
            if lsn <= self._lsn:
                return self._lsn
            prev = self._lsn
            self._lsn = int(lsn)
            self._roll(self._lsn + 1)
            self._append_locked(_ADVANCE, {"prev": prev})
            counters.inc("wal.advances")
            get_logger().warning(
                "wal: advanced LSN %d -> %d past a restored snapshot "
                "cut (journal had truncated below it) — new appends "
                "cannot collide with snapshot-covered LSNs", prev,
                self._lsn)
            return self._lsn

    def _roll(self, first_lsn: int) -> None:
        """Caller holds the lock: close the current segment (fsynced —
        a rolled segment is immutable and must be durable before the
        next one starts) and open a new one named by its first LSN."""
        if self._fh is not None:
            _maybe_fsync(self._fh)
            self._fh.close()
        self._seg_path = self._seg_name(first_lsn)
        self._fh = open(self._seg_path, "ab")
        self._seg_size = self._fh.tell()
        _fsync_dir(self.dir)

    def sync(self) -> None:
        with self._lock:
            if self._fh is not None:
                _maybe_fsync(self._fh)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                _maybe_fsync(self._fh)
                self._fh.close()
                self._fh = None

    # -- replay / recovery ---------------------------------------------------

    def replay(self) -> Tuple[List[Tuple[int, str, Any]], Dict[str, int]]:
        """Scan every segment, verify every record, truncate the first
        invalid suffix, and position the log for appends.  Returns
        ``(records, stats)`` where records is the valid ``(lsn, kind,
        data)`` sequence in order."""
        records: List[Tuple[int, str, Any]] = []
        stats = {"records": 0, "bytes": 0, "truncated_tails": 0,
                 "corrupt_records": 0, "dropped_segments": 0}
        with self._lock:
            segs = self.segments()
            expected = None  # next LSN we must see (None until first)
            stop_at: Optional[Tuple[int, int]] = None  # (seg index, off)
            for i, (first_lsn, path) in enumerate(segs):
                with open(path, "rb") as fh:
                    blob = fh.read()
                off = 0
                while off < len(blob):
                    bad = None
                    if off + _LEN.size > len(blob):
                        bad = "short length prefix"
                    else:
                        (flen,) = _LEN.unpack_from(blob, off)
                        if not 0 < flen <= _MAX_RECORD:
                            bad = f"implausible record length {flen}"
                        elif off + _LEN.size + flen > len(blob):
                            bad = "short record body"
                    if bad is None:
                        frame = blob[off + _LEN.size:
                                     off + _LEN.size + flen]
                        try:
                            payload, _meta = _integrity.open_bytes(frame)
                            lsn, kind, data = _loads(payload)
                        except Exception as e:  # noqa: BLE001 — any
                            # failure here is corruption, by definition
                            bad = f"record failed verification: {e}"
                        else:
                            if expected is not None and lsn != expected:
                                if (kind == _ADVANCE and lsn > expected
                                        and isinstance(data, dict)
                                        and data.get("prev")
                                        == expected - 1):
                                    # a sealed advance marker whose
                                    # "prev" chains to the record before
                                    # it: an intentional, snapshot-
                                    # covered LSN jump (advance_to), not
                                    # a hole in the history
                                    pass
                                else:
                                    bad = (f"LSN discontinuity: got "
                                           f"{lsn}, expected {expected}")
                    if bad is not None:
                        tail = (i == len(segs) - 1)
                        if tail:
                            stats["truncated_tails"] += 1
                            counters.inc("wal.truncated_tails")
                        else:
                            stats["corrupt_records"] += 1
                            counters.inc("wal.corrupt_records")
                        get_logger().warning(
                            "wal: %s segment %s at offset %d (%s) — "
                            "recovering to the last durable point",
                            "torn tail in" if tail else
                            "corrupt record in", path, off, bad)
                        from ..common import flight_recorder as _flight
                        _flight.record(
                            "wal.truncated_tail" if tail
                            else "wal.corrupt_record",
                            segment=os.path.basename(path), offset=off,
                            reason=bad)
                        stop_at = (i, off)
                        break
                    records.append((lsn, kind, data))
                    stats["records"] += 1
                    stats["bytes"] += _LEN.size + flen
                    counters.inc("wal.replay_records")
                    counters.inc("wal.replay_bytes", _LEN.size + flen)
                    expected = lsn + 1
                    off += _LEN.size + flen
                if stop_at is not None:
                    break
            if stop_at is not None:
                i, off = stop_at
                with open(segs[i][1], "r+b") as fh:
                    fh.truncate(off)
                    os.fsync(fh.fileno())
                # everything after the corruption point is not part of
                # the valid prefix: later segments are discarded, never
                # replayed past a hole in the history
                for _, path in segs[i + 1:]:
                    os.remove(path)
                    stats["dropped_segments"] += 1
                    counters.inc("wal.dropped_segments")
                _fsync_dir(self.dir)
            self._lsn = records[-1][0] if records else 0
            # position appends at the end of the last surviving segment
            segs = self.segments()
            if segs:
                self._seg_path = segs[-1][1]
                self._fh = open(self._seg_path, "ab")
                self._seg_size = self._fh.tell()
            self._replayed = True
            gauges.set("wal.lsn", self._lsn)
        return records, stats

    # -- retention -----------------------------------------------------------

    def truncate_upto(self, lsn: int) -> int:
        """Remove whole segments whose records are all covered by a
        durable snapshot at ``lsn`` (a segment is removable when the
        NEXT segment starts at or before ``lsn + 1``).  Returns the
        number of segments removed."""
        removed = 0
        with self._lock:
            segs = self.segments()
            for (start, path), (nxt_start, _) in zip(segs, segs[1:]):
                if nxt_start <= lsn + 1:
                    os.remove(path)
                    removed += 1
                else:
                    break
            if removed:
                _fsync_dir(self.dir)
                counters.inc("wal.truncated_segments", removed)
        return removed

    def lag_bytes(self) -> int:
        """Bytes of journal a cold start would have to replay — the
        on-disk size of the live segments (retention keeps this bounded
        by roughly one cut interval of traffic)."""
        total = 0
        for _, path in self.segments():
            try:
                total += os.path.getsize(path)
            except OSError:
                pass
        return total

    def debug_state(self) -> dict:
        with self._lock:
            lsn, seg = self._lsn, self._seg_path
        return {"kind": "wal", "name": self.name, "dir": self.dir,
                "lsn": lsn, "fsync": self._fsync,
                "segment": os.path.basename(seg) if seg else None,
                "segments": len(self.segments()),
                "lag_bytes": self.lag_bytes()}


# -- atomic snapshot persistence ---------------------------------------------


def _manifest_path(dirpath: str, name: str) -> str:
    return os.path.join(dirpath, f"{name}-manifest.json")


def _snap_path(dirpath: str, name: str, lsn: int) -> str:
    return os.path.join(dirpath, f"{name}-snap-{lsn:016d}.bin")


def _atomic_write(path: str, data: bytes) -> None:
    """write-to-temp + fsync + rename: the path either holds the old
    complete content or the new one, never a torn mix."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        _maybe_fsync(fh)
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


def save_snapshot(dirpath: str, state: dict, *, lsn: int,
                  generation: int, name: str = "kv",
                  retain: int = 2) -> str:
    """Persist one durable cut atomically and prune old ones.  The
    manifest (itself atomically replaced) names the newest cut and
    carries the version vector, so an operator (or ``bps_doctor``) can
    see what a cold start would restore without opening the blob."""
    os.makedirs(dirpath, exist_ok=True)
    blob = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
    frame = _integrity.seal_bytes(blob, key=f"{name}-snap", seq=lsn)
    path = _snap_path(dirpath, name, lsn)
    _atomic_write(path, frame)
    manifest = {"name": name, "lsn": int(lsn),
                "generation": int(generation),
                "file": os.path.basename(path),
                "ts": time.time(),
                "versions": {str(k): int(v) for k, v in
                             (state.get("versions") or {}).items()}}
    _atomic_write(_manifest_path(dirpath, name),
                  json.dumps(manifest, sort_keys=True).encode())
    counters.inc("wal.snapshot_saves")
    gauges.set("wal.last_snapshot_lsn", int(lsn))
    # retention: newest `retain` cuts stay; the WAL caller separately
    # truncates segments the newest cut covers
    snaps = _list_snaps(dirpath, name)
    for _, old in snaps[:-retain] if retain > 0 else []:
        try:
            os.remove(old)
        except OSError:
            pass
    return path


def _list_snaps(dirpath: str, name: str) -> List[Tuple[int, str]]:
    out = []
    prefix, suffix = f"{name}-snap-", ".bin"
    try:
        names = os.listdir(dirpath)
    except OSError:
        return []
    for fn in names:
        if fn.startswith(prefix) and fn.endswith(suffix):
            mid = fn[len(prefix):-len(suffix)]
            if mid.isdigit():
                out.append((int(mid), os.path.join(dirpath, fn)))
    out.sort()
    return out


def load_snapshot(dirpath: str, name: str = "kv"
                  ) -> Tuple[Optional[dict], int]:
    """Newest snapshot that VERIFIES, as ``(state, lsn)`` —
    ``(None, 0)`` when no usable cut exists.  A corrupt blob falls back
    to the next-newest (counted, flight-recorded), never silently
    restored."""
    for lsn, path in reversed(_list_snaps(dirpath, name)):
        try:
            with open(path, "rb") as fh:
                frame = fh.read()
            payload, _meta = _integrity.open_bytes(frame)
            state = _loads(payload)
        except Exception as e:  # noqa: BLE001 — corruption, by definition
            counters.inc("wal.snapshot_corrupt")
            get_logger().error(
                "wal: snapshot %s failed verification (%s) — falling "
                "back to an older cut", path, e)
            from ..common import flight_recorder as _flight
            _flight.record("wal.snapshot_corrupt",
                           file=os.path.basename(path), reason=str(e))
            continue
        counters.inc("wal.snapshot_loads")
        return state, lsn
    return None, 0


# -- the KVStore coupling ----------------------------------------------------


class DurableKV:
    """One KVStore's durable plane: the journal, the checkpoint cycle,
    and the recovery stats from open time.  Created via :func:`attach`
    (which recovers the store from disk first, then arms journaling)."""

    def __init__(self, store, dirpath: str, *, fsync: str,
                 fsync_interval_s: float, segment_bytes: int,
                 retain: int):
        self.store = store
        self.dir = dirpath
        self.retain = retain
        self.wal = WriteAheadLog(dirpath, fsync=fsync,
                                 fsync_interval_s=fsync_interval_s,
                                 segment_bytes=segment_bytes, name="kv")
        self.recover_stats: Dict[str, int] = {}
        self._ckpt_lock = threading.Lock()
        self._ckpt_lsn = 0
        # the /debug/state "wal" section lists DurableKV (journal view
        # + checkpoint_lsn + recover_stats), not the bare journal — a
        # standalone WriteAheadLog is a unit-test construction
        from ..common import metrics as _metrics
        _metrics.register_component("wal", self)

    def _recover(self) -> Dict[str, int]:
        """Snapshot restore + journal replay into the store, BEFORE
        journaling is armed (replay must not re-journal itself)."""
        t0 = time.monotonic()
        state, snap_lsn = load_snapshot(self.dir, "kv")
        if state is not None:
            self.store.restore_durable_state(state)
        records, stats = self.wal.replay()
        applied = 0
        for lsn, kind, data in records:
            if lsn <= snap_lsn:
                continue  # covered by the snapshot we restored
            if kind == _ADVANCE:
                continue  # LSN jump marker, not a mutation
            self.store.apply_wal_record(kind, data)
            applied += 1
        if snap_lsn > self.wal.lsn:
            # corruption truncated the journal BELOW the restored cut
            # (a corrupt record between the cut point and the tail, or
            # a fully-corrupt live segment).  Jump the LSN past the
            # snapshot so new appends are never assigned LSNs an
            # existing cut covers — otherwise the next recovery's
            # "lsn <= snap_lsn" skip above would silently discard
            # acknowledged, fsynced mutations.
            stats["advanced_to"] = self.wal.advance_to(snap_lsn)
        self._ckpt_lsn = snap_lsn
        stats.update(snapshot_lsn=snap_lsn, applied=applied,
                     had_snapshot=int(state is not None),
                     elapsed_ms=int((time.monotonic() - t0) * 1000))
        self.recover_stats = stats
        counters.inc("wal.recoveries")
        gauges.set("wal.lag_bytes", self.wal.lag_bytes())
        if state is not None or records:
            from ..common import flight_recorder as _flight
            _flight.record("wal.recovered", dir=self.dir,
                           snapshot_lsn=snap_lsn, applied=applied,
                           **{k: stats[k] for k in
                              ("truncated_tails", "corrupt_records",
                               "dropped_segments")})
            get_logger().warning(
                "wal: cold-start recovery from %s — snapshot lsn %d + "
                "%d replayed record(s) in %dms (%d torn tail(s), %d "
                "corrupt record(s))", self.dir, snap_lsn, applied,
                stats["elapsed_ms"], stats["truncated_tails"],
                stats["corrupt_records"])
        return stats

    def checkpoint(self, force: bool = False) -> bool:
        """Persist a durable cut of the store and truncate the journal
        it covers.  Cheap no-op when nothing was journaled since the
        last cut.  Returns True when a cut was written."""
        with self._ckpt_lock:
            # the cut's LSN comes from durable_state(), which captures it
            # UNDER the store lock — reading self.wal.lsn here and the
            # state separately would let a push journal+merge in between,
            # and replay after restore would then double-apply that delta
            state = self.store.durable_state()
            lsn = int(state.pop("wal_lsn", self.wal.lsn))
            if not force and lsn <= self._ckpt_lsn:
                gauges.set("wal.lag_bytes", self.wal.lag_bytes())
                return False
            save_snapshot(self.dir, state, lsn=lsn,
                          generation=state.get("generation", 0),
                          name="kv", retain=self.retain)
            self.wal.truncate_upto(lsn)
            self._ckpt_lsn = lsn
            gauges.set("wal.lag_bytes", self.wal.lag_bytes())
            return True

    def close(self) -> None:
        self.wal.close()

    def debug_state(self) -> dict:
        d = self.wal.debug_state()
        d.update(checkpoint_lsn=self._ckpt_lsn,
                 recover_stats=dict(self.recover_stats))
        return d


def attach(store, dirpath: str, cfg=None) -> DurableKV:
    """Recover ``store`` from ``dirpath`` (snapshot + journal replay),
    then arm journaling on it — the one call that turns an in-memory
    KVStore into a durable one."""
    if cfg is None:
        from ..common.config import get_config
        cfg = get_config()
    dur = DurableKV(store, dirpath, fsync=cfg.wal_fsync,
                    fsync_interval_s=cfg.wal_fsync_interval_s,
                    segment_bytes=cfg.wal_segment_bytes,
                    retain=cfg.wal_retain_snapshots)
    dur._recover()
    store.bind_wal(dur)
    return dur


def recover(dirpath: str, store=None, cfg=None):
    """Cold-start helper: build (or fill) a KVStore from the durable
    state at ``dirpath``; returns ``(store, stats)``."""
    if store is None:
        from .kv_store import KVStore
        store = KVStore()
    dur = attach(store, dirpath, cfg)
    return store, dur.recover_stats


# -- the process-lifetime trainer-side store ---------------------------------
#
# Like the obs server and the time-series sampler, the durable store is
# a PROCESS singleton: it survives suspend/resume (an elastic world
# change must not close and re-replay the journal) and is (re)opened by
# ``bps.init()`` when BYTEPS_DURABLE_DIR is set.

_proc_lock = threading.Lock()
_proc: Optional[Tuple[Any, DurableKV]] = None


def ensure_process_store(cfg=None) -> Tuple[Any, DurableKV]:
    """Open (once per process) the durable trainer-side KVStore under
    ``<durable_dir>/trainer``; later calls return the same pair."""
    global _proc
    if cfg is None:
        from ..common.config import get_config
        cfg = get_config()
    if not cfg.durable_dir:
        raise RuntimeError("BYTEPS_DURABLE_DIR is not set — there is no "
                           "durable state plane to open")
    with _proc_lock:
        if _proc is None:
            from .kv_store import KVStore
            store = KVStore()
            dur = attach(store, os.path.join(cfg.durable_dir, "trainer"),
                         cfg)
            _proc = (store, dur)
        return _proc


def recover_process_store(cfg=None) -> Tuple[Any, DurableKV]:
    """Cold-start recovery of the trainer-side store: close any open
    incarnation and rebuild it from disk.  DESTRUCTIVE to a live
    incarnation: components already holding the old store object keep a
    reference that no longer journals, and any journal tail the chaos
    ``fsync`` site dropped is gone — only call this when no in-memory
    state is authoritative (``fault/recovery.py`` keeps a surviving
    process's open store and rebuilds only when none is open)."""
    global _proc
    with _proc_lock:
        if _proc is not None:
            _proc[1].close()
            _proc = None
    return ensure_process_store(cfg)


def process_store():
    """The open durable trainer-side store, or None."""
    return None if _proc is None else _proc[0]


def _reset_for_tests() -> None:
    global _proc
    with _proc_lock:
        if _proc is not None:
            try:
                _proc[1].close()
            except Exception:  # noqa: BLE001 — test teardown best-effort
                pass
            _proc = None
