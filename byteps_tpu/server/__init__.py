"""Asynchronous parameter-store semantics (reference byteps/server/).

On TPU the synchronous path needs no server at all — ``psum`` over the mesh
*is* the sum-and-barrier (SURVEY.md §2.3).  What still needs server
semantics is asynchronous training (BYTEPS_ENABLE_ASYNC, reference
server.cc:310-314,417-419): workers push weight *deltas* and pull fresh
weights with no barrier.  kv_store.py provides that as a host-side store.
"""

from .kv_store import KVStore  # noqa: F401
from .serve_client import PullClient  # noqa: F401
from .serving import ServingPlane, SnapshotStore  # noqa: F401
from .serving_tier import ServingTier  # noqa: F401
