"""Serving-tier autoscaler: signal-driven scale proposals over the bus.

The tier's economics hinge on the consistent-hash ring
(``serve_ring.py``): a host joining or leaving moves ~1/N of the key
space, so scaling host-by-host is cheap — IF something decides when.
This module is that something, fed by the signals the repo already
maintains:

- the ``serve.*`` pull/shed figures serving hosts attach to their
  directory re-registrations (``serve_register`` meta rides the
  membership bus, ``fault/membership.py``),
- the hosts' ``hot_keys()`` histograms (same channel),
- and the PR-9 **slowness tracker** (``utils/slowness.py``): per-host
  phi scores at site ``serve_pull`` (router-observed pull latency) and
  ``transport`` (publisher-observed ship RTT) — a gray-failing host is
  EXCLUDED from replica placement before it is dead.

:meth:`TierAutoscaler.decide` is a pure function of a signals dict (the
unit-testable core); :meth:`step` gathers signals, applies the cooldown,
and acts: scale-DOWN retires a victim through the tier (directory
unregister — every ring consumer heals at the next sync), scale-UP posts
the target through the bus (verb ``serve_scale``) for whoever launches
host processes (serve_bench ``--hosts``, an operator, a k8s controller)
to read — the autoscaler proposes, membership disposes.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

from ..common.logging import get_logger
from ..common.telemetry import counters, gauges

__all__ = ["ScaleDecision", "TierAutoscaler"]


@dataclasses.dataclass
class ScaleDecision:
    action: str                 # "up" | "down" | "hold"
    target: int                 # proposed host count
    victims: List[int]          # hosts to retire (action == "down")
    probation: List[int]        # gray-failing hosts excluded from placement
    placement: Dict[object, List[int]]   # hot key -> replica host set
    reason: str


class TierAutoscaler:
    """Proposes the serving-tier size and placement from live signals.

    Policy (deliberately boring — the interesting part is the signal
    plumbing and that proposals travel the BUS, not a config file):

    - **up** when the tier sheds (``shed_rate`` > 0) or the slowest
      healthy host's phi crosses the config threshold with no idle
      capacity, and the ceiling allows;
    - **down** when per-host pull rate sits under ``low_pulls_per_s``
      with zero shedding and the floor allows — victims are probationed
      hosts first (demote the gray one), else the smallest arc;
    - **hold** otherwise, and always inside the cooldown window.
    """

    def __init__(self, tier, *, min_hosts: Optional[int] = None,
                 max_hosts: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 low_pulls_per_s: float = 50.0,
                 hot_n: int = 8,
                 dispose: str = "retire"):
        from ..common.config import get_config
        cfg = get_config()
        if dispose not in ("retire", "drain"):
            raise ValueError("dispose must be 'retire' (unregister the "
                             "victim now) or 'drain' (propose it to the "
                             "fleet reconciler's graceful drain)")
        self.tier = tier
        self.dispose = dispose
        self.min_hosts = (cfg.serve_tier_min_hosts if min_hosts is None
                          else int(min_hosts))
        self.max_hosts = (cfg.serve_tier_max_hosts if max_hosts is None
                          else int(max_hosts))
        self.cooldown_s = (cfg.serve_tier_cooldown_s if cooldown_s is None
                           else float(cooldown_s))
        self.low_pulls_per_s = float(low_pulls_per_s)
        self.hot_n = int(hot_n)
        self._phi = cfg.slowness_phi
        self._last_decision = 0.0
        self._last_counts: Dict[int, Dict[str, float]] = {}
        self._last_poll = 0.0

    # -- signal gathering ----------------------------------------------------

    def signals(self) -> dict:
        """One consistent signals dict: per-host pull/shed RATES (deltas
        of the cumulative figures hosts attach to their directory
        re-registrations), per-host slowness phi (max over the
        ``serve_pull`` and ``transport`` sites), arc shares, hot keys,
        and the directory's current shape."""
        info = self.tier.directory.info()
        now = time.monotonic()
        dt = max(now - self._last_poll, 1e-6) if self._last_poll else None
        self._last_poll = now
        # first sample: no deltas exist yet, so every rate below is a
        # structural zero — "warm" lets decide() hold instead of
        # mistaking no-data-yet for an idle tier and retiring a host
        # that is in fact serving heavy traffic
        warm = dt is not None
        from ..fault.membership import SERVE_RANK_BASE
        from ..utils import slowness as _slowness
        tracker = _slowness.tracker()
        scores: Dict[int, float] = {}
        # serve_pull observations are keyed by bare host id (the
        # router's peer); transport observations by the endpoint peer =
        # SERVE_RANK_BASE + host_id — fold the latter back into host-id
        # space, and ignore transport peers below the base (those are
        # TRAINER ranks: rank 2 being slow must not probation host 2)
        for peer, phi in tracker.scores(site="serve_pull").items():
            scores[peer] = max(scores.get(peer, 0.0), phi)
        for peer, phi in tracker.scores(site="transport").items():
            if peer >= SERVE_RANK_BASE:
                h = peer - SERVE_RANK_BASE
                scores[h] = max(scores.get(h, 0.0), phi)
        hosts = sorted(info["hosts"])
        rates: Dict[int, dict] = {}
        hot: Dict[object, int] = {}
        for h in hosts:
            meta = info["meta"].get(h, {})
            cur = {"pulls": float(meta.get("pulls", 0)),
                   "sheds": float(meta.get("sheds", 0))}
            prev = self._last_counts.get(h)
            if prev is not None and dt is not None:
                rates[h] = {
                    "pulls_per_s": max(0.0, (cur["pulls"] - prev["pulls"])
                                       / dt),
                    "shed_per_s": max(0.0, (cur["sheds"] - prev["sheds"])
                                      / dt)}
            else:
                rates[h] = {"pulls_per_s": 0.0, "shed_per_s": 0.0}
            self._last_counts[h] = cur
            for k in meta.get("hot", ()):
                hot[k] = hot.get(k, 0) + 1
        return {
            "hosts": hosts,
            "warm": warm,
            "gen": info["gen"],
            "rates": rates,
            "pulls_per_s": sum(r["pulls_per_s"] for r in rates.values()),
            "shed_per_s": sum(r["shed_per_s"] for r in rates.values()),
            "slow": {h: scores.get(h, 0.0) for h in hosts},
            "phi_threshold": self._phi,
            "arc_share": self.tier.ring.arc_share(),
            "hot_keys": sorted(hot, key=lambda k: (-hot[k], str(k)))
            [:self.hot_n],
        }

    # -- the pure decision ---------------------------------------------------

    def decide(self, sig: dict) -> ScaleDecision:
        hosts: List[int] = list(sig["hosts"])
        n = len(hosts)
        phi_t = sig.get("phi_threshold", self._phi)
        probation = sorted(h for h in hosts
                           if sig["slow"].get(h, 0.0) >= phi_t)
        healthy = [h for h in hosts if h not in probation]
        placement = self._placement(sig, healthy or hosts)
        if n == 0:
            return ScaleDecision("up", max(self.min_hosts, 1), [],
                                 probation, placement, "no hosts")
        shed = sig.get("shed_per_s", 0.0)
        pulls = sig.get("pulls_per_s", 0.0)
        if (shed > 0.0 or len(healthy) < self.min_hosts) \
                and n < self.max_hosts:
            why = (f"shedding {shed:.1f}/s" if shed > 0.0
                   else f"only {len(healthy)} healthy host(s)")
            return ScaleDecision("up", n + 1, [], probation, placement, why)
        if not sig.get("warm", True):
            # zero observed rates on the FIRST sample mean "no deltas
            # yet", not "idle" — scaling down on them would retire (and
            # ban) a healthy host mid-traffic
            return ScaleDecision("hold", n, [], probation, placement,
                                 "warming up (first sample)")
        if (n > self.min_hosts and shed == 0.0
                and pulls / n < self.low_pulls_per_s):
            if probation:
                victim = probation[0]
                why = f"host {victim} on probation (phi >= {phi_t})"
            else:
                share = sig.get("arc_share", {})
                victim = min(hosts, key=lambda h: (share.get(h, 0.0), h))
                why = (f"idle tier ({pulls / n:.1f} pulls/s/host < "
                       f"{self.low_pulls_per_s})")
            return ScaleDecision("down", n - 1, [victim], probation,
                                 placement, why)
        return ScaleDecision("hold", n, [], probation, placement,
                             "within bounds")

    def _placement(self, sig: dict, hosts: List[int]
                   ) -> Dict[object, List[int]]:
        """Replica placement for the hot keys over the HEALTHY host set
        — probationed hosts carry no hot arcs (the gray-failure
        machinery governing placement, not just reporting it)."""
        if not hosts:
            return {}
        from .serve_ring import ServeRing
        ring = ServeRing(hosts, vnodes=self.tier.ring.vnodes)
        return {k: ring.replica_hosts(k, self.tier.replicas)
                for k in sig.get("hot_keys", ())}

    # -- the actuation loop --------------------------------------------------

    def step(self, force: bool = False) -> Optional[ScaleDecision]:
        """Gather → decide → act, inside the cooldown.  Returns the
        decision taken (None while cooling down)."""
        now = time.monotonic()
        if not force and now - self._last_decision < self.cooldown_s:
            return None
        sig = self.signals()
        decision = self.decide(sig)
        self._last_decision = now
        self.tier.set_probation(decision.probation)
        gauges.set("serve.tier_target", decision.target)
        if decision.action == "hold":
            return decision
        get_logger().warning("serve autoscaler: %s -> %d host(s): %s",
                             decision.action, decision.target,
                             decision.reason)
        # the proposal travels the BUS either way: launchers watch the
        # target, and a scale-down additionally retires its victims now
        try:
            self.tier.directory.set_target(decision.target)
        except (ConnectionError, TimeoutError):
            get_logger().warning("serve autoscaler: target proposal "
                                 "could not reach the bus")
        if decision.action == "up":
            counters.inc("serve.tier_scale_up")
        else:
            counters.inc("serve.tier_scale_down")
            if self.dispose == "drain":
                # the autoscaler PROPOSES, the reconciler DISPOSES:
                # victims ride the bus and are retired through the
                # graceful drain (in-flight pulls finish, final
                # unregister handshake, bounded by the drain deadline)
                try:
                    self.tier.directory.propose_victims(decision.victims)
                except (ConnectionError, TimeoutError):
                    get_logger().warning("serve autoscaler: victim "
                                         "proposal could not reach the "
                                         "bus")
            else:
                for v in decision.victims:
                    self.tier.retire_host(v, reason=decision.reason)
        return decision
