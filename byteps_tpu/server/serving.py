"""Parameter-serving plane: versioned snapshots, delta pulls, replicas.

The trajectory so far only ever *trains*: every byte the KV store moves
is a push.  The ROADMAP's north star also **serves** — inference fleets,
feature stores, and continuous-learning consumers pulling fresh
parameters while training pushes continue (ROADMAP open item 4).  This
module is that read plane, layered on :class:`~.kv_store.KVStore`
without touching its write semantics:

**Versioned snapshots** (:class:`SnapshotStore`): a snapshot is a
consistent copy-on-write cut of the whole store at a monotonic
``snapshot_id`` with a per-key version vector.  Cutting copies NOTHING —
``KVStore.snapshot_refs`` marks every key COW under one lock
acquisition, and later pushes replace arrays instead of mutating them,
so a snapshot's arrays stay frozen while the push path keeps running.
Publication is atomic (one ring swap under a lock): a reader either
sees the previous complete snapshot or the new complete snapshot, never
a torn multi-key cut.  Retention is bounded (``BYTEPS_SERVE_RETENTION``).

**Delta pulls** (:meth:`SnapshotServer.pull`): a pull carries the
client's last ``snapshot_id``; the reply ships only keys whose version
advanced since — wire-encoded with the key's registered training codec
when one exists ("Compressed Communication for Distributed Training",
PAPERS.md: reuse the push-path codecs on the read path, turning pull
fan-out from O(model) to O(churn) bytes).  Every reply payload crosses
the PR-4 sealed-envelope hop with NACK/bounded-retransmit at chaos site
``serve_pull``.  A ``since_id`` that aged out of retention falls back to
a full snapshot (``serve.full_pulls``).

**Hot-key replication** (:class:`ServingPlane` +
``ServerAssigner.replica_set``): keys hot by pull-count histogram are
mirrored to ``BYTEPS_SERVE_REPLICAS`` shards at each cut; reads fan
across the replica endpoints round-robin, writes stay primary-routed,
and a dead replica degrades to primary-served reads
(``serve.replica_fallback``) instead of erroring.  Elastic world
changes re-clamp the endpoint set and rebuild the replica sets
(``ServerAssigner.reshard`` keeps the pull histogram).

**Staleness-bounded async pulls** live client-side in
:mod:`~byteps_tpu.server.serve_client`.

All ``serve.*`` counters/gauges land in the PR-6 metrics registry, so
they ride ``/metrics``, ``cluster_metrics()``, and ``bps_top``.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common import integrity as _integrity
from ..common.lock_witness import named_lock
from ..common import tracing as _tracing
from ..common.logging import get_logger
from ..common.telemetry import counters, gauges, histograms
from ..fault import injector as _fault
from ..utils.slowness import LatencyQuantile
from ..utils import slowness as _slowness
from .kv_store import KVStore
from .sharding import ServerAssigner

__all__ = ["ServeUnavailable", "Snapshot", "SnapshotRing", "SnapshotStore",
           "ServeItem", "ServeReply", "SnapshotServer", "ServingPlane",
           "active_planes", "notify_world_change"]


class ServeUnavailable(ConnectionError):
    """The addressed serving endpoint cannot answer (dead replica, or no
    snapshot published yet).  The plane's router treats it as a routing
    signal — fall to the next replica, then the primary — never as a
    client-visible failure while any endpoint lives."""


@dataclasses.dataclass(frozen=True)
class Snapshot:
    """One immutable cut of the store.  ``refs`` are read-only
    copy-on-write views (see ``KVStore.snapshot_refs``) — holding a
    snapshot costs no memory until training pushes to its keys.

    ``enc_cache`` memoizes codec wire encodings per key: the arrays are
    frozen, so N clients refreshing against the same cut must not pay N
    identical compressions (replica mirrors SHARE the primary
    snapshot's cache).  Benign race: concurrent encoders of the same
    key compute the same bytes and one write wins."""

    id: int
    ts: float
    versions: Dict[str, int]
    refs: Dict[str, np.ndarray]
    # store generation at cut time (KVStore.clear() bumps it): a delta
    # base from another generation is unusable — versions restarted
    gen: int = 0
    # codecs captured at cut time (one store-lock acquisition per cut),
    # so serving a pull never touches the live store lock
    codecs: Dict[str, tuple] = dataclasses.field(default_factory=dict,
                                                 compare=False)
    enc_cache: Dict[str, bytes] = dataclasses.field(default_factory=dict,
                                                    compare=False)


class SnapshotRing:
    """Bounded retention ring with atomic publish: ``latest()`` swaps in
    one reference assignment under the lock, so a concurrent reader gets
    either the previous complete snapshot or the new one — a torn
    multi-key view is structurally impossible."""

    def __init__(self, retention: int):
        if retention < 1:
            raise ValueError("retention must be >= 1")
        self.retention = retention
        self._lock = named_lock("serve.ring")
        self._snaps: "collections.OrderedDict[int, Snapshot]" = \
            collections.OrderedDict()
        self._latest: Optional[Snapshot] = None

    def publish(self, snap: Snapshot) -> None:
        with self._lock:
            self._snaps[snap.id] = snap
            while len(self._snaps) > self.retention:
                del self._snaps[min(self._snaps)]   # oldest id, not
                #                                     oldest insertion
            if self._latest is None or snap.id >= self._latest.id:
                # never regress: a racing out-of-order publish must not
                # move readers back in time
                self._latest = snap

    def latest(self) -> Optional[Snapshot]:
        with self._lock:
            return self._latest

    def get(self, snapshot_id: int) -> Optional[Snapshot]:
        with self._lock:
            return self._snaps.get(snapshot_id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._snaps)


class SnapshotStore:
    """Cuts consistent snapshots of a :class:`KVStore` into a
    :class:`SnapshotRing`.

    With ``cut_interval_s`` set, the store's write-subscription hook
    drives cutting: each consistent write point (push, or
    ``write_batch`` exit) cuts a fresh snapshot unless one younger than
    the interval exists — the cut itself runs in the pusher's thread
    AFTER the store lock is released, and copies nothing (COW).
    ``cut_fn`` lets an owner substitute its own publication step for
    the throttled cut (``ServingPlane`` passes its replica-mirroring
    ``cut`` so write-driven cutting feeds the replicas too); the
    interval throttle lives HERE either way.  :meth:`detach` removes
    the store subscription — subscribers are strongly referenced, so a
    dropped owner must detach or the store pins it forever."""

    def __init__(self, store: KVStore, retention: Optional[int] = None,
                 cut_interval_s: Optional[float] = None, cut_fn=None,
                 defer_subscribe: bool = False):
        from ..common.config import get_config
        cfg = get_config()
        self.store = store
        self.ring = SnapshotRing(cfg.serve_retention if retention is None
                                 else retention)
        self._ids = itertools.count(1)
        self._cut_lock = named_lock("serve.cut_throttle")
        self._last_cut = 0.0
        self._interval = cut_interval_s
        self._cut_fn = cut_fn if cut_fn is not None else self.cut
        self._subscribed = False
        if cut_interval_s is not None and not defer_subscribe:
            self.attach()

    def attach(self) -> None:
        """Install the write subscription (idempotent).  Split out of
        ``__init__`` so an owner passing ``cut_fn`` into itself can
        finish constructing BEFORE a pusher thread's write hook can
        call back into it (``defer_subscribe=True``)."""
        if not self._subscribed and self._interval is not None:
            self._subscribed = True
            self.store.subscribe(self._on_write)

    def detach(self) -> None:
        """Stop write-driven cutting (idempotent)."""
        if self._subscribed:
            self._subscribed = False
            self.store.unsubscribe(self._on_write)

    def cut(self) -> Snapshot:
        """Cut and atomically publish a snapshot of the store NOW.
        Serialized (concurrent cutters coalesce into a strict id order);
        the store lock is held only for the COW reference grab inside
        ``snapshot_refs`` — never while anything is copied."""
        with self._cut_lock:
            refs, gen = self.store.snapshot_refs()
            snap = Snapshot(id=next(self._ids), ts=time.monotonic(),
                            versions={k: v for k, (_, v) in refs.items()},
                            refs={k: a for k, (a, _) in refs.items()},
                            gen=gen, codecs=self.store.codec_infos())
            self.ring.publish(snap)
            self._last_cut = snap.ts
        counters.inc("serve.snapshot_cuts")
        gauges.set("serve.snapshot_id", snap.id)
        gauges.set("serve.snapshots_retained", len(self.ring))
        # durable state plane (server/wal.py): a store with durability
        # attached persists this cut atomically and truncates the
        # journal it covers — every published snapshot is also the
        # bound on cold-start replay cost.  AFTER publish, outside the
        # cut lock's critical copy path: a failed disk must not fail
        # the in-memory publication readers are waiting on.
        dur = getattr(self.store, "_durable", None)
        if dur is not None:
            try:
                dur.checkpoint()
            except OSError:
                get_logger().error(
                    "serving: durable checkpoint failed after cut %d — "
                    "the journal keeps the history until a later cut "
                    "lands", snap.id, exc_info=True)
        return snap

    def _on_write(self, key: str, version: int) -> None:
        del key, version  # the cut covers the whole store regardless
        if (self._interval is None
                or time.monotonic() - self._last_cut < self._interval):
            return
        self._cut_fn()


@dataclasses.dataclass
class ServeItem:
    """One key in a pull reply.  ``payload`` is the verified wire
    payload: an ndarray for raw keys, the codec's encoded bytes for
    compressed keys (``codec`` then carries the kwargs/numel/dtype the
    client rebuilds its decoder from).  ``wire_nbytes`` is the
    wire-ENCODED size — the figure delta-pull byte accounting is
    denominated in."""

    payload: object
    version: int
    wire_nbytes: int
    codec: Optional[Tuple[dict, int, str]] = None


@dataclasses.dataclass
class ServeReply:
    snapshot_id: int
    full: bool
    items: Dict[str, ServeItem]
    wire_bytes: int
    server_id: int
    # admission control (server/serving_tier.py): True = the host shed
    # this pull — "keep serving your cache, it is still inside your
    # staleness bound" — a deliberate near-zero-cost answer, not data.
    # The plane's in-process endpoints never shed.
    shed: bool = False
    # router-local (never on the wire): SOME of the merged per-host
    # replies were shed.  The client applies the fresh slices but must
    # NOT advance its freshness clock — the shed hosts' keys are only
    # guaranteed inside the bound as of NOW, not for another full bound
    shed_partial: bool = False


class SnapshotServer:
    """One serving endpoint (the primary, or a replica mirror) answering
    pulls from a snapshot ring.  Every payload crosses the
    chaos-instrumented ``serve_pull`` envelope hop on the way out —
    same NACK/retransmit machine as the push paths."""

    def __init__(self, ring: SnapshotRing, store: Optional[KVStore] = None,
                 server_id: int = 0, partial: bool = False):
        self.ring = ring
        self.store = store  # back-reference only; codecs ride each
        #                     snapshot (captured at cut time), so the
        #                     read path never touches the store lock
        self.server_id = server_id
        # a PARTIAL endpoint (replica mirror) holds a hot-key subset:
        # asked for a key outside its snapshot it must REFUSE (the
        # router falls through to the primary) — silently skipping the
        # key would stamp the reply with a snapshot id whose version
        # vector already covers the key, and the missed update would
        # never be re-shipped until the key next changes
        self.partial = partial
        self.alive = True
        # gray-failure chaos hook (docs/gray_failures.md): a per-ENDPOINT
        # sustained delay — the slow-but-alive serving replica the
        # hedged-pull path exists for (the injector's `slow` kind is
        # per-process; this hook throttles ONE endpoint of a plane)
        self.delay_s = 0.0

    def kill(self) -> None:
        """Chaos hook: the endpoint stops answering (a dead replica)."""
        self.alive = False

    def revive(self) -> None:
        self.alive = True

    # -- the reply wire hop -------------------------------------------------

    def _ship(self, key: str, payload, sid: int, nbytes: int, opener,
              sealer):
        """The reply hop for one key's payload: sealed envelope +
        NACK/retransmit at site ``serve_pull``, with the same loopback
        fast path as the push receivers (in-process hop + no chaos armed
        = the CRC would verify bytes against themselves)."""
        if not _integrity.enabled():
            if _fault.ENABLED:
                if isinstance(payload, (bytes, memoryview)):
                    payload = _fault.corrupt_bytes("serve_pull",
                                                   bytes(payload))
                else:
                    payload = np.asarray(
                        _fault.corrupt("serve_pull", payload))
                _fault.fire("serve_pull")
            return payload
        if _integrity.loopback_fast() and not _fault.ENABLED:
            # COW-frozen read-only view: safe to hand out without a copy
            return payload

        def wasted():
            counters.inc("serve.pull_bytes_wasted", nbytes)

        frame = sealer(payload, key=key, seq=sid, worker=self.server_id)
        return _integrity.wire_transmit(
            frame, key=key, worker=self.server_id, seq=sid,
            site="serve_pull", opener=opener, who="serve", on_reject=wasted)

    def pull(self, since_id: Optional[int] = None,
             keys: Optional[List[str]] = None) -> ServeReply:
        """Answer one pull: only keys whose version advanced past the
        client's ``since_id`` snapshot, from the LATEST snapshot (never
        the live store — a mid-update multi-key read is impossible by
        construction).  ``since_id`` unknown or aged out of retention →
        full snapshot."""
        tctx = _tracing.current()
        t_ep0 = time.monotonic() if tctx is not None else 0.0
        if not self.alive:
            counters.inc("serve.unavailable")
            raise ServeUnavailable(
                f"serving endpoint {self.server_id} is down")
        if self.delay_s:
            # the slow-but-alive endpoint: answers correctly, late (the
            # per-ENDPOINT gray-failure hook; the injector's per-process
            # `slow`/`delay` kinds keep firing per shipped key at the
            # existing serve_pull hop — a second entry-point fire here
            # would double-inject and burn `n=` budgets off-count)
            counters.inc("serve.slow_endpoint_delays")
            time.sleep(self.delay_s)
        snap = self.ring.latest()
        if snap is None:
            counters.inc("serve.unavailable")
            raise ServeUnavailable(
                f"serving endpoint {self.server_id} has no snapshot yet")
        base = self.ring.get(since_id) if since_id is not None else None
        if base is not None and base.gen != snap.gen:
            # the store was cleared between the client's snapshot and
            # now: versions restarted at 0, so the vectors are not
            # comparable — a "delta" would skip every re-initialized
            # key and serve pre-clear values as fresh
            base = None
        full = base is None
        if since_id is not None and full:
            counters.inc("serve.retention_miss")
        wanted = snap.versions.keys() if keys is None else keys
        if self.partial and any(k not in snap.versions for k in wanted):
            # mirror coverage raced a cut (the key left this replica's
            # set, or was requested before its first mirror): refuse so
            # the router degrades to an endpoint that CAN answer
            counters.inc("serve.unavailable")
            raise ServeUnavailable(
                f"replica {self.server_id} does not mirror every "
                "requested key")
        items: Dict[str, ServeItem] = {}
        wire_total = 0
        for k in wanted:
            if k not in snap.versions:
                continue
            if not full and snap.versions[k] <= base.versions.get(k, -1):
                continue  # unchanged since the client's snapshot
            value = snap.refs[k]
            # codec from the SNAPSHOT (captured at cut time): the hot
            # read path must not contend on the live store lock per key
            info = snap.codecs.get(k)
            if info is not None:
                kwargs, comp, numel, dtype = info
                wire = snap.enc_cache.get(k)
                if wire is None:
                    wire = comp.wire_encode(
                        comp.compress(value, comp.init_state())[0])
                    snap.enc_cache[k] = wire
                nbytes = len(wire)
                payload = bytes(self._ship(
                    k, wire, snap.id, nbytes, _integrity.open_bytes,
                    _integrity.seal_bytes))
                items[k] = ServeItem(payload, snap.versions[k], nbytes,
                                     (dict(kwargs), numel,
                                      np.dtype(dtype).str))
            else:
                nbytes = value.nbytes
                payload = self._ship(k, value, snap.id, nbytes,
                                     _integrity.open_array,
                                     _integrity.seal_array)
                items[k] = ServeItem(payload, snap.versions[k], nbytes)
            wire_total += nbytes
        counters.inc("serve.full_pulls" if full else "serve.delta_pulls")
        counters.inc("serve.pull_keys", len(items))
        counters.inc("serve.pull_bytes", wire_total)
        if tctx is not None:
            # the captured pull's serving leg: span on this endpoint's
            # track, closing flow ``f`` — the router opened the arc
            tr = _tracing.tracer()
            now = time.monotonic()
            tr.record_traced(tctx.trace_id, "serve.pull",
                             f"serve/{self.server_id}", t_ep0, now,
                             snapshot_id=snap.id, full=full,
                             keys=len(items))
            tr.flow(tctx.trace_id, "f", f"serve/{self.server_id}", now)
        return ServeReply(snapshot_id=snap.id, full=full, items=items,
                          wire_bytes=wire_total, server_id=self.server_id)


# -- the plane: primary + replicas + routing --------------------------------

_planes: "weakref.WeakSet[ServingPlane]" = weakref.WeakSet()


def active_planes() -> List["ServingPlane"]:
    return list(_planes)


def notify_world_change(view) -> None:
    """Called by :mod:`~byteps_tpu.fault.membership` when the elastic
    world changes: every live plane re-clamps its endpoint set and
    rebuilds replica routing (a dead replica's keys degrade to primary
    reads instead of erroring)."""
    for plane in active_planes():
        try:
            plane.on_world_change(view)
        except Exception:  # noqa: BLE001 — serving must never fail a
            # membership transition
            get_logger().error("serving: on_world_change failed",
                               exc_info=True)


class ServingPlane:
    """The read plane over one :class:`KVStore`: a primary endpoint that
    serves everything plus ``BYTEPS_SERVE_REPLICAS - 1`` replica mirrors
    serving the hot keys, with per-pull routing, fallback, and the
    ``serve.*`` metric surface.

    ``cut()`` is the publication point: it cuts a snapshot, re-ranks
    hotness (``ServerAssigner`` pull histogram), and mirrors the hot
    subset to each replica in the key's replica set.  Call it at your
    consistency boundaries (e.g. once per training step), or pass
    ``cut_interval_s`` to let store writes drive it."""

    def __init__(self, store: KVStore, *,
                 replicas: Optional[int] = None,
                 retention: Optional[int] = None,
                 hot_keys: Optional[int] = None,
                 cut_interval_s: Optional[float] = None,
                 assigner: Optional[ServerAssigner] = None,
                 hedge: Optional[bool] = None):
        from ..common.config import get_config
        cfg = get_config()
        n = cfg.serve_replicas if replicas is None else replicas
        if n < 1:
            raise ValueError("replicas must be >= 1 (the primary)")
        self.store = store
        self.num_endpoints = n
        # Hedged pulls (ISSUE 10, docs/gray_failures.md): fire a backup
        # pull to the next replica when the first endpoint has not
        # answered within the hedge delay — first response wins, losers
        # are discarded (reads are idempotent; the seq-token machinery
        # that makes PUSHES idempotent is what lets a duplicated wire
        # frame downstream be dropped harmlessly).  Off by default: the
        # per-pull thread costs real throughput, so it is the explicit
        # `hedge=True` / BYTEPS_STRAGGLER_POLICY=hedge trade — bounded
        # tail latency under one slow serving endpoint for overhead on
        # every hedged pull.  Delay: BYTEPS_SERVE_HEDGE_MS fixed, or
        # (default 0) adaptive — the p99 of recent WINNING pull
        # latencies, so the observed-latency ring never learns the slow
        # endpoint's figure as "normal".
        self._hedge = (cfg.straggler_policy == "hedge" if hedge is None
                       else bool(hedge))
        self._hedge_ms = cfg.serve_hedge_ms
        self._hedge_lat = LatencyQuantile()
        self.assigner = assigner if assigner is not None else ServerAssigner(
            num_servers=n, fn="djb2", mixed_mode=False, bound=101,
            replicas=n, hot_keys=(cfg.serve_hot_keys if hot_keys is None
                                  else hot_keys))
        self._lock = named_lock("serving_plane")
        self._cut_serial = named_lock("serving_plane.cut")
        self._rr = 0
        # key -> replica endpoint ids mirroring it (rebuilt at each cut)
        self._mirrored: Dict[str, List[int]] = {}
        self._alive_clamp = n
        # the SnapshotStore comes LAST: with cut_interval_s it
        # subscribes cut_fn=self.cut to the store's write hook, and a
        # pusher thread already landing deltas would invoke a
        # half-constructed plane (cut_fn=self.cut: a write-triggered
        # cut must also re-mirror the replicas — a bare SnapshotStore
        # cut would publish primary-only snapshots and the replicas
        # would idle forever)
        self.snapstore = SnapshotStore(store, retention=retention,
                                       cut_interval_s=cut_interval_s,
                                       cut_fn=self.cut,
                                       defer_subscribe=True)
        self.primary = SnapshotServer(self.snapstore.ring, store,
                                      server_id=0)
        self.replicas = [
            SnapshotServer(SnapshotRing(self.snapstore.ring.retention),
                           store, server_id=i, partial=True)
            for i in range(1, n)]
        _planes.add(self)
        from ..common import metrics as _metrics
        _metrics.register_component("serving_plane", self)
        # last: only a FULLY constructed plane may receive write hooks
        self.snapstore.attach()

    # -- publication ---------------------------------------------------------

    def close(self) -> None:
        """Detach from the store's write hook and the module plane
        registry so a dropped plane can actually be collected (the
        store's subscriber list holds strong references)."""
        self.snapstore.detach()
        _planes.discard(self)

    def cut(self) -> Snapshot:
        """Publish: snapshot the store, re-rank hotness, mirror the hot
        subset to each replica.  Atomic per endpoint (ring swap); the
        primary is published first so a replica is never AHEAD of the
        endpoint its misses fall back to.  Serialized end to end —
        concurrent cutters (several pusher threads crossing the
        auto-cut interval at once) must not interleave their replica
        publishes out of id order."""
        with self._cut_serial:
            return self._cut_locked()

    def _cut_locked(self) -> Snapshot:
        snap = self.snapstore.cut()
        sets = self.assigner.rebuild_replicas()
        mirrored: Dict[str, List[int]] = {}
        per_replica: Dict[int, Dict[str, int]] = {}
        dead = {r.server_id for r in self.replicas if not r.alive}
        for key, shard_set in sets.items():
            if key not in snap.versions:
                continue
            # a replica discovered dead (ServeUnavailable at pull time)
            # leaves the mirror sets at the NEXT cut: between kill and
            # cut, pulls pay one serve.replica_fallback hop; after it,
            # routing never touches the corpse again
            ids = [s for s in shard_set
                   if s != 0 and s < self._alive_clamp and s not in dead]
            if ids:
                mirrored[key] = ids
                for sid in ids:
                    per_replica.setdefault(sid, {})[key] = \
                        snap.versions[key]
        for rep in self.replicas:
            keys = per_replica.get(rep.server_id, {})
            if not keys:
                continue
            rep.ring.publish(Snapshot(
                id=snap.id, ts=snap.ts, versions=dict(keys),
                refs={k: snap.refs[k] for k in keys},
                gen=snap.gen, codecs=snap.codecs,
                enc_cache=snap.enc_cache))
        with self._lock:
            self._mirrored = mirrored
        gauges.set("serve.hot_keys", len(mirrored))
        gauges.set("serve.dead_replicas",
                   sum(1 for r in self.replicas if not r.alive))
        return snap

    # -- routing -------------------------------------------------------------

    def _read_candidates(self, keys: Optional[List[str]],
                         since_id: Optional[int]) -> List[SnapshotServer]:
        """Replica endpoints that mirror EVERY key in the RESOLVED
        request list AND still retain the client's ``since_id``
        snapshot, rotated round-robin — cold keys, partial coverage, or
        a delta base the replica cannot serve all route to the primary
        (a replica must never silently inflate a delta pull into a full
        one just because its mirror history started later).  The
        ``alive`` flag is deliberately NOT consulted: a dead replica is
        discovered at pull time (``ServeUnavailable`` →
        ``serve.replica_fallback``) and leaves the mirror sets at the
        next :meth:`cut`, exactly like a real router learning of a dead
        peer from a failed read."""
        with self._lock:
            mirrored = self._mirrored
            if not mirrored or not self.replicas:
                return []
            if not keys:
                # no keys resolved (empty request, or no snapshot yet):
                # nothing for a replica to cover — primary answers
                return []
            eligible: Optional[set] = None
            for k in keys:
                ids = set(mirrored.get(k, ()))
                eligible = ids if eligible is None else (eligible & ids)
                if not eligible:
                    return []
            reps = [r for r in self.replicas
                    if r.server_id in eligible
                    and (since_id is None
                         or r.ring.get(since_id) is not None)]
            if not reps:
                return []
            self._rr = (self._rr + 1) % len(reps)
            return reps[self._rr:] + reps[:self._rr]

    def pull(self, since_id: Optional[int] = None,
             keys: Optional[List[str]] = None,
             record: bool = True,
             hedge: Optional[bool] = None) -> ServeReply:
        """One routed pull: fan across the replica set for hot keys,
        degrade to the primary on any replica failure — a pull fails
        only when the PRIMARY cannot answer.  With hedging on (plane
        default or per-call ``hedge=``) and at least one eligible
        replica, the attempts race instead of running sequentially:
        the backup fires after the hedge delay and the first response
        wins, so no single slow endpoint owns the tail."""
        t0 = time.perf_counter()
        # causal tracing (ISSUE 12): sample this pull; the context is
        # installed around the SEQUENTIAL candidate chain only — hedge
        # attempts run on worker threads the contextvar does not reach
        tctx, t_tr0 = _tracing.begin_sample("serve.route")
        # resolve keys=None to the latest snapshot's key list, NOT
        # store.keys(): the hot read path must not contend on the live
        # store lock — and a partial replica needs the explicit list to
        # verify its coverage
        wanted = keys
        if wanted is None:
            snap = self.snapstore.ring.latest()
            wanted = list(snap.versions) if snap is not None else []
        if record:
            self.assigner.record_pulls(wanted)
        cands = self._read_candidates(wanted, since_id)
        use_hedge = self._hedge if hedge is None else bool(hedge)
        hedged = bool(use_hedge and cands)
        if hedged:
            reply = self._pull_hedged(cands, since_id, keys, wanted)
        else:
            with _tracing.use(tctx):
                reply = None
                for rep in cands:
                    try:
                        reply = rep.pull(since_id=since_id, keys=wanted)
                        counters.inc("serve.replica_reads")
                        break
                    except ServeUnavailable:
                        counters.inc("serve.replica_fallback")
                        continue
                if reply is None:
                    reply = self.primary.pull(since_id=since_id, keys=keys)
                    counters.inc("serve.primary_reads")
        counters.inc("serve.pulls")
        histograms.observe("serve.pull_ms",
                           (time.perf_counter() - t0) * 1e3)
        if tctx is not None:
            tr = _tracing.tracer()
            now = time.monotonic()
            tr.record_traced(tctx.trace_id, "serve.route", "serve/plane",
                             t_tr0, now, keys=len(wanted), hedged=hedged)
            if not hedged:
                # the winning endpoint closed this arc with its ``f``;
                # hedged attempts ran outside the context, so opening an
                # arc here would leave an orphan ``s``
                tr.flow(tctx.trace_id, "s", "serve/plane", t_tr0)
        return reply

    # -- hedging -------------------------------------------------------------

    def _hedge_delay_s(self) -> float:
        """How long the first attempt gets before the backup fires:
        the fixed BYTEPS_SERVE_HEDGE_MS when set, else the p99 of
        recent winning pull latencies (floored so scheduler jitter
        cannot hedge every pull, capped so a cold ring cannot park the
        tail)."""
        if self._hedge_ms > 0:
            return self._hedge_ms / 1e3
        q = self._hedge_lat.quantile(0.99)
        if q is None:
            return 0.002          # cold start: no history yet
        return min(max(q, 0.0005), 0.25)

    def _pull_hedged(self, cands: List[SnapshotServer],
                     since_id: Optional[int], keys: Optional[List[str]],
                     wanted: List[str]) -> ServeReply:
        """Race the read candidates: fire the first, then one more per
        elapsed hedge delay until something answers.  First successful
        response wins; late duplicates are counted and dropped
        (``serve.hedge_discarded``) — a pull is idempotent, so
        discarding is the whole duplicate story.  A candidate that
        FAILS fast (``ServeUnavailable``) does not consume the budget
        forever: once every attempt has failed and none succeeded, the
        primary's error propagates exactly as on the sequential path.
        Every attempt's latency feeds the slowness tracker
        (``site="serve_pull"``), so a chronically slow endpoint is
        visible in ``/debug/state`` and ``bps_top`` even while hedging
        hides it from clients."""
        endpoints: List[Tuple[SnapshotServer, Optional[List[str]]]] = [
            (rep, wanted) for rep in cands]
        endpoints.append((self.primary, keys))
        done = threading.Event()
        wake = threading.Event()   # ANY attempt outcome (win or failure)
        lock = threading.Lock()
        state = {"reply": None, "winner": None, "failed": 0, "exc": None}
        total = len(endpoints)

        def attempt(ep: SnapshotServer, ep_keys, hedged: bool) -> None:
            t0 = time.perf_counter()
            try:
                r = ep.pull(since_id=since_id, keys=ep_keys)
            except Exception as e:  # noqa: BLE001 — ServeUnavailable is
                # the routing signal; anything else still must COUNT
                # (an uncounted dead attempt would park the final wait
                # forever) and propagates if nothing answers
                with lock:
                    state["failed"] += 1
                    state["exc"] = e
                    if state["failed"] >= total and state["reply"] is None:
                        done.set()
                wake.set()
                return
            dt = time.perf_counter() - t0
            _slowness.tracker().observe(ep.server_id, dt, site="serve_pull")
            with lock:
                if state["reply"] is None:
                    state["reply"] = r
                    state["winner"] = ep
                    # winners only: the delay ring must keep describing
                    # HEALTHY latency, not learn the straggler's
                    self._hedge_lat.observe(dt)
                    if hedged:
                        counters.inc("serve.hedge_wins")
                    done.set()
                else:
                    counters.inc("serve.hedge_discarded")
            wake.set()

        delay = self._hedge_delay_s()
        launched = 0
        answered = False
        for i, (ep, ep_keys) in enumerate(endpoints):
            threading.Thread(target=attempt, args=(ep, ep_keys, i > 0),
                             daemon=True, name="bps-serve-hedge").start()
            launched += 1
            if i == 1:
                counters.inc("serve.hedged_pulls")
            if i == total - 1 or answered:
                break
            # wait out the hedge delay — but wake on every attempt
            # outcome: an answer stops hedging, and fast failures
            # covering EVERY launched attempt fire the next candidate
            # immediately (a dead leading replica must not tax each
            # pull the full delay when the sequential path would fall
            # through instantly)
            deadline = time.monotonic() + delay
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not wake.wait(remaining):
                    break   # delay elapsed: hedge
                wake.clear()
                with lock:
                    if state["reply"] is not None:
                        answered = True
                        break
                    if state["failed"] >= launched:
                        break   # everyone so far failed: next, NOW
            if answered:
                break
        done.wait()
        with lock:
            reply, winner = state["reply"], state["winner"]
            exc = state["exc"]
        if reply is None:
            raise exc if exc is not None else ServeUnavailable(
                "no serving endpoint answered the hedged pull")
        counters.inc("serve.primary_reads" if winner is self.primary
                     else "serve.replica_reads")
        return reply

    # -- elastic -------------------------------------------------------------

    def reshard(self, alive_endpoints: int) -> None:
        """Clamp the endpoint set to ``alive_endpoints`` (a shrunk world)
        or re-open it (a rejoin), and re-derive the replica sets over
        the surviving shards — the pull histogram is retained, so
        hotness carries over.  Reads already in flight against a
        now-dead replica fall back through the normal routing path."""
        alive = max(1, min(alive_endpoints, self.num_endpoints))
        with self._lock:
            self._alive_clamp = alive
            self._mirrored = {}
        for rep in self.replicas:
            if rep.server_id >= alive:
                rep.kill()
            else:
                rep.revive()
        self.assigner.reshard(alive)
        counters.inc("serve.reshards")
        if self.snapstore.ring.latest() is not None:
            self.cut()  # re-mirror under the new shape immediately

    def on_world_change(self, view) -> None:
        self.reshard(min(self.num_endpoints, view.num_workers))

    # -- observability -------------------------------------------------------

    def debug_state(self) -> dict:
        snap = self.snapstore.ring.latest()
        with self._lock:
            mirrored = len(self._mirrored)
            clamp = self._alive_clamp
        return {
            "kind": "serving_plane",
            "endpoints": self.num_endpoints,
            "alive_clamp": clamp,
            "dead_replicas": [r.server_id for r in self.replicas
                              if not r.alive],
            "snapshot_id": snap.id if snap is not None else None,
            "snapshots_retained": len(self.snapstore.ring),
            "hot_keys_mirrored": mirrored,
            "load": self.assigner.load_summary(),
        }
