"""Shared shard-geometry math for flat sharded optimizer state.

Two paths keep optimizer state as one flat padded f32 vector sharded
over mesh axes: ``parallel/zero.py`` (in-graph SPMD ZeRO — the whole
model as one vector, collectives inside the train step) and
``core/sharded_update.py`` (the engine's fused sharded weight update,
ISSUE 20 — one vector per declared tensor, collectives on the engine's
push_pull pipeline).  The padding rule, the axis resolution, and the
"which optimizer-state leaves are sharded" spec rule must be the SAME
in both, or a state exported from one layout could not be re-imported
into the other and the two `sharded_update=True` adapters would drift.
This module is that single source; zero.py re-exports these under its
historical private names.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import CommContext, DCN_AXIS, ICI_AXIS

__all__ = [
    "padded_size",
    "resolve_axes",
    "spec_of_opt",
    "init_sharded_opt_state",
]


def padded_size(n: int, ranks: int) -> int:
    """Pad to a multiple of ranks*128 so every shard is lane-aligned (the
    partitioner's 512-elem tile rule, common/partitioner.py, scaled to the
    shard grid)."""
    quantum = ranks * 128
    return (n + quantum - 1) // quantum * quantum


def resolve_axes(comm: CommContext, shard_axes: str):
    """(scatter/gather axes, remaining-sum axes, shard count).

    "all": shard over every DP axis — minimum memory (1/R).
    "ici": HSDP / hybrid sharding — shard within a slice, replicate
    across slices: the per-step all_gather/psum_scatter ride ICI only,
    and DCN carries just a psum of the 1/n_ici gradient shard (the
    layout multi-slice pods want when DCN bandwidth, not HBM, is the
    constraint).
    """
    if shard_axes == "all":
        return comm.dp_axes, (), comm.num_ranks
    if shard_axes == "ici":
        return (ICI_AXIS,), (DCN_AXIS,), comm.n_ici
    raise ValueError(
        f"shard_axes must be 'all' or 'ici', got {shard_axes!r}")


def spec_of_opt(tree, padded: int, axes):
    """PartitionSpec tree for flat-sharded optimizer state: vectors of
    the master's padded length are sharded over ``axes``, everything
    else (step counters, scalar hyperparams) is replicated."""
    return jax.tree.map(
        lambda x: P(axes) if (getattr(x, "ndim", 0) == 1
                              and x.shape[0] == padded) else P(),
        tree)


def init_sharded_opt_state(comm: CommContext, tx, master, padded: int,
                           axes):
    """``tx.init(master)`` with every padded-length leaf COMMITTED to the
    shard layout (``P(axes)``) and everything else replicated.  The pin
    matters: zeros_like outputs carry no data dependence on the input,
    so XLA propagation would replicate them."""
    shapes = jax.eval_shape(tx.init, master)
    out_sh = jax.tree.map(lambda s: NamedSharding(comm.mesh, s),
                          spec_of_opt(shapes, padded, axes))
    return jax.jit(tx.init, out_shardings=out_sh)(master)
